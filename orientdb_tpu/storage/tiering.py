"""Tiered snapshots: a device-managed hot/cold adjacency plane.

r04 measured ~1.34 GB of per-device adjacency at SF100 shape — with
property columns and dictionaries on top, the north-star graphs do not
fit one device's HBM. This module splits a :class:`GraphSnapshot`'s
adjacency into a **device-resident hot tier** and a **host-pinned cold
tier** so over-cap graphs keep serving instead of failing the upload:

- Each (edge class, direction) partition's flat ``[E]`` arrays are cut
  into contiguous **vertex-range blocks** of roughly
  ``config.tier_block_edges`` edges (edge-balanced, so hub vertices
  never split a block). Block values live in a fixed device **pool**
  of ``P`` pages; a ``page_of[B]`` indirection maps blocks to pages
  (−1 = cold). Pools, ``page_of`` and the per-vertex block index are
  ordinary ``DeviceGraph.arrays`` entries, i.e. jit ARGUMENTS of every
  compiled plan — residency changes are functional array updates that
  reach every cached executable with zero retrace.
- **Placement** is degree-skew seeded (blocks holding the
  highest-degree vertices load first — the `degree_skew` bench block's
  distribution says hubs dominate touch probability) and maintained
  LRU by touch recency.
- **Faulting** happens at recording time: the eager recording run sees
  concrete frontiers, so the solver asks the manager to make every
  touched block resident *before* the gather reads it, and the touched
  set becomes the plan's **tier footprint**. Replays are sync-free:
  `dispatch` re-ensures the footprint (async ``jax.device_put`` uploads
  that overlap the dispatch plane — recorded as ``prefetch``-kind
  transfers in the obs/timeline flight recorder), and a device-side
  **cold-miss flag** folds into the SizeSchedule overflow surface so a
  parameter-generic replay that wanders off its recorded footprint
  re-records (which faults the new blocks in) instead of returning
  garbage.
- **Eviction** under ``config.tier_hbm_cap_bytes`` follows the PR-15
  epoch discipline at the array level: updates are functional, so an
  in-flight dispatch keeps the pool arrays it was handed alive until it
  drains — use-after-free is structurally impossible. Dispatch-time
  pins only steer the eviction CHOICE (prefer unpinned, LRU) and feed
  the ``tier_thrash`` alert: reload of a recently evicted block counts
  as thrash, surfaced as the ``tier.thrash`` gauge + alert rule rather
  than a silent cliff.

Composition guards: tiered snapshots are single-device and immutable —
attaching a mesh or arming delta maintenance on one refuses loudly
(mirroring the mesh + overlay guard in ops/device_graph).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import orientdb_tpu.obs.timeline as TL
import orientdb_tpu.ops.csr as K
from orientdb_tpu.chaos.faults import FaultError, fault
from orientdb_tpu.obs.trace import span
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.metrics import metrics

#: reload of a block evicted within this many ensure calls counts as a
#: thrash event; the ``tier.thrash`` gauge is events over the window
_THRASH_WINDOW = 32

#: pool arrays per partition (own/nbr/eid), int32 each
_POOL_ARRAYS = 3


def adjacency_bytes(snap) -> int:
    """Resident-form HBM bytes of the flat adjacency (the four ``[E]``
    int32 arrays plus both indptrs, per edge class) — the quantity
    ``tier_hbm_cap_bytes`` caps. Property columns upload lazily and are
    budgeted separately (hbm.pruned_column_bytes)."""
    total = 0
    for csr in snap.edge_classes.values():
        E = int(csr.dst.shape[0])
        total += 4 * (4 * E + int(csr.indptr_out.shape[0]) + int(csr.indptr_in.shape[0]))
    return total


class _Partition:
    """Host-side layout + residency bookkeeping for one
    (edge class, direction) partition of the adjacency."""

    __slots__ = (
        "cname", "d", "V", "E", "W", "Wp", "B", "P",
        "edge_start", "block_of_v", "vdeg", "prio",
        "host", "page_of", "block_of_page", "free_pages",
        "lru", "pins", "evicted_at", "neg_row",
    )

    def __init__(self, cname: str, d: str, indptr: np.ndarray,
                 host: Dict[str, np.ndarray]) -> None:
        self.cname = cname
        self.d = d
        self.V = int(indptr.shape[0]) - 1
        self.E = int(host["nbr"].shape[0])
        deg = np.diff(indptr).astype(np.int64)
        deg_max = int(deg.max()) if deg.size else 0
        self.W = max(int(config.tier_block_edges), deg_max, 1)
        # quotient blocking: a vertex belongs to the block of its first
        # edge's W-quotient, so a block spans < W + deg_max edges —
        # vectorized, and hubs never split across blocks
        self.Wp = K.bucket(self.W + deg_max, minimum=8)
        q = (indptr[:-1].astype(np.int64) // self.W) if self.V else np.zeros(0, np.int64)
        uq, inv = np.unique(q, return_inverse=True)
        self.B = int(uq.shape[0])
        self.block_of_v = inv.astype(np.int32)
        first_v = np.searchsorted(inv, np.arange(self.B), side="left")
        self.edge_start = np.concatenate(
            [indptr[first_v].astype(np.int64), [self.E]]
        ).astype(np.int32)
        self.vdeg = deg.astype(np.int32)
        # degree-skew placement priority: the hottest block holds the
        # highest-degree vertex (hubs dominate frontier touch odds)
        if self.B:
            self.prio = np.maximum.reduceat(deg, first_v)
        else:
            self.prio = np.zeros(0, np.int64)
        self.host = host  # name -> [E] int32 in this partition's order
        # residency state (reset per install)
        self.page_of = np.full(self.B, -1, np.int32)
        self.block_of_page = np.zeros(0, np.int32)
        self.free_pages: List[int] = []
        self.lru: Dict[int, int] = {}
        self.pins: Dict[int, int] = {}
        self.evicted_at: Dict[int, int] = {}
        self.P = 0
        self.neg_row = None

    @property
    def key(self) -> Tuple[str, str]:
        return (self.cname, self.d)

    def block_bytes(self) -> int:
        return self.Wp * 4 * _POOL_ARRAYS

    def block_values(self, name: str, b: int) -> np.ndarray:
        lo, hi = int(self.edge_start[b]), int(self.edge_start[b + 1])
        out = np.full(self.Wp, -1, np.int32)
        out[: hi - lo] = self.host[name][lo:hi]
        return out


def _keys(cname: str, d: str) -> Dict[str, str]:
    p = f"t:{cname}:{d}"
    return {
        "own": f"{p}:own", "nbr": f"{p}:nbr", "eid": f"{p}:eid",
        "pageof": f"{p}:pageof", "blockv": f"{p}:blockv",
        "estart": f"{p}:estart",
    }


class TierManager:
    """Hot/cold residency manager for one snapshot's adjacency.

    Built by :func:`maybe_tier_snapshot` when the snapshot's adjacency
    exceeds ``config.tier_hbm_cap_bytes``; installed into the snapshot's
    DeviceGraph at build time (`install`). All residency mutation runs
    under ``self.lock``; dispatches grab their jit-arg pytree inside
    `prepare_dispatch` so a concurrent eviction can never hand a plan a
    torn (pool, page_of) pair."""

    def __init__(self, snap, cap_bytes: int) -> None:
        self.snap = snap
        self.cap = int(cap_bytes)
        self.lock = threading.RLock()
        self.parts: Dict[Tuple[str, str], _Partition] = {}
        for cname, csr in snap.edge_classes.items():
            E = int(csr.dst.shape[0])
            if E == 0:
                continue
            out_host = {
                "own": csr.edge_src_np().astype(np.int32),
                "nbr": np.asarray(csr.dst, np.int32),
                # out-partition edge ids ARE the CSR positions
                "eid": np.arange(E, dtype=np.int32),
            }
            in_host = {
                # per-edge owning dst in in-CSR order (reverse hops
                # activate the dst endpoint)
                "own": np.repeat(
                    np.arange(int(csr.indptr_in.shape[0]) - 1, dtype=np.int32),
                    np.diff(csr.indptr_in),
                ),
                "nbr": np.asarray(csr.src, np.int32),
                "eid": np.asarray(csr.edge_id_in, np.int32),
            }
            pair = [
                _Partition(cname, d, indptr, host)
                for d, indptr, host in (
                    ("out", np.asarray(csr.indptr_out), out_host),
                    ("in", np.asarray(csr.indptr_in), in_host),
                )
            ]
            # a class tiers as a PAIR or not at all: the resident
            # reverse hop reads the out-order arrays (dst/edge_src), so
            # paging one direction while the other stays flat would
            # leave the flat path without its arrays. Single-block
            # partitions gain nothing from paging anyway.
            if all(p.B >= 2 for p in pair):
                for p in pair:
                    self.parts[p.key] = p
        self._size_pools()
        self._dg = None
        self.ensure_seq = 0
        self.evictions = 0
        self.prefetch_hits = 0
        self.prefetch_misses = 0
        self._thrash: deque = deque()

    def _size_pools(self) -> None:
        """Split the byte cap across partitions proportionally to their
        edge counts; each partition gets at least one page."""
        tot = sum(p.E for p in self.parts.values()) or 1
        for part in self.parts.values():
            share = self.cap * part.E // tot
            part.P = max(1, min(part.B, int(share // part.block_bytes())))

    def pages_dir(self, cname: str, d: str) -> bool:
        return (cname, d) in self.parts

    # -- device install -----------------------------------------------------

    def install(self, dg) -> None:
        """Upload the tier plane into a freshly built DeviceGraph:
        block indexes, empty pools, and the degree-skew hot seed."""
        with self.lock:
            self._dg = dg
            for part in self.parts.values():
                part.page_of = np.full(part.B, -1, np.int32)
                part.block_of_page = np.full(part.P, -1, np.int32)
                part.free_pages = list(range(part.P))
                part.lru.clear()
                part.pins.clear()
                part.evicted_at.clear()
                part.neg_row = jnp.full((part.Wp,), -1, jnp.int32)
                keys = _keys(part.cname, part.d)
                # seed the pool host-side (ONE upload per array) with
                # the highest-priority blocks
                order = np.argsort(-part.prio, kind="stable")[: part.P]
                pools = {
                    n: np.full((part.P, part.Wp), -1, np.int32)
                    for n in ("own", "nbr", "eid")
                }
                for p, b in enumerate(order):
                    b = int(b)
                    for n in pools:
                        pools[n][p] = part.block_values(n, b)
                    part.page_of[b] = p
                    part.block_of_page[p] = b
                    part.lru[b] = 0
                part.free_pages = list(range(len(order), part.P))
                for n in ("own", "nbr", "eid"):
                    dg._put(keys[n], pools[n])
                dg._put(keys["pageof"], part.page_of)
                dg._put(keys["blockv"], part.block_of_v)
                dg._put(keys["estart"], part.edge_start)
            self._publish()

    # -- residency ----------------------------------------------------------

    def ensure_vertices(self, cname: str, d: str, verts: np.ndarray,
                        touched: Optional[Set] = None) -> None:
        """Recording-time fault: make every block owning an edge of
        these (concrete) frontier vertices resident before the gather
        reads it. Runs inside the allowlisted recording boundary, so the
        host-side index math is an intentional sync."""
        part = self.parts.get((cname, d))
        if part is None:
            return
        v = np.asarray(verts).reshape(-1)
        v = v[(v >= 0) & (v < part.V)]
        if v.size == 0:
            return
        v = v[part.vdeg[v] > 0]
        if v.size == 0:
            return
        blocks = np.unique(part.block_of_v[v])
        self._ensure_blocks(part, [int(b) for b in blocks], touched)

    def ensure_frontier(self, cname: str, d: str, frontier: np.ndarray,
                        touched: Optional[Set] = None) -> None:
        """Recording-time fault for a [C, vb] frontier bitmap."""
        part = self.parts.get((cname, d))
        if part is None:
            return
        fa = np.asarray(frontier).any(axis=0)[: part.V]
        self.ensure_vertices(cname, d, np.nonzero(fa)[0], touched)

    def prepare_dispatch(self, footprint: FrozenSet, arg_subset):
        """Dispatch-time footprint prefetch + atomic jit-arg grab: the
        recorded footprint's cold blocks upload (async device_put — the
        copies queue ahead of the dispatch and overlap the device work
        in front of them), pins bump, and the plan's argument pytree is
        snapshotted under the lock so eviction can never tear it."""
        with self.lock:
            by_part: Dict[Tuple[str, str], List[int]] = {}
            for key, b in footprint:
                by_part.setdefault(key, []).append(int(b))
            for key, blocks in by_part.items():
                part = self.parts.get(key)
                if part is not None:
                    self._ensure_blocks(part, blocks, None, pin=True)
            return arg_subset()

    def release_footprint(self, footprint: FrozenSet) -> None:
        with self.lock:
            for key, b in footprint:
                part = self.parts.get(key)
                if part is not None:
                    n = part.pins.get(int(b), 0)
                    if n <= 1:
                        part.pins.pop(int(b), None)
                    else:
                        part.pins[int(b)] = n - 1

    def _ensure_blocks(self, part: _Partition, blocks: List[int],
                       touched: Optional[Set], pin: bool = False) -> None:
        """Make ALL of ``blocks`` resident simultaneously.

        Simultaneity is not optional: the caller is one expansion (the
        recording's eager gather reads every block it touches in one
        kernel) or one fused replay dispatch (which snapshots the pool
        arrays ONCE as jit args). When the request exceeds the pool —
        free pages plus evictable blocks outside the request — the pool
        GROWS to the working set: the cap is enforced between queries
        (LRU eviction shrinks residency back toward it), never inside a
        dispatch, where violating it is the only way to be correct.
        Growth is loud (``tier.pool_grow`` + the hot_bytes gauge)."""
        dg = self._dg
        if dg is None:
            return
        self.ensure_seq += 1
        seq = self.ensure_seq
        requested = set(blocks)
        need = []
        for b in blocks:
            if touched is not None:
                touched.add((part.key, b))
            part.lru[b] = seq
            if pin:
                part.pins[b] = part.pins.get(b, 0) + 1
            if part.page_of[b] < 0:
                need.append(b)
            else:
                self.prefetch_hits += 1
                metrics.incr("tier.prefetch.hits")
        if need:
            evictable = sum(
                1
                for b2 in range(part.B)
                if part.page_of[b2] >= 0 and b2 not in requested
            )
            short = len(need) - len(part.free_pages) - evictable
            if short > 0:
                self._grow_pool(part, short)
            self._load_blocks(part, need, seq, requested)
        self._publish()

    def _grow_pool(self, part: _Partition, extra: int) -> None:
        dg = self._dg
        keys = _keys(part.cname, part.d)
        for n in ("own", "nbr", "eid"):
            pad = jnp.full((extra, part.Wp), -1, jnp.int32)
            dg._arrays[keys[n]] = jnp.concatenate([dg._arrays[keys[n]], pad])
        part.free_pages.extend(range(part.P, part.P + extra))
        part.block_of_page = np.concatenate(
            [part.block_of_page, np.full(extra, -1, np.int32)]
        )
        part.P += extra
        metrics.incr("tier.pool_grow")
        metrics.incr("tier.pool_grow_pages", extra)
        from orientdb_tpu.obs.memledger import memledger

        for n in ("own", "nbr", "eid"):
            memledger.register_graph_array(dg, keys[n], dg._arrays[keys[n]])
        memledger.note_event(
            "pool_grow",
            f"{part.cname}/{part.d}: +{extra} pages -> P={part.P}",
        )

    def _load_blocks(self, part: _Partition, need: List[int], seq: int,
                     requested: Set[int]) -> None:
        # the cold-block upload wave is a device transfer: run it under
        # the device fault domain's escalation ladder (lazy import —
        # tpu_engine module-imports this file). A retry re-grabs pages
        # for not-yet-resident blocks; pages grabbed by a failed pass
        # stay recyclable via the free/LRU machinery. Exhaustion raises
        # DeviceQuarantined (an Uncompilable) — the dispatch above this
        # ensure degrades to the oracle.
        from orientdb_tpu.exec import devicefault

        dg = self._dg
        keys = _keys(part.cname, part.d)
        nbytes = len(need) * part.block_bytes()
        t0 = time.monotonic()

        def _upload() -> None:
            devicefault.transfer_point()
            for b in need:
                if part.page_of[b] >= 0:
                    continue  # a prior attempt already landed it
                last = part.evicted_at.get(b)
                if last is not None and seq - last <= _THRASH_WINDOW:
                    self._thrash.append(seq)
                    metrics.incr("tier.thrash_events")
                p = self._grab_page(part, requested)
                for n in ("own", "nbr", "eid"):
                    vals = part.block_values(n, b)
                    try:
                        # scrub.flip chaos crossing: corrupt the
                        # DEVICE-bound pool row only — the partition's
                        # host blocks keep the truth, so the scrub
                        # sweep provably detects + reloads
                        with fault.point("scrub.flip"):
                            pass
                    except FaultError:
                        from orientdb_tpu.storage.scrub import chaos_flip

                        vals = chaos_flip(vals)
                    row = jax.device_put(vals)
                    dg._arrays[keys[n]] = dg._arrays[keys[n]].at[p].set(row)
                dg._arrays[keys["pageof"]] = (
                    dg._arrays[keys["pageof"]].at[b].set(p)
                )
                part.page_of[b] = p
                part.block_of_page[p] = b
                self.prefetch_misses += 1
                metrics.incr("tier.prefetch.misses")

        with span("tier.prefetch", cname=part.cname, d=part.d,
                  blocks=len(need)):
            devicefault.domain.run(_upload, tier=self, stage="prefetch")
        TL.add_transfer(t0, time.monotonic(), nbytes, "prefetch")
        TL.mark("tier_prefetch")
        # the functional .at[].set writes produced NEW pool arrays:
        # refresh their ledger attribution (reconcile tracks liveness
        # through the registered array identity)
        from orientdb_tpu.obs.memledger import memledger

        for n in ("own", "nbr", "eid", "pageof"):
            memledger.register_graph_array(dg, keys[n], dg._arrays[keys[n]])

    def _grab_page(self, part: _Partition, protect: Set[int]) -> int:
        if part.free_pages:
            return part.free_pages.pop()
        # LRU victim outside the current request, unpinned preferred; a
        # fully pinned remainder still evicts (functional arrays keep
        # in-flight dispatches safe) but counts the forced choice
        resident = [
            b
            for b in range(part.B)
            if part.page_of[b] >= 0 and b not in protect
        ]
        victim = min(
            resident,
            key=lambda b: (part.pins.get(b, 0) > 0, part.lru.get(b, -1)),
        )
        if part.pins.get(victim, 0) > 0:
            metrics.incr("tier.evict_pinned")
        return self._evict(part, victim)

    def _evict(self, part: _Partition, b: int) -> int:
        dg = self._dg
        keys = _keys(part.cname, part.d)
        with span("tier.evict", cname=part.cname, d=part.d, block=int(b)):
            p = int(part.page_of[b])
            # invalidate the page's owner row so the flattened bitmap
            # hop masks its slots out; nbr/eid stay stale-but-masked,
            # and the gather path guards via page_of
            dg._arrays[keys["own"]] = (
                dg._arrays[keys["own"]].at[p].set(part.neg_row)
            )
            dg._arrays[keys["pageof"]] = (
                dg._arrays[keys["pageof"]].at[b].set(jnp.int32(-1))
            )
            part.page_of[b] = -1
            part.block_of_page[p] = -1
            part.lru.pop(b, None)
            part.evicted_at[b] = self.ensure_seq
            self.evictions += 1
            metrics.incr("tier.evictions.total")
            from orientdb_tpu.obs.memledger import memledger

            for n in ("own", "pageof"):
                memledger.register_graph_array(
                    dg, keys[n], dg._arrays[keys[n]]
                )
        TL.mark("tier_evict")
        return p

    # -- observability ------------------------------------------------------

    def hot_bytes(self) -> int:
        total = 0
        for part in self.parts.values():
            total += part.P * part.block_bytes()
            total += 4 * (part.B + part.B + 1 + part.V + part.P)
        return total

    def pool_bytes(self) -> int:
        """Device bytes the hot pools occupy RIGHT NOW (pages only —
        ``hot_bytes`` adds the per-partition index overhead). The
        numerator the ``hbm_headroom`` rule's cap gauge divides."""
        return sum(
            part.P * part.block_bytes() for part in self.parts.values()
        )

    def headroom_bytes(self) -> int:
        return max(0, int(self.cap) - self.hot_bytes())

    def thrash_rate(self) -> float:
        floor = self.ensure_seq - _THRASH_WINDOW
        while self._thrash and self._thrash[0] <= floor:
            self._thrash.popleft()
        return float(len(self._thrash))

    def _publish(self) -> None:
        metrics.gauge("tier.hot_bytes", self.hot_bytes())
        # pool occupancy + the cap as gauges: the hbm_headroom rule's
        # denominator, and the invisible-occupancy fix — pool_grow was
        # a loud counter but nothing showed HOW BIG the pool is
        metrics.gauge("tier.pool_bytes", self.pool_bytes())
        metrics.gauge("tier.cap_bytes", float(self.cap))
        metrics.gauge("tier.headroom_bytes", self.headroom_bytes())
        metrics.gauge("tier.evictions", self.evictions)
        looked = self.prefetch_hits + self.prefetch_misses
        metrics.gauge(
            "tier.prefetch_hit",
            (self.prefetch_hits / looked) if looked else 1.0,
        )
        metrics.gauge("tier.thrash", self.thrash_rate())

    def unpublish(self) -> None:
        """Retract this tier's gauges from the process-global registry
        (device free / detach): gauges otherwise outlive the plane and
        a stale ``tier.cap_bytes``/``tier.thrash`` keeps reading as a
        live signal to alert rules and dashboards. A later re-admission
        republishes on the next ``_publish()``."""
        for g in (
            "tier.hot_bytes",
            "tier.pool_bytes",
            "tier.cap_bytes",
            "tier.headroom_bytes",
            "tier.evictions",
            "tier.prefetch_hit",
            "tier.thrash",
        ):
            metrics.drop_gauge(g)

    def stats(self) -> Dict:
        return {
            "cap_bytes": self.cap,
            "hot_bytes": self.hot_bytes(),
            "pool_bytes": self.pool_bytes(),
            "headroom_bytes": self.headroom_bytes(),
            "partitions": len(self.parts),
            "evictions": self.evictions,
            "prefetch_hits": self.prefetch_hits,
            "prefetch_misses": self.prefetch_misses,
            "thrash": self.thrash_rate(),
        }


# ---------------------------------------------------------------------------
# paged kernels (trace-safe: read everything through the arrays pytree)
# ---------------------------------------------------------------------------


def paged_hop(arrays, cname: str, d: str, emask, frontier):
    """One frontier bitmap hop over a paged partition: the pool
    flattens to a [P*Wp] edge list whose unused/evicted slots carry
    owner −1 and mask out; an optional [E] emask gathers through the
    per-slot global edge id."""
    keys = _keys(cname, d)
    own = arrays[keys["own"]].reshape(-1)
    nbr = arrays[keys["nbr"]].reshape(-1)
    m = own >= 0
    if emask is not None:
        eid = arrays[keys["eid"]].reshape(-1)
        m = m & K.take_pad(emask, eid, False)
    return K.bitmap_hop(own, nbr, m, frontier)


def paged_hop_miss(arrays, cname: str, d: str, frontier):
    """Device-side cold-miss flag for a frontier hop: any active vertex
    with edges whose block is not resident."""
    keys = _keys(cname, d)
    blockv = arrays[keys["blockv"]]
    pageof = arrays[keys["pageof"]]
    ind_key = f"e:{cname}:indptr_{'out' if d == 'out' else 'in'}"
    indptr = arrays[ind_key]
    V = blockv.shape[0]
    fa = frontier.any(axis=0)[:V]
    deg = indptr[1:] - indptr[:-1]
    act = fa & (deg > 0)
    touched = jnp.zeros(pageof.shape[0], bool).at[blockv].max(act)
    return (touched & (pageof < 0)).any()


def paged_expand(arrays, cname: str, d: str, srcs, offsets, total_dev,
                 out_size: int, Wp: int):
    """CSR gather over a paged partition: row/edge_pos come from the
    resident indptr exactly as the flat path's gather_expand; the
    neighbor (and, reverse, the out-order edge id) read from the pool
    through the block→page indirection. Returns
    ``(row, eid, nbr, cold_miss_flag)`` — cold slots null out and flag,
    so replays off their recorded footprint overflow-re-record."""
    keys = _keys(cname, d)
    ind_key = f"e:{cname}:indptr_{'out' if d == 'out' else 'in'}"
    indptr = arrays[ind_key]
    row, edge_pos, _n = K.gather_expand(
        indptr, jnp.zeros((0,), jnp.int32), srcs, offsets, total_dev, out_size
    )
    blockv = arrays[keys["blockv"]]
    pageof = arrays[keys["pageof"]]
    estart = arrays[keys["estart"]]
    V = blockv.shape[0]
    src = K.take_pad(srcs, row, jnp.int32(-1))
    live = row >= 0
    b = jnp.take(blockv, jnp.clip(src, 0, max(V - 1, 0)))
    p = jnp.take(pageof, b)
    local = edge_pos - jnp.take(estart, b)
    flat = jnp.clip(p, 0) * Wp + jnp.clip(local, 0, Wp - 1)
    nbr = jnp.take(arrays[keys["nbr"]].reshape(-1), flat)
    if d == "out":
        eid = edge_pos
    else:
        eid = jnp.take(arrays[keys["eid"]].reshape(-1), flat)
    cold = live & (p < 0)
    ok = live & ~cold
    row = jnp.where(ok, row, -1)
    eid = jnp.where(ok, eid, -1)
    nbr = jnp.where(ok, nbr, -1)
    return row, eid, nbr, cold.any()


# ---------------------------------------------------------------------------
# admission
# ---------------------------------------------------------------------------


def maybe_tier_snapshot(snap) -> Optional[TierManager]:
    """Snapshot admission: when ``tier_hbm_cap_bytes`` is set and the
    snapshot's adjacency exceeds it, attach a TierManager so the device
    build pages adjacency instead of uploading it flat. Under-cap
    snapshots stay fully resident. Tiered + mesh and tiered + delta
    overlay refuse loudly — both planes assume flat resident
    adjacency."""
    cap = int(config.tier_hbm_cap_bytes)
    if cap <= 0:
        return None
    existing = getattr(snap, "_tier", None)
    if existing is not None:
        return existing
    if adjacency_bytes(snap) <= cap:
        return None
    from orientdb_tpu.obs.memledger import memledger

    if getattr(snap, "_mesh", None) is not None:
        # refusals used to raise loudly and vanish: count them and
        # keep the last reason visible in GET /debug/memory
        memledger.note_refusal(
            "mesh", "adjacency exceeds the cap but a mesh is attached"
        )
        raise ValueError(
            "tiered snapshots are single-device: adjacency exceeds "
            "tier_hbm_cap_bytes but a mesh is attached — raise the cap, "
            "drop the mesh, or shard the graph instead"
        )
    if getattr(snap, "_overlay", None) is not None:
        memledger.note_refusal(
            "overlay",
            "adjacency exceeds the cap with a delta overlay armed",
        )
        raise ValueError(
            "delta-maintained snapshots cannot tier: adjacency exceeds "
            "tier_hbm_cap_bytes with a delta overlay armed — compact to "
            "a clean snapshot before tiering"
        )
    tier = snap._tier = TierManager(snap, cap)
    metrics.incr("tier.admissions")
    return tier
