from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import Schema, SchemaClass, Property, PropertyType
from orientdb_tpu.models.record import Document, Vertex, Edge, Direction
from orientdb_tpu.models.database import Database

__all__ = [
    "RID",
    "Schema",
    "SchemaClass",
    "Property",
    "PropertyType",
    "Document",
    "Vertex",
    "Edge",
    "Direction",
    "Database",
]
