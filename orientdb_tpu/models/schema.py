"""Schema: classes, properties, inheritance, cluster mapping.

Analog of OrientDB's schema layer ([E] core/.../metadata/schema/ —
OSchemaShared, OClassImpl, OPropertyImpl; SURVEY.md §2 "Schema/metadata"):

- classes form a single-inheritance-plus-interfaces hierarchy; here we keep
  multiple-superclass support the way OrientDB 3.x does (a class may have
  several superclasses);
- the roots ``V`` and ``E`` make a class a vertex or edge class;
- each class owns one or more *clusters* (record buckets); polymorphic reads
  on a class scan its clusters plus all subclasses' clusters;
- properties carry a type and optional constraints (mandatory, notNull,
  min/max, readOnly) and may be indexed.

The TPU snapshot builder uses the schema to decide which columnar property
arrays to materialize per class.
"""

from __future__ import annotations

import enum
from typing import Dict, Iterable, List, Optional, Sequence, Set

from orientdb_tpu.models.rid import RID


class PropertyType(enum.Enum):
    """Subset of OrientDB's OType ([E] core/.../metadata/schema/OType.java)."""

    BOOLEAN = "BOOLEAN"
    INTEGER = "INTEGER"
    LONG = "LONG"
    FLOAT = "FLOAT"
    DOUBLE = "DOUBLE"
    STRING = "STRING"
    DATETIME = "DATETIME"
    EMBEDDED = "EMBEDDED"
    EMBEDDEDLIST = "EMBEDDEDLIST"
    EMBEDDEDMAP = "EMBEDDEDMAP"
    LINK = "LINK"
    LINKLIST = "LINKLIST"
    LINKBAG = "LINKBAG"
    BINARY = "BINARY"
    ANY = "ANY"

    @classmethod
    def infer(cls, value) -> "PropertyType":
        if isinstance(value, bool):
            return cls.BOOLEAN
        if isinstance(value, int):
            return cls.LONG
        if isinstance(value, float):
            return cls.DOUBLE
        if isinstance(value, str):
            return cls.STRING
        if isinstance(value, RID):
            return cls.LINK
        if isinstance(value, dict):
            return cls.EMBEDDEDMAP
        if isinstance(value, (list, tuple)):
            return cls.EMBEDDEDLIST
        if isinstance(value, bytes):
            return cls.BINARY
        return cls.ANY


class Property:
    """A schema property ([E] OPropertyImpl)."""

    def __init__(
        self,
        name: str,
        ptype: PropertyType,
        mandatory: bool = False,
        not_null: bool = False,
        read_only: bool = False,
        min_value=None,
        max_value=None,
        linked_class: Optional[str] = None,
    ) -> None:
        self.name = name
        self.type = ptype
        self.mandatory = mandatory
        self.not_null = not_null
        self.read_only = read_only
        self.min_value = min_value
        self.max_value = max_value
        self.linked_class = linked_class

    def validate(self, value) -> None:
        if value is None:
            if self.not_null or self.mandatory:
                raise ValueError(f"property '{self.name}' cannot be null")
            return
        if self.min_value is not None and value < self.min_value:
            raise ValueError(f"property '{self.name}' below min {self.min_value}")
        if self.max_value is not None and value > self.max_value:
            raise ValueError(f"property '{self.name}' above max {self.max_value}")

    def __repr__(self) -> str:
        return f"Property({self.name}:{self.type.value})"


class SchemaClass:
    """A schema class ([E] OClassImpl). Created through :class:`Schema`."""

    def __init__(self, schema: "Schema", name: str, cluster_ids: Sequence[int]) -> None:
        self._schema = schema
        self.name = name
        self.cluster_ids: List[int] = list(cluster_ids)
        self.superclass_names: List[str] = []
        self.properties: Dict[str, Property] = {}
        self.abstract = False
        # strict_mode: reject fields not declared in the schema
        # (OrientDB schema-full mode; default is schema-hybrid).
        self.strict_mode = False

    # -- hierarchy ---------------------------------------------------------

    @property
    def superclasses(self) -> List["SchemaClass"]:
        return [self._schema.get_class(n) for n in self.superclass_names]

    def add_superclass(self, name: str) -> None:
        sup = self._schema.get_class(name)
        if sup is None:
            raise ValueError(f"superclass '{name}' does not exist")
        if self.name in sup.all_superclass_names() | {sup.name}:
            raise ValueError(f"inheritance cycle: {self.name} <-> {name}")
        if name not in self.superclass_names:
            self.superclass_names.append(name)

    def all_superclass_names(self) -> Set[str]:
        out: Set[str] = set()
        stack = list(self.superclass_names)
        while stack:
            n = stack.pop()
            if n in out:
                continue
            out.add(n)
            sup = self._schema.get_class(n)
            if sup is not None:
                stack.extend(sup.superclass_names)
        return out

    def is_subclass_of(self, name: str) -> bool:
        return name == self.name or name in self.all_superclass_names()

    def subclasses(self, include_self: bool = True) -> List["SchemaClass"]:
        """All classes at or below this one (polymorphic scan set)."""
        out = []
        for c in self._schema.classes():
            if c.is_subclass_of(self.name) and (include_self or c.name != self.name):
                out.append(c)
        return out

    @property
    def is_vertex_type(self) -> bool:
        return self.is_subclass_of("V")

    @property
    def is_edge_type(self) -> bool:
        return self.is_subclass_of("E")

    # -- properties --------------------------------------------------------

    def create_property(self, name: str, ptype: PropertyType, **kw) -> Property:
        if name in self.properties:
            raise ValueError(f"property '{name}' already exists on {self.name}")
        prop = Property(name, ptype, **kw)
        self.properties[name] = prop
        if self._schema.on_ddl is not None:
            self._schema.on_ddl(
                {
                    "op": "create_property",
                    "class": self.name,
                    "name": name,
                    "ptype": ptype.value,
                    "kw": {
                        "mandatory": prop.mandatory,
                        "not_null": prop.not_null,
                        "read_only": prop.read_only,
                        "min_value": prop.min_value,
                        "max_value": prop.max_value,
                        "linked_class": prop.linked_class,
                    },
                }
            )
        return prop

    def get_property(self, name: str) -> Optional[Property]:
        """Property lookup, walking superclasses."""
        if name in self.properties:
            return self.properties[name]
        for sup in self.superclasses:
            p = sup.get_property(name)
            if p is not None:
                return p
        return None

    def effective_properties(self) -> Dict[str, Property]:
        """All properties including inherited (nearest definition wins)."""
        out: Dict[str, Property] = {}
        for sup in reversed(self.superclasses):
            out.update(sup.effective_properties())
        out.update(self.properties)
        return out

    def validate(self, fields: Dict[str, object]) -> None:
        props = self.effective_properties()
        for pname, prop in props.items():
            if prop.mandatory and pname not in fields:
                raise ValueError(f"mandatory property '{pname}' missing on {self.name}")
            if pname in fields:
                prop.validate(fields[pname])
        if self.strict_mode:
            for fname in fields:
                if fname not in props and not fname.startswith("@"):
                    raise ValueError(
                        f"field '{fname}' not declared in strict class {self.name}"
                    )

    def __repr__(self) -> str:
        sup = f" extends {','.join(self.superclass_names)}" if self.superclass_names else ""
        return f"SchemaClass({self.name}{sup})"


class Schema:
    """Class registry + cluster-id allocation ([E] OSchemaShared).

    Cluster ids are allocated sequentially; cluster 0 is reserved for
    internal metadata (OrientDB reserves low clusters for internal records).
    """

    FIRST_USER_CLUSTER = 1

    def __init__(self) -> None:
        self._classes: Dict[str, SchemaClass] = {}
        self._next_cluster = Schema.FIRST_USER_CLUSTER
        self._cluster_to_class: Dict[int, str] = {}
        # DDL observer (the WAL hooks in here when durability is armed —
        # orientdb_tpu.storage.durability). None while bootstrapping.
        self.on_ddl = None
        # Bootstrap the graph roots, like OrientDB's default V / E classes.
        self.create_class("V")
        self.create_class("E")

    # -- classes -----------------------------------------------------------

    def create_class(
        self,
        name: str,
        superclasses: Iterable[str] = (),
        abstract: bool = False,
        clusters: int = 1,
    ) -> SchemaClass:
        if self.get_class(name) is not None:
            raise ValueError(f"class '{name}' already exists")
        # Validate superclasses and wire them BEFORE registering, so a bad
        # superclass never leaves a half-registered class behind.
        cls = SchemaClass(self, name, [])
        cls.abstract = abstract
        for sup in superclasses:
            cls.add_superclass(sup)
        ids = [] if abstract else [self._allocate_cluster() for _ in range(clusters)]
        cls.cluster_ids = list(ids)
        self._classes[name.lower()] = cls
        for cid in ids:
            self._cluster_to_class[cid] = name
        if self.on_ddl is not None:
            self.on_ddl(
                {
                    "op": "create_class",
                    "name": cls.name,
                    "superclasses": list(cls.superclass_names),
                    "abstract": abstract,
                    "clusters": clusters,
                }
            )
        return cls

    def create_vertex_class(self, name: str, **kw) -> SchemaClass:
        return self.create_class(name, superclasses=("V",), **kw)

    def create_edge_class(self, name: str, **kw) -> SchemaClass:
        return self.create_class(name, superclasses=("E",), **kw)

    def alter_class(self, name: str, attribute: str, value) -> SchemaClass:
        """[E] OAlterClassStatement attribute mutation: SUPERCLASS
        (+Name/-Name), STRICTMODE, ABSTRACT. Emits one replicable DDL
        op; rename has its own entry point (:meth:`rename_class`)."""
        cls = self.get_class_or_raise(name)
        attr = attribute.upper()
        if attr == "SUPERCLASS":
            sign, sup = value
            if sign == "+":
                cls.add_superclass(sup)
            else:
                cls.superclass_names = [
                    s
                    for s in cls.superclass_names
                    if s.lower() != sup.lower()
                ]
        elif attr == "STRICTMODE":
            cls.strict_mode = bool(value)
        elif attr == "ABSTRACT":
            cls.abstract = bool(value)
            if not cls.abstract and not cls.cluster_ids:
                cid = self._allocate_cluster()
                cls.cluster_ids.append(cid)
                self._cluster_to_class[cid] = cls.name
        else:
            raise ValueError(f"unsupported ALTER CLASS attribute {attr!r}")
        if self.on_ddl is not None:
            self.on_ddl(
                {
                    "op": "alter_class",
                    "name": cls.name,
                    "attribute": attr,
                    "value": list(value)
                    if isinstance(value, tuple)
                    else value,
                }
            )
        return cls

    def rename_class(self, old: str, new: str) -> SchemaClass:
        """Rename a class, rewiring cluster→class mapping and every
        subclass's superclass reference. Record/index rewrites are the
        Database's job (Database.rename_class drives both)."""
        cls = self.get_class_or_raise(old)
        if self.get_class(new) is not None:
            raise ValueError(f"class '{new}' already exists")
        old_name = cls.name
        del self._classes[old_name.lower()]
        cls.name = new
        self._classes[new.lower()] = cls
        for cid in cls.cluster_ids:
            self._cluster_to_class[cid] = new
        for c in self._classes.values():
            if any(s.lower() == old_name.lower() for s in c.superclass_names):
                c.superclass_names = [
                    new if s.lower() == old_name.lower() else s
                    for s in c.superclass_names
                ]
        if self.on_ddl is not None:
            self.on_ddl(
                {"op": "rename_class", "old": old_name, "new": new}
            )
        return cls

    def get_class(self, name: str) -> Optional[SchemaClass]:
        return self._classes.get(name.lower())

    def get_class_or_raise(self, name: str) -> SchemaClass:
        c = self.get_class(name)
        if c is None:
            raise ValueError(f"class '{name}' not found in schema")
        return c

    def drop_class(self, name: str) -> None:
        cls = self.get_class_or_raise(name)
        for c in self.classes():
            if name in c.superclass_names:
                raise ValueError(f"class '{name}' has subclass '{c.name}'")
        for cid in cls.cluster_ids:
            self._cluster_to_class.pop(cid, None)
        del self._classes[name.lower()]
        if self.on_ddl is not None:
            self.on_ddl({"op": "drop_class", "name": cls.name})

    def exists_class(self, name: str) -> bool:
        return self.get_class(name) is not None

    def classes(self) -> List[SchemaClass]:
        return list(self._classes.values())

    # -- clusters ----------------------------------------------------------

    def _allocate_cluster(self) -> int:
        cid = self._next_cluster
        self._next_cluster += 1
        return cid

    def add_cluster(self, class_name: str) -> int:
        cls = self.get_class_or_raise(class_name)
        cid = self._allocate_cluster()
        cls.cluster_ids.append(cid)
        self._cluster_to_class[cid] = cls.name
        if self.on_ddl is not None:
            self.on_ddl({"op": "add_cluster", "class": cls.name})
        return cid

    def class_of_cluster(self, cluster_id: int) -> Optional[SchemaClass]:
        name = self._cluster_to_class.get(cluster_id)
        return self.get_class(name) if name else None

    def polymorphic_cluster_ids(self, class_name: str) -> List[int]:
        """Cluster ids of the class and all its subclasses (scan set)."""
        cls = self.get_class_or_raise(class_name)
        out: List[int] = []
        for sub in cls.subclasses(include_self=True):
            out.extend(sub.cluster_ids)
        return sorted(out)
