"""Record identity.

Analog of OrientDB's ``ORecordId`` ([E] core/.../id/ORecordId.java): every
record is addressed ``#<clusterId>:<clusterPosition>``. Cluster ids map to
schema classes through the schema (SURVEY.md §2 "Clusters & RIDs").

In the TPU snapshot layer, RIDs are remapped to dense per-class vertex
indices (the §3.5 RID-remapping-table concept); this class is the host-side
identity only.
"""

from __future__ import annotations

from typing import NamedTuple


class RID(NamedTuple):
    cluster: int
    position: int

    def __str__(self) -> str:
        return f"#{self.cluster}:{self.position}"

    def __repr__(self) -> str:
        return f"RID({self.cluster}, {self.position})"

    @property
    def is_persistent(self) -> bool:
        return self.cluster >= 0 and self.position >= 0

    @classmethod
    def parse(cls, text: str) -> "RID":
        t = text.strip()
        if not t.startswith("#"):
            raise ValueError(f"not a RID: {text!r}")
        c, _, p = t[1:].partition(":")
        return cls(int(c), int(p))


#: Placeholder RID for new, not-yet-saved records (OrientDB uses #-1:-1 style
#: temporary RIDs inside transactions).
NEW_RID = RID(-1, -1)
