"""Lucene-grade fulltext: analyzers, BM25 scoring, phrase/boolean queries.

Analog of the reference's Lucene index engine ([E] lucene/
``OLuceneFullTextIndex`` + ``OLuceneIndexEngine``; SURVEY.md §2 "Lucene":
"analyzers, scoring, phrase/boolean query syntax" are the gap the plain
token inverted index leaves). Redesign, not an embedded Lucene:

- **Analyzers** — pluggable token pipelines. ``standard`` lowercases,
  splits on non-alphanumerics, and drops English stopwords;
  ``simple`` keeps stopwords (the legacy FullTextIndex behavior);
  ``keyword`` indexes the whole value as one token; ``english`` adds a
  light suffix stemmer (ies/es/s, ing, ed) over ``standard``.
- **Positional postings** — token → {rid → positions}, enabling phrase
  queries with slop.
- **Query language** — Lucene-style:
  ``term``, ``ter*`` (prefix), ``"exact phrase"``, ``"phrase"~2``
  (slop), ``+required``, ``-prohibited``, ``a AND b``, ``a OR b``,
  ``NOT a``, parentheses. Bare juxtaposition is OR, as in Lucene's
  default operator.
- **BM25 ranking** — k1=1.2, b=0.75 over the boolean match set, the
  scoring Lucene 8+ defaults to.

`LuceneFullTextIndex` plugs into the IndexManager as index type
``FULLTEXT`` with ``engine="lucene"`` metadata (created via
``create_index(..., "FULLTEXT", analyzer=...)`` path in
models/indexes.py) and is queried through ``search``/``search_all``
(legacy OR/AND surface), :meth:`match` (boolean query → RID set) and
:meth:`ranked` (scored, sorted). SQL surface: the ``search_index()``
function in exec/eval.py.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Set, Tuple

from orientdb_tpu.models.rid import RID

# the classic Lucene/Snowball English stopword list (public domain)
ENGLISH_STOPWORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or "
    "such that the their then there these they this to was will with".split()
)


def _alnum_tokens(text: str) -> List[str]:
    out, cur = [], []
    for ch in text.lower():
        if ch.isalnum():
            cur.append(ch)
        elif cur:
            out.append("".join(cur))
            cur = []
    if cur:
        out.append("".join(cur))
    return out


def _light_stem(tok: str) -> str:
    """Small suffix stripper (a Porter step-1 subset): plural and
    -ing/-ed endings, guarded so short tokens survive intact."""
    if len(tok) > 4 and tok.endswith("ies"):
        return tok[:-3] + "y"
    if len(tok) > 3 and tok.endswith("es") and not tok.endswith("ses"):
        return tok[:-1]  # caches → cache (keep the e)
    if len(tok) > 3 and tok.endswith("s") and not tok.endswith("ss"):
        return tok[:-1]
    if len(tok) > 5 and tok.endswith("ing"):
        return tok[:-3]
    if len(tok) > 4 and tok.endswith("ed"):
        return tok[:-2]
    return tok


class Analyzer:
    """Token pipeline: text → position-carrying token list."""

    name = "base"

    def tokens(self, text) -> List[str]:
        raise NotImplementedError


class SimpleAnalyzer(Analyzer):
    name = "simple"

    def tokens(self, text) -> List[str]:
        return [] if text is None else _alnum_tokens(str(text))


class StandardAnalyzer(Analyzer):
    name = "standard"

    def __init__(self, stopwords=ENGLISH_STOPWORDS) -> None:
        self.stopwords = stopwords

    def tokens(self, text) -> List[str]:
        if text is None:
            return []
        # stopwords are REPLACED by '' placeholders, not removed: phrase
        # positions must keep their gaps ("out of memory" with 'of'
        # stopped still matches slop-0 via position arithmetic)
        return [
            t if t not in self.stopwords else ""
            for t in _alnum_tokens(str(text))
        ]


class EnglishAnalyzer(StandardAnalyzer):
    name = "english"

    def tokens(self, text) -> List[str]:
        return [
            _light_stem(t) if t else ""
            for t in super().tokens(text)
        ]


class KeywordAnalyzer(Analyzer):
    name = "keyword"

    def tokens(self, text) -> List[str]:
        return [] if text is None else [str(text)]


ANALYZERS = {
    "simple": SimpleAnalyzer,
    "standard": StandardAnalyzer,
    "english": EnglishAnalyzer,
    "keyword": KeywordAnalyzer,
}


def get_analyzer(name: Optional[str]) -> Analyzer:
    cls = ANALYZERS.get((name or "standard").lower())
    if cls is None:
        raise ValueError(
            f"unknown analyzer {name!r}; expected one of {sorted(ANALYZERS)}"
        )
    return cls()


# ---------------------------------------------------------------------------
# query language
# ---------------------------------------------------------------------------


class QueryNode:
    pass


class TermQ(QueryNode):
    def __init__(self, text: str, prefix: bool = False) -> None:
        self.text = text
        self.prefix = prefix


class PhraseQ(QueryNode):
    def __init__(self, text: str, slop: int = 0) -> None:
        self.text = text
        self.slop = slop


class BoolQ(QueryNode):
    """must / should / must_not, Lucene-style."""

    def __init__(self, must, should, must_not) -> None:
        self.must = must
        self.should = should
        self.must_not = must_not


class _QueryParser:
    """Recursive descent over the Lucene-style grammar:

    or     := and (OR and)*
    and    := unary (AND unary)*
    bool   := unary*            # bare juxtaposition = OR (Lucene default)
    unary  := [+|-|NOT] atom
    atom   := '(' or ')' | '"'...'"'[~N] | term['*']
    """

    def __init__(self, q: str) -> None:
        self.toks = self._lex(q)
        self.i = 0

    @staticmethod
    def _lex(q: str) -> List[Tuple[str, str]]:
        out: List[Tuple[str, str]] = []
        i, n = 0, len(q)
        while i < n:
            c = q[i]
            if c.isspace():
                i += 1
            elif c in "()+-":
                out.append((c, c))
                i += 1
            elif c == '"':
                j = q.find('"', i + 1)
                if j < 0:
                    raise ValueError(f"unterminated phrase in query: {q!r}")
                phrase = q[i + 1 : j]
                i = j + 1
                slop = 0
                if i < n and q[i] == "~":
                    i += 1
                    k = i
                    while k < n and q[k].isdigit():
                        k += 1
                    slop = int(q[i:k] or 0)
                    i = k
                out.append(("phrase", phrase + ("\x00%d" % slop)))
            else:
                k = i
                while k < n and not q[k].isspace() and q[k] not in '()+-"':
                    k += 1
                word = q[i:k]
                i = k
                up = word.upper()
                if up in ("AND", "OR", "NOT"):
                    out.append((up, word))
                else:
                    out.append(("term", word))
        return out

    def _peek(self):
        return self.toks[self.i] if self.i < len(self.toks) else (None, None)

    def _next(self):
        t = self._peek()
        self.i += 1
        return t

    def parse(self) -> QueryNode:
        node = self._or()
        if self.i < len(self.toks):
            raise ValueError(f"trailing tokens in query at {self.toks[self.i]}")
        return node

    def _or(self) -> QueryNode:
        terms = [self._and()]
        while self._peek()[0] == "OR":
            self._next()
            terms.append(self._and())
        if len(terms) == 1:
            return terms[0]
        return BoolQ([], terms, [])

    def _and(self) -> QueryNode:
        groups = [self._juxta()]
        while self._peek()[0] == "AND":
            self._next()
            groups.append(self._juxta())
        if len(groups) == 1:
            return groups[0]
        return BoolQ(groups, [], [])

    def _juxta(self) -> QueryNode:
        """Adjacent clauses: +must / -must_not / bare should (OR)."""
        must, should, must_not = [], [], []
        while True:
            kind, _ = self._peek()
            if kind in (None, ")", "AND", "OR"):
                break
            if kind == "+":
                self._next()
                must.append(self._atom())
            elif kind in ("-", "NOT"):
                self._next()
                must_not.append(self._atom())
            else:
                should.append(self._atom())
        if not (must or should or must_not):
            raise ValueError("empty query clause")
        if len(should) == 1 and not must and not must_not:
            return should[0]
        return BoolQ(must, should, must_not)

    def _atom(self) -> QueryNode:
        kind, val = self._next()
        if kind == "(":
            node = self._or()
            if self._next()[0] != ")":
                raise ValueError("unbalanced parenthesis in query")
            return node
        if kind == "phrase":
            text, slop = val.rsplit("\x00", 1)
            return PhraseQ(text, int(slop))
        if kind == "term":
            if val.endswith("*") and len(val) > 1:
                return TermQ(val[:-1], prefix=True)
            return TermQ(val)
        raise ValueError(f"unexpected token {val!r} in query")


def parse_query(q: str) -> QueryNode:
    return _QueryParser(q).parse()


# ---------------------------------------------------------------------------
# the index
# ---------------------------------------------------------------------------


class LuceneFullTextIndex:
    """Positional inverted index with BM25 ranking and boolean/phrase
    retrieval. Registered by the IndexManager under type FULLTEXT when
    an ``analyzer`` is requested (legacy token index otherwise)."""

    BM25_K1 = 1.2
    BM25_B = 0.75

    def __init__(self, name, class_name, fields, analyzer="standard"):
        self.name = name
        self.class_name = class_name
        self.fields = list(fields)
        self.type = "FULLTEXT"
        self.analyzer_name = (analyzer or "standard").lower()
        self.analyzer = get_analyzer(analyzer)
        #: token → {rid → (positions,)}
        self._post: Dict[str, Dict[RID, Tuple[int, ...]]] = {}
        #: rid → (doc token length, indexed tokens)
        self._docs: Dict[RID, Tuple[int, frozenset]] = {}
        self._total_len = 0
        #: sorted token list cache for prefix queries (rebuilt lazily)
        self._sorted: Optional[List[str]] = None

    # -- IndexManager SPI ---------------------------------------------------

    def clear(self) -> None:
        """Drop every posting (REBUILD INDEX re-populates from a scan)."""
        self._post = {}
        self._docs = {}
        self._total_len = 0
        self._sorted = None

    @property
    def unique(self) -> bool:
        return False

    @property
    def range_capable(self) -> bool:
        return False

    def index_doc(self, doc) -> None:
        positions: Dict[str, List[int]] = {}
        pos = 0
        for f in self.fields:
            toks = self.analyzer.tokens(doc.get(f))
            for t in toks:
                if t:
                    positions.setdefault(t, []).append(pos)
                pos += 1
            pos += 8  # field gap: phrases never straddle two fields
        for t, ps in positions.items():
            self._post.setdefault(t, {})[doc.rid] = tuple(ps)
        if positions:
            self._docs[doc.rid] = (pos, frozenset(positions))
            self._total_len += pos
            self._sorted = None
        self.__dict__.pop("_search_memo", None)  # eval.py per-query memo

    def unindex_doc(self, rid: RID) -> None:
        self.__dict__.pop("_search_memo", None)  # eval.py per-query memo
        entry = self._docs.pop(rid, None)
        if entry is None:
            return
        length, toks = entry
        self._total_len -= length
        for t in toks:
            bucket = self._post.get(t)
            if bucket is not None:
                bucket.pop(rid, None)
                if not bucket:
                    del self._post[t]
                    self._sorted = None

    def get(self, key) -> Set[RID]:
        """Token lookup (the `FROM index:Name WHERE key=` surface)."""
        toks = [t for t in self.analyzer.tokens(key) if t]
        out: Set[RID] = set()
        for t in toks:
            out |= set(self._post.get(t, ()))
        return out

    def keys(self) -> List[str]:
        return list(self._post)

    def size(self) -> int:
        return len(self._docs)

    def __repr__(self) -> str:
        return (
            f"LuceneFullTextIndex({self.name} on {self.class_name}"
            f"{self.fields} analyzer={self.analyzer_name})"
        )

    # -- retrieval ----------------------------------------------------------

    def _term_set(self, node: TermQ) -> Set[RID]:
        toks = [t for t in self.analyzer.tokens(node.text) if t]
        if not toks:
            return set()
        if node.prefix:
            if self._sorted is None:
                self._sorted = sorted(self._post)
            import bisect

            pre = toks[0]
            lo = bisect.bisect_left(self._sorted, pre)
            out: Set[RID] = set()
            for t in self._sorted[lo:]:
                if not t.startswith(pre):
                    break
                out |= set(self._post[t])
            return out
        if len(toks) == 1:
            return set(self._post.get(toks[0], ()))
        # a multi-token "term" (analyzer split it): implicit phrase
        return self._phrase_set(PhraseQ(node.text, 0))

    def _phrase_set(self, node: PhraseQ) -> Set[RID]:
        toks = self.analyzer.tokens(node.text)
        # keep placeholder gaps: positions must line up across stopwords
        live = [(i, t) for i, t in enumerate(toks) if t]
        if not live:
            return set()
        base = set(self._post.get(live[0][1], ()))
        for _i, t in live[1:]:
            base &= set(self._post.get(t, ()))
        span = len(toks) - 1
        out = set()
        for rid in base:
            plists = [
                (off, self._post[t][rid]) for off, t in live
            ]
            off0, first = plists[0]
            ok = False
            for p in first:
                start = p - off0
                # every token within start+offset ± slop, in order
                if self._phrase_at(plists, start, node.slop, span):
                    ok = True
                    break
            if ok:
                out.add(rid)
        return out

    @staticmethod
    def _phrase_at(plists, start: int, slop: int, span: int) -> bool:
        """Exact (slop=0): token i at start+off_i. With slop, each token
        may shift up to `slop` positions right of its slot (the common
        ordered-window interpretation)."""
        for off, ps in plists:
            want = start + off
            if not any(want <= p <= want + slop for p in ps):
                return False
        return True

    def match(self, query) -> Set[RID]:
        """RIDs matching a Lucene-style boolean/phrase query string."""
        node = query if isinstance(query, QueryNode) else parse_query(query)
        return self._eval(node)

    def _universe(self) -> Set[RID]:
        return set(self._docs)

    def _eval(self, node: QueryNode) -> Set[RID]:
        if isinstance(node, TermQ):
            return self._term_set(node)
        if isinstance(node, PhraseQ):
            return self._phrase_set(node)
        assert isinstance(node, BoolQ)
        out: Optional[Set[RID]] = None
        for m in node.must:
            s = self._eval(m)
            out = s if out is None else (out & s)
        if node.should:
            s_or: Set[RID] = set()
            for s in node.should:
                s_or |= self._eval(s)
            # Lucene: should-clauses are optional when must exists
            out = s_or if out is None else out
        if out is None:
            out = self._universe() if node.must_not else set()
        for m in node.must_not:
            out -= self._eval(m)
        return out

    # -- scoring ------------------------------------------------------------

    def _query_terms(self, node: QueryNode) -> List[str]:
        if isinstance(node, TermQ):
            return [t for t in self.analyzer.tokens(node.text) if t]
        if isinstance(node, PhraseQ):
            return [t for t in self.analyzer.tokens(node.text) if t]
        terms: List[str] = []
        for part in node.must + node.should:
            terms.extend(self._query_terms(part))
        return terms

    def bm25(self, rid: RID, terms: Sequence[str]) -> float:
        N = len(self._docs) or 1
        avgdl = (self._total_len / N) if N else 1.0
        entry = self._docs.get(rid)
        if entry is None:
            return 0.0
        dl = entry[0]
        score = 0.0
        for t in terms:
            bucket = self._post.get(t)
            if not bucket:
                continue
            tf = len(bucket.get(rid, ()))
            if not tf:
                continue
            df = len(bucket)
            idf = math.log(1.0 + (N - df + 0.5) / (df + 0.5))
            denom = tf + self.BM25_K1 * (
                1 - self.BM25_B + self.BM25_B * dl / avgdl
            )
            score += idf * tf * (self.BM25_K1 + 1) / denom
        return score

    def ranked(self, query, limit: Optional[int] = None):
        """[(rid, score)] for the boolean match set, BM25-descending
        (ties by RID for determinism)."""
        node = query if isinstance(query, QueryNode) else parse_query(query)
        terms = self._query_terms(node)
        hits = [(rid, self.bm25(rid, terms)) for rid in self._eval(node)]
        hits.sort(key=lambda rs: (-rs[1], str(rs[0])))
        return hits[:limit] if limit is not None else hits

    # -- legacy FullTextIndex surface --------------------------------------

    def search(self, query) -> Set[RID]:
        """RIDs matching ANY query token (legacy OR surface)."""
        out: Set[RID] = set()
        for t in self.analyzer.tokens(query):
            if t:
                out |= set(self._post.get(t, ()))
        return out

    def search_all(self, query) -> Set[RID]:
        """RIDs matching EVERY query token (legacy AND surface)."""
        toks = [t for t in self.analyzer.tokens(query) if t]
        if not toks:
            return set()
        out = set(self._post.get(toks[0], ()))
        for t in toks[1:]:
            out &= set(self._post.get(t, ()))
        return out
