"""Host-side index framework.

Analog of OrientDB's index layer ([E] core/.../index/ — OIndexManagerShared,
OIndexAbstract over OSBTree/OCellBTree/OLocalHashTable durable structures;
SURVEY.md §2 "Indexes"). The reference persists indexes as on-disk B-trees /
extendible hash tables; the host store here is in-RAM, so the honest analogs
are a dict (hash index) and a sorted key list (range-capable "sbtree" index).
The TPU layer builds its *own* columnar sorted-array indexes inside snapshots
(`orientdb_tpu/storage/snapshot.py`) — these host indexes serve the write
path, uniqueness constraints, and the host executor's index-scan steps.
"""

from __future__ import annotations

import bisect
import math
from typing import Dict, Iterator, List, Optional, Set, Tuple, TYPE_CHECKING

from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.record import Document

if TYPE_CHECKING:  # pragma: no cover
    from orientdb_tpu.models.database import Database


class DuplicateKeyError(Exception):
    """[E] ORecordDuplicatedException: unique-index violation."""


class Index:
    """One index over (class, fields).

    types: UNIQUE / NOTUNIQUE (sbtree-style, range-capable) and
    UNIQUE_HASH_INDEX / NOTUNIQUE_HASH_INDEX (point lookups only).
    """

    RANGE_TYPES = {"UNIQUE", "NOTUNIQUE"}
    HASH_TYPES = {"UNIQUE_HASH_INDEX", "NOTUNIQUE_HASH_INDEX"}

    def __init__(self, name: str, class_name: str, fields: List[str], index_type: str):
        index_type = index_type.upper()
        if index_type not in self.RANGE_TYPES | self.HASH_TYPES:
            raise ValueError(f"unsupported index type {index_type}")
        self.name = name
        self.class_name = class_name
        self.fields = list(fields)
        self.type = index_type
        self._map: Dict[object, Set[RID]] = {}
        self._reverse: Dict[RID, object] = {}
        self._sorted_keys: List[object] = []  # maintained for range types

    def clear(self) -> None:
        """Drop every entry (REBUILD INDEX re-populates from a scan);
        subclasses share the same storage attributes."""
        self._map = {}
        self._reverse = {}
        self._sorted_keys = []

    @property
    def unique(self) -> bool:
        return self.type.startswith("UNIQUE")

    @property
    def range_capable(self) -> bool:
        return self.type in self.RANGE_TYPES

    def _key_of(self, doc: Document):
        vals = tuple(doc.get(f) for f in self.fields)
        if any(v is None for v in vals):
            return None  # null keys are not indexed (OrientDB default)
        return vals[0] if len(vals) == 1 else vals

    # manager-facing hooks (FullTextIndex overrides with multi-key puts)

    def index_doc(self, doc: Document) -> None:
        self.put(self._key_of(doc), doc.rid)

    def unindex_doc(self, rid: RID) -> None:
        self.remove(rid)

    # -- mutation ----------------------------------------------------------

    def put(self, key, rid: RID) -> None:
        if key is None:
            return
        bucket = self._map.get(key)
        if bucket is None:
            bucket = self._map[key] = set()
            if self.range_capable:
                bisect.insort(self._sorted_keys, key)
        if self.unique and bucket and rid not in bucket:
            other = next(iter(bucket))
            raise DuplicateKeyError(
                f"index '{self.name}': key {key!r} already mapped to {other}"
            )
        bucket.add(rid)
        self._reverse[rid] = key

    def remove(self, rid: RID) -> None:
        key = self._reverse.pop(rid, None)
        if key is None:
            return
        bucket = self._map.get(key)
        if bucket is not None:
            bucket.discard(rid)
            if not bucket:
                del self._map[key]
                if self.range_capable:
                    i = bisect.bisect_left(self._sorted_keys, key)
                    if i < len(self._sorted_keys) and self._sorted_keys[i] == key:
                        self._sorted_keys.pop(i)

    # -- lookup ------------------------------------------------------------

    def get(self, key) -> Set[RID]:
        return set(self._map.get(key, ()))

    def contains_key(self, key) -> bool:
        return key in self._map

    def range(
        self,
        lo=None,
        hi=None,
        lo_inclusive: bool = True,
        hi_inclusive: bool = True,
    ) -> Iterator[Tuple[object, Set[RID]]]:
        if not self.range_capable:
            raise ValueError(f"index '{self.name}' ({self.type}) is not range-capable")
        keys = self._sorted_keys
        start = 0
        if lo is not None:
            start = (
                bisect.bisect_left(keys, lo)
                if lo_inclusive
                else bisect.bisect_right(keys, lo)
            )
        end = len(keys)
        if hi is not None:
            end = (
                bisect.bisect_right(keys, hi)
                if hi_inclusive
                else bisect.bisect_left(keys, hi)
            )
        for i in range(start, end):
            k = keys[i]
            yield k, set(self._map[k])

    def keys(self) -> List[object]:
        return list(self._sorted_keys) if self.range_capable else list(self._map)

    def size(self) -> int:
        return sum(len(b) for b in self._map.values())

    def __repr__(self) -> str:
        return f"Index({self.name} {self.type} on {self.class_name}{self.fields})"


class FullTextIndex(Index):
    """Token inverted index — the fulltext engine analog ([E] lucene/
    ``OLuceneFullTextIndex``; SURVEY.md §2 "Lucene"): field text is
    lowercased and split on non-alphanumerics, each token maps to the
    posting set of RIDs. Query via :meth:`search` (OR) /
    :meth:`search_all` (AND), ``db.indexes.fulltext_search``, or the SQL
    ``FROM index:Name WHERE key = 'token'`` target form. Spatial — the
    reference's other Lucene engine — is out of scope."""

    def __init__(self, name, class_name, fields):
        # bypass the parent's type whitelist; postings are hash-style
        self.name = name
        self.class_name = class_name
        self.fields = list(fields)
        self.type = "FULLTEXT"
        self._map = {}
        self._reverse = {}
        self._sorted_keys = []

    @property
    def unique(self) -> bool:
        return False

    @property
    def range_capable(self) -> bool:
        return False

    @staticmethod
    def tokenize(text) -> List[str]:
        if text is None:
            return []
        out, cur = [], []
        for ch in str(text).lower():
            if ch.isalnum():
                cur.append(ch)
            elif cur:
                out.append("".join(cur))
                cur = []
        if cur:
            out.append("".join(cur))
        return out

    def index_doc(self, doc: Document) -> None:
        tokens = set()
        for f in self.fields:
            tokens.update(self.tokenize(doc.get(f)))
        for t in tokens:
            self._map.setdefault(t, set()).add(doc.rid)
        if tokens:
            self._reverse[doc.rid] = frozenset(tokens)
        self.__dict__.pop("_search_memo", None)  # eval.py per-query memo

    def unindex_doc(self, rid: RID) -> None:
        self.__dict__.pop("_search_memo", None)  # eval.py per-query memo
        tokens = self._reverse.pop(rid, None)
        if not tokens:
            return
        for t in tokens:
            bucket = self._map.get(t)
            if bucket is not None:
                bucket.discard(rid)
                if not bucket:
                    del self._map[t]

    def search(self, query) -> Set[RID]:
        """RIDs matching ANY query token."""
        out: Set[RID] = set()
        for t in self.tokenize(query):
            out |= self._map.get(t, set())
        return out

    def search_all(self, query) -> Set[RID]:
        """RIDs matching EVERY query token."""
        toks = self.tokenize(query)
        if not toks:
            return set()
        out = set(self._map.get(toks[0], set()))
        for t in toks[1:]:
            out &= self._map.get(t, set())
        return out


class SpatialIndex(Index):
    """Geo point index over a (latitude, longitude) field pair — the
    spatial half of the reference's Lucene module ([E] lucene/
    ``OLuceneSpatialIndex`` over point shapes; SURVEY.md §2 "Lucene").

    Redesign: instead of an embedded Lucene/JTS engine, a 1°×1° hash
    grid — each record hashes to the cell containing its point, and
    :meth:`near` returns the union of every cell a great-circle radius
    can touch (longitude wraps across the antimeridian; a radius
    reaching past a pole widens to all longitudes). The result is a
    SUPERSET of the true matches, which is exactly the contract the
    planner's index pruning needs: rows are still filtered by the full
    WHERE (``distance(lat, lng, :x, :y) < r``), on device when the
    query compiles, so the grid only shrinks the scanned set."""

    CELL = 1.0  # degrees per grid cell
    #: km→degree conversion for the COVERING range: deliberately below
    #: the smallest real degree of latitude (~110.57 km) so the cell
    #: range always overcovers — `near` must stay a superset
    KM_PER_DEG = 110.0

    def __init__(self, name, class_name, fields):
        if len(fields) != 2:
            raise ValueError("SPATIAL index needs exactly (lat, lng) fields")
        self.name = name
        self.class_name = class_name
        self.fields = list(fields)
        self.type = "SPATIAL"
        self._map = {}
        self._reverse = {}
        self._sorted_keys = []

    @property
    def unique(self) -> bool:
        return False

    @property
    def range_capable(self) -> bool:
        return False

    def _cell(self, lat: float, lng: float) -> Tuple[int, int]:
        lat = max(-90.0, min(90.0, float(lat)))
        lng = ((float(lng) + 180.0) % 360.0) - 180.0
        return (
            int(math.floor(lat / self.CELL)),
            int(math.floor(lng / self.CELL)),
        )

    def index_doc(self, doc: Document) -> None:
        lat, lng = doc.get(self.fields[0]), doc.get(self.fields[1])
        if not isinstance(lat, (int, float)) or not isinstance(lng, (int, float)):
            return
        cell = self._cell(lat, lng)
        self._map.setdefault(cell, set()).add(doc.rid)
        self._reverse[doc.rid] = cell

    def unindex_doc(self, rid: RID) -> None:
        cell = self._reverse.pop(rid, None)
        if cell is None:
            return
        bucket = self._map.get(cell)
        if bucket is not None:
            bucket.discard(rid)
            if not bucket:
                del self._map[cell]

    def near(self, lat: float, lng: float, max_km: float) -> Set[RID]:
        """Candidate RIDs within ``max_km`` of the point (superset)."""
        lat = max(-90.0, min(90.0, float(lat)))
        dlat = max_km / self.KM_PER_DEG
        lat_lo, lat_hi = lat - dlat, lat + dlat
        n_lng = int(round(360.0 / self.CELL))
        # the tightest parallel in the band has the largest longitude
        # span; past a pole every longitude is reachable
        if lat_lo <= -90.0 or lat_hi >= 90.0:
            wrap_all = True
        else:
            max_abs = max(abs(lat_lo), abs(lat_hi))
            cosl = math.cos(math.radians(max_abs))
            if cosl <= 1e-9:
                wrap_all = True
            else:
                dlng = max_km / (self.KM_PER_DEG * cosl)
                wrap_all = dlng >= 180.0
        out: Set[RID] = set()
        c_lat_lo = int(math.floor(max(-90.0, lat_lo) / self.CELL))
        c_lat_hi = int(math.floor(min(90.0, lat_hi) / self.CELL))
        if wrap_all:
            for (clat, clng), rids in self._map.items():
                if c_lat_lo <= clat <= c_lat_hi:
                    out |= rids
            return out
        lng0 = ((float(lng) + 180.0) % 360.0) - 180.0
        c_lng_lo = int(math.floor((lng0 - dlng) / self.CELL))
        c_lng_hi = int(math.floor((lng0 + dlng) / self.CELL))
        for clat in range(c_lat_lo, c_lat_hi + 1):
            for clng in range(c_lng_lo, c_lng_hi + 1):
                wrapped = ((clng + n_lng // 2) % n_lng) - n_lng // 2
                bucket = self._map.get((clat, wrapped))
                if bucket:
                    out |= bucket
        return out


class IndexManager:
    """[E] OIndexManagerShared: registry + save/delete hooks."""

    def __init__(self, db: "Database") -> None:
        self._db = db
        self._indexes: Dict[str, Index] = {}

    def create_index(
        self,
        name: str,
        class_name: str,
        fields: List[str],
        index_type: str = "NOTUNIQUE",
        engine: Optional[str] = None,
        metadata: Optional[Dict] = None,
    ) -> Index:
        """``engine="LUCENE"`` (or an ``analyzer`` key in ``metadata``)
        selects the scored positional fulltext engine
        (models/fulltext.LuceneFullTextIndex — analyzers, BM25, boolean/
        phrase queries); plain FULLTEXT keeps the legacy token index."""
        if name.lower() in self._indexes:
            raise ValueError(f"index '{name}' already exists")
        cls = self._db.schema.get_class_or_raise(class_name)
        up = index_type.upper()
        lucene = (engine or "").upper() == "LUCENE" or bool(
            (metadata or {}).get("analyzer")
        )
        if up in ("FULLTEXT", "FULLTEXT_HASH_INDEX") and lucene:
            from orientdb_tpu.models.fulltext import LuceneFullTextIndex

            idx: Index = LuceneFullTextIndex(
                name, cls.name, fields,
                analyzer=(metadata or {}).get("analyzer", "standard"),
            )
        elif up in ("FULLTEXT", "FULLTEXT_HASH_INDEX"):
            idx = FullTextIndex(name, cls.name, fields)
        elif up == "SPATIAL":
            idx = SpatialIndex(name, cls.name, fields)
        else:
            idx = Index(name, cls.name, fields, index_type)
        # Build over existing records (OrientDB rebuilds on creation).
        for doc in self._db.browse_class(cls.name, polymorphic=True):
            idx.index_doc(doc)
        self._indexes[name.lower()] = idx
        entry = {
            "op": "create_index",
            "name": name,
            "class": cls.name,
            "fields": list(fields),
            "type": index_type,
        }
        if engine:
            entry["engine"] = engine
        if metadata:
            entry["metadata"] = dict(metadata)
        self._db._wal_log(entry)
        return idx

    def drop_index(self, name: str) -> None:
        if self._indexes.pop(name.lower(), None) is not None:
            self._db._wal_log({"op": "drop_index", "name": name})

    def get_index(self, name: str) -> Optional[Index]:
        return self._indexes.get(name.lower())

    def all(self) -> List[Index]:
        return list(self._indexes.values())

    def for_class(self, class_name: str) -> List[Index]:
        cls = self._db.schema.get_class(class_name)
        if cls is None:
            return []
        out = []
        for i in self._indexes.values():
            icls = self._db.schema.get_class(i.class_name)
            if cls.is_subclass_of(i.class_name) or (
                icls is not None and icls.is_subclass_of(cls.name)
            ):
                out.append(i)
        return out

    def drop_for_class(self, class_name: str) -> None:
        """Drop every index defined directly on ``class_name`` (class drop)."""
        for name in [
            n for n, i in self._indexes.items() if i.class_name.lower() == class_name.lower()
        ]:
            del self._indexes[name]

    @staticmethod
    def _is_fulltext(idx) -> bool:
        # covers both the legacy token index and the Lucene-grade engine
        return getattr(idx, "type", "").upper() == "FULLTEXT"

    def fulltext_for(self, class_name: str, field: str) -> Optional["FullTextIndex"]:
        """Single-field fulltext index covering ``class_name.field``."""
        cls = self._db.schema.get_class(class_name)
        if cls is None:
            return None
        for idx in self._indexes.values():
            if (
                self._is_fulltext(idx)
                and field in idx.fields
                and cls.is_subclass_of(idx.class_name)
            ):
                return idx
        return None

    def fulltext_search(self, class_name: str, field: str, query: str, mode: str = "any"):
        """Documents matching the query tokens through the fulltext index."""
        idx = self.fulltext_for(class_name, field)
        if idx is None:
            raise ValueError(f"no fulltext index on {class_name}.{field}")
        rids = idx.search_all(query) if mode == "all" else idx.search(query)
        out = []
        for rid in sorted(rids):
            d = self._db.load(rid)
            if d is not None:
                out.append(d)
        return out

    def fulltext_ranked(
        self, index_name: str, query: str, limit: Optional[int] = None
    ):
        """BM25-ranked fulltext search through a Lucene-grade index:
        [(document, score)] best-first ([E] the Lucene engine's scored
        result cursor)."""
        idx = self.get_index(index_name)
        if idx is None or not hasattr(idx, "ranked"):
            raise ValueError(
                f"'{index_name}' is not a Lucene-grade fulltext index"
            )
        out = []
        for rid, score in idx.ranked(query, limit=limit):
            d = self._db.load(rid)
            if d is not None:
                out.append((d, score))
        return out

    def best_for(self, class_name: str, field: str) -> Optional[Index]:
        """Single-field index usable for a lookup on ``class_name.field``."""
        cls = self._db.schema.get_class(class_name)
        if cls is None:
            return None
        for idx in self._indexes.values():
            if self._is_fulltext(idx):
                continue  # token keys — not usable for value lookups
            if idx.fields == [field] and cls.is_subclass_of(idx.class_name):
                return idx
        return None

    # -- hooks wired from Database.save/delete -----------------------------

    def validate_save(self, doc: Document, rid_hint=None, exclude_rids=()) -> None:
        """Raise DuplicateKeyError BEFORE any store/index mutation if saving
        ``doc`` would violate a unique index (two-phase validate-then-apply:
        keeps store and indexes consistent on constraint failure).

        ``exclude_rids``: holders to ignore — records a pending batch
        deletes/rewrites before this doc applies (2PC phase-1 validation
        of a delete-then-recreate batch must not see the doomed holder)."""
        rid = rid_hint if rid_hint is not None else doc.rid
        for idx in self._applicable(doc):
            if not idx.unique:
                continue
            key = idx._key_of(doc)
            if key is None:
                continue
            holders = idx.get(key) - {rid} - set(exclude_rids)
            if holders:
                raise DuplicateKeyError(
                    f"index '{idx.name}': key {key!r} already mapped to "
                    f"{next(iter(holders))}"
                )

    def unique_keys_of(self, doc: Document) -> List[tuple]:
        """The ``(index_name, key)`` pairs ``doc`` would claim in unique
        indexes — lets 2PC phase-1 detect two staged creates in one
        batch fighting over the same key (neither is a holder yet, so
        validate_save alone cannot see the collision)."""
        out = []
        for idx in self._applicable(doc):
            if not idx.unique:
                continue
            key = idx._key_of(doc)
            if key is not None:
                out.append((idx.name, key))
        return out

    def on_save(self, doc: Document) -> None:
        for idx in self._applicable(doc):
            idx.unindex_doc(doc.rid)
            idx.index_doc(doc)

    def on_delete(self, doc: Document) -> None:
        for idx in self._applicable(doc):
            idx.unindex_doc(doc.rid)

    def applicable_for_class(self, class_name: str) -> List[Index]:
        """Indexes that constrain records OF ``class_name`` — those whose
        defining class is at or above it (the save-path rule; contrast
        for_class, which also returns subclass indexes for class drops)."""
        cls = self._db.schema.get_class(class_name)
        if cls is None:
            return []
        return [i for i in self._indexes.values() if cls.is_subclass_of(i.class_name)]

    def _applicable(self, doc: Document) -> List[Index]:
        return self.applicable_for_class(doc.class_name)
