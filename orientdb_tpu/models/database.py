"""The host-side record store.

Plays the role of OrientDB's embedded database + storage layer for the new
framework's host side ([E] core/.../db/document/ODatabaseDocumentEmbedded +
core/.../storage/memory/ODirectMemoryStorage; SURVEY.md §2 "memory storage"):
an in-RAM cluster-based record store behind the same conceptual API, with
MVCC version checks on save (the OTransactionOptimistic commit-time check,
[E] core/.../tx/OTransactionOptimistic.java — SURVEY.md §3.4).

Writes live here on the host; the TPU path is a read-optimized accelerator
over immutable columnar *snapshots* built from this store (north-star
design: MATCH is a read workload, writes stay in the host store).

Durability is provided by the storage layer (``orientdb_tpu.storage``):
an op-level write-ahead log with checkpoint/recovery
(``storage/durability.py`` — armed via ``enable_durability`` /
``open_database``; the pure in-memory engine remains the default), plus
portable JSON export/import (the §3.5 ingest path) and snapshot epochs.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from orientdb_tpu.models.rid import RID, NEW_RID
from orientdb_tpu.models.record import Document, Edge, Vertex, Direction
from orientdb_tpu.models.schema import Schema, PropertyType
from orientdb_tpu.utils.logging import get_logger

log = get_logger("database")


class ConcurrentModificationError(Exception):
    """MVCC conflict ([E] OConcurrentModificationException): the stored record
    version moved past the version the writer read."""


class RecordNotFoundError(Exception):
    pass


class _Cluster:
    """One record bucket ([E] OPaginatedCluster): append-only position list.

    Positions of deleted records hold ``None`` (OrientDB keeps deleted
    positions as tombstones — RIDs are never reused within a cluster).
    """

    __slots__ = ("cluster_id", "records", "cold")

    def __init__(self, cluster_id: int) -> None:
        self.cluster_id = cluster_id
        self.records: List[Optional[Document]] = []
        #: optional capacity tier (storage/coldstore.ColdTier): slots may
        #: then hold ColdRef markers that fault back on access
        self.cold = None

    def append(self, doc: Document) -> int:
        self.records.append(doc)
        return len(self.records) - 1

    def get_slot(self, position: int):
        """Raw slot value: Document, ColdRef marker, or None."""
        if 0 <= position < len(self.records):
            return self.records[position]
        return None

    def get(self, position: int) -> Optional[Document]:
        doc = self.get_slot(position)
        if doc is not None and self.cold is not None and not isinstance(
            doc, Document
        ):
            # point read of an evicted record: fault it back hot
            return self.cold.fault(doc)
        return doc

    def tombstone(self, position: int) -> None:
        if 0 <= position < len(self.records):
            self.records[position] = None

    def __iter__(self) -> Iterator[Document]:
        for doc in self.records:
            if doc is None:
                continue
            if self.cold is not None and not isinstance(doc, Document):
                # scans materialize TRANSIENTLY (no hot-set admission):
                # a full class scan must not thrash the cache — the 2Q
                # scan-resistance property of the reference's page cache
                yield self.cold.materialize(doc)
            else:
                yield doc

    def live_count(self) -> int:
        return sum(1 for d in self.records if d is not None)


class Database:
    """An embedded multi-model database instance.

    API shape follows OrientDB's ``ODatabaseSession``: ``new_vertex`` /
    ``new_edge`` / ``save`` / ``load`` / ``delete`` / ``browse_class`` /
    ``query`` / ``command``. One global lock serializes writes
    (the reference's storage commit is effectively single-writer per
    storage, SURVEY.md §3.4).
    """

    def __init__(self, name: str = "db") -> None:
        self.name = name
        self.schema = Schema()
        self._clusters: Dict[int, _Cluster] = {}
        self._lock = threading.RLock()
        # Monotonic snapshot epoch: bumped on every committed write so the
        # query layer knows when an attached TPU snapshot is stale.
        self.mutation_epoch = 0
        # Attached columnar snapshot (set by orientdb_tpu.storage.snapshot).
        self._snapshot = None
        self._snapshot_epoch = -1
        # Index manager is attached lazily by orientdb_tpu.models.indexes.
        self._indexes = None
        # Sequence/function libraries (models/metadata.py), lazy.
        self._sequences = None
        self._functions = None
        self._scheduler = None
        # Hook manager ([E] ORecordHook registry) attached lazily.
        self._hooks = None
        # Optimistic transactions ([E] OTransactionOptimistic): one active
        # tx per thread; the per-thread suspended flag routes writes
        # directly to the store while THAT thread's commit is applying its
        # buffered ops (other threads' transactions stay routed).
        self._tx_local = threading.local()
        # Round-robin cluster selection per class ([E] cluster selection
        # strategies, SURVEY.md §2 "Clusters & RIDs").
        self._rr_state: Dict[str, int] = {}
        # Write-ahead log (orientdb_tpu.storage.durability). None = the
        # pure in-memory engine; armed via enable_durability/open_database.
        self._wal = None
        self._durability_dir = None
        # Cold-data capacity tier (storage/coldstore.enable_cold_tier):
        # bounds the RAM-resident hot set; None = all records stay hot.
        self._cold_tier = None
        self._on_new_cluster = None
        # Write-ownership forwarding (parallel/forwarding.WriteOwner):
        # set on non-owner cluster members — their writes forward to the
        # owning member instead of diverging locally ([E] the reference's
        # per-cluster server-owner routing). None = this node owns writes.
        self._write_owner = None
        # Per-class ownership overrides ([E] ODistributedConfiguration's
        # per-cluster server-owner lists): class (lower) -> WriteOwner to
        # forward to, or None meaning THIS member owns the class locally
        # even when _write_owner is set — two members then accept local
        # writes for their classes CONCURRENTLY, each replicating its own
        # stream (parallel/cluster.Cluster.assign_class_owner).
        self._class_owners: Dict[str, object] = {}
        # Cross-owner distributed transactions (parallel/twophase): rids
        # locked by an in-flight prepared 2PC batch — every local write
        # path refuses them until the batch commits/aborts/expires.
        self._tx2pc_locks: Dict[RID, str] = {}
        self._tx2pc_registry = None
        # Incremental snapshot maintenance (storage/deltas): when armed,
        # the maintainer applies CDC deltas to the attached snapshot
        # device-side instead of the wholesale detach+re-upload path;
        # current_snapshot(require_fresh=True) catches up through it.
        self._snapshot_maintainer = None
        # Replication apply serialization (parallel/replication): push
        # and pull applies to THIS database take it so a signal-stopped
        # puller's in-flight pull can't race its replacement. A real
        # attribute (not a lazy __dict__.setdefault at the acquire
        # sites) so locklint's static graph and the runtime sanitizer
        # agree on the lock's identity.
        self._repl_lock = threading.Lock()

    # -- WAL ---------------------------------------------------------------

    def _wal_log(self, entry: Dict) -> None:
        """Append a logical op to the WAL. During a transaction commit
        apply (suspended writes) ops buffer and flush as ONE atomic tx
        entry only after the commit succeeds — a compensated commit leaves
        no WAL trace (see exec/tx.py)."""
        w = self._wal
        if w is None or w.replaying:
            return
        if getattr(self._tx_local, "suppress_wal", False):
            # applying a FOREIGN owner's replication stream (multi-owner
            # mode): those entries belong to the other owner's WAL — re-
            # logging them here would interleave streams and double-ship
            return
        if self._tx_suspended:
            buf = getattr(self._tx_local, "wal_buffer", None)
            if buf is not None:
                buf.append(entry)
                return
        lsn = w.append(entry)
        self._mark_ckpt_dirty(entry)
        # changefeed tap BEFORE the quorum push: the entry is committed
        # and durable locally, and the push may block on the network (or
        # raise QuorumError with the entry still in the WAL — in-doubt
        # writes are exactly what at-least-once delivery must carry)
        from orientdb_tpu.cdc.feed import notify_commit

        notify_commit(self, entry, lsn)
        self._quorum_push(entry, lsn)

    def _mark_ckpt_dirty(self, entry: Dict) -> None:
        """Track which records changed since the last (full or delta)
        checkpoint, so `storage.durability.delta_checkpoint` serializes
        O(dirty) records instead of the whole database. Derived from the
        WAL entry itself, so every append site feeds it."""
        dirty = self.__dict__.setdefault("_ckpt_dirty", set())
        stack = [entry]
        while stack:
            e = stack.pop()
            op = e.get("op")
            if op in ("tx", "bulk"):
                stack.extend(e.get("ops", ()))
            elif op in ("create", "update", "delete"):
                dirty.add(e["rid"])

    def _quorum_push(self, entry: Dict, lsn: int) -> None:
        """Synchronous majority replication when this database is a
        quorum-mode primary (parallel/replication.py QuorumPusher): the
        write does not return until a majority of the cluster holds the
        entry. Raises QuorumError with the entry already in the local WAL
        (in-doubt) when the cluster cannot ack.

        Holding db._lock across the majority wait would serialize every
        other writer (and reader paths taking the lock) behind network
        waits — up to quorum_timeout+0.5 s per slow/dead replica. The
        entry is already durably appended and LSN-ordered, and the
        replica side enforces prefix contiguity with push-side backfill
        (replication.apply_pushed_entries / QuorumPusher._push_one), so
        the push is deferred to the write-section exit when this thread
        holds the lock: `save`/`delete`/`new_edge`/tx-commit flush via
        `_flush_quorum()` AFTER releasing it. The writer still blocks
        until majority ack (same QuorumError surface), just without the
        db-wide lock held."""
        q = getattr(self, "_repl_quorum", None)
        if q is None:
            return
        payload = {**entry, "lsn": lsn}
        if getattr(self._tx_local, "defer_quorum", 0) > 0:
            pending = getattr(self._tx_local, "pending_quorum", None)
            if pending is None:
                pending = self._tx_local.pending_quorum = []
            pending.append(payload)
            return
        # outside a deferral section (e.g. DDL through _wal_log): push
        # inline — possibly under db._lock, the pre-deferral behavior
        q.replicate(payload)

    def _quorum_deferral(self):
        """Context manager wrapped around each locked write section
        (save/delete/new_edge/tx-commit): quorum pushes inside it queue
        and flush at the OUTERMOST section exit — after that section has
        released db._lock — via `_flush_quorum`. Counter-based so nested
        sections (save() inside new_edge()) flush once, and so _wal_log
        sites NOT wrapped (DDL) keep pushing inline instead of
        stranding entries on the thread-local queue."""
        import contextlib

        @contextlib.contextmanager
        def section():
            tl = self._tx_local
            tl.defer_quorum = getattr(tl, "defer_quorum", 0) + 1
            try:
                yield
            finally:
                tl.defer_quorum -= 1
                if tl.defer_quorum == 0:
                    self._flush_quorum()

        return section()

    def _flush_quorum(self) -> None:
        """Ship quorum pushes deferred by `_quorum_push` inside a
        deferral section. Raises the first QuorumError after attempting
        every pending entry, so a failed early push cannot silently
        swallow later in-doubt entries."""
        pending = getattr(self._tx_local, "pending_quorum", None)
        if not pending:
            return
        self._tx_local.pending_quorum = []
        q = getattr(self, "_repl_quorum", None)
        if q is None:
            return
        first_err = None
        for payload in pending:
            try:
                q.replicate(payload)
            except Exception as e:
                if first_err is None:
                    first_err = e
        if first_err is not None:
            raise first_err

    # -- cluster plumbing --------------------------------------------------

    def _cluster(self, cid: int) -> _Cluster:
        c = self._clusters.get(cid)
        if c is None:
            c = self._clusters[cid] = _Cluster(cid)
            if self._on_new_cluster is not None:
                self._on_new_cluster(c)
        return c

    @staticmethod
    def _require_concrete(cls) -> None:
        if not cls.cluster_ids:
            raise ValueError(f"class '{cls.name}' is abstract")

    def _select_cluster(self, class_name: str) -> int:
        cls = self.schema.get_class_or_raise(class_name)
        self._require_concrete(cls)
        i = self._rr_state.get(cls.name, 0)
        self._rr_state[cls.name] = i + 1
        return cls.cluster_ids[i % len(cls.cluster_ids)]

    # -- record lifecycle --------------------------------------------------

    def _owner_for(self, class_name: str):
        """The WriteOwner this class's writes forward to, or None when
        this member commits them locally (it owns the class — either as
        the primary default or via a per-class assignment)."""
        key = class_name.lower()
        if key in self._class_owners:
            return self._class_owners[key]
        return self._write_owner

    def rename_class(self, old: str, new: str) -> None:
        """ALTER CLASS <old> NAME <new> ([E] OAlterClassStatement):
        schema rename plus the record/index rewrite the schema layer
        cannot do — every record of the class points at the new name,
        indexes follow, spilled cold records re-spill."""
        with self._lock:
            cls = self.schema.get_class_or_raise(old)
            docs = list(self.browse_class(cls.name, polymorphic=False))
            # only indexes DEFINED ON this class follow the rename —
            # for_class() also returns super/subclass indexes, which
            # must keep their own class names
            idxs = [
                ix
                for ix in (
                    self._indexes.all() if self._indexes is not None else []
                )
                if ix.class_name.lower() == cls.name.lower()
            ]
            self.schema.rename_class(cls.name, new)
            for d in docs:
                d.class_name = new
                if self._cold_tier is not None:
                    self._cold_tier.on_save(d)
            for ix in idxs:
                ix.class_name = new
            key = old.lower()
            if key in self._class_owners:
                self._class_owners[new.lower()] = self._class_owners.pop(
                    key
                )
            self.mutation_epoch += 1
            self._poison_overlay(f"class renamed: {old} -> {new}")

    def _poison_overlay(self, reason: str) -> None:
        """Schema mutations the CDC stream cannot express (renames,
        drops) invalidate a delta-maintained snapshot: poison the
        overlay so the next catch-up compacts. Lock-free flag write —
        callers hold self._lock, and the maintainer's catch-up takes
        its own lock BEFORE self._lock (taking it here would invert).
        Materialized views die with the overlay: their class footprints
        are keyed by the OLD names, so no future event would ever
        invalidate them (a renamed-away class's view would serve its
        stale result forever)."""
        snap = self._snapshot
        ov = getattr(snap, "_overlay", None) if snap is not None else None
        if ov is not None:
            ov.poison(reason)
        vm = getattr(self, "_view_manager", None)
        if vm is not None:
            vm.invalidate_all(reason)

    def _check_2pc_lock(self, rid) -> None:
        """Refuse a write to a rid locked by an in-flight prepared
        distributed tx (parallel/twophase) — unless THIS thread is that
        tx's own phase-2 commit, or the lock's deadline passed (presumed
        abort: a vanished coordinator must not wedge the record; the
        registry refuses a late commit of the expired txid). Callers
        hold self._lock."""
        if not self._tx2pc_locks:
            return
        held = self._tx2pc_locks.get(rid)
        if held is None:
            return
        txid, deadline = held
        if getattr(self._tx_local, "tx2pc_commit", None) == txid:
            return
        import time as _t

        if _t.time() >= deadline:
            del self._tx2pc_locks[rid]
            return
        raise ConcurrentModificationError(
            f"{rid} is locked by in-flight distributed tx {txid}"
        )

    def _forwarded_tx(self):
        """The active ForwardedTransaction, or None. A tx on a NON-OWNER
        member buffers with no local schema/store mutation and executes
        at the owner on commit (parallel/forwarding.ForwardedTransaction
        — [E] the reference's distributed tx task batch)."""
        tx = self.tx
        if tx is None:
            return None
        from orientdb_tpu.parallel.forwarding import ForwardedTransaction

        return tx if isinstance(tx, ForwardedTransaction) else None

    def new_element(self, class_name: str = "O", **fields) -> Document:
        """Create (and save) a plain document."""
        if self._owner_for(class_name) is not None and self.tx is None:
            # non-owner member: forward BEFORE any local schema mutation
            # (auto-creating the class here would diverge this replica)
            doc = Document(class_name, fields)
            doc._db = self
            return self.save(doc)
        ftx = self._forwarded_tx()
        if ftx is not None:
            # buffered for the owner: NO local schema mutation
            doc = Document(class_name, fields)
            doc._db = self
            return ftx.save(doc)
        tx = self.tx
        if (
            tx is not None
            and not self._tx_suspended
            and self._owner_for(class_name) is not None
        ):
            # foreign-owned class inside a local tx: NO local schema
            # mutation (the 2PC sub-batch creates it at the owner)
            doc = Document(class_name, fields)
            doc._db = self
            return tx.save(doc)
        if not self.schema.exists_class(class_name):
            self.schema.create_class(class_name)
        doc = Document(class_name, fields)
        doc._db = self
        return self.save(doc)

    def _resolve_vertex_class(self, class_name: str):
        """Vertex class, auto-created if absent (shared by new_vertex and
        the bulk loader so the two ingest paths cannot drift)."""
        cls = self.schema.get_class(class_name)
        if cls is None:
            cls = self.schema.create_vertex_class(class_name)
        if not cls.is_vertex_type:
            raise ValueError(f"class '{class_name}' is not a vertex class")
        return cls

    def _resolve_edge_class(self, class_name: str):
        cls = self.schema.get_class(class_name)
        if cls is None:
            cls = self.schema.create_edge_class(class_name)
        if not cls.is_edge_type:
            raise ValueError(f"class '{class_name}' is not an edge class")
        return cls

    def new_blob(self, data: bytes) -> "Blob":
        """Create (and save) a raw-bytes record ([E] ORecordBytes —
        ``db.save(new ORecordBytes(bytes))``)."""
        from orientdb_tpu.models.record import Blob

        if self._write_owner is None and not self.schema.exists_class(
            "OBlob"
        ):
            # non-owners skip local schema mutation: the owner creates
            # OBlob when the forwarded save arrives (see new_element)
            self.schema.create_class("OBlob")
        b = Blob(data)
        b._db = self
        return self.save(b)

    def new_vertex(self, class_name: str = "V", **fields) -> Vertex:
        if self._owner_for(class_name) is not None and self.tx is None:
            # non-owner: forward before local class auto-creation (see
            # new_element) — the owner resolves/creates the class
            v = Vertex(class_name, fields)
            v._db = self
            self.save(v)
            return v
        ftx = self._forwarded_tx()
        if ftx is not None:
            v = Vertex(class_name, fields)
            v._db = self
            ftx.save(v)
            return v
        tx = self.tx
        if (
            tx is not None
            and not self._tx_suspended
            and self._owner_for(class_name) is not None
        ):
            # foreign-owned class inside a local tx: NO local schema
            # mutation (the 2PC sub-batch creates it at the owner;
            # auto-creating here would fork the owner's DDL stream)
            v = Vertex(class_name, fields)
            v._db = self
            tx.save(v)
            return v
        cls = self._resolve_vertex_class(class_name)
        v = Vertex(cls.name, fields)
        v._db = self
        self.save(v)
        return v

    def new_edge(
        self, class_name: str, src: Vertex, dst: Vertex, **fields
    ) -> Edge:
        """Create an edge src -OUT-> dst and wire both adjacency bags.

        Mirrors OVertex.addEdge ([E]): the edge document gets out/in links,
        the source vertex appends to ``out_<cls>``, the target to
        ``in_<cls>``.
        """
        ftx = self._forwarded_tx()
        if ftx is not None:
            # buffered for the owner; endpoints may be tx-temps
            return ftx.new_edge(class_name, src, dst, **fields)
        if self._owner_for(class_name) is not None and self.tx is None:
            # non-owner: forward BEFORE local edge-class auto-creation
            # (the owner resolves/creates the class; see new_element)
            if not (src.rid.is_persistent and dst.rid.is_persistent):
                raise ValueError(
                    "both endpoints must be saved before creating an edge"
                )
            resp = self._owner_for(class_name).create_edge(
                class_name, src.rid, dst.rid, dict(fields)
            )
            e = Edge(class_name, fields)
            e._db = self
            e.out_rid = src.rid
            e.in_rid = dst.rid
            if resp.get("@rid"):
                e.rid = RID.parse(resp["@rid"])
                e.version = resp.get("@version", 1)
            return e
        tx = self.tx
        if (
            tx is not None
            and not self._tx_suspended
            and self._owner_for(class_name) is not None
        ):
            # foreign-owned edge class inside a local tx: NO local
            # schema mutation (the 2PC sub-batch resolves it at the
            # owner)
            return tx.new_edge(class_name, src, dst, **fields)
        cls = self._resolve_edge_class(class_name)
        if tx is not None and not self._tx_suspended:
            return tx.new_edge(cls.name, src, dst, **fields)
        if not (src.rid.is_persistent and dst.rid.is_persistent):
            raise ValueError("both endpoints must be saved before creating an edge")
        with self._quorum_deferral():
            with self._lock:
                e = Edge(cls.name, fields)
                e._db = self
                e.out_rid = src.rid
                e.in_rid = dst.rid
                self.save(e)
                src._bag(Direction.OUT, cls.name).append(e.rid)
                dst._bag(Direction.IN, cls.name).append(e.rid)
                src.version += 1
                dst.version += 1
                if self._cold_tier is not None:
                    # bag mutations bypass save(): re-spill the endpoints
                    # or an eviction would fault back stale adjacency
                    self._cold_tier.on_save(src)
                    self._cold_tier.on_save(dst)
        return e

    def save(self, doc: Document) -> Document:
        tx = self.tx
        if tx is not None and not self._tx_suspended:
            return tx.save(doc)
        if self._owner_for(doc.class_name) is not None:
            return self._forward_save(doc)
        # deferred quorum pushes ship after the lock is released (see
        # _quorum_push); also on failure — an entry logged before a
        # later hook raised is already durable and must still ack
        with self._quorum_deferral():
            return self._save_locked(doc)

    def _forward_save(self, doc: Document) -> Document:
        """Non-owner member: route the write to the cluster owner; the
        committed record comes back via replication. The returned doc
        carries the owner-assigned RID/version."""
        if isinstance(doc, Edge):
            raise ValueError("edges are created via new_edge (forwarded)")
        from orientdb_tpu.models.record import Blob

        owner = self._owner_for(doc.class_name)
        is_new = doc.rid is NEW_RID or not doc.rid.is_persistent
        if is_new:
            resp = owner.create(
                doc.class_name,
                doc.fields(),
                kind="vertex"
                if isinstance(doc, Vertex)
                else "blob" if isinstance(doc, Blob) else "document",
            )
            doc.rid = RID.parse(resp["@rid"])
        else:
            resp = owner.update(
                doc.rid, doc.fields(), base_version=doc.version
            )
        doc.version = resp.get("@version", doc.version)
        doc._db = self
        return doc

    def _save_locked(self, doc: Document) -> Document:
        with self._lock:
            cls = self.schema.get_class(doc.class_name)
            if cls is None:
                cls = self.schema.create_class(doc.class_name)
            cls.validate(doc.fields())
            if self._indexes is not None:
                # Two-phase: unique-constraint check BEFORE any mutation so a
                # violation can never leave store and indexes diverged
                # (the reference rolls the tx back on
                # ORecordDuplicatedException).
                self._indexes.validate_save(doc)
            is_new = doc.rid is NEW_RID or not doc.rid.is_persistent
            if not is_new:
                self._check_2pc_lock(doc.rid)
            if self._hooks is not None:
                self._hooks.fire(
                    "before_create" if is_new else "before_update", doc
                )
            if is_new:
                cid = self._select_cluster(doc.class_name)
                pos = self._cluster(cid).append(doc)
                doc.rid = RID(cid, pos)
                doc.version = 1
                doc._db = self
            else:
                stored = self._load_raw(doc.rid)
                if stored is None:
                    raise RecordNotFoundError(str(doc.rid))
                if stored is not doc and stored.version != doc.version:
                    raise ConcurrentModificationError(
                        f"{doc.rid}: stored v{stored.version} != tx v{doc.version}"
                    )
                doc.version += 1
                self._cluster(doc.rid.cluster).records[doc.rid.position] = doc
            if self._indexes is not None:
                try:
                    self._indexes.on_save(doc)
                except Exception:
                    # Defense in depth behind validate_save (non-unique
                    # failures): don't leave a new record half-written.
                    if is_new:
                        self._cluster(doc.rid.cluster).tombstone(doc.rid.position)
                        self._indexes.on_delete(doc)
                        doc.rid = NEW_RID
                        doc.version = 0
                    raise
            self.mutation_epoch += 1
            if self._wal is not None:
                from orientdb_tpu.storage.durability import entry_for_save

                self._wal_log(entry_for_save(doc, is_new))
            if self._cold_tier is not None:
                # save-through to the capacity tier (spill + keep hot)
                self._cold_tier.on_save(doc)
            if self._hooks is not None:
                self._hooks.fire("after_create" if is_new else "after_update", doc)
        return doc

    def _load_raw(self, rid: RID) -> Optional[Document]:
        c = self._clusters.get(rid.cluster)
        return c.get(rid.position) if c else None

    def load(self, rid: RID) -> Optional[Document]:
        if isinstance(rid, str):
            rid = RID.parse(rid)
        tx = self.tx
        if tx is not None and not self._tx_suspended:
            return tx.load(rid)
        return self._load_raw(rid)

    def exists(self, rid: RID) -> bool:
        return self._load_raw(rid) is not None

    def delete(self, doc: Document) -> None:
        """Delete a record; vertices cascade-delete their incident edges,
        edges detach from both endpoint bags (OrientDB DELETE VERTEX/EDGE
        semantics)."""
        tx = self.tx
        if tx is not None and not self._tx_suspended:
            tx.delete(doc)
            return
        if self._owner_for(doc.class_name) is not None:
            self._owner_for(doc.class_name).delete(doc.rid)
            doc._deleted = True
            return
        with self._quorum_deferral():
            self._delete_locked(doc)

    def _delete_locked(self, doc: Document) -> None:
        with self._lock:
            if doc.rid.is_persistent:
                self._check_2pc_lock(doc.rid)
            if self._hooks is not None:
                self._hooks.fire("before_delete", doc)
            if isinstance(doc, Vertex):
                for edge in list(doc.edges(Direction.BOTH)):
                    # cascaded edges go through the full hook pipeline too
                    # (the reference fires ORecordHook per deleted record)
                    self._delete_edge(edge, fire_hooks=True)
            elif isinstance(doc, Edge):
                self._delete_edge(doc)
            was_persistent = doc.rid.is_persistent
            if was_persistent:
                if self._indexes is not None:
                    self._indexes.on_delete(doc)
                self._cluster(doc.rid.cluster).tombstone(doc.rid.position)
            doc._deleted = True
            if self._cold_tier is not None:
                self._cold_tier.on_delete(doc)
            self.mutation_epoch += 1
            if was_persistent and self._wal is not None:
                from orientdb_tpu.storage.durability import entry_for_delete

                self._wal_log(entry_for_delete(doc))
            if self._hooks is not None:
                self._hooks.fire("after_delete", doc)

    def _delete_edge(self, edge: Edge, fire_hooks: bool = False) -> None:
        if fire_hooks and self._hooks is not None:
            self._hooks.fire("before_delete", edge)
        src = self.load(edge.out_rid)
        dst = self.load(edge.in_rid)
        if isinstance(src, Vertex):
            bag = src._bag(Direction.OUT, edge.class_name)
            if edge.rid in bag:
                bag.remove(edge.rid)
                src.version += 1  # adjacency changed: same MVCC bump as new_edge
        if isinstance(dst, Vertex):
            bag = dst._bag(Direction.IN, edge.class_name)
            if edge.rid in bag:
                bag.remove(edge.rid)
                dst.version += 1
        if self._cold_tier is not None:
            # bag mutations bypass save(): re-spill the endpoints (see
            # new_edge) so eviction cannot fault back stale adjacency
            if isinstance(src, Vertex):
                self._cold_tier.on_save(src)
            if isinstance(dst, Vertex):
                self._cold_tier.on_save(dst)
        if edge.rid.is_persistent:
            if self._indexes is not None:
                self._indexes.on_delete(edge)
            self._cluster(edge.rid.cluster).tombstone(edge.rid.position)
        if fire_hooks:
            edge._deleted = True
            if self._hooks is not None:
                self._hooks.fire("after_delete", edge)

    # -- scans -------------------------------------------------------------

    def browse_class(
        self, class_name: str, polymorphic: bool = True
    ) -> Iterator[Document]:
        """Scan all live records of a class ([E] browseClass)."""
        cls = self.schema.get_class_or_raise(class_name)
        cids = (
            self.schema.polymorphic_cluster_ids(cls.name)
            if polymorphic
            else list(cls.cluster_ids)
        )
        tx = self.tx if not self._tx_suspended else None
        for cid in cids:
            c = self._clusters.get(cid)
            if c is None:
                continue
            if tx is None:
                yield from c
            else:
                for doc in c:
                    view = tx.overlay(doc)
                    if view is not None:
                        yield view
        if tx is not None:
            yield from tx.browse_extra(cls.name, polymorphic)

    def browse_cluster(self, cluster_id: int) -> Iterator[Document]:
        c = self._clusters.get(cluster_id)
        if c is not None:
            yield from c

    def count_class(self, class_name: str, polymorphic: bool = True) -> int:
        tx = self.tx if not self._tx_suspended else None
        if tx is not None:
            return sum(1 for _ in self.browse_class(class_name, polymorphic))
        # no tx overlay: tally cluster tombstone-free slots directly —
        # planner estimates call this per query, so it must not iterate
        # records ([E] OClass.count reads cluster sizes, not records)
        cls = self.schema.get_class_or_raise(class_name)
        cids = (
            self.schema.polymorphic_cluster_ids(cls.name)
            if polymorphic
            else list(cls.cluster_ids)
        )
        return sum(
            self._clusters[cid].live_count()
            for cid in cids
            if cid in self._clusters
        )

    def drop_class(self, class_name: str) -> None:
        """Drop a schema class and its indexes (records are abandoned, as in
        the reference's non-'UNSAFE' class drop which requires empty class;
        here we require the class to have no live records)."""
        with self._lock:
            cls = self.schema.get_class_or_raise(class_name)
            if any(True for _ in self.browse_class(cls.name, polymorphic=False)):
                raise ValueError(f"class '{cls.name}' is not empty; delete records first")
            if self._indexes is not None:
                self._indexes.drop_for_class(cls.name)
            self.schema.drop_class(cls.name)
            self._poison_overlay(f"class dropped: {cls.name}")

    # -- indexes -----------------------------------------------------------

    @property
    def indexes(self):
        if self._indexes is None:
            from orientdb_tpu.models.indexes import IndexManager

            self._indexes = IndexManager(self)
        return self._indexes

    # -- metadata: sequences & stored functions ----------------------------

    @property
    def sequences(self):
        """[E] OSequenceLibrary."""
        if self._sequences is None:
            from orientdb_tpu.models.metadata import SequenceManager

            self._sequences = SequenceManager(self)
        return self._sequences

    @property
    def functions(self):
        """[E] OFunctionLibrary."""
        if self._functions is None:
            from orientdb_tpu.models.metadata import FunctionManager

            self._functions = FunctionManager(self)
        return self._functions

    @property
    def scheduler(self):
        """Scheduled events ([E] OScheduler): OSchedule records firing
        stored functions on cron rules. Start the loop explicitly with
        ``db.scheduler.start()``."""
        if self._scheduler is None:
            from orientdb_tpu.exec.scheduler import Scheduler

            with self._lock:
                if self._scheduler is None:
                    self._scheduler = Scheduler(self)
        return self._scheduler

    # -- hooks & transactions ----------------------------------------------

    @property
    def hooks(self):
        """Record hook registry ([E] ORecordHook)."""
        if self._hooks is None:
            from orientdb_tpu.exec.hooks import HookManager

            self._hooks = HookManager(self)
        return self._hooks

    @property
    def tx(self):
        """The thread's active transaction, if any."""
        return getattr(self._tx_local, "tx", None)

    @property
    def _tx_suspended(self) -> bool:
        return getattr(self._tx_local, "suspended", False)

    @_tx_suspended.setter
    def _tx_suspended(self, value: bool) -> None:
        self._tx_local.suspended = value

    def begin(self):
        """Start an optimistic transaction ([E] ODatabaseSession.begin).
        On a non-owner cluster member the transaction buffers locally
        and EXECUTES AT THE OWNER on commit as one atomic batch ([E]
        the distributed tx task, SURVEY.md:126)."""
        if self.tx is not None:
            raise RuntimeError("transaction already active on this thread")
        if self._write_owner is not None:
            from orientdb_tpu.parallel.forwarding import (
                ForwardedTransaction,
            )

            t = ForwardedTransaction(self)
            self._tx_local.tx = t
            return t
        from orientdb_tpu.exec.tx import Transaction

        t = Transaction(self)
        self._tx_local.tx = t
        return t

    def commit(self):
        t = self.tx
        if t is None:
            raise RuntimeError("no active transaction")
        return t.commit()

    def rollback(self) -> None:
        t = self.tx
        if t is None:
            raise RuntimeError("no active transaction")
        t.rollback()

    def _end_tx(self, t) -> None:
        if self.tx is t:
            self._tx_local.tx = None

    # -- query layer -------------------------------------------------------

    def query(self, sql: str, params: Optional[Dict[str, object]] = None, **kw):
        """Run an idempotent statement ([E] ODatabaseSession.query)."""
        from orientdb_tpu.exec.engine import execute_query

        return execute_query(self, sql, params or {}, **kw)

    def query_batch(self, sqls, params_list=None, **kw):
        """Run a batch of idempotent statements in ~one device round trip
        (the single-chip DP axis: dispatch all compiled plans back-to-back,
        overlap every device→host transfer). Returns one ResultSet per
        statement, in order."""
        from orientdb_tpu.exec.engine import execute_query_batch

        return execute_query_batch(self, sqls, params_list, **kw)

    def command(self, sql: str, params: Optional[Dict[str, object]] = None, **kw):
        """Run any statement, including writes ([E] ODatabaseSession.command)."""
        from orientdb_tpu.exec.engine import execute_command

        return execute_command(self, sql, params or {}, **kw)

    def execute(
        self,
        language: str,
        script: str,
        params: Optional[Dict[str, object]] = None,
        **kw,
    ):
        """Run a SQL batch script ([E] ODatabaseSession.execute /
        OCommandScript): multiple statements, LET/IF/RETURN/SLEEP, one
        session context. Returns a ResultSet like query/command."""
        if language.lower() != "sql":
            raise ValueError(
                f"script language {language!r} not supported (sql only)"
            )
        from orientdb_tpu.exec.result import ResultSet
        from orientdb_tpu.exec.script import execute_script

        return ResultSet(execute_script(self, script, params or kw or {}))

    def explain(self, sql: str, params: Optional[Dict[str, object]] = None):
        from orientdb_tpu.exec.engine import explain

        return explain(self, sql, params or {})

    # -- snapshot attach ---------------------------------------------------

    def attach_snapshot(self, snapshot, mesh=None) -> None:
        if mesh is not None:
            snapshot._mesh = mesh
        # tier admission (storage/tiering): with tier_hbm_cap_bytes set
        # and the snapshot's adjacency over it, the device build pages
        # adjacency hot/cold instead of uploading flat. Refuses loudly
        # on a meshed or delta-maintained snapshot.
        from orientdb_tpu.storage.tiering import maybe_tier_snapshot

        maybe_tier_snapshot(snapshot)
        self._snapshot = snapshot
        self._snapshot_epoch = self.mutation_epoch

    def detach_snapshot(self) -> None:
        """Drop the attached snapshot and FREE its HBM buffers (the
        device arrays delete eagerly; see GraphSnapshot.release_device).
        Queries fall back to the oracle until a new snapshot attaches."""
        snap = self._snapshot
        self._snapshot = None
        if snap is not None:
            snap.release_device()

    def current_snapshot(self, require_fresh: bool = False):
        if self._snapshot is None:
            return None
        if require_fresh and self._snapshot_epoch != self.mutation_epoch:
            m = self._snapshot_maintainer
            if m is not None:
                # incremental path (storage/deltas): apply the pending
                # CDC delta batch device-side — the epoch catches up
                # without dropping a single HBM buffer. A poisoned
                # overlay compacts (full rebuild) inside catch_up.
                m.catch_up()
            if (
                self._snapshot is None
                or self._snapshot_epoch != self.mutation_epoch
            ):
                return None
        return self._snapshot

    @property
    def snapshot_is_stale(self) -> bool:
        return (
            self._snapshot is not None
            and self._snapshot_epoch != self.mutation_epoch
        )
