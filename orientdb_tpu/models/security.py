"""Users, roles, and resource permissions.

Analog of [E] OSecurityShared / OUser / ORole (SURVEY.md §2
"Schema/metadata" security): named users with salted PBKDF2 password
hashes and roles granting CRUD permissions on resources. The server layer
authenticates every request against this registry; the default roles
mirror the reference's admin/reader/writer triple.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Dict, List, Optional, Set

READ = "read"
CREATE = "create"
UPDATE = "update"
DELETE = "delete"
ALL = (READ, CREATE, UPDATE, DELETE)

# Distinct resource kinds (the reference's ORule resource tree:
# database.class.*, database.schema, server.databases …). Record CRUD,
# schema DDL, and database create/drop are separate resources so the
# writer role can hold record CRUD without server-level powers.
RES_RECORD = "record"
RES_SCHEMA = "schema"
RES_DATABASE = "database"
#: users/roles/grants themselves ([E] database.security in the ORule
#: tree): only admin-grade roles may mutate them
RES_SECURITY = "security"

_SCHEMA_DDL_HEADS = ("class", "property", "index", "sequence", "function")


def classify_sql(sql: str):
    """Map a statement to its (resource, op) for permission checks.

    SELECT/MATCH/TRAVERSE/EXPLAIN → (record, read); CREATE/DROP/ALTER/
    TRUNCATE of schema objects → (schema, update); everything else
    (DML, BEGIN/COMMIT…) → (record, update).
    """
    toks = sql.split(None, 2)
    head = toks[0].lower() if toks else ""
    if head in ("select", "match", "traverse", "explain", "profile"):
        return RES_RECORD, READ
    if head == "insert":
        return RES_RECORD, CREATE
    if head == "delete":
        return RES_RECORD, DELETE
    if head in ("grant", "revoke"):
        return RES_SECURITY, UPDATE
    if head == "find":  # FIND REFERENCES is read-only
        return RES_RECORD, READ
    if head == "move":  # MOVE VERTEX deletes the source record
        return RES_RECORD, DELETE
    if head in ("create", "drop", "alter", "truncate", "rebuild"):
        target = toks[1].lower() if len(toks) > 1 else ""
        if head == "truncate" and target == "record":
            return RES_RECORD, DELETE
        if target in _SCHEMA_DDL_HEADS:
            return RES_SCHEMA, UPDATE
        if target == "user":
            return RES_SECURITY, UPDATE
        if head == "create" and target in ("vertex", "edge"):
            return RES_RECORD, CREATE
    return RES_RECORD, UPDATE


class SecurityError(Exception):
    pass


class Role:
    """A named permission set over resources ('*' = any resource)."""

    def __init__(self, name: str) -> None:
        self.name = name
        #: resource (class name or '*') → set of allowed ops
        self.grants: Dict[str, Set[str]] = {}

    def grant(self, resource: str, *ops: str) -> "Role":
        self.grants.setdefault(resource.lower(), set()).update(ops or ALL)
        return self

    def revoke(self, resource: str, *ops: str) -> "Role":
        g = self.grants.get(resource.lower())
        if g is not None:
            g.difference_update(ops or ALL)
        return self

    def allows(self, resource: str, op: str) -> bool:
        for key in (resource.lower(), "*"):
            if op in self.grants.get(key, ()):
                return True
        return False


class User:
    def __init__(self, name: str, password: str, roles: List[Role]) -> None:
        self.name = name
        self.salt = os.urandom(16)
        self.pw_hash = self._hash(password, self.salt)
        self.roles = list(roles)
        self.active = True

    @staticmethod
    def _hash(password: str, salt: bytes) -> bytes:
        return hashlib.pbkdf2_hmac("sha256", password.encode(), salt, 10_000)

    def check_password(self, password: str) -> bool:
        return self.active and hmac.compare_digest(
            self.pw_hash, self._hash(password, self.salt)
        )

    def set_password(self, password: str) -> None:
        self.salt = os.urandom(16)
        self.pw_hash = self._hash(password, self.salt)

    def allows(self, resource: str, op: str) -> bool:
        return self.active and any(r.allows(resource, op) for r in self.roles)


class SecurityManager:
    """Per-database user/role registry with the reference's default triple
    (admin/admin all, reader read-only, writer no schema ops)."""

    def __init__(self, admin_password: str = "admin") -> None:
        self.roles: Dict[str, Role] = {}
        self.users: Dict[str, User] = {}
        # admin's '*' grant covers record/schema/database via the fallback;
        # reader and writer get per-resource grants only — writer has
        # record CRUD but cannot touch the schema or create/drop databases.
        self.create_role("admin").grant("*", *ALL)
        (
            self.create_role("reader")
            .grant(RES_RECORD, READ)
            .grant(RES_SCHEMA, READ)
            .grant(RES_DATABASE, READ)
        )
        (
            self.create_role("writer")
            .grant(RES_RECORD, *ALL)
            .grant(RES_SCHEMA, READ)
            .grant(RES_DATABASE, READ)
        )
        self.create_user("admin", admin_password, ["admin"])
        self.create_user("reader", "reader", ["reader"])
        self.create_user("writer", "writer", ["writer"])

    def create_role(self, name: str) -> Role:
        if name.lower() in self.roles:
            raise SecurityError(f"role '{name}' exists")
        r = self.roles[name.lower()] = Role(name)
        return r

    def get_role(self, name: str) -> Optional[Role]:
        return self.roles.get(name.lower())

    def create_user(self, name: str, password: str, role_names: List[str]) -> User:
        if name.lower() in self.users:
            raise SecurityError(f"user '{name}' exists")
        roles = []
        for rn in role_names:
            r = self.get_role(rn)
            if r is None:
                raise SecurityError(f"role '{rn}' not found")
            roles.append(r)
        u = self.users[name.lower()] = User(name, password, roles)
        return u

    def drop_user(self, name: str) -> bool:
        return self.users.pop(name.lower(), None) is not None

    def authenticate(self, name: str, password: str) -> Optional[User]:
        audit = getattr(self, "audit", None)
        chain = getattr(self, "chain", None)
        if chain is not None:
            # pluggable authenticator chain (server/auth.py: password,
            # token, LDAP import, Kerberos tickets — [E] the
            # OSecurityAuthenticator chain)
            u = chain.authenticate(self, name, password)
        else:
            u = self.users.get(name.lower())
            if u is not None and not u.check_password(password):
                u = None
        if u is not None:
            if audit is not None:
                # log the AUTHENTICATED identity — token/ticket logins
                # pass an empty caller name and resolve it from the
                # credential, and the audit trail needs attribution
                audit.auth_ok(u.name)
            return u
        if audit is not None:
            if name:
                audit.auth_fail(name)
            else:
                # bearer-token logins pass an empty caller name; a failed
                # token must still leave an attributable trail, so log a
                # marker plus a short digest of the presented credential
                # (never the token itself)
                import hashlib

                digest = hashlib.sha256(
                    (password or "").encode()
                ).hexdigest()[:12]
                audit.auth_fail(f"<bearer>#{digest}")
        return None

    def check(self, user: User, resource: str, op: str) -> None:
        if not user.allows(resource, op):
            audit = getattr(self, "audit", None)
            if audit is not None:
                audit.denied(user.name, resource, op)
            raise SecurityError(
                f"user '{user.name}' lacks {op} permission on '{resource}'"
            )
