"""Schema-level stored functions and sequences.

Analog of the reference's function/sequence metadata ([E]
core/.../metadata/function/OFunction + core/.../metadata/sequence/
OSequence, OSequenceLibrary — SURVEY.md §2 "Schema/metadata" row lists
"functions, sequences" as part of the metadata surface).

- ``Sequence`` — monotonic id generator: ``sequence('s').next()`` /
  ``.current()`` / ``.reset()`` from SQL. ORDERED semantics (every next
  durable when a WAL is armed); CACHED reserves ``cache`` ids per WAL
  record, trading at-most-``cache`` lost ids on crash for fewer appends
  (the reference's cached sequence makes the same trade).
- ``StoredFunction`` — a named SQL statement or expression invocable as
  ``name(args...)`` in any expression context ([E] OFunction with
  language=SQL; the reference's javascript language has no sandboxed
  analog here and is rejected).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from orientdb_tpu.exec.result import Result


class SequenceError(Exception):
    pass


class Sequence:
    __slots__ = ("name", "seq_type", "start", "increment", "cache", "_value",
                 "_reserved_until", "_db", "_lock")

    def __init__(self, db, name, seq_type="ORDERED", start=0, increment=1, cache=20):
        self.name = name
        self.seq_type = seq_type.upper()
        self.start = start
        self.increment = increment
        self.cache = max(1, cache)
        self._value = start
        self._reserved_until = start
        self._db = db
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += self.increment
            if self._db is not None and self._db._wal is not None:
                if self.seq_type == "CACHED":
                    # reserve a block: replay resumes past the reservation,
                    # losing at most `cache` ids on crash
                    if (self._value - self._reserved_until) * self.increment >= 0:
                        self._reserved_until = (
                            self._value + self.increment * self.cache
                        )
                        self._db._wal_log(
                            {"op": "seq_set", "name": self.name,
                             "value": self._reserved_until}
                        )
                else:
                    self._db._wal_log(
                        {"op": "seq_set", "name": self.name, "value": self._value}
                    )
            return self._value

    def current(self) -> int:
        return self._value

    def reset(self) -> int:
        with self._lock:
            self._value = self.start
            self._reserved_until = self.start
            if self._db is not None and self._db._wal is not None:
                self._db._wal_log(
                    {"op": "seq_set", "name": self.name, "value": self._value}
                )
            return self._value

    def set_value(self, v: int) -> None:
        with self._lock:
            self._value = v
            self._reserved_until = v

    def __repr__(self) -> str:
        return f"Sequence({self.name}={self._value})"


class SequenceManager:
    """[E] OSequenceLibrary."""

    def __init__(self, db) -> None:
        self._db = db
        self._seqs: Dict[str, Sequence] = {}

    def create(self, name, seq_type="ORDERED", start=0, increment=1, cache=20) -> Sequence:
        key = name.lower()
        if key in self._seqs:
            raise SequenceError(f"sequence '{name}' already exists")
        if seq_type.upper() not in ("ORDERED", "CACHED"):
            raise SequenceError(f"unknown sequence type {seq_type!r}")
        s = Sequence(self._db, name, seq_type, start, increment, cache)
        self._seqs[key] = s
        self._db._wal_log(
            {
                "op": "create_sequence",
                "name": name,
                "type": s.seq_type,
                "start": start,
                "increment": increment,
                "cache": cache,
            }
        )
        return s

    def get(self, name: str) -> Optional[Sequence]:
        return self._seqs.get(name.lower())

    def get_or_raise(self, name: str) -> Sequence:
        s = self.get(name)
        if s is None:
            raise SequenceError(f"sequence '{name}' not found")
        return s

    def drop(self, name: str) -> None:
        if self._seqs.pop(name.lower(), None) is not None:
            self._db._wal_log({"op": "drop_sequence", "name": name})

    def alter(self, name, start=None, increment=None, cache=None) -> Sequence:
        s = self.get_or_raise(name)
        if start is not None:
            s.start = start
            s.set_value(start)
        if increment is not None:
            s.increment = increment
        if cache is not None:
            s.cache = max(1, cache)
        # log only the EXPLICITLY altered fields: replaying an
        # increment-only alter must not reset the live value to start
        # (sequence ids feed unique keys — a reset reissues them)
        self._db._wal_log(
            {
                "op": "alter_sequence",
                "name": s.name,
                "start": start,
                "increment": increment,
                "cache": cache,
            }
        )
        return s

    def all(self) -> List[Sequence]:
        return list(self._seqs.values())


class FunctionError(Exception):
    pass


class StoredFunction:
    __slots__ = ("name", "parameters", "body", "language", "idempotent", "_compiled")

    def __init__(self, name, body, parameters=(), language="sql", idempotent=True):
        self.name = name
        self.body = body
        self.parameters = list(parameters)
        self.language = language.lower()
        self.idempotent = idempotent
        self._compiled = None

    def _compile(self):
        if self._compiled is None:
            from orientdb_tpu.sql.parser import ParseError, parse

            try:
                self._compiled = ("stmt", parse(self.body))
            except ParseError:
                # an expression body: wrap as a SELECT projection
                self._compiled = ("expr", parse(f"SELECT {self.body} AS result"))
        return self._compiled

    def invoke(self, db, args, parent_ctx=None):
        """Run the function body with the declared parameter names bound
        as context VARIABLES (the body references them bare, the way [E]
        OFunction binds its parameters); returns the scalar for expression
        bodies, the row list otherwise."""
        if len(args) > len(self.parameters):
            raise FunctionError(
                f"function '{self.name}' takes {len(self.parameters)} args"
            )
        from orientdb_tpu.exec.eval import EvalContext
        from orientdb_tpu.exec.oracle import execute_statement

        call_ctx = EvalContext(db, params={}, parent=parent_ctx)
        for i, p in enumerate(self.parameters):
            call_ctx.variables[p] = args[i] if i < len(args) else None
        kind, stmt = self._compile()
        rows = execute_statement(db, stmt, {}, parent_ctx=call_ctx)
        if kind == "expr":
            return rows[0].get_property("result") if rows else None
        out = [r.element if r.is_element else r for r in rows]
        return out


class FunctionManager:
    """[E] OFunctionLibrary-ish registry."""

    def __init__(self, db) -> None:
        self._db = db
        self._fns: Dict[str, StoredFunction] = {}

    def create(self, name, body, parameters=(), language="sql", idempotent=True) -> StoredFunction:
        key = name.lower()
        if key in self._fns:
            raise FunctionError(f"function '{name}' already exists")
        if language.lower() not in ("sql",):
            raise FunctionError(
                f"language {language!r} not supported (sql only; the "
                "reference's javascript has no sandboxed analog here)"
            )
        f = StoredFunction(name, body, parameters, language, idempotent)
        # compile eagerly: a syntactically bad body fails at CREATE
        f._compile()
        self._fns[key] = f
        self._db._wal_log(
            {
                "op": "create_function",
                "name": name,
                "body": body,
                "parameters": list(parameters),
                "language": language,
                "idempotent": idempotent,
            }
        )
        return f

    def get(self, name: str) -> Optional[StoredFunction]:
        return self._fns.get(name.lower())

    def drop(self, name: str) -> None:
        if self._fns.pop(name.lower(), None) is not None:
            self._db._wal_log({"op": "drop_function", "name": name})

    def all(self) -> List[StoredFunction]:
        return list(self._fns.values())


def rows_for(op: str, **props) -> List[Result]:
    return [Result(props={"operation": op, **props})]
