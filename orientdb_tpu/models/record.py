"""Records: documents, vertices, edges.

Analog of OrientDB's record layer ([E] core/.../record/impl/ — ODocument,
OVertexDocument, OEdgeDocument; SURVEY.md §2 "Record types" / "Graph model"):

- :class:`Document` — schema-hybrid field map with a version counter (MVCC)
  and a RID once saved;
- :class:`Vertex` — document + adjacency bags. OrientDB stores adjacency in
  per-edge-class ``ORidBag`` fields named ``out_<EdgeClass>`` /
  ``in_<EdgeClass>``; here the analog is a dict of edge-class -> list of edge
  RIDs per direction (the embedded-list small-degree form; there is no
  sbtree promotion because the host store is in-RAM — high-degree handling
  happens in the columnar snapshot/TPU layer instead);
- :class:`Edge` — document + ``out``/``in`` endpoint RIDs (OrientDB's edge
  direction convention: ``out`` = source vertex, ``in`` = target vertex).
"""

from __future__ import annotations

import enum
from typing import Dict, Iterator, List, Optional, TYPE_CHECKING

from orientdb_tpu.models.rid import RID, NEW_RID

if TYPE_CHECKING:  # pragma: no cover
    from orientdb_tpu.models.database import Database


class Direction(enum.Enum):
    OUT = "out"
    IN = "in"
    BOTH = "both"

    @property
    def opposite(self) -> "Direction":
        if self is Direction.OUT:
            return Direction.IN
        if self is Direction.IN:
            return Direction.OUT
        return Direction.BOTH


class Document:
    """A schema-hybrid record ([E] ODocument)."""

    __slots__ = ("_db", "class_name", "rid", "version", "_fields", "_deleted")

    def __init__(self, class_name: str, fields: Optional[Dict[str, object]] = None):
        self._db: Optional["Database"] = None
        self.class_name = class_name
        self.rid: RID = NEW_RID
        self.version = 0
        self._fields: Dict[str, object] = dict(fields or {})
        self._deleted = False

    # -- fields ------------------------------------------------------------

    def get(self, name: str, default=None):
        # Attribute pseudo-fields, as in OrientDB SQL (@rid, @class, @version).
        if name == "@rid":
            return self.rid
        if name == "@class":
            return self.class_name
        if name == "@version":
            return self.version
        return self._fields.get(name, default)

    def _tx_touch(self) -> None:
        """Let an active transaction capture this record's pre-image BEFORE
        an in-place mutation, so rollback can restore it (tx-local copies
        returned by tx.load don't need this — only shared store objects)."""
        db = self._db
        if db is None or not self.rid.is_persistent:
            return
        tx = db.tx
        if tx is not None and tx.active and not db._tx_suspended:
            tx.touch(self)

    def set(self, name: str, value) -> "Document":
        self._tx_touch()
        self._fields[name] = value
        return self

    def update(self, **fields) -> "Document":
        self._tx_touch()
        self._fields.update(fields)
        return self

    def remove_field(self, name: str) -> None:
        self._tx_touch()
        self._fields.pop(name, None)

    def has(self, name: str) -> bool:
        return name in self._fields

    def field_names(self) -> List[str]:
        return list(self._fields.keys())

    def fields(self) -> Dict[str, object]:
        return dict(self._fields)

    def __getitem__(self, name: str):
        return self.get(name)

    def __setitem__(self, name: str, value):
        self.set(name, value)

    def __contains__(self, name: str) -> bool:
        return name in self._fields

    # -- persistence -------------------------------------------------------

    def save(self) -> "Document":
        if self._db is None:
            raise RuntimeError("record is not attached to a database")
        self._db.save(self)
        return self

    def delete(self) -> None:
        if self._db is None:
            raise RuntimeError("record is not attached to a database")
        self._db.delete(self)

    @property
    def is_vertex(self) -> bool:
        return isinstance(self, Vertex)

    @property
    def is_edge(self) -> bool:
        return isinstance(self, Edge)

    def to_dict(self, include_meta: bool = True) -> Dict[str, object]:
        out = dict(self._fields)
        if include_meta:
            out["@rid"] = str(self.rid)
            out["@class"] = self.class_name
            out["@version"] = self.version
        return out

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self.class_name}{self.rid} {self._fields})"

    # Identity semantics (python default): the store returns the same object
    # for the same RID, and RIDs mutate on first save, so rid-based
    # hashing would break sets/dicts across save(). Semantic dedup in the
    # query layer keys on `doc.rid` explicitly.


class Blob(Document):
    """A raw-bytes record ([E] ORecordBytes / OBlob, SURVEY.md §2
    "Record types"): payload bytes with no schema fields. Stored in the
    reserved class ``OBlob`` and addressed by RID like any record; the
    bytes ride the checkpoint/WAL/export codecs base64-framed."""

    __slots__ = ()

    def __init__(self, data: bytes = b"") -> None:
        super().__init__("OBlob", {"data": bytes(data)})

    @classmethod
    def from_fields(cls, fields: Dict[str, object]) -> "Blob":
        """Rebuild from a persisted field map, keeping EVERY field (a
        blob may carry metadata like a mime type alongside `data`)."""
        b = cls(fields.get("data", b"") or b"")
        b._fields = dict(fields)
        return b

    @property
    def data(self) -> bytes:
        return self._fields.get("data", b"")

    @data.setter
    def data(self, value: bytes) -> None:
        self.set("data", bytes(value))

    def __len__(self) -> int:
        return len(self.data)


class RidBag:
    """Adjacency container ([E] ORidBag): an ordered list of edge RIDs
    that transparently *promotes* past a threshold — the reference's
    embedded→sbtree-bonsai switch. Promoted bags keep a membership set
    (O(1) ``in``) and remove by TOMBSTONE (O(1) amortized — the list
    compacts once tombstones pass half the length), so cascade-deleting
    a supernode's 10^5 edges is linear, not quadratic. Small bags stay a
    bare list with no set overhead."""

    __slots__ = ("_items", "_set", "_removed")

    PROMOTE_AT = 64  # [E] RID_BAG_EMBEDDED_TO_SBTREEBONSAI_THRESHOLD analog

    def __init__(self, items: Optional[List[RID]] = None) -> None:
        self._items: List[RID] = list(items or ())
        self._set = set(self._items) if len(self._items) > self.PROMOTE_AT else None
        self._removed: Optional[set] = None

    def _compact(self) -> None:
        if self._removed:
            self._items = [r for r in self._items if r not in self._removed]
        self._removed = None

    def append(self, rid: RID) -> None:
        if self._removed and rid in self._removed:
            self._compact()  # rare re-add of a tombstoned rid
        self._items.append(rid)
        if self._set is not None:
            self._set.add(rid)
        elif len(self._items) > self.PROMOTE_AT:
            self._set = set(self._items)

    def remove(self, rid: RID) -> None:
        if self._set is None:
            self._items.remove(rid)
            return
        if rid not in self._set:
            raise ValueError(f"{rid} not in bag")
        self._set.discard(rid)
        if self._removed is None:
            self._removed = set()
        self._removed.add(rid)
        if len(self._removed) * 2 > len(self._items):
            self._compact()

    def __contains__(self, rid: RID) -> bool:
        if self._set is not None:
            return rid in self._set
        return rid in self._items

    def __iter__(self):
        if not self._removed:
            return iter(self._items)
        removed = self._removed
        return iter([r for r in self._items if r not in removed])

    def __len__(self) -> int:
        return len(self._items) - (len(self._removed) if self._removed else 0)

    @property
    def promoted(self) -> bool:
        return self._set is not None

    def __repr__(self) -> str:
        return f"RidBag({len(self)}{'*' if self.promoted else ''})"


class Vertex(Document):
    """A vertex record with adjacency bags ([E] OVertexDocument)."""

    __slots__ = ("_out_edges", "_in_edges")

    def __init__(self, class_name: str, fields: Optional[Dict[str, object]] = None):
        super().__init__(class_name, fields)
        # edge class name -> RidBag of edge RIDs
        self._out_edges: Dict[str, RidBag] = {}
        self._in_edges: Dict[str, RidBag] = {}

    def _bag(self, direction: Direction, edge_class: str) -> RidBag:
        bags = self._out_edges if direction is Direction.OUT else self._in_edges
        bag = bags.get(edge_class)
        if bag is None:
            bag = bags[edge_class] = RidBag()
        elif not isinstance(bag, RidBag):
            # restore paths may assign plain lists; adopt in place
            bag = bags[edge_class] = RidBag(bag)
        return bag

    def _edge_classes(self, direction: Direction) -> List[str]:
        if direction is Direction.OUT:
            return list(self._out_edges.keys())
        if direction is Direction.IN:
            return list(self._in_edges.keys())
        seen = list(self._out_edges.keys())
        seen += [k for k in self._in_edges.keys() if k not in seen]
        return seen

    def _resolve_edge_classes(self, direction: Direction, edge_class: Optional[str]) -> List[str]:
        """Edge classes to scan, honoring polymorphism on the requested class."""
        present = self._edge_classes(direction)
        if edge_class is None:
            return present
        if self._db is None:
            return [c for c in present if c == edge_class]
        req = self._db.schema.get_class(edge_class)
        if req is None:
            return []
        out = []
        for c in present:
            sc = self._db.schema.get_class(c)
            if sc is not None and sc.is_subclass_of(req.name):
                out.append(c)
        return out

    def edges(
        self, direction: Direction = Direction.BOTH, edge_class: Optional[str] = None
    ) -> Iterator["Edge"]:
        """Iterate incident edges (analog of OVertex.getEdges)."""
        assert self._db is not None
        dirs = (
            [Direction.OUT, Direction.IN]
            if direction is Direction.BOTH
            else [direction]
        )
        for d in dirs:
            for cls_name in self._resolve_edge_classes(d, edge_class):
                for erid in list(self._bag(d, cls_name)):
                    e = self._db.load(erid)
                    if e is not None:
                        yield e  # type: ignore[misc]

    def vertices(
        self, direction: Direction = Direction.BOTH, edge_class: Optional[str] = None
    ) -> Iterator["Vertex"]:
        """Iterate adjacent vertices (analog of OVertex.getVertices).

        This is the host-side, per-record traversal primitive — exactly the
        hot loop the TPU engine replaces with batched CSR expansion
        (SURVEY.md §3.3).
        """
        assert self._db is not None
        for edge in self.edges(direction, edge_class):
            if direction is Direction.BOTH:
                other = edge.in_rid if edge.out_rid == self.rid else edge.out_rid
            elif direction is Direction.OUT:
                other = edge.in_rid
            else:
                other = edge.out_rid
            v = self._db.load(other)
            if v is not None:
                yield v  # type: ignore[misc]

    def degree(
        self, direction: Direction = Direction.BOTH, edge_class: Optional[str] = None
    ) -> int:
        n = 0
        dirs = (
            [Direction.OUT, Direction.IN]
            if direction is Direction.BOTH
            else [direction]
        )
        for d in dirs:
            for cls_name in self._resolve_edge_classes(d, edge_class):
                n += len(self._bag(d, cls_name))
        return n


class Edge(Document):
    """An edge record ([E] OEdgeDocument): out = source, in = target."""

    __slots__ = ("out_rid", "in_rid")

    def __init__(self, class_name: str, fields: Optional[Dict[str, object]] = None):
        super().__init__(class_name, fields)
        self.out_rid: RID = NEW_RID
        self.in_rid: RID = NEW_RID

    def get(self, name: str, default=None):
        # OrientDB exposes the endpoints as the `out` / `in` link properties.
        if name == "out":
            return self.out_rid
        if name == "in":
            return self.in_rid
        return super().get(name, default)

    def from_vertex(self) -> Vertex:
        assert self._db is not None
        v = self._db.load(self.out_rid)
        assert isinstance(v, Vertex)
        return v

    def to_vertex(self) -> Vertex:
        assert self._db is not None
        v = self._db.load(self.in_rid)
        assert isinstance(v, Vertex)
        return v
