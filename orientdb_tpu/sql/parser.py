"""Recursive-descent SQL parser.

Hand-written replacement for the reference's JavaCC-generated parser ([E]
core/.../sql/parser/OrientSql.jj → OStatement subclasses; SURVEY.md §2 "SQL
parser"). Produces the dataclass AST in `orientdb_tpu/sql/ast.py`.

Grammar coverage (the OrientDB 3.x surface exercised by the BASELINE configs
plus the core CRUD/DDL statements): SELECT, MATCH (arrow + method path
forms, NOT patterns, OPTIONAL, WHILE/maxDepth), TRAVERSE, INSERT, UPDATE,
DELETE (record/vertex/edge), CREATE CLASS/PROPERTY/INDEX/VERTEX/EDGE,
DROP CLASS/PROPERTY/INDEX, ALTER PROPERTY, EXPLAIN/PROFILE, BEGIN/COMMIT/
ROLLBACK, LIVE SELECT.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from orientdb_tpu.sql import ast as A
from orientdb_tpu.sql.lexer import Token, tokenize, LexError


class ParseError(Exception):
    def __init__(self, message: str, token: Optional[Token] = None) -> None:
        if token is not None:
            message = f"{message} (at {token.kind} {token.text!r}, pos {token.pos})"
        super().__init__(message)


# Comparison operators normalized to canonical spelling.
_CMP_OPS = {"=": "=", "==": "=", "!=": "!=", "<>": "!=", "<": "<", "<=": "<=",
            ">": ">", ">=": ">="}

_CMP_KEYWORDS = (
    "LIKE",
    "IN",
    "CONTAINS",
    "CONTAINSANY",
    "CONTAINSALL",
    "CONTAINSKEY",
    "CONTAINSVALUE",
    "CONTAINSTEXT",
    "MATCHES",
    "INSTANCEOF",
)


class Parser:
    def __init__(self, text: str) -> None:
        try:
            self.toks = tokenize(text)
        except LexError as e:
            raise ParseError(str(e)) from e
        self.i = 0
        self._param_counter = 0

    # -- token helpers -----------------------------------------------------

    def peek(self, offset: int = 0) -> Token:
        j = min(self.i + offset, len(self.toks) - 1)
        return self.toks[j]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "EOF":
            self.i += 1
        return t

    def at_op(self, text: str, offset: int = 0) -> bool:
        t = self.peek(offset)
        return t.kind == "OP" and t.text == text

    def at_kw(self, word: str, offset: int = 0) -> bool:
        t = self.peek(offset)
        return t.kind == "IDENT" and t.text.upper() == word.upper()

    def eat_op(self, text: str) -> Token:
        if not self.at_op(text):
            raise ParseError(f"expected '{text}'", self.peek())
        return self.next()

    def eat_kw(self, word: str) -> Token:
        if not self.at_kw(word):
            raise ParseError(f"expected {word}", self.peek())
        return self.next()

    def try_op(self, text: str) -> bool:
        if self.at_op(text):
            self.next()
            return True
        return False

    def try_kw(self, word: str) -> bool:
        if self.at_kw(word):
            self.next()
            return True
        return False

    def eat_ident(self) -> str:
        t = self.peek()
        if t.kind != "IDENT":
            raise ParseError("expected identifier", t)
        self.next()
        return t.value  # type: ignore[return-value]

    def expect_eof(self) -> None:
        if self.peek().kind != "EOF":
            raise ParseError("unexpected trailing input", self.peek())

    # -- entry -------------------------------------------------------------

    def parse_statement(self) -> A.Statement:
        t = self.peek()
        if t.kind != "IDENT":
            raise ParseError("expected a statement keyword", t)
        kw = t.text.upper()
        if kw == "SELECT":
            return self.parse_select()
        if kw == "MATCH":
            return self.parse_match()
        if kw == "TRAVERSE":
            return self.parse_traverse()
        if kw == "INSERT":
            return self.parse_insert()
        if kw == "UPDATE":
            return self.parse_update()
        if kw == "DELETE":
            return self.parse_delete()
        if kw == "CREATE":
            return self.parse_create()
        if kw == "DROP":
            return self.parse_drop()
        if kw == "ALTER":
            return self.parse_alter()
        if kw in ("EXPLAIN", "PROFILE"):
            self.next()
            inner = self.parse_statement()
            return A.ExplainStatement(inner, profile=(kw == "PROFILE"))
        if kw == "BEGIN":
            self.next()
            return A.BeginStatement()
        if kw == "COMMIT":
            self.next()
            retries = None
            if self.try_kw("RETRY"):
                retries = int(self.next().value)  # type: ignore[arg-type]
            return A.CommitStatement(retries)
        if kw == "ROLLBACK":
            self.next()
            return A.RollbackStatement()
        if kw == "LIVE":
            self.next()
            sel = self.parse_select()
            assert isinstance(sel, A.SelectStatement)
            return A.LiveSelectStatement(sel)
        if kw == "TRUNCATE":
            return self.parse_truncate()
        if kw == "MOVE":
            return self.parse_move_vertex()
        if kw == "REBUILD":
            self.next()
            self.eat_kw("INDEX")
            if self.at_op("*"):
                self.next()
                return A.RebuildIndexStatement("*")
            name = self.eat_ident()
            while self.at_op("."):
                self.next()
                name += "." + self.eat_ident()
            return A.RebuildIndexStatement(name)
        if kw in ("GRANT", "REVOKE"):
            self.next()
            permission = self.eat_ident().upper()
            self.eat_kw("ON")
            resource = self.parse_resource_path()
            self.eat_kw("TO" if kw == "GRANT" else "FROM")
            role = self.eat_ident()
            if kw == "GRANT":
                return A.GrantStatement(permission, resource, role)
            return A.RevokeStatement(permission, resource, role)
        if kw == "FIND":
            self.next()
            self.eat_kw("REFERENCES")
            rt = self.next()
            if rt.kind != "RID":
                raise ParseError("expected RID after FIND REFERENCES", rt)
            classes: List[str] = []
            if self.at_op("["):
                self.next()
                classes = self.parse_name_list()
                self.eat_op("]")
            return A.FindReferencesStatement(rt.text, tuple(classes))
        raise ParseError(f"unsupported statement '{t.text}'", t)

    def parse_resource_path(self) -> str:
        """A dotted security resource name (database.class.P, server.*)."""
        parts = [self.eat_ident() if not self.at_op("*") else self._star()]
        while self.at_op("."):
            self.next()
            parts.append(
                self._star() if self.at_op("*") else self.eat_ident()
            )
        return ".".join(parts)

    def _star(self) -> str:
        self.eat_op("*")
        return "*"

    def parse_truncate(self) -> A.Statement:
        self.eat_kw("TRUNCATE")
        if self.try_kw("CLASS"):
            name = self.eat_ident()
            polymorphic = self.try_kw("POLYMORPHIC")
            unsafe = self.try_kw("UNSAFE")
            return A.TruncateClassStatement(name, polymorphic, unsafe)
        if self.try_kw("RECORD"):
            rids = []
            if self.at_op("["):
                self.next()
                while not self.at_op("]"):
                    rt = self.next()
                    if rt.kind != "RID":
                        raise ParseError("expected RID", rt)
                    rids.append(rt.text)
                    if self.at_op(","):
                        self.next()
                self.eat_op("]")
            else:
                rt = self.next()
                if rt.kind != "RID":
                    raise ParseError("expected RID", rt)
                rids.append(rt.text)
            return A.TruncateRecordStatement(tuple(rids))
        raise ParseError("unsupported TRUNCATE", self.peek())

    def parse_move_vertex(self) -> A.Statement:
        self.eat_kw("MOVE")
        self.eat_kw("VERTEX")
        t = self.peek()
        source: object
        if t.kind == "RID":
            self.next()
            source = t.text
        elif self.at_op("("):
            self.next()
            source = self.parse_select()
            self.eat_op(")")
        else:
            raise ParseError("expected RID or (subquery) in MOVE VERTEX", t)
        self.eat_kw("TO")
        self.eat_kw("CLASS")
        self.eat_op(":")
        return A.MoveVertexStatement(source, self.eat_ident())

    # -- SELECT ------------------------------------------------------------

    def parse_select(self) -> A.SelectStatement:
        self.eat_kw("SELECT")
        distinct = False
        if self.at_kw("DISTINCT") and not (
            self.at_op("(", 1)  # legacy distinct(expr) function call
        ):
            self.next()
            distinct = True
        projections: List[A.Projection] = []
        if not (self.at_kw("FROM") or self.peek().kind == "EOF"):
            projections = self.parse_projections()
        target = None
        if self.try_kw("FROM"):
            target = self.parse_target()
        lets: List[A.LetItem] = []
        if self.try_kw("LET"):
            lets = self.parse_lets()
        where = self.parse_expression() if self.try_kw("WHERE") else None
        group_by: Tuple[A.Expression, ...] = ()
        if self.at_kw("GROUP"):
            self.next()
            self.eat_kw("BY")
            group_by = tuple(self.parse_expr_list())
        order_by = self.parse_order_by()
        unwind: Tuple[str, ...] = ()
        if self.try_kw("UNWIND"):
            unwind = tuple(self.parse_name_list())
        skip, limit = self.parse_skip_limit()
        timeout = None
        if self.try_kw("TIMEOUT"):
            timeout = int(self.next().value)  # type: ignore[arg-type]
        return A.SelectStatement(
            projections=tuple(projections),
            target=target,
            where=where,
            group_by=group_by,
            order_by=order_by,
            unwind=unwind,
            skip=skip,
            limit=limit,
            lets=tuple(lets),
            timeout_ms=timeout,
            distinct=distinct,
        )

    def parse_projections(self) -> List[A.Projection]:
        out = []
        while True:
            expr = self.parse_expression()
            alias = None
            if self.try_kw("AS"):
                alias = self.eat_ident()
            out.append(A.Projection(expr, alias))
            if not self.try_op(","):
                break
        return out

    def parse_lets(self) -> List[A.LetItem]:
        out = []
        while True:
            t = self.peek()
            if t.kind == "VAR":
                self.next()
                name = t.value
            else:
                name = self.eat_ident()
            self.eat_op("=")
            if self.at_op("("):
                # could be a subquery or a parenthesized expression
                save = self.i
                self.next()
                if self.peek().kind == "IDENT" and self.peek().text.upper() in (
                    "SELECT",
                    "MATCH",
                    "TRAVERSE",
                ):
                    sub = self.parse_statement()
                    self.eat_op(")")
                    out.append(A.LetItem(name, sub))
                else:
                    self.i = save
                    out.append(A.LetItem(name, self.parse_expression()))
            else:
                out.append(A.LetItem(name, self.parse_expression()))
            if not self.try_op(","):
                break
        return out

    def parse_order_by(self) -> Tuple[A.OrderByItem, ...]:
        if not self.at_kw("ORDER"):
            return ()
        self.next()
        self.eat_kw("BY")
        items = []
        while True:
            expr = self.parse_expression()
            asc = True
            if self.try_kw("DESC"):
                asc = False
            elif self.try_kw("ASC"):
                asc = True
            items.append(A.OrderByItem(expr, asc))
            if not self.try_op(","):
                break
        return tuple(items)

    def parse_skip_limit(self):
        skip = limit = None
        # OrientDB allows SKIP/LIMIT in either order; parse_unary admits the
        # idiomatic `LIMIT -1` (unlimited)
        for _ in range(2):
            if self.try_kw("SKIP"):
                skip = self.parse_unary()
            elif self.try_kw("LIMIT"):
                limit = self.parse_unary()
        return skip, limit

    def parse_expr_list(self) -> List[A.Expression]:
        out = [self.parse_expression()]
        while self.try_op(","):
            out.append(self.parse_expression())
        return out

    def parse_name_list(self) -> List[str]:
        out = [self.eat_ident()]
        while self.try_op(","):
            out.append(self.eat_ident())
        return out

    # -- FROM targets ------------------------------------------------------

    def parse_target(self) -> A.Target:
        t = self.peek()
        if t.kind == "RID":
            self.next()
            return A.RidTarget((A.RIDLiteral(*t.value),))
        if self.at_op("["):
            self.next()
            rids = []
            while not self.at_op("]"):
                rt = self.next()
                if rt.kind != "RID":
                    raise ParseError("expected RID in list target", rt)
                rids.append(A.RIDLiteral(*rt.value))
                self.try_op(",")
            self.eat_op("]")
            return A.RidTarget(tuple(rids))
        if self.at_op("("):
            self.next()
            if self.peek().kind == "IDENT" and self.peek().text.upper() in (
                "SELECT",
                "MATCH",
                "TRAVERSE",
            ):
                sub = self.parse_statement()
                self.eat_op(")")
                return A.SubQueryTarget(sub)
            expr = self.parse_expression()
            self.eat_op(")")
            return A.ExpressionTarget(expr)
        if t.kind == "VAR":
            self.next()
            return A.ExpressionTarget(A.ContextVar(t.value))
        if self.at_op(":"):
            self.next()
            return A.ExpressionTarget(A.Parameter(name=self.eat_ident()))
        if t.kind == "IDENT":
            word = t.text.upper()
            if word == "CLUSTER" and self.at_op(":", 1):
                self.next()
                self.next()
                nt = self.next()
                return A.ClusterTarget(
                    nt.value if nt.kind in ("IDENT", "STRING") else int(nt.value)
                )
            if word == "INDEX" and self.at_op(":", 1):
                self.next()
                self.next()
                # index names may contain dots: Class.field
                name = self.eat_ident()
                while self.at_op(".") :
                    self.next()
                    name += "." + self.eat_ident()
                return A.IndexTarget(name)
            name = self.eat_ident()
            return A.ClassTarget(name)
        raise ParseError("expected query target", t)

    # -- MATCH -------------------------------------------------------------

    def parse_match(self) -> A.MatchStatement:
        self.eat_kw("MATCH")
        paths = [self.parse_match_path()]
        while self.try_op(","):
            paths.append(self.parse_match_path())
        self.eat_kw("RETURN")
        distinct = self.try_kw("DISTINCT")
        returns = self.parse_projections()
        group_by: Tuple[A.Expression, ...] = ()
        if self.at_kw("GROUP"):
            self.next()
            self.eat_kw("BY")
            group_by = tuple(self.parse_expr_list())
        order_by = self.parse_order_by()
        unwind: Tuple[str, ...] = ()
        if self.try_kw("UNWIND"):
            unwind = tuple(self.parse_name_list())
        skip, limit = self.parse_skip_limit()
        return A.MatchStatement(
            paths=tuple(paths),
            returns=tuple(returns),
            distinct=distinct,
            group_by=group_by,
            order_by=order_by,
            unwind=unwind,
            skip=skip,
            limit=limit,
        )

    def parse_match_path(self) -> A.MatchPath:
        negated = self.try_kw("NOT")
        first = self.parse_match_filter()
        items = []
        while True:
            item = self.try_parse_path_item()
            if item is None:
                break
            items.append(item)
        return A.MatchPath(first, tuple(items), negated=negated)

    def try_parse_path_item(self) -> Optional[A.MatchPathItem]:
        # arrow forms:  -EC->{..}   <-EC-{..}   -EC-{..}   -->{..}  <--{..}  --{..}
        # method forms: .out('EC'){..}  .outE('EC'){..}.inV(){..}  etc.
        if self.at_op("-"):
            self.next()
            edge_classes, edge_filter = self.parse_arrow_middle()
            if self.peek().kind == "ARROW":
                self.next()
                direction = "out"
            elif self.at_op("-"):
                self.next()
                direction = "both"
            else:
                raise ParseError("expected '->' or '-' to close match arrow", self.peek())
            target = self.parse_match_filter()
            return A.MatchPathItem(direction, edge_classes, target, edge_filter)
        if self.at_op("<") and self.at_op("-", 1):
            self.next()
            self.next()
            edge_classes, edge_filter = self.parse_arrow_middle()
            self.eat_op("-")
            target = self.parse_match_filter()
            return A.MatchPathItem("in", edge_classes, target, edge_filter)
        if self.at_op("."):
            self.next()
            method = self.eat_ident()
            m = method.lower()
            valid = {"out": "out", "in": "in", "both": "both",
                     "oute": "out", "ine": "in", "bothe": "both"}
            if m not in valid and m not in ("outv", "inv", "bothv"):
                raise ParseError(f"unsupported match method '{method}'", self.peek())
            self.eat_op("(")
            classes = []
            while not self.at_op(")"):
                ct = self.next()
                if ct.kind not in ("STRING", "IDENT"):
                    raise ParseError("expected edge class name", ct)
                classes.append(ct.value)
                self.try_op(",")
            self.eat_op(")")
            mid_filter = None
            if self.at_op("{"):
                mid_filter = self.parse_match_filter()
            if m in ("oute", "ine", "bothe"):
                # edge-step form: .outE('EC'){edge filter}.inV(){vertex filter}
                edge_filter = mid_filter
                if self.at_op("."):
                    self.next()
                    vm = self.eat_ident().lower()
                    if vm not in ("inv", "outv", "bothv"):
                        raise ParseError(f"expected inV()/outV() after {method}()", self.peek())
                    self.eat_op("(")
                    self.eat_op(")")
                    target = (
                        self.parse_match_filter() if self.at_op("{") else A.MatchFilter()
                    )
                else:
                    # bare .outE('EC'){as: e}: the *edge* is the target binding
                    return A.MatchPathItem(
                        valid[m],
                        tuple(classes),
                        mid_filter or A.MatchFilter(),
                        None,
                        method=method,
                    )
                return A.MatchPathItem(
                    valid[m], tuple(classes), target, edge_filter, method=method
                )
            if m in ("outv", "inv", "bothv"):
                # standalone .inV()/.outV() after a bare edge binding: moves
                # from a bound edge alias to its endpoint vertex
                target = mid_filter if mid_filter is not None else A.MatchFilter()
                return A.MatchPathItem(m, (), target, None, method=method)
            target = mid_filter if mid_filter is not None else A.MatchFilter()
            return A.MatchPathItem(valid[m], tuple(classes), target, None, method=method)
        return None

    def parse_arrow_middle(self):
        """Between the dashes of an arrow: optional edge class name and/or
        `{...}` edge filter braces."""
        edge_classes: Tuple[str, ...] = ()
        edge_filter = None
        if self.peek().kind == "IDENT":
            edge_classes = (self.eat_ident(),)
        if self.at_op("{"):
            edge_filter = self.parse_match_filter()
            if edge_filter.class_name and not edge_classes:
                edge_classes = (edge_filter.class_name,)
        return edge_classes, edge_filter

    def parse_match_filter(self) -> A.MatchFilter:
        self.eat_op("{")
        alias = class_name = rid = where = while_cond = None
        max_depth = None
        optional = False
        depth_alias = path_alias = None
        while not self.at_op("}"):
            key = self.eat_ident().lower()
            self.eat_op(":")
            if key == "class":
                t = self.next()
                if t.kind not in ("IDENT", "STRING"):
                    raise ParseError("expected class name", t)
                class_name = t.value
            elif key == "as":
                alias = self.eat_ident()
            elif key == "rid":
                t = self.next()
                if t.kind != "RID":
                    raise ParseError("expected RID", t)
                rid = A.RIDLiteral(*t.value)
            elif key == "where":
                self.eat_op("(")
                where = self.parse_expression()
                self.eat_op(")")
            elif key == "while":
                self.eat_op("(")
                while_cond = self.parse_expression()
                self.eat_op(")")
            elif key == "maxdepth":
                t = self.next()
                if t.kind != "NUMBER":
                    raise ParseError("expected number for maxDepth", t)
                max_depth = int(t.value)
            elif key == "optional":
                t = self.next()
                optional = str(t.value).lower() == "true"
            elif key == "depthalias":
                depth_alias = self.eat_ident()
            elif key == "pathalias":
                path_alias = self.eat_ident()
            else:
                raise ParseError(f"unknown match filter key '{key}'", self.peek())
            self.try_op(",")
        self.eat_op("}")
        return A.MatchFilter(
            alias=alias,
            class_name=class_name,
            rid=rid,
            where=where,
            while_cond=while_cond,
            max_depth=max_depth,
            optional=optional,
            depth_alias=depth_alias,
            path_alias=path_alias,
        )

    # -- TRAVERSE ----------------------------------------------------------

    def parse_traverse(self) -> A.TraverseStatement:
        self.eat_kw("TRAVERSE")
        fields: List[A.Expression] = []
        if not self.at_kw("FROM"):
            fields = self.parse_expr_list()
        self.eat_kw("FROM")
        target = self.parse_target()
        max_depth = None
        while_cond = None
        limit = None
        strategy = "DEPTH_FIRST"
        while True:
            if self.try_kw("MAXDEPTH"):
                max_depth = int(self.next().value)  # type: ignore[arg-type]
            elif self.try_kw("WHILE"):
                while_cond = self.parse_expression()
            elif self.try_kw("LIMIT"):
                limit = self.parse_primary()
            elif self.try_kw("STRATEGY"):
                strategy = self.eat_ident().upper()
                if strategy not in ("DEPTH_FIRST", "BREADTH_FIRST"):
                    raise ParseError(f"unknown strategy {strategy}")
            else:
                break
        return A.TraverseStatement(
            fields=tuple(fields),
            target=target,
            max_depth=max_depth,
            while_cond=while_cond,
            limit=limit,
            strategy=strategy,
        )

    # -- INSERT ------------------------------------------------------------

    def parse_insert(self) -> A.InsertStatement:
        self.eat_kw("INSERT")
        self.eat_kw("INTO")
        cluster = None
        class_name = None
        if self.at_kw("CLUSTER") and self.at_op(":", 1):
            self.next()
            self.next()
            cluster = self.eat_ident()
        else:
            class_name = self.eat_ident()
        set_fields: Tuple[Tuple[str, A.Expression], ...] = ()
        content: Optional[A.Expression] = None
        from_select: Optional[A.Statement] = None
        if self.try_kw("SET"):
            set_fields = tuple(self.parse_set_items())
        elif self.try_kw("CONTENT"):
            content = self.parse_expression()
        elif self.at_op("("):
            self.next()
            names = self.parse_name_list()
            self.eat_op(")")
            self.eat_kw("VALUES")
            rows: List[Tuple[Tuple[str, A.Expression], ...]] = []
            while True:
                self.eat_op("(")
                vals = self.parse_expr_list()
                self.eat_op(")")
                if len(vals) != len(names):
                    raise ParseError("VALUES arity mismatch")
                rows.append(tuple(zip(names, vals)))
                if not self.try_op(","):
                    break
            if len(rows) == 1:
                set_fields = rows[0]
            else:
                # multi-row insert: encode as content list of maps
                content = A.ListExpr(
                    tuple(A.MapExpr(tuple((k, v) for k, v in row)) for row in rows)
                )
        elif self.try_kw("FROM"):
            from_select = self.parse_statement()
        else:
            raise ParseError(
                "expected SET / CONTENT / VALUES / FROM in INSERT", self.peek()
            )
        return_expr: Optional[A.Expression] = None
        if self.try_kw("RETURN"):
            return_expr = self.parse_expression()
        return A.InsertStatement(
            class_name,
            cluster,
            set_fields=set_fields,
            content=content,
            from_select=from_select,
            return_expr=return_expr,
        )

    def parse_set_items(self) -> List[Tuple[str, A.Expression]]:
        out = []
        while True:
            name = self.eat_ident()
            self.eat_op("=")
            out.append((name, self.parse_expression()))
            if not self.try_op(","):
                break
        return out

    # -- UPDATE ------------------------------------------------------------

    def parse_update(self) -> A.UpdateStatement:
        self.eat_kw("UPDATE")
        target = self.parse_target()
        ops: List[A.UpdateOp] = []
        while True:
            if self.try_kw("SET"):
                ops.append(A.UpdateOp("SET", tuple(self.parse_set_items())))
            elif self.try_kw("INCREMENT"):
                ops.append(A.UpdateOp("INCREMENT", tuple(self.parse_set_items())))
            elif self.try_kw("REMOVE"):
                items = []
                while True:
                    name = self.eat_ident()
                    if self.try_op("="):
                        items.append((name, self.parse_expression()))
                    else:
                        items.append((name, A.Literal(None)))
                    if not self.try_op(","):
                        break
                ops.append(A.UpdateOp("REMOVE", tuple(items)))
            elif self.try_kw("CONTENT"):
                ops.append(A.UpdateOp("CONTENT", (("", self.parse_expression()),)))
            elif self.try_kw("MERGE"):
                ops.append(A.UpdateOp("MERGE", (("", self.parse_expression()),)))
            else:
                break
        upsert = self.try_kw("UPSERT")
        return_mode = None
        if self.try_kw("RETURN"):
            return_mode = self.eat_ident().upper()
            if return_mode not in ("COUNT", "BEFORE", "AFTER"):
                raise ParseError(f"unknown UPDATE RETURN mode {return_mode}")
        where = self.parse_expression() if self.try_kw("WHERE") else None
        _, limit = self.parse_skip_limit()
        return A.UpdateStatement(
            target=target,
            ops=tuple(ops),
            upsert=upsert,
            where=where,
            limit=limit,
            return_mode=return_mode,
        )

    # -- DELETE ------------------------------------------------------------

    def parse_delete(self) -> A.DeleteStatement:
        self.eat_kw("DELETE")
        kind = "RECORD"
        edge_from = edge_to = None
        if self.try_kw("VERTEX"):
            kind = "VERTEX"
            target = self.parse_target()
        elif self.try_kw("EDGE"):
            kind = "EDGE"
            target: A.Target = A.ClassTarget("E")
            if self.peek().kind == "IDENT" and not (
                self.at_kw("FROM") or self.at_kw("WHERE") or self.at_kw("LIMIT")
            ):
                target = A.ClassTarget(self.eat_ident())
            elif self.peek().kind == "RID":
                t = self.next()
                target = A.RidTarget((A.RIDLiteral(*t.value),))
            if self.try_kw("FROM"):
                edge_from = self.parse_expression()
                if self.try_kw("TO"):
                    edge_to = self.parse_expression()
        else:
            self.eat_kw("FROM")
            target = self.parse_target()
        where = self.parse_expression() if self.try_kw("WHERE") else None
        _, limit = self.parse_skip_limit()
        return A.DeleteStatement(
            target=target,
            where=where,
            limit=limit,
            kind=kind,
            edge_from=edge_from,
            edge_to=edge_to,
        )

    # -- CREATE / DROP / ALTER --------------------------------------------

    def parse_create(self) -> A.Statement:
        self.eat_kw("CREATE")
        if self.try_kw("CLASS"):
            name = self.eat_ident()
            if_not_exists = False
            if self.try_kw("IF"):
                self.eat_kw("NOT")
                self.eat_kw("EXISTS")
                if_not_exists = True
            sups: List[str] = []
            if self.try_kw("EXTENDS"):
                sups = self.parse_name_list()
            abstract = self.try_kw("ABSTRACT")
            return A.CreateClassStatement(
                name, tuple(sups), abstract=abstract, if_not_exists=if_not_exists
            )
        if self.try_kw("PROPERTY"):
            cls = self.eat_ident()
            self.eat_op(".")
            prop = self.eat_ident()
            if_not_exists = False
            if self.try_kw("IF"):
                self.eat_kw("NOT")
                self.eat_kw("EXISTS")
                if_not_exists = True
            ptype = self.eat_ident().upper()
            linked = None
            if self.peek().kind == "IDENT" and not self.at_kw("UNSAFE"):
                linked = self.eat_ident()
            return A.CreatePropertyStatement(cls, prop, ptype, linked, if_not_exists)
        if self.try_kw("INDEX"):
            name = self.eat_ident()
            cls = None
            fields: Tuple[str, ...] = ()
            if self.at_op("."):
                self.next()
                field = self.eat_ident()
                cls = name
                name = f"{cls}.{field}"
                fields = (field,)
            if self.try_kw("ON"):
                cls = self.eat_ident()
                self.eat_op("(")
                fields = tuple(self.parse_name_list())
                self.eat_op(")")
            itype = self.eat_ident().upper()
            while self.peek().kind == "IDENT" and self.peek().text.upper() in (
                "HASH_INDEX",
                "INDEX",
            ):
                itype += "_" + self.eat_ident().upper()
            # [E] Lucene module's forms: ENGINE LUCENE and METADATA {...}
            engine = None
            metadata = None
            if self.peek().kind == "IDENT" and self.peek().text.upper() == "ENGINE":
                self.next()
                engine = self.eat_ident().upper()
            if self.peek().kind == "IDENT" and self.peek().text.upper() == "METADATA":
                self.next()
                metadata = self.parse_expression()
            return A.CreateIndexStatement(
                name, cls, fields, itype, engine=engine, metadata=metadata
            )
        if self.try_kw("VERTEX"):
            cls = self.eat_ident() if self.peek().kind == "IDENT" and not (
                self.at_kw("SET") or self.at_kw("CONTENT")
            ) else "V"
            if self.try_kw("SET"):
                return A.CreateVertexStatement(cls, tuple(self.parse_set_items()))
            if self.try_kw("CONTENT"):
                return A.CreateVertexStatement(cls, content=self.parse_expression())
            return A.CreateVertexStatement(cls)
        if self.try_kw("EDGE"):
            cls = self.eat_ident()
            self.eat_kw("FROM")
            from_expr = self.parse_from_to_operand()
            self.eat_kw("TO")
            to_expr = self.parse_from_to_operand()
            if self.try_kw("SET"):
                return A.CreateEdgeStatement(
                    cls, from_expr, to_expr, tuple(self.parse_set_items())
                )
            if self.try_kw("CONTENT"):
                return A.CreateEdgeStatement(
                    cls, from_expr, to_expr, content=self.parse_expression()
                )
            return A.CreateEdgeStatement(cls, from_expr, to_expr)
        if self.try_kw("SEQUENCE"):
            name = self.eat_ident()
            seq_type, start, increment, cache = "ORDERED", 0, 1, 20
            while True:
                if self.try_kw("TYPE"):
                    seq_type = self.eat_ident().upper()
                elif self.try_kw("START"):
                    start = self._int_value()
                elif self.try_kw("INCREMENT"):
                    increment = self._int_value()
                elif self.try_kw("CACHE"):
                    cache = self._int_value()
                else:
                    break
            return A.CreateSequenceStatement(name, seq_type, start, increment, cache)
        if self.try_kw("FUNCTION"):
            name = self.eat_ident()
            t = self.next()
            if t.kind != "STRING":
                raise ParseError("expected quoted function body", t)
            body = t.value
            parameters: Tuple[str, ...] = ()
            idempotent = True
            language = "sql"
            while True:
                if self.try_kw("PARAMETERS"):
                    self.eat_op("[")
                    parameters = tuple(self.parse_name_list())
                    self.eat_op("]")
                elif self.try_kw("IDEMPOTENT"):
                    v = self.next()
                    idempotent = str(v.value).lower() == "true"
                elif self.try_kw("LANGUAGE"):
                    language = self.eat_ident().lower()
                else:
                    break
            return A.CreateFunctionStatement(name, body, parameters, idempotent, language)
        if self.try_kw("USER"):
            name = self.eat_ident()
            self.eat_kw("IDENTIFIED")
            self.eat_kw("BY")
            t = self.next()
            if t.kind not in ("STRING", "IDENT"):
                raise ParseError("expected password", t)
            password = str(t.value)
            roles: List[str] = []
            if self.try_kw("ROLE"):
                if self.at_op("["):
                    self.next()
                    roles = self.parse_name_list()
                    self.eat_op("]")
                else:
                    roles = [self.eat_ident()]
            return A.CreateUserStatement(name, password, tuple(roles))
        raise ParseError("unsupported CREATE", self.peek())

    def _int_value(self) -> int:
        neg = self.try_op("-")
        t = self.next()
        if t.kind != "NUMBER":
            raise ParseError("expected number", t)
        v = int(t.value)
        return -v if neg else v

    def parse_from_to_operand(self) -> A.Expression:
        """CREATE EDGE FROM/TO operand: RID, (subquery), list, or param."""
        if self.at_op("("):
            self.next()
            if self.peek().kind == "IDENT" and self.peek().text.upper() in (
                "SELECT",
                "MATCH",
                "TRAVERSE",
            ):
                sub = self.parse_statement()
                self.eat_op(")")
                # wrap subquery as expression via a function marker
                return A.FunctionCall("$subquery", (A.Literal(sub),))
            expr = self.parse_expression()
            self.eat_op(")")
            return expr
        return self.parse_expression()

    def parse_drop(self) -> A.Statement:
        self.eat_kw("DROP")
        if self.try_kw("CLASS"):
            name = self.eat_ident()
            if_exists = False
            if self.try_kw("IF"):
                self.eat_kw("EXISTS")
                if_exists = True
            return A.DropClassStatement(name, if_exists)
        if self.try_kw("PROPERTY"):
            cls = self.eat_ident()
            self.eat_op(".")
            return A.DropPropertyStatement(cls, self.eat_ident())
        if self.try_kw("INDEX"):
            name = self.eat_ident()
            while self.at_op("."):
                self.next()
                name += "." + self.eat_ident()
            return A.DropIndexStatement(name)
        if self.try_kw("SEQUENCE"):
            return A.DropSequenceStatement(self.eat_ident())
        if self.try_kw("FUNCTION"):
            return A.DropFunctionStatement(self.eat_ident())
        if self.try_kw("USER"):
            return A.DropUserStatement(self.eat_ident())
        raise ParseError("unsupported DROP", self.peek())

    def parse_alter(self) -> A.Statement:
        self.eat_kw("ALTER")
        if self.try_kw("SEQUENCE"):
            name = self.eat_ident()
            start = increment = cache = None
            while True:
                if self.try_kw("START"):
                    start = self._int_value()
                elif self.try_kw("INCREMENT"):
                    increment = self._int_value()
                elif self.try_kw("CACHE"):
                    cache = self._int_value()
                else:
                    break
            return A.AlterSequenceStatement(name, start, increment, cache)
        if self.try_kw("CLASS"):
            cls = self.eat_ident()
            attr = self.eat_ident().upper()
            if attr == "SUPERCLASS":
                sign = "+"
                if self.at_op("+") or self.at_op("-"):
                    sign = self.next().text
                return A.AlterClassStatement(
                    cls, attr, (sign, self.eat_ident())
                )
            if attr in ("STRICTMODE", "ABSTRACT"):
                v = self.eat_ident().upper()
                if v not in ("TRUE", "FALSE"):
                    raise ParseError(
                        f"expected TRUE/FALSE for {attr}", self.peek()
                    )
                return A.AlterClassStatement(cls, attr, v == "TRUE")
            if attr == "NAME":
                t = self.next()
                if t.kind not in ("IDENT", "STRING"):
                    raise ParseError("expected new class name", t)
                return A.AlterClassStatement(cls, attr, t.value)
            if attr == "ADDCLUSTER":
                if self.peek().kind == "NUMBER":
                    # the reference accepted numeric cluster ids; here
                    # ids are engine-assigned — reject with the reason
                    # instead of a trailing-token ParseError at EOF
                    raise ParseError(
                        "ADDCLUSTER takes a cluster NAME: cluster ids "
                        "are assigned automatically",
                        self.peek(),
                    )
                name = (
                    self.eat_ident()
                    if self.peek().kind == "IDENT"
                    else None
                )
                return A.AlterClassStatement(cls, attr, name)
            raise ParseError(f"unsupported ALTER CLASS attribute {attr}")
        self.eat_kw("PROPERTY")
        cls = self.eat_ident()
        self.eat_op(".")
        prop = self.eat_ident()
        attr = self.eat_ident().upper()
        value = self.parse_expression()
        return A.AlterPropertyStatement(cls, prop, attr, value)

    # -- expressions (precedence climbing) ---------------------------------

    def parse_expression(self) -> A.Expression:
        return self.parse_or()

    def parse_or(self) -> A.Expression:
        left = self.parse_and()
        while self.at_kw("OR"):
            self.next()
            left = A.Binary("OR", left, self.parse_and())
        return left

    def parse_and(self) -> A.Expression:
        left = self.parse_not()
        while self.at_kw("AND"):
            self.next()
            left = A.Binary("AND", left, self.parse_not())
        return left

    def parse_not(self) -> A.Expression:
        if self.at_kw("NOT"):
            self.next()
            return A.Unary("NOT", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> A.Expression:
        left = self.parse_additive()
        t = self.peek()
        if t.kind == "OP" and t.text in _CMP_OPS:
            self.next()
            return A.Binary(_CMP_OPS[t.text], left, self.parse_additive())
        if t.kind == "IDENT":
            kw = t.text.upper()
            if kw in _CMP_KEYWORDS:
                self.next()
                return A.Binary(kw, left, self.parse_additive())
            if kw == "BETWEEN":
                self.next()
                low = self.parse_additive()
                self.eat_kw("AND")
                high = self.parse_additive()
                return A.Between(left, low, high)
            if kw == "IS":
                self.next()
                negated = self.try_kw("NOT")
                if self.try_kw("NULL"):
                    return A.IsNull(left, negated)
                if self.try_kw("DEFINED"):
                    return A.IsDefined(left, negated)
                raise ParseError("expected NULL or DEFINED after IS", self.peek())
            if kw == "NOT":
                # NOT IN / NOT LIKE / NOT CONTAINS... / NOT BETWEEN
                nxt = self.peek(1)
                if nxt.kind == "IDENT" and nxt.text.upper() in _CMP_KEYWORDS:
                    self.next()
                    op = self.next().text.upper()
                    return A.Unary("NOT", A.Binary(op, left, self.parse_additive()))
                if nxt.kind == "IDENT" and nxt.text.upper() == "BETWEEN":
                    self.next()
                    self.next()
                    low = self.parse_additive()
                    self.eat_kw("AND")
                    high = self.parse_additive()
                    return A.Unary("NOT", A.Between(left, low, high))
        return left

    def parse_additive(self) -> A.Expression:
        left = self.parse_multiplicative()
        while True:
            if self.at_op("+"):
                self.next()
                left = A.Binary("+", left, self.parse_multiplicative())
            elif self.at_op("-"):
                self.next()
                left = A.Binary("-", left, self.parse_multiplicative())
            elif self.at_op("||"):
                self.next()
                left = A.Binary("||", left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self) -> A.Expression:
        left = self.parse_unary()
        while True:
            if self.at_op("*"):
                self.next()
                left = A.Binary("*", left, self.parse_unary())
            elif self.at_op("/"):
                self.next()
                left = A.Binary("/", left, self.parse_unary())
            elif self.at_op("%"):
                self.next()
                left = A.Binary("%", left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> A.Expression:
        if self.at_op("-"):
            self.next()
            return A.Unary("-", self.parse_unary())
        if self.at_op("+"):
            self.next()
            return A.Unary("+", self.parse_unary())
        return self.parse_postfix()

    def parse_postfix(self) -> A.Expression:
        expr = self.parse_primary()
        while True:
            if self.at_op("."):
                self.next()
                name = self.eat_ident()
                if self.at_op("("):
                    self.next()
                    args = [] if self.at_op(")") else self.parse_expr_list()
                    self.eat_op(")")
                    expr = A.MethodCall(expr, name, tuple(args))
                else:
                    expr = A.FieldAccess(expr, name)
            elif self.at_op("["):
                self.next()
                idx = self.parse_expression()
                self.eat_op("]")
                expr = A.IndexAccess(expr, idx)
            else:
                return expr

    def parse_primary(self) -> A.Expression:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            return A.Literal(t.value)
        if t.kind == "STRING":
            self.next()
            return A.Literal(t.value)
        if t.kind == "RID":
            self.next()
            return A.RIDLiteral(*t.value)
        if t.kind == "VAR":
            self.next()
            return A.ContextVar(t.value)
        if self.at_op("?"):
            self.next()
            p = A.Parameter(index=self._param_counter)
            self._param_counter += 1
            return p
        if self.at_op(":"):
            self.next()
            return A.Parameter(name=self.eat_ident())
        if self.at_op("("):
            self.next()
            if self.peek().kind == "IDENT" and self.peek().text.upper() in (
                "SELECT",
                "MATCH",
                "TRAVERSE",
            ):
                sub = self.parse_statement()
                self.eat_op(")")
                return A.FunctionCall("$subquery", (A.Literal(sub),))
            expr = self.parse_expression()
            self.eat_op(")")
            return expr
        if self.at_op("["):
            self.next()
            items = [] if self.at_op("]") else self.parse_expr_list()
            self.eat_op("]")
            return A.ListExpr(tuple(items))
        if self.at_op("{"):
            self.next()
            pairs = []
            while not self.at_op("}"):
                kt = self.next()
                if kt.kind not in ("IDENT", "STRING"):
                    raise ParseError("expected map key", kt)
                self.eat_op(":")
                pairs.append((kt.value, self.parse_expression()))
                self.try_op(",")
            self.eat_op("}")
            return A.MapExpr(tuple(pairs))
        if self.at_op("*"):
            self.next()
            return A.Star()
        if t.kind == "IDENT":
            word = t.text.upper()
            if word == "TRUE":
                self.next()
                return A.Literal(True)
            if word == "FALSE":
                self.next()
                return A.Literal(False)
            if word == "NULL":
                self.next()
                return A.Literal(None)
            name = self.eat_ident()
            if self.at_op("("):
                self.next()
                if self.try_op("*"):
                    self.eat_op(")")
                    return A.FunctionCall(name.lower(), (A.Star(),))
                args = [] if self.at_op(")") else self.parse_expr_list()
                self.eat_op(")")
                return A.FunctionCall(name.lower(), tuple(args))
            return A.Identifier(name)
        raise ParseError("expected expression", t)


def parse(text: str) -> A.Statement:
    """Parse one SQL statement (analog of [E] OStatementCache.parse)."""
    p = Parser(text)
    stmt = p.parse_statement()
    p.expect_eof()
    return stmt
