from orientdb_tpu.sql.parser import parse, ParseError

__all__ = ["parse", "ParseError"]
