"""SQL abstract syntax tree.

Analog of OrientDB's parser AST ([E] core/.../sql/parser/ — one class per
JavaCC production: OStatement, OSelectStatement, OMatchStatement,
OTraverseStatement, OWhereClause, OExpression…; SURVEY.md §2 "SQL parser").
The reference generates ~80k LoC from a JavaCC grammar; here the AST is a
compact set of dataclasses produced by a hand-written recursive-descent
parser (`orientdb_tpu/sql/parser.py`) — pure data, consumed by BOTH the
pure-Python oracle interpreter (`exec/oracle.py`) and the TPU MATCH compiler
(`exec/tpu_engine.py`), which is what keeps the two engines parity-testable.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Expressions
# ---------------------------------------------------------------------------


class Expression:
    """Base class ([E] OExpression)."""

    __slots__ = ()


@dataclasses.dataclass(frozen=True)
class Literal(Expression):
    value: object


@dataclasses.dataclass(frozen=True)
class Star(Expression):
    """`*` in projections / count(*)."""


@dataclasses.dataclass(frozen=True)
class Identifier(Expression):
    """A bare name: field, alias, or class, resolved at eval time."""

    name: str


@dataclasses.dataclass(frozen=True)
class Parameter(Expression):
    """Named `:name` or positional `?` query parameter."""

    name: Optional[str] = None
    index: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class ContextVar(Expression):
    """`$depth`, `$path`, `$current`, `$parent`, `$matched`, `$matches`…"""

    name: str  # without the leading $


@dataclasses.dataclass(frozen=True)
class RIDLiteral(Expression):
    cluster: int
    position: int


@dataclasses.dataclass(frozen=True)
class ListExpr(Expression):
    items: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class MapExpr(Expression):
    pairs: Tuple[Tuple[str, Expression], ...]


@dataclasses.dataclass(frozen=True)
class FieldAccess(Expression):
    """`base.name` (document field / result property / map key)."""

    base: Expression
    name: str


@dataclasses.dataclass(frozen=True)
class IndexAccess(Expression):
    """`base[index]`."""

    base: Expression
    index: Expression


@dataclasses.dataclass(frozen=True)
class MethodCall(Expression):
    """`base.name(args…)` — graph methods out()/in()/both()/outE()… and
    item methods size()/toLowerCase()/asString()…"""

    base: Expression
    name: str
    args: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class FunctionCall(Expression):
    """Top-level `name(args…)`: aggregates and SQL functions."""

    name: str
    args: Tuple[Expression, ...]


@dataclasses.dataclass(frozen=True)
class Unary(Expression):
    op: str  # 'NOT' | '-' | '+'
    expr: Expression


@dataclasses.dataclass(frozen=True)
class Binary(Expression):
    """Binary operator. op is normalized upper-case: AND OR = != < <= > >=
    + - * / % LIKE IN CONTAINS CONTAINSANY CONTAINSALL CONTAINSKEY
    CONTAINSVALUE CONTAINSTEXT MATCHES INSTANCEOF."""

    op: str
    left: Expression
    right: Expression


@dataclasses.dataclass(frozen=True)
class Between(Expression):
    expr: Expression
    low: Expression
    high: Expression


@dataclasses.dataclass(frozen=True)
class IsNull(Expression):
    expr: Expression
    negated: bool  # IS NOT NULL


@dataclasses.dataclass(frozen=True)
class IsDefined(Expression):
    expr: Expression
    negated: bool  # IS NOT DEFINED


# ---------------------------------------------------------------------------
# Statements
# ---------------------------------------------------------------------------


class Statement:
    """Base class ([E] OStatement)."""

    __slots__ = ()

    #: idempotent statements may run through Database.query()
    is_idempotent = False


@dataclasses.dataclass(frozen=True)
class Projection:
    expr: Expression
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class OrderByItem:
    expr: Expression
    ascending: bool = True


# -- FROM targets -----------------------------------------------------------


class Target:
    __slots__ = ()


@dataclasses.dataclass(frozen=True)
class ClassTarget(Target):
    name: str
    polymorphic: bool = True  # FROM Class; FROM CLUSTER:x is separate


@dataclasses.dataclass(frozen=True)
class ClusterTarget(Target):
    name_or_id: object  # cluster name (str) or id (int)


@dataclasses.dataclass(frozen=True)
class RidTarget(Target):
    rids: Tuple[RIDLiteral, ...]


@dataclasses.dataclass(frozen=True)
class IndexTarget(Target):
    """FROM INDEX:name — scans index entries as {key, rid} rows."""

    name: str


@dataclasses.dataclass(frozen=True)
class SubQueryTarget(Target):
    query: "Statement"


@dataclasses.dataclass(frozen=True)
class ExpressionTarget(Target):
    """FROM (expression) producing records, e.g. a parameter of RIDs."""

    expr: Expression


# -- SELECT -----------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LetItem:
    name: str  # without the $
    value: object  # Expression or Statement (subquery)


@dataclasses.dataclass(frozen=True)
class SelectStatement(Statement):
    projections: Tuple[Projection, ...]  # empty => select whole record
    target: Optional[Target]
    where: Optional[Expression] = None
    group_by: Tuple[Expression, ...] = ()
    order_by: Tuple[OrderByItem, ...] = ()
    unwind: Tuple[str, ...] = ()
    skip: Optional[Expression] = None
    limit: Optional[Expression] = None
    lets: Tuple[LetItem, ...] = ()
    timeout_ms: Optional[int] = None
    distinct: bool = False

    is_idempotent = True


# -- MATCH ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MatchFilter:
    """The `{...}` node filter ([E] OMatchFilter): keys class/as/rid/where/
    while/maxDepth/optional/depthAlias/pathAlias."""

    alias: Optional[str] = None
    class_name: Optional[str] = None
    rid: Optional[RIDLiteral] = None
    where: Optional[Expression] = None
    while_cond: Optional[Expression] = None
    max_depth: Optional[int] = None
    optional: bool = False
    depth_alias: Optional[str] = None
    path_alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class MatchPathItem:
    """One arrow ([E] OMatchPathItem / PatternEdge source syntax).

    Either arrow form (`-EdgeClass->`, `<-EC-`, `-EC-`) or method form
    (`.out('EC')`, `.inE('EC')`…). ``edge_filter`` holds `{...}` placed on
    the arrow's edge braces for edge-property predicates; ``target`` is the
    destination node filter.
    """

    direction: str  # 'out' | 'in' | 'both'
    edge_classes: Tuple[str, ...]  # empty = any edge class
    target: MatchFilter
    edge_filter: Optional[MatchFilter] = None
    method: Optional[str] = None  # out/in/both/outE/inE/bothE/outV/inV when method form
    negated: bool = False  # NOT pattern arrow


@dataclasses.dataclass(frozen=True)
class MatchPath:
    """`{first} item item …` — one comma-separated pattern arm."""

    first: MatchFilter
    items: Tuple[MatchPathItem, ...]
    negated: bool = False  # NOT {..}-..->{..} arm


@dataclasses.dataclass(frozen=True)
class MatchStatement(Statement):
    paths: Tuple[MatchPath, ...]
    returns: Tuple[Projection, ...]
    distinct: bool = False
    group_by: Tuple[Expression, ...] = ()
    order_by: Tuple[OrderByItem, ...] = ()
    unwind: Tuple[str, ...] = ()
    skip: Optional[Expression] = None
    limit: Optional[Expression] = None

    is_idempotent = True


# -- TRAVERSE ---------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class TraverseStatement(Statement):
    """[E] OTraverseStatement: TRAVERSE <fields> FROM <target>
    [MAXDEPTH n] [WHILE cond] [LIMIT n] [STRATEGY s]."""

    fields: Tuple[Expression, ...]  # projection-ish: out(), in(), *, field names
    target: Optional[Target]
    max_depth: Optional[int] = None
    while_cond: Optional[Expression] = None
    limit: Optional[Expression] = None
    strategy: str = "DEPTH_FIRST"  # or BREADTH_FIRST

    is_idempotent = True


# -- DML --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class InsertStatement(Statement):
    class_name: Optional[str]
    cluster: Optional[str] = None
    set_fields: Tuple[Tuple[str, Expression], ...] = ()
    content: Optional[Expression] = None  # MapExpr
    from_select: Optional[Statement] = None
    return_expr: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class UpdateOp:
    kind: str  # SET | INCREMENT | REMOVE | PUT | ADD | CONTENT | MERGE
    items: Tuple[Tuple[str, Expression], ...]


@dataclasses.dataclass(frozen=True)
class UpdateStatement(Statement):
    target: Target
    ops: Tuple[UpdateOp, ...]
    upsert: bool = False
    where: Optional[Expression] = None
    limit: Optional[Expression] = None
    return_mode: Optional[str] = None  # COUNT | BEFORE | AFTER


@dataclasses.dataclass(frozen=True)
class DeleteStatement(Statement):
    target: Target
    where: Optional[Expression] = None
    limit: Optional[Expression] = None
    # kind: RECORD (DELETE FROM), VERTEX (DELETE VERTEX), EDGE (DELETE EDGE)
    kind: str = "RECORD"
    edge_from: Optional[Expression] = None  # DELETE EDGE FROM x TO y
    edge_to: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class CreateVertexStatement(Statement):
    class_name: str = "V"
    set_fields: Tuple[Tuple[str, Expression], ...] = ()
    content: Optional[Expression] = None


@dataclasses.dataclass(frozen=True)
class CreateEdgeStatement(Statement):
    class_name: str
    from_expr: Expression  # rid / subquery / list
    to_expr: Expression
    set_fields: Tuple[Tuple[str, Expression], ...] = ()
    content: Optional[Expression] = None


# -- DDL --------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CreateClassStatement(Statement):
    name: str
    superclasses: Tuple[str, ...] = ()
    abstract: bool = False
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreatePropertyStatement(Statement):
    class_name: str
    property_name: str
    property_type: str
    linked_class: Optional[str] = None
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateIndexStatement(Statement):
    name: str
    class_name: Optional[str]
    fields: Tuple[str, ...]
    index_type: str
    #: [E] the Lucene module's CREATE INDEX ... ENGINE LUCENE form
    engine: Optional[str] = None
    #: METADATA {...} literal (e.g. {"analyzer": "english"})
    metadata: Optional["Expression"] = None


@dataclasses.dataclass(frozen=True)
class DropClassStatement(Statement):
    name: str
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class DropPropertyStatement(Statement):
    class_name: str
    property_name: str


@dataclasses.dataclass(frozen=True)
class DropIndexStatement(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class AlterPropertyStatement(Statement):
    class_name: str
    property_name: str
    attribute: str  # MANDATORY | NOTNULL | READONLY | MIN | MAX
    value: Expression


@dataclasses.dataclass(frozen=True)
class CreateSequenceStatement(Statement):
    """[E] OSequence DDL: CREATE SEQUENCE s TYPE ORDERED START n INCREMENT n."""

    name: str
    seq_type: str = "ORDERED"
    start: int = 0
    increment: int = 1
    cache: int = 20


@dataclasses.dataclass(frozen=True)
class AlterSequenceStatement(Statement):
    name: str
    start: Optional[int] = None
    increment: Optional[int] = None
    cache: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class DropSequenceStatement(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class CreateFunctionStatement(Statement):
    """[E] OFunction DDL: CREATE FUNCTION name "body" PARAMETERS [a,b]."""

    name: str
    body: str
    parameters: Tuple[str, ...] = ()
    idempotent: bool = True
    language: str = "sql"


@dataclasses.dataclass(frozen=True)
class DropFunctionStatement(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class TruncateClassStatement(Statement):
    """[E] OTruncateClassStatement: TRUNCATE CLASS <name> [POLYMORPHIC]
    [UNSAFE] — delete every record of the class (vertices cascade their
    incident edges unless UNSAFE skips graph consistency)."""

    class_name: str
    polymorphic: bool = False
    unsafe: bool = False


@dataclasses.dataclass(frozen=True)
class TruncateRecordStatement(Statement):
    """[E] OTruncateRecordStatement: TRUNCATE RECORD <rid>[, <rid>…]."""

    rids: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class AlterClassStatement(Statement):
    """[E] OAlterClassStatement: ALTER CLASS <name> <attribute> <value>.
    Supported attributes: NAME (rename), SUPERCLASS (+Name / -Name),
    STRICTMODE, ABSTRACT."""

    class_name: str
    attribute: str
    value: object  # str | bool | ("+"|"-", name)


@dataclasses.dataclass(frozen=True)
class MoveVertexStatement(Statement):
    """[E] OMoveVertexStatement: MOVE VERTEX <rid|(subquery)> TO
    CLASS:<name> — re-home vertices into another class, rewiring every
    incident edge to the new rid."""

    source: object  # rid string or SelectStatement
    target_class: str


@dataclasses.dataclass(frozen=True)
class RebuildIndexStatement(Statement):
    """[E] ORebuildIndexStatement: REBUILD INDEX <name|*> — drop the
    entries and re-index from a full class scan."""

    name: str  # "*" rebuilds every index


@dataclasses.dataclass(frozen=True)
class GrantStatement(Statement):
    """[E] OGrantStatement: GRANT <permission> ON <resource> TO <role>."""

    permission: str
    resource: str
    role: str


@dataclasses.dataclass(frozen=True)
class RevokeStatement(Statement):
    """[E] ORevokeStatement: REVOKE <permission> ON <resource> FROM <role>."""

    permission: str
    resource: str
    role: str


@dataclasses.dataclass(frozen=True)
class CreateUserStatement(Statement):
    """[E] OCreateUserStatement (3.x): CREATE USER u IDENTIFIED BY pw
    [ROLE [r1,r2]]."""

    name: str
    password: str
    roles: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class DropUserStatement(Statement):
    name: str


@dataclasses.dataclass(frozen=True)
class FindReferencesStatement(Statement):
    """[E] OFindReferencesStatement: FIND REFERENCES <rid> [[Class,…]] —
    every record whose link/linklist fields point at the rid."""

    rid: str
    classes: Tuple[str, ...] = ()

    is_idempotent = True


# -- misc -------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ExplainStatement(Statement):
    inner: Statement
    profile: bool = False  # PROFILE actually executes and times

    is_idempotent = True


@dataclasses.dataclass(frozen=True)
class BeginStatement(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class CommitStatement(Statement):
    retries: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class RollbackStatement(Statement):
    pass


@dataclasses.dataclass(frozen=True)
class LiveSelectStatement(Statement):
    """LIVE SELECT FROM <class> — push notifications on matching changes
    ([E] OLiveQueryHookV2, SURVEY.md §2 'Live queries / hooks')."""

    inner: SelectStatement

    is_idempotent = True
