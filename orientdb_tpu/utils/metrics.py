"""Process-wide metrics registry.

Analog of the reference's ``OProfiler``/``OAbstractProfiler`` ([E]
core/.../common/profiler/; SURVEY.md §5.1/§5.5): named counters and
duration stats, exported over the HTTP server's ``/metrics`` endpoint
(the JMX/`/profiler` analog) and readable in-process for tests.

Two primitive kinds, both thread-safe:
- counters   — ``incr("query.tpu")``
- durations  — ``observe("query.tpu.dispatch", seconds)`` keeping
  count/total/max so rates and tails are recoverable.
"""

from __future__ import annotations

import threading
from typing import Dict


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._durations: Dict[str, Dict[str, float]] = {}
        self._gauges: Dict[str, float] = {}

    def incr(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        """Set an instantaneous value (e.g. per-device HBM bytes)."""
        with self._lock:
            self._gauges[name] = value

    def gauge_value(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def drop_gauge(self, name: str) -> None:
        """Remove a gauge so the series goes ABSENT in the exposition —
        the honest shape for "no current data" (a window-derived gauge
        whose window emptied must not keep exporting its last value as
        if it were live)."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            d = self._durations.get(name)
            if d is None:
                d = self._durations[name] = {"count": 0, "total_s": 0.0, "max_s": 0.0}
            d["count"] += 1
            d["total_s"] += seconds
            d["max_s"] = max(d["max_s"], seconds)

    def counter(self, name: str) -> int:
        with self._lock:
            return self._counters.get(name, 0)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "durations": {k: dict(v) for k, v in self._durations.items()},
                "gauges": dict(self._gauges),
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._durations.clear()
            self._gauges.clear()


#: the process-wide instance (the reference's OProfiler is a singleton too)
metrics = MetricsRegistry()


class timed:
    """Context manager: ``with timed("query.tpu.dispatch"): ...``"""

    def __init__(self, name: str) -> None:
        self.name = name

    def __enter__(self):
        import time

        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        import time

        metrics.observe(self.name, time.perf_counter() - self._t0)
        return False
