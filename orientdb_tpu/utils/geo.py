"""Shared geodesic constants + host haversine.

Oracle evaluation (`exec/eval.py`), the device predicate compiler
(`ops/predicates.py`), and the spatial index probe (`exec/oracle.py`)
must agree bit-for-bit on these for engine parity — one definition site
([E] OSQLFunctionDistance's constants)."""

from __future__ import annotations

import math

#: mean earth radius, km ([E] OSQLFunctionDistance)
EARTH_RADIUS_KM = 6371.0

#: km → miles scale for the optional unit argument
MILES_PER_KM = 0.621371192

#: accepted spellings of the miles unit argument
MILE_UNITS = frozenset(("mi", "mile", "miles"))


def haversine_km(lat1, lon1, lat2, lon2) -> float:
    lat1, lon1, lat2, lon2 = (
        math.radians(float(v)) for v in (lat1, lon1, lat2, lon2)
    )
    h = (
        math.sin((lat2 - lat1) / 2) ** 2
        + math.cos(lat1) * math.cos(lat2) * math.sin((lon2 - lon1) / 2) ** 2
    )
    return 2.0 * EARTH_RADIUS_KM * math.asin(min(1.0, math.sqrt(h)))
