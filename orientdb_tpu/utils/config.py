"""Typed global configuration.

Analog of OrientDB's ``OGlobalConfiguration`` enum of typed keys
([E] core/.../config/OGlobalConfiguration.java, SURVEY.md §5.6), redesigned as
a single dataclass with environment-variable overrides (``ORIENTTPU_<FIELD>``)
instead of JVM system properties.

The per-session ``TRAVERSE_ENGINE`` switch (north star: sessions set
``TRAVERSE_ENGINE=tpu`` to route MATCH through the TPU backend instead of the
interpreted per-record path) lives here as the *default*; sessions may
override it per query.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional


def _env(name: str, default, cast):
    raw = os.environ.get(f"ORIENTTPU_{name.upper()}")
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclasses.dataclass
class GlobalConfiguration:
    # Query engine selection: "tpu" (compiled batched path), "oracle"
    # (pure-Python reference interpreter — the parity oracle), or "auto"
    # (tpu when a snapshot is attached, oracle otherwise).
    traverse_engine: str = "auto"

    # Expansion/compaction buffers are padded to powers of two >= this to
    # bound recompilation while keeping buffers small (ops/csr.bucket).
    min_expansion_cap: int = 8
    # Hard ceiling on a single expansion output buffer (rows). Expansions
    # that would exceed it are chunked over the binding table
    # (tpu_engine._expand_one_dir_chunked).
    max_expansion_cap: int = 1 << 22

    # Byte budget for one variable-depth frontier bitmap chunk
    # ([rows, bucket(V)] bools): the chunk row count shrinks as the graph
    # grows so deep-traversal memory stays bounded at SF100-scale vertex
    # counts (SURVEY.md §5.7).
    var_depth_bitmap_budget: int = 1 << 26

    # Buffer headroom multiplier for recorded size schedules: compiled
    # plans size buffers at bucket(observed * headroom), so
    # parameter-generic replays tolerate result sets up to that much
    # larger before an overflow re-record. 1.0 = exact-bucket sizing.
    schedule_headroom: float = 2.0

    # Extra empty BFS levels recorded past frontier exhaustion in
    # variable-depth (WHILE) plans: replays whose walks go up to this many
    # levels deeper than the recording still execute in place instead of
    # re-recording (depth varies with the query parameter).
    var_depth_pad_levels: int = 2

    # Schedule variants kept per cached statement: parameter values whose
    # live sizes exceed every variant's capacities record a new variant
    # rather than thrash-replacing one plan.
    plan_variants: int = 8

    # Plan cache entries (analog of OExecutionPlanCache [E]).
    plan_cache_size: int = 256

    # Device-memory budget for a replay's pre-materialized result page
    # ladder (pow2 prefixes in int32+int16, ~12 bytes/slot total): plans
    # whose ladder would exceed this emit only the full-width buffers, so
    # wide plans never triple their result memory under deep batches.
    result_page_budget_bytes: int = 16 << 20

    # Full result buffers at or below this many bytes skip the
    # meta-gated page election entirely: the replay returns ONE fused
    # buffer (data + meta row) whose copy starts in the batch's first
    # transfer wave. On the tunneled link every buffer fetch carries a
    # fixed cost, so for few-KB results one fused copy beats the
    # meta-then-elected-page protocol (the round-3 LDBC IS3–IS7
    # regression); above the threshold the election's byte savings win.
    result_direct_bytes: int = 64 << 10

    # Root candidates seed from a host index when the root WHERE has an
    # equality over an indexed field ([E] the index-vs-scan choice):
    # point lookups become V-independent instead of hull scans.
    index_root_seed: bool = True

    # Row-returning plans join the vmapped group dispatch when one
    # lane's full int32 result stack fits this budget (the group stacks
    # B of them on device); bigger plans keep per-lane dispatch + page
    # election.
    result_group_lane_bytes: int = 4 << 20

    # Vmapped group lanes materialize O(E) int32 intermediates in the
    # fused edge-predicate select; the group width is capped so
    # lanes × 4E stays inside this budget (v5e chips carry 16 GB HBM;
    # the graph itself plus runtime overhead take the rest). Oversized
    # batches dispatch as several capped Executes instead of OOMing
    # the compile and falling back to per-lane.
    group_hbm_budget_bytes: int = 6 << 30

    # Per-query property-column pruning (SURVEY.md §7's SF100 memory
    # plan): property columns upload to HBM on a plan's first reference
    # instead of eagerly at snapshot attach — columns no query touches
    # never cost device memory. False restores eager uploads.
    column_prune: bool = True

    # Query RESULT cache ([E] OCommandCache) — rows of idempotent queries
    # keyed by (sql, params, engine), invalidated by the mutation epoch.
    # Disabled by default, matching the reference.
    command_cache_enabled: bool = False
    command_cache_size: int = 512
    # Parsed-statement cache entries (analog of OStatementCache [E]).
    statement_cache_size: int = 1024

    # Sharding: device-mesh axis names (parallel/mesh_graph.py shards the
    # CSR over the shard axis; replicas carry independent query streams).
    mesh_shard_axis: str = "shards"
    mesh_replica_axis: str = "replicas"

    # Observability (orientdb_tpu/obs): queries slower than this many
    # milliseconds enter the slow-query log (0 disables); the ring keeps
    # the most recent slowlog_capacity entries, and the span tracer keeps
    # the most recent trace_capacity finished spans.
    slow_query_ms: float = 1000.0
    slowlog_capacity: int = 256
    trace_capacity: int = 4096
    # Query statistics & continuous profiling (obs/stats, obs/profile):
    # fraction of queries/traces folded into the per-fingerprint stats
    # table and the span-profile aggregator (1.0 = everything, 0
    # disables); the table keeps the query_stats_capacity hottest
    # fingerprints (LRU).
    stats_sample_rate: float = 1.0
    query_stats_capacity: int = 512

    # Dispatch flight recorder (obs/timeline): bounded ring of
    # per-dispatch lifecycle records (enqueue → lane window → plan
    # resolve → upload/ring hit → device dispatch → compute done →
    # transfer → result delivered) feeding the overlap accounting pass,
    # GET /debug/timeline (Chrome-trace/Perfetto export), and the
    # orienttpu_overlap_* gauges. timeline_capacity is the ring size
    # (0 disables recording entirely); recording also rides the
    # stats_sample_rate sampling decision. timeline_window_s bounds the
    # default export/accounting window (scrape-time gauges, the HTTP
    # endpoint's default, the debug bundle's timeline section).
    timeline_capacity: int = 2048
    timeline_window_s: float = 120.0

    # Critical-path attribution (obs/critpath; README "Critical-path
    # attribution"): each sampled request (the stats_sample_rate
    # decision) becomes a waterfall of named segments feeding
    # GET /stats/critpath, the per-SloClass rollups, and the
    # latency_regression alert's blame annotation. critpath_enabled
    # turns the plane off entirely; critpath_capacity bounds the ring
    # of recent decompositions (0 keeps aggregates but no ring);
    # critpath_blame_ratio is the fractional per-segment growth
    # (current window vs older history) a segment must show before the
    # blame diff names it.
    critpath_enabled: bool = True
    critpath_capacity: int = 512
    critpath_blame_ratio: float = 0.25

    # Admission control (server/http_server, server/binary_server):
    # shed WRITE requests with 503 + Retry-After when the listener's
    # in-flight depth or a database's staged-2PC backlog crosses these
    # thresholds — bounded queues beat collapse under overload. The
    # internal replication/2PC routes are exempt (shedding a phase-2
    # commit would CREATE in-doubt transactions). 0 disables a check.
    http_max_inflight: int = 128
    tx2pc_staged_max: int = 256
    # the Retry-After hint handed to shed clients; the shared
    # RetryPolicy (parallel/resilience) honors it over its own backoff
    retry_after_s: float = 0.5

    # Cross-session micro-batching (server/coalesce): concurrent
    # sessions' single queries land in per-database dispatch LANES
    # keyed by query fingerprint, so a drain forms a homogeneous
    # micro-batch hitting one compiled plan. Each lane's collection
    # window adapts to recent arrival rate and device time per batch,
    # hard-capped at coalesce_window_max_ms — the cap bounds the p50 a
    # lone query can lose to batch formation. A drain takes at most
    # coalesce_max_batch items; a lane idle longer than
    # coalesce_lane_idle_s stops its worker thread (a fresh submit
    # rebuilds it), and a database keeps at most coalesce_lanes_max
    # lanes (least-recently-used lane reaped past that).
    coalesce_window_max_ms: float = 5.0
    coalesce_max_batch: int = 256
    coalesce_lane_idle_s: float = 30.0
    coalesce_lanes_max: int = 64

    # Change-data-capture (orientdb_tpu/cdc): per-consumer event queues
    # are bounded at cdc_queue_max — a slow consumer either blocks the
    # producer (policy "block", bounded by cdc_poll_timeout_s) or sheds
    # its queue and transparently catches back up from the WAL.
    # cdc_poll_timeout_s also caps the default HTTP /changes long-poll
    # wait. Durable named cursors idle longer than
    # cdc_cursor_retention_s seconds are pruned at the next ack
    # (0 disables pruning).
    cdc_queue_max: int = 1024
    cdc_poll_timeout_s: float = 10.0
    cdc_cursor_retention_s: float = 7 * 86400.0

    # Alerting & health watchdog (obs/alerts, obs/watchdog): the
    # watchdog thread starts with Server and evaluates the alert-rule
    # catalog every watchdog_interval_s seconds over the registry
    # snapshot — nothing runs on the query hot path. A rule must breach
    # for alert_pending_ticks consecutive ticks before its alert fires
    # (pending -> firing); resolved alerts land in a bounded history
    # ring of alert_history_capacity entries.
    watchdog_enabled: bool = True
    watchdog_interval_s: float = 5.0
    alert_pending_ticks: int = 2
    alert_history_capacity: int = 256
    # Per-rule thresholds (the built-in catalog; README "Alerting &
    # health watchdog" documents each rule):
    alert_repl_lag_entries: int = 64
    alert_indoubt_age_s: float = 30.0
    alert_cdc_queue_depth: int = 512
    alert_wal_bytes: int = 1 << 30
    alert_rss_bytes: int = 12 << 30
    alert_jax_buffer_bytes: int = 14 << 30
    alert_recompiles_per_min: float = 30.0
    # Latency-regression baseline: a fingerprint's per-tick mean must
    # exceed its online EWMA by alert_latency_mads deviations (EWMA of
    # absolute deviation, the online MAD analog) with at least
    # alert_latency_min_calls calls in the tick to breach.
    alert_latency_mads: float = 6.0
    alert_latency_min_calls: int = 20
    # Two-window error-budget burn rate: breach when the short AND long
    # window error rates both exceed alert_burn_factor x the SLO
    # error-rate target.
    alert_slo_error_rate: float = 0.05
    alert_burn_factor: float = 4.0
    # Overlap-regression rule (obs/timeline + obs/alerts): the
    # device-idle fraction over the recent timeline window must exceed
    # its online EWMA baseline by alert_overlap_idle_mads deviations to
    # breach, and only when the window holds at least
    # alert_overlap_min_records dispatch records (idle computed over
    # two dispatches is noise, not regression evidence).
    alert_overlap_idle_mads: float = 6.0
    alert_overlap_min_records: int = 16

    # Trace-correlated logging (utils/logging): the bounded in-memory
    # ring of recent structured log records fed into the debug bundle's
    # admin-only "logs" section.
    log_ring_capacity: int = 512

    # Traffic simulator (workloads/driver): defaults for the closed-
    # loop mixed LDBC driver — concurrent client sessions (split HTTP/
    # binary), operations per session, the SNB-shaped write fraction of
    # the mix, and the settle window after chaos clears (replicas catch
    # up, breakers half-open, alerts resolve) before the SLO verdict.
    workload_sessions: int = 8
    workload_ops: int = 50
    workload_update_ratio: float = 0.1
    workload_settle_s: float = 8.0
    # SLO verdicts (obs/slo): default per-query-class targets a spec
    # inherits when a class declares none — p50/p99 latency ceilings
    # (milliseconds, read from the query-stats histograms), minimum
    # per-class success rate, and the error-budget burn ceiling (run
    # error rate over alert_slo_error_rate; > slo_max_burn fails).
    slo_p50_ms: float = 500.0
    slo_p99_ms: float = 5000.0
    slo_availability: float = 0.99
    slo_max_burn: float = 1.0

    # Incremental HBM snapshot maintenance (storage/deltas): a
    # delta-maintained snapshot pre-allocates this many spare vertex
    # rows and per-edge-class spare edge slots; committed writes apply
    # as device-side scatter patches into them instead of detaching the
    # snapshot. When the fullest slab (or the tombstone fraction)
    # crosses delta_compact_ratio, the maintainer folds the slabs back
    # into a clean CSR (epoch compaction, storage/epochs idiom).
    delta_slab_vertex_rows: int = 1024
    delta_slab_edge_slots: int = 4096
    delta_compact_ratio: float = 0.75

    # Tiered snapshots (storage/tiering; README "Tiered snapshots &
    # HBM cap"): when tier_hbm_cap_bytes > 0 and a snapshot's flat
    # adjacency exceeds it, admission attaches a TierManager — the
    # adjacency pages between a device-resident hot pool and host-pinned
    # cold blocks instead of uploading flat. 0 disables tiering.
    # tier_block_edges sets the target edges per block (the quotient
    # blocking widens a block that lands on a hub vertex rather than
    # splitting it). alert_tier_thrash is the tier_thrash alert
    # threshold: thrash events (reload of a recently evicted block)
    # per thrash window before the rule fires.
    tier_hbm_cap_bytes: int = 0
    tier_block_edges: int = 65536
    alert_tier_thrash: float = 8.0

    # Device-memory ledger (obs/memledger; README "Device-memory
    # ledger"): every serving-path device allocation registers an
    # attributed entry. memledger_sample_rate throttles only the
    # trace-id capture (byte totals stay exact — the sampled fast path
    # that holds registration under the <1.35x overhead guard).
    # memledger_leak_s is the lease age past which an outstanding
    # snapshot retain() reads as an epoch leak (hbm_epoch_leak rule;
    # 0 disables). memledger_tolerance bounds the live-but-untracked
    # residue reconcile() accepts as an instrumentation gap.
    # memledger_headroom_fraction of tier.cap_bytes is where the
    # hbm_headroom rule fires.
    memledger_enabled: bool = True
    memledger_sample_rate: float = 1.0
    memledger_leak_s: float = 30.0
    memledger_watermark_capacity: int = 256
    memledger_tolerance: float = 0.25
    memledger_headroom_fraction: float = 0.9

    # Materialized continuous MATCH views (exec/views): results of hot
    # fingerprints (>= view_min_calls recorded calls in the stats
    # table) are kept resident and served at cache speed, invalidated
    # CDC-EXACTLY — only events touching a view's class footprint kill
    # it, so unrelated writes never cost a recompute (unlike the
    # epoch-keyed command cache). view_cache_size bounds entries per
    # database; 0 disables the plane.
    view_min_calls: int = 8
    view_cache_size: int = 64

    # Device fault domain (exec/devicefault; README "Failure modes &
    # recovery"): every dispatch/fetch path runs under an escalation
    # ladder — classify, retry (devicefault_retry_attempts attempts
    # within devicefault_retry_budget_s seconds under the shared
    # RetryPolicy), memledger-guided relief on OOM, then quarantine the
    # plan's fingerprint to the oracle for devicefault_quarantine_ttl_s
    # seconds (probe re-admission after; failed probes double the TTL).
    # When relief leaves the memledger total above
    # devicefault_headroom_fraction x tier_hbm_cap_bytes (or an OOM
    # survives relief), the admission plane sheds writes with 503 +
    # Retry-After for devicefault_shed_s seconds.
    # alert_device_faults_per_min is the device_fault_storm rule's
    # classified-faults-per-minute threshold.
    devicefault_retry_attempts: int = 3
    devicefault_retry_budget_s: float = 2.0
    devicefault_quarantine_ttl_s: float = 15.0
    devicefault_shed_s: float = 2.0
    devicefault_headroom_fraction: float = 0.9
    alert_device_faults_per_min: float = 60.0

    # Continuous correctness plane (exec/audit, storage/scrub; README
    # "Continuous correctness: parity audits, scrub & fsck"):
    # audit_sample_rate is the fraction of compiled results shadow-
    # re-executed on the pure-Python oracle and digest-compared (rides
    # the stats sampling decision; 0 disables the auditor — the
    # default, parity audits are opt-in per deployment). The bounded
    # audit queue holds audit_queue_max captures (overflow drops count
    # parity.audit_dropped); a divergence record samples up to
    # audit_diff_rows rows per side and the replayable divergence ring
    # keeps audit_history_capacity records. scrub_enabled runs one
    # budgeted device-state scrub rotation per watchdog tick,
    # re-hashing at most scrub_budget_bytes of resident device blocks
    # against host-truth checksums per sweep.
    audit_sample_rate: float = 0.0
    audit_queue_max: int = 256
    audit_diff_rows: int = 5
    audit_history_capacity: int = 64
    scrub_enabled: bool = True
    scrub_budget_bytes: int = 16 << 20

    # Alert threshold (obs/alerts delta_slab_pressure): fires when the
    # snapshot.delta.slab_fill gauge crosses this fraction — deltas are
    # outpacing compaction.
    alert_slab_fill: float = 0.9

    # WAL / durability for the host record store
    # (orientdb_tpu.storage.durability): when wal_enabled and wal_dir are
    # set, server-created databases recover-or-create durably under
    # <wal_dir>/<name>; embedded databases opt in via
    # enable_durability/open_database. wal_fsync fsyncs every append.
    wal_enabled: bool = False
    wal_dir: Optional[str] = None
    wal_fsync: bool = False
    # fsync'd appends route through the C++ group-commit appender
    # (native/walappend.cpp) when its build is available; False pins the
    # pure-Python write+fsync path.
    wal_native: bool = True

    @classmethod
    def from_env(cls) -> "GlobalConfiguration":
        c = cls()
        for f in dataclasses.fields(cls):
            cast = f.type if isinstance(f.type, type) else None
            if cast is None:
                # dataclass stores the annotation as a string under
                # `from __future__ import annotations`
                cast = {"str": str, "int": int, "bool": bool, "float": float}.get(
                    str(f.type), str
                )
            setattr(c, f.name, _env(f.name, getattr(c, f.name), cast))
        return c


# Process-wide instance (OGlobalConfiguration is a static enum in the
# reference; a module-level singleton is the honest analog).
config = GlobalConfiguration.from_env()
