from orientdb_tpu.utils.config import GlobalConfiguration, config
from orientdb_tpu.utils.logging import get_logger

__all__ = ["GlobalConfiguration", "config", "get_logger"]
