"""Structured logging (analog of OLogManager, [E] core/.../log/OLogManager.java).

Grown into the trace-correlated half of the alerting plane (ISSUE 10):

- a **LogRecord factory** stamps every record with the active
  ``trace_id``/``span_id`` from :mod:`orientdb_tpu.obs.trace`, so a log
  line emitted inside a query's span joins that query's trace, slowlog
  entry, stats row — and any alert whose exemplar names the trace;
- ``ORIENTTPU_LOG_FORMAT=json`` switches the stream handler to
  one-JSON-object-per-line structured output (``ts``, ``level``,
  ``logger``, ``msg``, plus ``trace_id``/``span_id`` when a span is
  active). The default text format is unchanged, so existing
  log-format assertions stay green;
- a bounded in-memory **log ring** (``config.log_ring_capacity``)
  captures recent records as JSON-friendly dicts and feeds the debug
  bundle's admin-only ``logs`` section — an alert, its exemplar trace,
  and the log lines it produced are joinable by one id.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from collections import deque
from typing import Dict, List, Optional

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"
_configured = False


def _current_ids():
    """(trace_id, span_id) of the innermost active span on this
    thread, or (None, None). Lazy import: logging configures before
    the obs package (and must keep working if it cannot load)."""
    try:
        from orientdb_tpu.obs.trace import current_span

        sp = current_span()
        if sp is not None:
            return sp.trace_id, sp.span_id
    except Exception:
        pass
    return None, None


def _install_record_factory() -> None:
    """Wrap the process LogRecord factory so EVERY record carries
    ``trace_id``/``span_id`` attributes (None outside any span) —
    formatters and the ring read them without hasattr dances."""
    base = logging.getLogRecordFactory()
    if getattr(base, "_orienttpu_traced", False):
        return  # already installed (re-entrant _ensure_configured)

    def factory(*args, **kwargs):
        record = base(*args, **kwargs)
        record.trace_id, record.span_id = _current_ids()
        return record

    factory._orienttpu_traced = True
    logging.setLogRecordFactory(factory)


class JsonFormatter(logging.Formatter):
    """One JSON object per line (``ORIENTTPU_LOG_FORMAT=json``)."""

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, object] = {
            "ts": round(record.created, 3),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        tid = getattr(record, "trace_id", None)
        if tid is not None:
            out["trace_id"] = tid
            out["span_id"] = getattr(record, "span_id", None)
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=str)


class LogRing(logging.Handler):
    """Bounded ring of recent records as JSON-friendly dicts — the
    debug bundle's ``logs`` section (admin-only, like the traces that
    share its ids). Capacity re-reads ``config.log_ring_capacity`` per
    emit so tests (and a live console) can retune without restarting."""

    def __init__(self) -> None:
        super().__init__(level=logging.NOTSET)
        self._mu = threading.Lock()
        self._ring: deque = deque()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            from orientdb_tpu.utils.config import config

            cap = max(int(config.log_ring_capacity), 0)
            entry: Dict[str, object] = {
                "ts": round(record.created, 3),
                "level": record.levelname,
                "logger": record.name,
                "msg": record.getMessage(),
                "trace_id": getattr(record, "trace_id", None),
                "span_id": getattr(record, "span_id", None),
            }
            with self._mu:
                if cap <= 0:
                    self._ring.clear()
                    return
                self._ring.append(entry)
                while len(self._ring) > cap:
                    self._ring.popleft()
        except Exception:  # a log record must never crash its caller
            pass

    def entries(self, limit: Optional[int] = None) -> List[Dict]:
        """Most recent first."""
        with self._mu:
            items = list(self._ring)
        items.reverse()
        return items if limit is None else items[:limit]

    def clear(self) -> None:
        with self._mu:
            self._ring.clear()


#: the process-wide ring (mirrors obs.slowlog.slowlog); attached to the
#: package logger by _ensure_configured
log_ring = LogRing()


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("ORIENTTPU_LOG_LEVEL", "WARNING").upper()
    _install_record_factory()
    logging.basicConfig(level=getattr(logging, level, logging.WARNING), format=_FORMAT)
    if os.environ.get("ORIENTTPU_LOG_FORMAT", "").lower() == "json":
        for h in logging.getLogger().handlers:
            if isinstance(h, logging.StreamHandler):
                h.setFormatter(JsonFormatter())
    # the ring rides the package logger so only orientdb_tpu records
    # land in it, regardless of what the root logger is formatted as
    pkg = logging.getLogger("orientdb_tpu")
    if log_ring not in pkg.handlers:
        pkg.addHandler(log_ring)
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _ensure_configured()
    return logging.getLogger(f"orientdb_tpu.{name}")
