"""Structured logging (analog of OLogManager, [E] core/.../log/OLogManager.java)."""

from __future__ import annotations

import logging
import os

_FORMAT = "%(asctime)s %(levelname)s [%(name)s] %(message)s"
_configured = False


def _ensure_configured() -> None:
    global _configured
    if _configured:
        return
    level = os.environ.get("ORIENTTPU_LOG_LEVEL", "WARNING").upper()
    logging.basicConfig(level=getattr(logging, level, logging.WARNING), format=_FORMAT)
    _configured = True


def get_logger(name: str) -> logging.Logger:
    _ensure_configured()
    return logging.getLogger(f"orientdb_tpu.{name}")
