// Group-commit WAL appender.
//
// The native side of the write-ahead log's append path
// (orientdb_tpu/storage/durability.py — the [E] OWALPage/OWriteAheadLog
// fsync path, SURVEY.md §2 "WAL"). Python frames each entry
// (crc + json + newline) and enqueues it here; a dedicated flusher
// thread writes and fsyncs whole batches, so N concurrent appenders pay
// ~one fsync instead of N (classic group commit). The enqueue/wait
// split lets the Python caller allocate LSNs under its own lock while
// the durability wait happens outside it with the GIL released.
//
// C API (ctypes):
//   void*    wal_open(const char* path, int do_fsync)
//   uint64_t wal_enqueue(void* h, const char* data, uint64_t len)
//   void     wal_wait(void* h, uint64_t gen)   // blocks until durable
//   void     wal_close(void* h)                // flushes, joins, closes

#include <cerrno>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <unistd.h>

namespace {

struct Wal {
  int fd = -1;
  bool do_fsync = true;
  std::mutex mu;
  std::condition_variable cv_flush;  // work available (or stopping)
  std::condition_variable cv_done;   // a batch became durable
  std::vector<char> pending;
  uint64_t enq_gen = 0;     // generation of the last enqueued entry
  uint64_t flushed_gen = 0; // generation durable on disk
  int err = 0;              // sticky errno from write/fsync failure
  bool stop = false;
  std::thread flusher;
};

void flusher_loop(Wal* w) {
  std::unique_lock<std::mutex> lk(w->mu);
  for (;;) {
    w->cv_flush.wait(lk, [w] { return w->stop || !w->pending.empty(); });
    if (w->pending.empty()) {
      if (w->stop) return;
      continue;
    }
    std::vector<char> batch;
    batch.swap(w->pending);
    uint64_t gen = w->enq_gen;
    lk.unlock();
    int batch_err = 0;
    size_t off = 0;
    while (off < batch.size()) {
      ssize_t n = ::write(w->fd, batch.data() + off, batch.size() - off);
      if (n < 0) {
        if (errno == EINTR) continue;
        batch_err = errno ? errno : EIO;
        break;
      }
      off += static_cast<size_t>(n);
    }
    if (batch_err == 0 && w->do_fsync && ::fsync(w->fd) != 0) {
      batch_err = errno ? errno : EIO;
    }
    lk.lock();
    // waiters must always wake, but a failed batch STICKS as an error:
    // wal_wait reports it and the Python caller raises instead of
    // acknowledging a commit that never reached disk
    if (batch_err != 0 && w->err == 0) w->err = batch_err;
    w->flushed_gen = gen;
    w->cv_done.notify_all();
  }
}

}  // namespace

extern "C" {

void* wal_open(const char* path, int do_fsync) {
  int fd = ::open(path, O_WRONLY | O_CREAT | O_APPEND, 0644);
  if (fd < 0) return nullptr;
  Wal* w = new Wal();
  w->fd = fd;
  w->do_fsync = do_fsync != 0;
  w->flusher = std::thread(flusher_loop, w);
  return w;
}

uint64_t wal_enqueue(void* h, const char* data, uint64_t len) {
  Wal* w = static_cast<Wal*>(h);
  std::lock_guard<std::mutex> lk(w->mu);
  w->pending.insert(w->pending.end(), data, data + len);
  w->enq_gen += 1;
  w->cv_flush.notify_one();
  return w->enq_gen;
}

int wal_wait(void* h, uint64_t gen) {
  // returns 0 when the generation is durable, else the sticky errno
  Wal* w = static_cast<Wal*>(h);
  std::unique_lock<std::mutex> lk(w->mu);
  w->cv_done.wait(lk, [w, gen] { return w->flushed_gen >= gen; });
  return w->err;
}

void wal_close(void* h) {
  Wal* w = static_cast<Wal*>(h);
  {
    std::lock_guard<std::mutex> lk(w->mu);
    w->stop = true;
    w->cv_flush.notify_one();
  }
  w->flusher.join();
  ::fsync(w->fd);
  ::close(w->fd);
  delete w;
}

}  // extern "C"
