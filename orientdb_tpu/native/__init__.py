"""Native (C++) runtime components.

The compute path is JAX/XLA; the runtime around it goes native where
the reference's does ([E] the storage engine's fsync/IO machinery is
the hottest non-compute path). Components build on demand with the
system toolchain and degrade gracefully: a missing compiler or failed
build falls back to the pure-Python implementation, never an error.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from orientdb_tpu.utils.logging import get_logger

log = get_logger("native")

_DIR = os.path.dirname(__file__)
_BUILD_LOCK = threading.Lock()
_CACHE: dict = {}


def _build(name: str) -> Optional[str]:
    """Compile ``<name>.cpp`` → ``lib<name>.so`` next to the source (once
    per source mtime); returns the .so path or None."""
    src = os.path.join(_DIR, f"{name}.cpp")
    so = os.path.join(_DIR, f"lib{name}.so")
    if not os.path.exists(src):
        return None
    if os.path.exists(so) and os.path.getmtime(so) >= os.path.getmtime(src):
        return so
    tmp = f"{so}.{os.getpid()}.tmp"  # pid-unique: concurrent processes
    # may both rebuild; os.replace keeps the publish atomic either way
    try:
        subprocess.run(
            [
                "g++",
                "-O2",
                "-std=c++17",
                "-shared",
                "-fPIC",
                "-o",
                tmp,
                src,
                "-lpthread",
            ],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, so)
        return so
    except Exception as e:  # no g++, compile error, sandboxed fs …
        log.warning("native build of %s failed (%s); using Python path", name, e)
        return None


def load(name: str) -> Optional[ctypes.CDLL]:
    """The shared library for ``name``, building if needed; None when
    unavailable (callers use their Python fallback)."""
    with _BUILD_LOCK:
        if name in _CACHE:
            return _CACHE[name]
        so = _build(name)
        lib = None
        if so is not None:
            try:
                lib = ctypes.CDLL(so)
            except OSError as e:
                log.warning("loading %s failed: %s", so, e)
        _CACHE[name] = lib
        return lib


class WalAppender:
    """ctypes face of the group-commit WAL appender (walappend.cpp)."""

    def __init__(self, lib: ctypes.CDLL, path: str, do_fsync: bool) -> None:
        lib.wal_open.restype = ctypes.c_void_p
        lib.wal_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.wal_enqueue.restype = ctypes.c_uint64
        lib.wal_enqueue.argtypes = [
            ctypes.c_void_p,
            ctypes.c_char_p,
            ctypes.c_uint64,
        ]
        lib.wal_wait.restype = ctypes.c_int
        lib.wal_wait.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.wal_close.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._h = lib.wal_open(path.encode(), 1 if do_fsync else 0)
        if not self._h:
            raise OSError(f"wal_open failed for {path}")

    def enqueue(self, line: bytes) -> int:
        return self._lib.wal_enqueue(self._h, line, len(line))

    def wait(self, gen: int) -> None:
        # blocks in native code with the GIL released — concurrent
        # appenders framing their lines meanwhile is the group commit
        err = self._lib.wal_wait(self._h, gen)
        if err:
            # durability failed (ENOSPC, I/O error): the committing
            # caller must see it, exactly as the Python write/fsync path
            # would raise
            raise OSError(err, os.strerror(err), "wal group-commit flush")

    def close(self) -> None:
        if self._h:
            self._lib.wal_close(self._h)
            self._h = None


def wal_appender(path: str, do_fsync: bool) -> Optional[WalAppender]:
    lib = load("walappend")
    if lib is None:
        return None
    try:
        return WalAppender(lib, path, do_fsync)
    except OSError:
        return None
