"""Deterministic, seedable fault injection with NAMED points.

The chaos tests so far monkeypatch one transport method per test
(``QuorumPusher._post`` in ``tests/test_replication_chaos.py``) — a
shape that cannot compose (one wrapper per test), cannot target the
other channels (forwarding, 2PC phases, WAL fsync, the binary framing)
and is not reproducible across runs. This module makes fault injection
first-class:

- every inter-node I/O site (and the WAL fsync) is wrapped in a NAMED
  injection point: ``with fault.point("repl.push"): urlopen(...)``.
  The catalog is :data:`POINTS`; the AST lint
  (``orientdb_tpu/chaos/iolint.py``) keeps new channels from bypassing
  it.
- a :class:`FaultPlan` is a seeded schedule of :class:`FaultRule`\\ s
  per point — drop / delay / error / crash actions, each with a match
  count, a skip count, and a firing probability drawn from the plan's
  OWN ``random.Random(seed)`` so a failing chaos run replays exactly.
- arming is process-wide (``fault.arm(plan)`` / ``fault.disarm()``)
  and cheap when disarmed: the fast path is one attribute read.

Actions:

``drop``
    raise :class:`FaultDropped` (an ``OSError``): the message vanished
    on the wire — callers see exactly a channel failure.
``delay``
    sleep ``delay_s`` then proceed (slow network / fsync stall).
``error``
    raise the rule's exception instance/factory (defaults to
    :class:`FaultError`).
``crash``
    raise :class:`SimulatedCrash` — a ``BaseException`` so it ESCAPES
    ordinary ``except Exception`` recovery exactly like a process
    death would; tests catch it at the "process" boundary and restart
    the member from its durability directory (the durable-2PC recovery
    path, ``storage/durability.open_database``).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional

from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("chaos")

#: the documented injection-point catalog (README "Failure modes &
#: recovery" lists what each one covers). Sites may add dynamic
#: suffixes; the lint only requires membership of a point CALL, not of
#: this set — the set is the operator-facing index.
POINTS = frozenset(
    {
        "fwd.req",  # WriteOwner._req: every forwarded HTTP request
        "repl.push",  # QuorumPusher._post: quorum-push apply RPC
        "repl.pull",  # ReplicaPuller.pull_once: delta-pull request
        "tx2pc.prepare",  # participant phase-1 (both flavors)
        "tx2pc.commit",  # participant phase-2 commit
        "tx2pc.abort",  # participant abort
        "tx2pc.decide",  # coordinator between phase 1 and phase 2
        "wal.fsync",  # WriteAheadLog append (write+flush+fsync)
        "bin.send",  # binary-protocol frame send (client and server)
        "bin.recv",  # binary-protocol frame receive
        "bin.connect",  # client socket connect
        "cluster.probe",  # /cluster/health member probe + scrape
        "cdc.push",  # changefeed delivery: binary push frame + HTTP
        # /changes long-poll response (orientdb_tpu/cdc)
        "workload.http",  # traffic-simulator HTTP client sessions
        # (workloads/driver): every simulated HTTP request is
        # injectable like any real channel
        "tpu.dispatch",  # device dispatch: compiled single / vmapped
        # group / lane executions (exec/tpu_engine, guarded by the
        # device fault domain's escalation ladder)
        "tpu.transfer",  # device transfers: H2D param/block uploads
        # and blocking D2H result drains (tpu_engine fetch sites,
        # storage/tiering prefetch waves)
        "tpu.oom",  # device memory exhaustion: crossed before every
        # dispatch AND transfer, classifies oom and actuates the
        # fault domain's memledger-guided relief
        "audit.mismatch",  # wrong compiled result: an `error` rule here
        # (exec/audit.corrupt_point, crossed after every compiled
        # execute) corrupts the SERVED rows so the shadow-oracle
        # parity auditor provably detects + quarantines them
        "scrub.flip",  # device-block bit flip: an `error` rule here
        # corrupts the device-bound copy of a delta-patch segment
        # (ops/device_graph.apply_patches) or a tier-pool block row
        # (storage/tiering._load_blocks) — host truth keeps the
        # original, so the scrub sweep provably detects + repairs
    }
)


class FaultError(OSError):
    """Generic injected failure (the ``error`` action's default)."""


class FaultDropped(FaultError):
    """The ``drop`` action: the message was lost on the wire."""


class SimulatedCrash(BaseException):
    """The ``crash`` action: simulated process death. Inherits
    BaseException deliberately so ``except Exception`` recovery paths
    do NOT swallow it — in-process chaos tests need the 'crash' to
    unwind like a real SIGKILL, then restart the member from disk."""


class FaultRule:
    """One scheduled fault at one point.

    ``times``  — fire at most this many matches (None = unlimited);
    ``after``  — skip this many matching hits first;
    ``p``      — firing probability per hit, drawn from the PLAN's rng;
    ``action`` — drop | delay | error | crash.
    """

    __slots__ = ("point", "action", "times", "after", "p", "delay_s",
                 "error", "fired", "_skipped")

    def __init__(
        self,
        point: str,
        action: str,
        times: Optional[int] = 1,
        after: int = 0,
        p: float = 1.0,
        delay_s: float = 0.05,
        error: Optional[Callable[[], BaseException]] = None,
    ) -> None:
        if action not in ("drop", "delay", "error", "crash"):
            raise ValueError(f"unknown fault action {action!r}")
        self.point = point
        self.action = action
        self.times = times
        self.after = after
        self.p = p
        self.delay_s = delay_s
        self.error = error
        self.fired = 0
        self._skipped = 0

    def _take(self, rng) -> bool:
        """Decide (under the injector lock) whether this hit fires."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self._skipped < self.after:
            self._skipped += 1
            return False
        if self.p < 1.0 and rng.random() >= self.p:
            return False
        self.fired += 1
        return True


class FaultPlan:
    """A seeded schedule of rules; build with chained :meth:`at` calls:

    >>> plan = FaultPlan(seed=7).at("repl.push", "drop", times=2)
    ...                          .at("wal.fsync", "delay", delay_s=0.1)
    """

    def __init__(self, seed: int = 0) -> None:
        import random

        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: Dict[str, List[FaultRule]] = {}

    def at(self, point: str, action: str, **kw) -> "FaultPlan":
        self.rules.setdefault(point, []).append(
            FaultRule(point, action, **kw)
        )
        return self

    def fired(self, point: Optional[str] = None) -> int:
        """Total fires (for one point, or the whole plan)."""
        rules = (
            self.rules.get(point, [])
            if point is not None
            else [r for rs in self.rules.values() for r in rs]
        )
        return sum(r.fired for r in rules)


class FaultInjector:
    """Process-wide injection registry. The no-plan fast path is one
    attribute read, so production code pays ~nothing for the points."""

    def __init__(self) -> None:
        self._plan: Optional[FaultPlan] = None
        self._lock = threading.Lock()
        #: point -> hit count (armed or not) — the coverage ledger the
        #: chaos tests assert against ("every named point was crossed")
        self.hits: Dict[str, int] = {}
        self._count_hits = False

    # -- arming -------------------------------------------------------------

    def arm(self, plan: FaultPlan) -> FaultPlan:
        with self._lock:
            self._plan = plan
        log.warning("chaos: armed plan seed=%s rules=%s", plan.seed,
                    sorted(plan.rules))
        return plan

    def disarm(self) -> None:
        with self._lock:
            self._plan = None

    @contextmanager
    def armed(self, plan: FaultPlan):
        """``with fault.armed(plan): ...`` — disarms on exit, always."""
        self.arm(plan)
        try:
            yield plan
        finally:
            self.disarm()

    def record_hits(self, on: bool = True) -> None:
        """Toggle the coverage ledger (off by default: the ledger dict
        write is the only per-hit cost worth avoiding in production)."""
        self._count_hits = on
        if on:
            self.hits.clear()

    # -- the injection point -------------------------------------------------

    @contextmanager
    def point(self, name: str):
        """Mark one inter-node I/O (or durability) site. Fires any
        armed rule BEFORE the wrapped block runs — a dropped/delayed
        message never reaches the channel, like a real network fault."""
        self.check(name)
        yield

    def check(self, name: str) -> None:
        """The non-context form for call sites that cannot nest a
        ``with`` (rarely needed; the lint only accepts ``point``)."""
        plan = self._plan
        if plan is None and not self._count_hits:
            return
        rule = None
        with self._lock:
            if self._count_hits:
                self.hits[name] = self.hits.get(name, 0) + 1
            if plan is not None:
                for r in plan.rules.get(name, ()):
                    if r._take(plan.rng):
                        rule = r
                        break
        if rule is None:
            return
        metrics.incr(f"chaos.fired.{name}")
        if rule.action == "delay":
            log.warning("chaos: delay %.3fs at %s", rule.delay_s, name)
            time.sleep(rule.delay_s)
            return
        if rule.action == "drop":
            log.warning("chaos: drop at %s", name)
            raise FaultDropped(f"[chaos] message dropped at {name}")
        if rule.action == "error":
            err = rule.error() if callable(rule.error) else rule.error
            if err is None:
                err = FaultError(f"[chaos] injected error at {name}")
            log.warning("chaos: error at %s: %r", name, err)
            raise err
        log.warning("chaos: CRASH at %s", name)
        raise SimulatedCrash(f"[chaos] simulated process crash at {name}")


#: the process-wide injector (mirrors utils.metrics.metrics)
fault = FaultInjector()
