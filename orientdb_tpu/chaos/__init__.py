"""Deterministic fault injection for partial-failure hardening.

``from orientdb_tpu.chaos import fault`` and wrap inter-node I/O in
``with fault.point("<name>"): ...``; tests arm a seeded
:class:`~orientdb_tpu.chaos.faults.FaultPlan` to drop/delay/error/crash
at those points reproducibly. ``orientdb_tpu/chaos/iolint.py`` is the
tier-1 lint keeping every channel routed through a point.
"""

from orientdb_tpu.chaos.faults import (  # noqa: F401
    POINTS,
    FaultDropped,
    FaultError,
    FaultInjector,
    FaultPlan,
    FaultRule,
    SimulatedCrash,
    fault,
)

__all__ = [
    "POINTS",
    "FaultDropped",
    "FaultError",
    "FaultInjector",
    "FaultPlan",
    "FaultRule",
    "SimulatedCrash",
    "fault",
]
