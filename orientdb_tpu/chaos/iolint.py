"""AST lint: every inter-node I/O call site routes through a fault point.

The chaos subsystem (``chaos/faults.py``) only covers what is wrapped —
a NEW channel added without a ``fault.point("...")`` would silently
bypass both injection and the resilience story built on it (breakers,
the chaos acceptance suite). This lint makes that a tier-1 failure
(pattern: ``obs/promlint.py``'s grammar lint): it parses every module
under ``orientdb_tpu/{parallel,server,client,obs}/`` and asserts that
any top-level function or method performing raw inter-node I/O —
``urlopen``, socket ``sendall``/``recv``/``create_connection`` — also
contains a ``*.point(...)`` call somewhere in its body (nested helper
functions count as part of their enclosing def).

``EXEMPT`` names the deliberate exceptions: helpers whose ONLY callers
already hold the point (so a second point would double-fire per
operation). The check now runs as the ``iolint`` pass of
``orientdb_tpu/analysis`` (enforced tier-1 by
``tests/test_analysis.py``); ``lint_package`` below stays as a
back-compat shim. The I/O vocabulary, ``EXEMPT``, and the
``_iter_points`` catalog cross-check live here, next to the fault
points they protect.
"""

from __future__ import annotations

import ast
import os
from typing import Iterator, List, Tuple

#: package-relative directories scanned for inter-node I/O
SCAN_DIRS = ("parallel", "server", "client", "obs", "cdc", "workloads")

#: bare-name calls that are inter-node I/O
IO_NAMES = frozenset({"urlopen", "create_connection"})
#: attribute calls that are inter-node I/O (sock.sendall, sock.recv,
#: urllib.request.urlopen, socket.create_connection)
IO_ATTRS = frozenset({"urlopen", "sendall", "recv", "create_connection"})

#: (module-relative path, function name) pairs allowed to do raw I/O
#: without their own point — every caller holds one already
EXEMPT = frozenset(
    {
        # recv_frame wraps the frame read in fault.point("bin.recv");
        # _recv_exact is its private chunk loop
        ("server/binary_server.py", "_recv_exact"),
    }
)

# -- device dispatch/transfer rule (exec/devicefault) ------------------------

#: package-relative dirs scanned for raw DEVICE calls (the tpu.* fault
#: points): the exec stack plus the tiered-snapshot upload plane
DEVICE_SCAN_DIRS = ("exec", "storage")
#: within DEVICE_SCAN_DIRS, only these path suffixes are device planes
#: (the rest of storage/ is host-side WAL/records)
DEVICE_SCAN_SUFFIXES = ("exec/", "storage/tiering.py")

#: attribute calls that cross the device boundary (jax.device_put,
#: arr.block_until_ready, arr.copy_to_host_async)
DEVICE_IO_ATTRS = frozenset(
    {"device_put", "block_until_ready", "copy_to_host_async"}
)
#: bare-name device sync helpers (tpu_engine's module-level wrappers)
DEVICE_IO_NAMES = frozenset({"_block_until_ready", "_copy_to_host_async"})
#: calls that count as routing through the device fault domain's chaos
#: crossings (exec/devicefault.dispatch_point / transfer_point), in
#: addition to a literal ``*.point(...)``
DEVICE_ROUTE_HELPERS = frozenset({"dispatch_point", "transfer_point"})

#: (module-relative path, function name) pairs allowed raw device calls
#: without routing through tpu.dispatch / tpu.transfer / tpu.oom
DEVICE_EXEMPT = frozenset(
    {
        # background AOT warm-ups / page-fn precompiles: off the
        # serving hot path, with their own retry-then-sentinel
        # discipline — a failed compile degrades to per-lane dispatch,
        # never a query error
        ("exec/tpu_engine.py", "ensure_compiled"),
        ("exec/tpu_engine.py", "_compile_page_async"),
        ("exec/tpu_engine.py", "precompile_group_pages"),
        ("exec/tpu_engine.py", "_compile_group_async"),
        # speculative result-page copies ride the dispatch they start
        # from (dispatch/dispatch_many hold the tpu.dispatch crossing;
        # a wrong guess is dropped, never awaited on its own)
        ("exec/tpu_engine.py", "_prefetch_elected"),
        ("exec/tpu_engine.py", "_group_dispatch"),
    }
)


def _is_device_io_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in DEVICE_IO_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in DEVICE_IO_ATTRS
    return False


def _is_device_route_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr == "point" or f.attr in DEVICE_ROUTE_HELPERS
    if isinstance(f, ast.Name):
        return f.id in DEVICE_ROUTE_HELPERS
    return False


def _is_io_call(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id in IO_NAMES
    if isinstance(f, ast.Attribute):
        return f.attr in IO_ATTRS
    return False


def _is_point_call(call: ast.Call) -> bool:
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr == "point"


def _outermost_functions(
    tree: ast.Module,
) -> Iterator[ast.FunctionDef]:
    """Top-level functions and class methods — nested defs (closures,
    local helpers) are checked as part of their enclosing function."""
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node
        elif isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield sub


def lint_source(src: str, rel: str) -> List[str]:
    """Lint one module's source; returns problems (empty = clean)."""
    problems: List[str] = []
    tree = ast.parse(src, filename=rel)
    for fn in _outermost_functions(tree):
        calls = [
            n for n in ast.walk(fn) if isinstance(n, ast.Call)
        ]
        if not any(_is_io_call(c) for c in calls):
            continue
        if (rel, fn.name) in EXEMPT:
            continue
        if not any(_is_point_call(c) for c in calls):
            problems.append(
                f"{rel}:{fn.lineno}: {fn.name}() performs inter-node "
                "I/O with no fault.point(...) — wrap the call site in a "
                "named injection point (chaos/faults.py) or add an "
                "EXEMPT entry with a justification"
            )
    return problems


def lint_device_source(src: str, rel: str) -> List[str]:
    """Device-rule twin of :func:`lint_source`: every outermost
    function in the device planes (``DEVICE_SCAN_SUFFIXES``) performing
    raw device calls must route through a chaos crossing — a literal
    ``*.point(...)`` or one of the devicefault helpers."""
    problems: List[str] = []
    if not any(
        rel.startswith(s) or rel == s.rstrip("/")
        for s in DEVICE_SCAN_SUFFIXES
    ):
        return problems
    tree = ast.parse(src, filename=rel)
    for fn in _outermost_functions(tree):
        calls = [n for n in ast.walk(fn) if isinstance(n, ast.Call)]
        if not any(_is_device_io_call(c) for c in calls):
            continue
        if (rel, fn.name) in DEVICE_EXEMPT:
            continue
        if not any(_is_device_route_call(c) for c in calls):
            problems.append(
                f"{rel}:{fn.lineno}: {fn.name}() crosses the device "
                "boundary with no tpu.* fault crossing — route through "
                "devicefault.dispatch_point()/transfer_point() (or a "
                "fault.point(...)) or add a DEVICE_EXEMPT entry with a "
                "justification"
            )
    return problems


def lint_package(root: str = None) -> List[str]:
    """Legacy entry point — now a thin shim over the framework pass
    (``orientdb_tpu.analysis``, pass ``iolint``): shared discovery,
    per-line suppressions, and reporting. ``root`` is the package
    directory (historical signature); returns problem strings (empty =
    every channel is injectable)."""
    from orientdb_tpu.analysis import core

    repo = None if root is None else os.path.dirname(
        os.path.abspath(root)
    )
    rep = core.run(passes=["iolint"], root=repo)
    scanned = tuple(f"orientdb_tpu/{d}/" for d in SCAN_DIRS)
    return [
        str(f)
        for f in rep.findings
        if f.pass_name == "iolint"
        # the old contract also reported unparsable scanned modules
        or (f.pass_name == "parse" and f.path.startswith(scanned))
    ]


def _iter_points(root: str = None) -> List[Tuple[str, int, str]]:
    """Every literal point name used in the scanned tree (for the
    catalog cross-check): (rel path, line, name)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[Tuple[str, int, str]] = []
    for d in SCAN_DIRS + ("storage", "exec", "chaos"):
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirs, files in os.walk(base):
            for f in sorted(files):
                if not f.endswith(".py"):
                    continue
                path = os.path.join(dirpath, f)
                rel = os.path.relpath(path, root).replace(os.sep, "/")
                with open(path, "r", encoding="utf-8") as fh:
                    tree = ast.parse(fh.read(), filename=rel)
                for n in ast.walk(tree):
                    if (
                        isinstance(n, ast.Call)
                        and _is_point_call(n)
                        and n.args
                        and isinstance(n.args[0], ast.Constant)
                        and isinstance(n.args[0].value, str)
                    ):
                        out.append((rel, n.lineno, n.args[0].value))
    return out
