"""Hermetic multichip dryrun — CPU-pinned sharded-MATCH parity check.

The driver validates the multi-chip sharding path by running
``__graft_entry__.dryrun_multichip(n)`` with N virtual devices. That check
is a pure *correctness* dryrun: it never needs the real TPU, and any
TPU-client state it touches (e.g. a libtpu client/terminal version skew
inside ``jax.device_put``) can only produce spurious failures. This module
therefore pins the **entire** JAX process to the CPU platform as its very
first act — before any backend can possibly initialize — and then runs the
full sharded execution body (`run_body`).

``__graft_entry__.dryrun_multichip`` runs this module in a fresh
subprocess with ``JAX_PLATFORMS=cpu`` set in the environment as well, so
even backend state created earlier in the *calling* process (e.g. the
driver compile-checking ``entry()`` on the real chip first) cannot leak in.

Reference analog: the multi-server-in-one-JVM distributed test pattern
(SURVEY.md §4) — prove the distributed plane without real cluster hardware.
"""

from __future__ import annotations

import os
import sys


def cpu_pinned_env(n_devices: int, base_env: dict) -> dict:
    """Env-var mutations pinning a JAX process to >= n_devices CPU devices.

    Keeps inherited XLA flags but forces OUR device count to be the
    winning (last) occurrence — XLA flag parsing is last-wins. Pure
    (returns a new dict); imports no jax, so safe to call from a parent
    process that must not initialize any backend.
    """
    env = dict(base_env)
    env["JAX_PLATFORMS"] = "cpu"
    kept = [
        f
        for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    env["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={n_devices}"]
    )
    return env


def pin_cpu(n_devices: int) -> None:
    """Pin this process to the CPU platform with >= n_devices devices.

    Must run before any JAX backend initializes. Uses both the env vars
    (read at first backend init) and `jax.config` updates (which win even
    when a plugin's sitecustomize imported jax early), so whichever path
    this interpreter took, the TPU client is never constructed.
    """
    os.environ.update(cpu_pinned_env(n_devices, os.environ))
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # backend already live (in-process test use) — count via XLA_FLAGS


# BASELINE-shaped query corpus: 1-hop with predicates; 2-hop COUNT via
# sharded psum weight passes; variable-depth WHILE via psum-OR bitmap hops;
# binding-referencing WHERE; NOT anti-join; parameter-generic replay;
# SELECT via the single-node-MATCH rewrite.
QUERIES = [
    (
        "MATCH {class:Profiles, as:p, where:(age > 40)}"
        "-HasFriend->{as:f, where:(age < 30)} RETURN p.uid AS p, f.uid AS f",
        None,
    ),
    (
        "MATCH {class:Profiles, as:p, where:(age > 40)}-HasFriend->{as:f}"
        "-HasFriend->{as:g, where:(age < 30)} RETURN count(*) AS n",
        None,
    ),
    (
        "MATCH {class:Profiles, as:p, where:(uid < 5)}-HasFriend->"
        "{as:f, while:($depth < 3)} RETURN p.uid AS p, f.uid AS f",
        None,
    ),
    (
        "MATCH {class:Profiles, as:p}-HasFriend->"
        "{as:f, where:(age < p.age)} RETURN p.uid AS p, f.uid AS f",
        None,
    ),
    (
        "MATCH {class:Profiles, as:p}-HasFriend->{as:f}, "
        "NOT {as:f}-HasFriend->{as:p} RETURN count(*) AS n",
        None,
    ),
    (
        "MATCH {class:Profiles, as:p, where:(uid < :lim)}"
        "-HasFriend->{as:f} RETURN p.uid AS p, f.uid AS f",
        {"lim": 9},
    ),
    (
        "SELECT name, age FROM Profiles WHERE age > 40 AND uid < :m",
        {"m": 40},
    ),
]


def run_body(n_devices: int) -> None:
    """Execute the sharded-MATCH parity corpus over an n-device mesh.

    Assumes devices are already provisioned (CPU-pinned via `pin_cpu`, or a
    test harness's forced-CPU conftest). Asserts record-run AND cached-plan
    sharded-replay parity against the oracle for every query shape.
    """
    from orientdb_tpu.parallel.sharded import make_mesh, provision_devices
    from orientdb_tpu.storage.ingest import generate_demodb
    from orientdb_tpu.storage.snapshot import attach_fresh_snapshot

    devs = provision_devices(n_devices)
    assert all(d.platform == "cpu" for d in devs[:n_devices]), (
        "dryrun must never touch a non-CPU backend; got "
        + str({d.platform for d in devs[:n_devices]})
    )
    replicas = 2 if (n_devices >= 4 and n_devices % 2 == 0) else 1
    mesh = make_mesh(n_devices, replicas=replicas, devices=devs[:n_devices])
    db = generate_demodb(n_profiles=64, avg_friends=4, seed=1)
    attach_fresh_snapshot(db, mesh=mesh)

    def canon(rows):
        return sorted(tuple(sorted(r.items())) for r in rows)

    # crash-safe evidence (obs/evidence, same stream discipline as
    # bench.py): a driver timeout mid-corpus still leaves every
    # completed query's parity verdict on disk. ORIENTTPU_EVIDENCE
    # overrides the path.
    import time as _time

    from orientdb_tpu.obs.evidence import evidence_sink

    sink = evidence_sink(
        os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(__file__))),
            "MULTICHIP_EVIDENCE.jsonl",
        )
    )
    for i, (sql, params) in enumerate(QUERIES):
        t0 = _time.perf_counter()
        recorded = canon(
            db.query(sql, params=params, engine="tpu", strict=True).to_dicts()
        )
        replayed = canon(
            db.query(sql, params=params, engine="tpu", strict=True).to_dicts()
        )
        oracle = canon(db.query(sql, params=params, engine="oracle").to_dicts())
        assert recorded == oracle, f"record-run parity broke: {sql}"
        assert replayed == oracle, f"sharded replay parity broke: {sql}"
        if sink is not None:
            sink.emit(
                "dryrun_query",
                {
                    "i": i,
                    "sql": sql[:80],
                    "rows": len(oracle),
                    "parity": "ok",
                    "s": round(_time.perf_counter() - t0, 3),
                },
            )

    # config-5 shape (BASELINE configs[4]): multi-class + EDGE property
    # column + multi-pattern edge-property WHERE, sharded on the same
    # mesh, against the exact numpy reference (array-native graph)
    from orientdb_tpu.storage.bigshape import (
        build_snb_shape,
        numpy_config5_count,
    )

    db5, snap5 = build_snb_shape(400, msgs_per_person=1, avg_knows=4, seed=7)
    snap5._mesh = mesh
    q5 = (
        "MATCH {class:Person, as:p, where:(age > 40)}"
        ".outE('knows'){where:(creationDate > :d)}"
        ".inV(){as:f, where:(age < 30)}, "
        "{class:Message, as:m}-hasCreator->{as:f} "
        "RETURN count(*) AS n"
    )
    for d in (12_000, 17_000):
        want = numpy_config5_count(snap5, d)
        got = db5.query(
            q5, params={"d": d}, engine="tpu", strict=True
        ).to_dicts()
        assert got == [{"n": want}], f"sharded config5 parity broke: d={d}"
    if sink is not None:
        sink.emit(
            "dryrun_done",
            {"mesh": dict(mesh.shape), "queries": len(QUERIES) + 1},
        )
    print(
        f"dryrun_multichip ok: mesh {dict(mesh.shape)}, "
        f"{len(QUERIES)} MATCH/SELECT queries + config5 edge-property-"
        "WHERE multi-pattern sharded-executed at oracle/numpy parity "
        "(platform=cpu, hermetic)"
    )


def main() -> int:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    pin_cpu(n)
    run_body(n)
    return 0


if __name__ == "__main__":
    sys.exit(main())
