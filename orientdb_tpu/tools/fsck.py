"""Durable-state fsck: verify every on-disk artifact class end to end.

The scrubber (``storage/scrub``) covers DEVICE state; this tool covers
the DURABLE tree — the artifacts a restart or restore would trust
blindly otherwise. Every format already embeds integrity metadata;
fsck is the one place that re-derives and cross-checks all of it:

- **WAL segments** (``wal.log`` + rotated ``wal-<uptolsn>.log``): the
  per-line CRC chain (``<crc32-hex-8> <json>``), in-file LSN
  monotonicity, and archive-name continuity (a rotated segment's
  filename carries its last covered LSN). A torn FINAL line of the
  LIVE log is a crash artifact recovery tolerates — warning, not
  error; any other damage is corruption.
- **checkpoints / deltas** (``checkpoint-<epoch>-<lsn>-<crc>.json``,
  ``delta-...``): filename-embedded crc32 vs the payload bytes, JSON
  well-formedness, and epoch/lsn fields matching the filename.
- **epoch snapshots** (``snapshot-<epoch>-<sha16>.npz``,
  storage/epochs): content-addressed sha256 prefix re-derived from the
  file bytes.
- **coldstore** (``cold-segment.jsonl`` + ``cold-meta.json``): spill
  lines must parse in order (a torn final line is a tolerated crash
  artifact), the meta must parse.
- **backup archives** (``--backup``): zip CRC sweep, manifest sanity,
  the format-3 payload/tail sha256s, and a full restore-and-rehash
  round trip — the archive must actually rebuild a database (torn
  captures included: the bundled WAL tail replays over the payload)
  and the rebuilt state must re-serialize.

Surfaces: ``python -m orientdb_tpu.tools.fsck <dir> [--backup <zip>]``
(exit 0 clean, 1 corrupt — naming every corrupt artifact), the console
``FSCK`` command, and the admin-only ``GET /debug/fsck``.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import zipfile
import zlib
from typing import Dict, List, Optional

from orientdb_tpu.utils.logging import get_logger

log = get_logger("fsck")


def _err(report: Dict, path: str, check: str, detail: str) -> None:
    report["errors"].append({"path": path, "check": check, "detail": detail})


def _warn(report: Dict, path: str, check: str, detail: str) -> None:
    report["warnings"].append({"path": path, "check": check, "detail": detail})


# -- WAL segments ------------------------------------------------------------


def _check_wal_segment(report: Dict, path: str, live: bool) -> None:
    with open(path, "rb") as f:
        raw = f.read()
    pos = 0
    last_lsn = None
    n = 0
    bad: Optional[str] = None
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        if nl < 0:
            bad = f"torn final line (no newline) at byte {pos}"
            break
        line = raw[pos:nl]
        pos = nl + 1
        if not line:
            continue
        if len(line) < 10 or line[8:9] != b" ":
            bad = f"malformed line framing at byte {nl - len(line)}"
            break
        crc_hex, data = line[:8], line[9:]
        try:
            want = int(crc_hex, 16)
        except ValueError:
            bad = f"unparsable CRC field at byte {nl - len(line)}"
            break
        if want != (zlib.crc32(data) & 0xFFFFFFFF):
            bad = (
                f"CRC mismatch at entry {n} (byte {nl - len(line)}): "
                f"stored {crc_hex.decode()} != computed "
                f"{zlib.crc32(data) & 0xFFFFFFFF:08x}"
            )
            break
        try:
            entry = json.loads(data)
        except Exception as e:
            bad = f"entry {n} JSON unparsable: {e}"
            break
        lsn = entry.get("lsn")
        if last_lsn is not None and isinstance(lsn, int) and lsn <= last_lsn:
            _err(
                report, path, "wal.lsn_order",
                f"entry {n} lsn {lsn} not above predecessor {last_lsn}",
            )
        if isinstance(lsn, int):
            last_lsn = lsn
        n += 1
    if bad is not None:
        tail = pos >= len(raw) or raw.find(b"\n", pos) < 0
        if live and tail:
            # crash artifact: recovery truncates the torn tail
            _warn(report, path, "wal.torn_tail", bad)
        else:
            _err(report, path, "wal.crc_chain", bad)
    if not live and last_lsn is not None:
        base = os.path.basename(path)
        try:
            upto = int(base[len("wal-"):-len(".log")])
        except ValueError:
            upto = None
        if upto is not None and last_lsn != upto:
            _err(
                report, path, "wal.segment_continuity",
                f"archive named upto lsn {upto} but last intact entry "
                f"is lsn {last_lsn}",
            )


# -- checkpoint / delta files ------------------------------------------------


def _check_digest_json(report: Dict, path: str, prefix: str) -> None:
    base = os.path.basename(path)
    stem = base[len(prefix):-len(".json")]
    parts = stem.rsplit("-", 2)
    if len(parts) != 3:
        _err(report, path, "name.format", "unparsable filename fields")
        return
    epoch_s, lsn_s, digest = parts
    with open(path, "rb") as f:
        data = f.read()
    got = format(zlib.crc32(data) & 0xFFFFFFFF, "08x")
    if got != digest:
        _err(
            report, path, "content.crc",
            f"filename digest {digest} != computed {got}",
        )
        return
    try:
        payload = json.loads(data)
    except Exception as e:
        _err(report, path, "content.json", f"unparsable payload: {e}")
        return
    for field, want in (("epoch", epoch_s), ("lsn", lsn_s)):
        if int(payload.get(field, -1)) != int(want):
            _err(
                report, path, "name.fields",
                f"payload {field}={payload.get(field)} != filename {want}",
            )


# -- epoch store -------------------------------------------------------------


def _check_epoch_snapshot(report: Dict, path: str) -> None:
    base = os.path.basename(path)
    digest = base.rsplit("-", 1)[-1].split(".")[0]
    with open(path, "rb") as f:
        data = f.read()
    got = hashlib.sha256(data).hexdigest()[:16]
    if got != digest:
        _err(
            report, path, "content.sha256",
            f"filename digest {digest} != computed {got}",
        )


# -- coldstore ---------------------------------------------------------------


def _check_cold_segment(report: Dict, path: str) -> None:
    with open(path, "rb") as f:
        raw = f.read()
    pos = 0
    n = 0
    while pos < len(raw):
        nl = raw.find(b"\n", pos)
        if nl < 0:
            _warn(
                report, path, "cold.torn_tail",
                f"torn final line (no newline) at byte {pos}",
            )
            return
        line = raw[pos:nl]
        if line:
            try:
                rec = json.loads(line)
                rec["rid"]
            except Exception as e:
                if raw.find(b"\n", nl + 1) < 0 and nl + 1 >= len(raw):
                    _warn(
                        report, path, "cold.torn_tail",
                        f"corrupt final line {n}: {e}",
                    )
                else:
                    _err(
                        report, path, "cold.segment",
                        f"corrupt spill line {n} (byte {pos}): {e}",
                    )
                return
        pos = nl + 1
        n += 1


def _check_cold_meta(report: Dict, path: str) -> None:
    try:
        with open(path, "rb") as f:
            json.loads(f.read())
    except Exception as e:
        _err(report, path, "cold.meta", f"unparsable cold meta: {e}")


# -- the tree walk -----------------------------------------------------------


def fsck_tree(directory: str) -> Dict:
    """Verify every recognized durable artifact under ``directory``
    (recursively). Returns the report; ``report['clean']`` is False iff
    any artifact failed a check outright."""
    report: Dict = {
        "directory": os.path.abspath(directory),
        "checked": {
            "wal_segments": 0, "checkpoints": 0, "deltas": 0,
            "epochs": 0, "coldstore": 0,
        },
        "errors": [], "warnings": [],
    }
    if not os.path.isdir(directory):
        _err(report, directory, "tree", "not a directory")
        report["clean"] = False
        return report
    for root, _dirs, files in os.walk(directory):
        for base in sorted(files):
            path = os.path.join(root, base)
            try:
                if base == "wal.log":
                    report["checked"]["wal_segments"] += 1
                    _check_wal_segment(report, path, live=True)
                elif base.startswith("wal-") and base.endswith(".log"):
                    report["checked"]["wal_segments"] += 1
                    _check_wal_segment(report, path, live=False)
                elif base.startswith("checkpoint-") and base.endswith(
                    ".json"
                ):
                    report["checked"]["checkpoints"] += 1
                    _check_digest_json(report, path, "checkpoint-")
                elif base.startswith("delta-") and base.endswith(".json"):
                    report["checked"]["deltas"] += 1
                    _check_digest_json(report, path, "delta-")
                elif base.startswith("snapshot-") and base.endswith(".npz"):
                    report["checked"]["epochs"] += 1
                    _check_epoch_snapshot(report, path)
                elif base == "cold-segment.jsonl":
                    report["checked"]["coldstore"] += 1
                    _check_cold_segment(report, path)
                elif base == "cold-meta.json":
                    report["checked"]["coldstore"] += 1
                    _check_cold_meta(report, path)
            except OSError as e:
                _err(report, path, "io", str(e))
    report["clean"] = not report["errors"]
    return report


# -- backup archives ---------------------------------------------------------


def fsck_backup(path: str) -> Dict:
    """Verify one backup zip: archive CRCs, manifest sanity, format-3
    content hashes, and the restore-and-rehash round trip (the bundled
    WAL tail replays over the payload — the torn-capture correction
    path is exercised whenever the archive carries a tail)."""
    report: Dict = {
        "backup": os.path.abspath(path),
        "errors": [], "warnings": [],
        "restored": False,
    }
    from orientdb_tpu.storage import backup as B

    try:
        with zipfile.ZipFile(path) as z:
            corrupt = z.testzip()
            if corrupt is not None:
                _err(
                    report, path, "zip.crc",
                    f"member {corrupt!r} fails the zip CRC sweep",
                )
                report["clean"] = False
                return report
            names = set(z.namelist())
            for member in (B.MANIFEST, B.PAYLOAD):
                if member not in names:
                    _err(
                        report, path, "zip.members",
                        f"archive is missing {member!r}",
                    )
                    report["clean"] = False
                    return report
            manifest = json.loads(z.read(B.MANIFEST))
            payload_bytes = z.read(B.PAYLOAD)
            tail_bytes = z.read(B.TAIL) if B.TAIL in names else b"[]"
    except (OSError, zipfile.BadZipFile, ValueError) as e:
        _err(report, path, "zip.open", str(e))
        report["clean"] = False
        return report
    report["manifest"] = {
        k: manifest.get(k)
        for k in ("format", "name", "epoch", "lsn", "upto_lsn")
    }
    if int(manifest.get("format", 0)) >= 3:
        for field, data in (
            ("sha256_payload", payload_bytes),
            ("sha256_tail", tail_bytes),
        ):
            want = manifest.get(field)
            got = hashlib.sha256(data).hexdigest()
            if want != got:
                _err(
                    report, path, f"content.{field}",
                    f"manifest {field} {want} != computed {got}",
                )
    else:
        _warn(
            report, path, "manifest.format",
            "pre-format-3 archive: no content hashes to verify",
        )
    if not report["errors"]:
        # restore-and-rehash: the archive must actually rebuild a
        # database (payload + bundled tail replay), and the rebuilt
        # state must re-serialize — a round trip through the exact
        # code paths a disaster recovery would take
        try:
            from orientdb_tpu.storage.durability import capture_payload

            db = B.restore_database(path, name="_fsck_restore")
            payload, lsn, _ = capture_payload(db, serialize_in_lock=True)
            rehash = hashlib.sha256(
                json.dumps(payload, separators=(",", ":")).encode()
            ).hexdigest()
            report["restored"] = True
            report["restore_rehash"] = rehash[:16]
            report["restore_lsn"] = lsn
        except Exception as e:
            _err(report, path, "restore.round_trip", f"restore failed: {e}")
    report["clean"] = not report["errors"]
    return report


# -- CLI ---------------------------------------------------------------------


def format_report(report: Dict) -> str:
    lines: List[str] = []
    target = report.get("directory") or report.get("backup")
    lines.append(f"fsck {target}")
    checked = report.get("checked")
    if checked:
        lines.append(
            "  checked: " + ", ".join(
                f"{k}={v}" for k, v in checked.items()
            )
        )
    if report.get("manifest"):
        lines.append(f"  manifest: {report['manifest']}")
    if "restored" in report:
        lines.append(
            f"  restore round trip: "
            f"{'ok (' + str(report.get('restore_rehash')) + ')' if report['restored'] else 'FAILED'}"
        )
    for w in report["warnings"]:
        lines.append(f"  WARN {w['check']}: {w['path']}: {w['detail']}")
    for e in report["errors"]:
        lines.append(f"  CORRUPT {e['check']}: {e['path']}: {e['detail']}")
    lines.append("  CLEAN" if report.get("clean") else "  CORRUPT TREE")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    backups: List[str] = []
    dirs: List[str] = []
    i = 0
    while i < len(argv):
        if argv[i] == "--backup":
            if i + 1 >= len(argv):
                print("usage: fsck [<directory>...] [--backup <zip>...]")
                return 2
            backups.append(argv[i + 1])
            i += 2
        else:
            dirs.append(argv[i])
            i += 1
    if not dirs and not backups:
        print("usage: fsck [<directory>...] [--backup <zip>...]")
        return 2
    rc = 0
    for d in dirs:
        report = fsck_tree(d)
        print(format_report(report))
        if not report["clean"]:
            rc = 1
    for b in backups:
        report = fsck_backup(b)
        print(format_report(report))
        if not report["clean"]:
            rc = 1
    return rc


if __name__ == "__main__":  # pragma: no cover - CLI entry
    raise SystemExit(main())
