"""JSON-configured extract/transform/load pipelines.

Analog of the reference's ETL module ([E] etl/ ``OETLProcessor`` with
extractor/transformer/loader blocks; SURVEY.md §2 "ETL"): a declarative
config drives rows from a source, through a transformer chain, into the
database. The config shape mirrors the reference's:

    {
      "source":      {"file": {"path": "people.csv"}},
      "extractor":   {"csv": {"separator": ",", "columnsOnFirstLine": true}},
      "transformers": [
        {"field": {"fieldName": "age", "type": "int"}},
        {"vertex": {"class": "Person"}},
        {"edge": {"class": "LivesIn", "joinFieldName": "city",
                   "lookup": "City.name", "direction": "out"}}
      ],
      "loader": {"odb": {"dbName": "people",
                          "indexes": [{"class": "Person",
                                       "fields": ["uid"],
                                       "type": "UNIQUE"}]}}
    }

Supported extractors: ``csv``, ``json`` (array-of-objects or
JSON-lines), ``rows`` (in-memory list — the test/fake source).
Transformers: ``field`` (rename/cast/drop/set), ``filter`` (keep rows
matching a SQL-ish WHERE evaluated per row), ``vertex`` (row → vertex of
a class), ``edge`` (link the current vertex to a looked-up vertex),
``merge`` (upsert by a key field through a unique index). Loader:
``odb`` (an embedded Database, with index bootstrap).
"""

from __future__ import annotations

import csv as _csv
import io
import json
from typing import Dict, Iterable, Iterator, List, Optional

from orientdb_tpu.models.database import Database
from orientdb_tpu.models.record import Vertex
from orientdb_tpu.utils.logging import get_logger

log = get_logger("etl")


class ETLError(Exception):
    pass


class ETLProcessor:
    """[E] OETLProcessor: one run() per configuration."""

    def __init__(self, config: Dict, db: Optional[Database] = None) -> None:
        self.config = config
        self.db = db
        self.stats = {"extracted": 0, "loaded_vertices": 0, "loaded_edges": 0,
                      "filtered": 0, "merged": 0}
        self._ast_cache: Dict[str, object] = {}

    # -- entry --------------------------------------------------------------

    def run(self) -> Database:
        db = self._loader_db()
        for row in self._extract():
            self.stats["extracted"] += 1
            ctx = {"row": dict(row), "vertex": None}
            if not self._transform(db, ctx):
                self.stats["filtered"] += 1
                continue
            if ctx["vertex"] is None:
                # document load: rows with no vertex transformer become
                # plain documents of the loader's default class
                cls = self.config.get("loader", {}).get("odb", {}).get(
                    "class", "O"
                )
                db.new_element(cls, **ctx["row"])
        log.info("etl: %s", self.stats)
        return db

    # -- extractors ---------------------------------------------------------

    def _source_text(self) -> str:
        src = self.config.get("source", {})
        if "file" in src:
            with open(src["file"]["path"], "r") as f:
                return f.read()
        if "content" in src:
            return src["content"]["value"]
        raise ETLError("source needs 'file' or 'content'")

    def _extract(self) -> Iterator[Dict]:
        ex = self.config.get("extractor", {})
        if "rows" in ex:
            yield from ex["rows"]["data"]
            return
        if "csv" in ex:
            opts = ex["csv"]
            text = self._source_text()
            reader = _csv.reader(
                io.StringIO(text), delimiter=opts.get("separator", ",")
            )
            rows = list(reader)
            if not rows:
                return
            if opts.get("columnsOnFirstLine", True):
                header, body = rows[0], rows[1:]
            else:
                header = opts.get("columns") or [
                    f"c{i}" for i in range(len(rows[0]))
                ]
                body = rows
            for vals in body:
                yield {h: _auto(v) for h, v in zip(header, vals)}
            return
        if "json" in ex:
            text = self._source_text().strip()
            if text.startswith("["):
                for item in json.loads(text):
                    yield item
            else:  # JSON-lines
                for line in text.splitlines():
                    if line.strip():
                        yield json.loads(line)
            return
        raise ETLError("extractor needs one of: rows, csv, json")

    # -- transformers -------------------------------------------------------

    def _transform(self, db: Database, ctx: Dict) -> bool:
        for t in self.config.get("transformers", []):
            if "field" in t:
                self._t_field(t["field"], ctx)
            elif "filter" in t:
                if not self._t_filter(db, t["filter"], ctx):
                    return False
            elif "vertex" in t:
                self._t_vertex(db, t["vertex"], ctx)
            elif "merge" in t:
                self._t_merge(db, t["merge"], ctx)
            elif "edge" in t:
                self._t_edge(db, t["edge"], ctx)
            else:
                raise ETLError(f"unknown transformer {sorted(t)!r}")
        return True

    def _t_field(self, cfg: Dict, ctx: Dict) -> None:
        row = ctx["row"]
        name = cfg["fieldName"]
        if cfg.get("operation") == "remove":
            row.pop(name, None)
            return
        if "rename" in cfg:
            if name in row:
                row[cfg["rename"]] = row.pop(name)
            return
        if "value" in cfg:
            row[name] = cfg["value"]
        if "type" in cfg and name in row and row[name] is not None:
            kind = cfg["type"]
            if kind == "bool":
                v = row[name]
                row[name] = (
                    v.strip().lower() in ("true", "1", "yes", "on")
                    if isinstance(v, str)
                    else bool(v)
                )
            else:
                row[name] = {"int": int, "float": float, "str": str}[kind](
                    row[name]
                )

    def _t_filter(self, db: Database, cfg: Dict, ctx: Dict) -> bool:
        from orientdb_tpu.exec.eval import EvalContext, evaluate, truthy
        from orientdb_tpu.sql.parser import Parser

        expr = cfg.get("expression")
        if expr is None:
            raise ETLError("filter transformer needs 'expression'")
        ast = self._ast_cache.get(expr)
        if ast is None:  # parse once per run, not once per row
            ast = self._ast_cache[expr] = Parser(expr).parse_expression()
        ectx = EvalContext(db, current=dict(ctx["row"]))
        return truthy(evaluate(ectx, ast))

    @staticmethod
    def _lookup_one(db: Database, cls: str, field: str, val):
        """First document of ``cls`` with field == val, via a single-field
        index when one exists, else a scan (shared by merge/edge)."""
        idx = db.indexes.best_for(cls, field) if db._indexes else None
        if idx is not None:
            rids = idx.get(val)
            return db.load(next(iter(sorted(rids)))) if rids else None
        if not db.schema.exists_class(cls):
            return None
        for d in db.browse_class(cls):
            if d.get(field) == val:
                return d
        return None

    def _t_vertex(self, db: Database, cfg: Dict, ctx: Dict) -> None:
        cls = cfg.get("class", "V")
        if not db.schema.exists_class(cls):
            db.schema.create_vertex_class(cls)
        fields = dict(ctx["row"])
        ctx["vertex"] = db.new_vertex(cls, **fields)
        self.stats["loaded_vertices"] += 1

    def _t_merge(self, db: Database, cfg: Dict, ctx: Dict) -> None:
        """Upsert by key field ([E] the merge transformer + lookup)."""
        cls = cfg.get("class", "V")
        key = cfg["joinFieldName"]
        if not db.schema.exists_class(cls):
            db.schema.create_vertex_class(cls)
        val = ctx["row"].get(key)
        existing = self._lookup_one(db, cls, key, val)
        if existing is not None:
            for k, v in ctx["row"].items():
                existing.set(k, v)
            db.save(existing)
            ctx["vertex"] = existing
            self.stats["merged"] += 1
        else:
            self._t_vertex(db, {"class": cls}, ctx)

    def _t_edge(self, db: Database, cfg: Dict, ctx: Dict) -> None:
        src = ctx["vertex"]
        if src is None:
            raise ETLError("edge transformer needs a vertex earlier in the chain")
        ecls = cfg.get("class", "E")
        if not db.schema.exists_class(ecls):
            db.schema.create_edge_class(ecls)
        join = cfg["joinFieldName"]
        lk_class, lk_field = cfg["lookup"].split(".", 1)
        val = ctx["row"].get(join)
        target = self._lookup_one(db, lk_class, lk_field, val)
        if target is None:
            if cfg.get("unresolvedLinkAction", "SKIP").upper() == "ERROR":
                raise ETLError(f"unresolved edge lookup {cfg['lookup']}={val!r}")
            return
        if not isinstance(target, Vertex):
            raise ETLError("edge lookup resolved to a non-vertex")
        if cfg.get("direction", "out") == "out":
            db.new_edge(ecls, src, target)
        else:
            db.new_edge(ecls, target, src)
        self.stats["loaded_edges"] += 1

    # -- loader -------------------------------------------------------------

    def _loader_db(self) -> Database:
        if self.db is not None:
            db = self.db
        else:
            cfg = self.config.get("loader", {}).get("odb", {})
            db = self.db = Database(cfg.get("dbName", "etl"))
        cfg = self.config.get("loader", {}).get("odb", {})
        for idx in cfg.get("indexes", []):
            name = idx.get("name", f"{idx['class']}.{'_'.join(idx['fields'])}")
            if db.indexes.get_index(name) is None:
                if not db.schema.exists_class(idx["class"]):
                    db.schema.create_vertex_class(idx["class"])
                db.indexes.create_index(
                    name, idx["class"], idx["fields"], idx.get("type", "NOTUNIQUE")
                )
        return db


def run_etl(config: Dict, db: Optional[Database] = None) -> Database:
    """One-shot helper ([E] the oetl.sh entry point)."""
    return ETLProcessor(config, db).run()


def _auto(v: str):
    """CSV value auto-typing (the reference's csv extractor does this)."""
    if v == "":
        return None
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v
