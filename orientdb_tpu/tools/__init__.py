"""CLI tools ([E] tools/ module: console, export/import)."""
