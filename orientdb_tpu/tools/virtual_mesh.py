"""Run a probe module in a subprocess pinned to a virtual CPU mesh.

The CPU device count is fixed at process start (XLA reads
``--xla_force_host_platform_device_count`` once), so every shard-count
probe needs its own process. ONE implementation of the env pinning,
launch, and last-JSON-line protocol, shared by ``bench.py``'s
``mesh_scaling``/``sharded_sf`` blocks and the standalone
``tools/mesh_scaling.py --sweep`` — the two must never diverge on the
probe contract.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List


def run_virtual_mesh_subprocess(
    module: str, argv: List, timeout: int, n_devices: int = 8
) -> Dict:
    """Launch ``python -m module *argv`` on an ``n_devices``-CPU mesh;
    returns the parsed last stdout JSON line, or an {"error": ...} dict
    carrying the best diagnostic (probes print their failure JSON to
    STDOUT before exiting nonzero; a hung or killed child reports too,
    never hangs the caller)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"{os.environ.get('XLA_FLAGS', '')} "
        f"--xla_force_host_platform_device_count={n_devices}"
    ).strip()
    try:
        p = subprocess.run(
            [sys.executable, "-m", module, *[str(a) for a in argv]],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        lines = p.stdout.strip().splitlines()
        if p.returncode != 0 or not lines:
            return {
                "error": (lines[-1] if lines else "")[-300:]
                or p.stderr[-300:]
            }
        return json.loads(lines[-1])
    except Exception as e:  # noqa: BLE001 - diagnostics only
        return {"error": str(e)[:300]}
