"""Bench-artifact diff: compare two ``BENCH_DETAIL_r{N}.json`` rounds.

The bench trajectory so far is raw JSON files — judging round N against
round M meant eyeballing two trees. This tool makes the comparison a
command with a machine-readable verdict (the standalone twin of
``bench.py --gate``, which only gates the CURRENT run):

    python -m orientdb_tpu.tools.perfdiff BENCH_DETAIL_r12.json \
        BENCH_DETAIL_r14.json [--json] [--tol 0.55] [--ms-tol 0.85] \
        [--overlap-tol 0.2] [--hbm-tol 1.5]

Compared signals (the bench gate's two, plus overlap and peak HBM):

- **q/s leaves** — every ``*qps`` number under ``extras`` (and the
  ``ldbc_is`` per-query families) plus the headline ``value``; a drop
  below ``--tol`` × base is a regression (default 0.55 = the measured
  ±40% tunnel-noise envelope);
- **phase-split ms leaves** — ``device_ms``/``host_ms`` per workload;
  the STABLE signal (device time never crosses the tunnel), gated at
  ``--ms-tol`` (default 0.85), sub-0.5 ms bases skipped as jitter;
- **overlap metrics** (once both rounds carry them — the obs/timeline
  ``overlap`` blocks in ``concurrent_sessions``, per-shard
  ``mesh_scaling`` records, and the headline tier's
  ``headline_overlap`` block, ROADMAP item 4's named acceptance
  leaves): device-idle fraction RISING or transfer-hidden fraction
  FALLING by more than ``--overlap-tol`` absolute (default 0.2) is a
  regression — the overlap machinery stopped hiding work even if
  wall-clock noise masks it. The tolerance is ABSOLUTE (not a ratio)
  because the fractions live in [0, 1]: a 0.2 swing is one fifth of
  the whole scale, far past scheduler jitter (~0.02), while ratio
  gates on near-zero idle fractions would trip on noise;
- **critical-path segment leaves** (once both rounds carry the
  obs/critpath ``critpath`` extras block — per-workload per-segment ms
  from the headline tier): gated with the phase-split discipline —
  ``--ms-tol`` ratio (default 0.85: current must stay under
  base/0.85), sub-``ms_floor`` (0.5 ms) bases skipped as jitter — so
  a regression names the SEGMENT that grew, not just the workload;
- **peak-HBM leaves** (once both rounds carry the obs/memledger
  ``memory`` evidence record): the attributed device-memory peak and
  each owner kind's peak; growth past ``--hbm-tol`` × base (default
  1.5) is a regression — a perf win that silently costs half again as
  much HBM is not a win. Sub-64 KiB bases are skipped as allocator
  noise.

Output: one JSON document on stdout — ``verdict`` ("pass" |
"regression"), per-signal regression/improvement lists, and the
headline ratio. Exit code 0 = pass, 2 = regression (the bench gate's
convention), 1 = unreadable input. ``--json`` keeps stdout pure JSON;
without it a human summary also prints to stderr.

Accepts either the detail-artifact shape (``{"value", "extras": ...}``)
or a driver-recorded ``BENCH_r{N}.json`` wrapper (``{"parsed": ...}``).
"""

from __future__ import annotations

import json
import sys
from typing import Dict, Iterator, List, Optional, Tuple


def _load(path: str) -> Optional[Dict]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"perfdiff: cannot read {path}: {e}", file=sys.stderr)
        return None
    if isinstance(doc, dict):
        doc = doc.get("parsed") or doc
    if not isinstance(doc, dict):
        print(f"perfdiff: {path} holds no result object", file=sys.stderr)
        return None
    return doc


def qps_leaves(d: Dict, prefix: str = "") -> Iterator[Tuple[str, float]]:
    """Every throughput leaf under an extras tree (the bench gate's
    walk: ``*qps`` keys anywhere, every numeric leaf under ldbc_is)."""
    for k, v in (d or {}).items():
        if isinstance(v, dict):
            yield from qps_leaves(v, f"{prefix}{k}.")
        elif isinstance(v, (int, float)) and (
            k.endswith("qps")
            or prefix.startswith("ldbc_is")
            or prefix.endswith("ldbc_is.")
        ):
            yield prefix + k, float(v)


def ms_leaves(d: Dict) -> Iterator[Tuple[str, float]]:
    for wl, split in (d or {}).items():
        if not isinstance(split, dict):
            continue
        for f in ("device_ms", "host_ms"):
            v = split.get(f)
            if isinstance(v, (int, float)):
                yield f"{wl}.{f}", float(v)


def overlap_leaves(extras: Dict) -> Iterator[Tuple[str, float]]:
    """(metric path, value) for every overlap fraction a round
    recorded: the concurrent_sessions block's and each mesh_scaling
    shard count's device-idle / transfer-hidden numbers."""

    def emit(tag: str, ov: Dict) -> Iterator[Tuple[str, float]]:
        if not isinstance(ov, dict) or not ov.get("records"):
            return
        idle = ov.get("device_idle_fraction")
        if isinstance(idle, (int, float)):
            yield f"{tag}.device_idle_fraction", float(idle)
        tr = ov.get("transfer")
        hidden = (
            tr.get("transfer_hidden_fraction")
            if isinstance(tr, dict)
            else ov.get("transfer_hidden_fraction")
        )
        if isinstance(hidden, (int, float)):
            yield f"{tag}.transfer_hidden_fraction", float(hidden)

    conc = (extras.get("concurrent_sessions") or {}).get("overlap")
    if conc:
        yield from emit("concurrent_sessions", conc)
    # the headline tier's own overlap block (ROADMAP item 4's named
    # acceptance leaves): device-idle / transfer-hidden over the
    # headline trio's dispatches
    head = extras.get("headline_overlap")
    if head:
        yield from emit("headline", head)
    for rec in extras.get("mesh_scaling") or []:
        if isinstance(rec, dict) and isinstance(rec.get("overlap"), dict):
            yield from emit(
                f"mesh_scaling.{rec.get('shards', '?')}", rec["overlap"]
            )


def segment_leaves(extras: Dict) -> Iterator[Tuple[str, float]]:
    """(metric path, ms) for the critical-path segment breakdown a
    round carried (the obs/critpath ``critpath`` extras block:
    ``{workload: {segment: ms_per_query}}``)."""
    for wl, segs in sorted((extras.get("critpath") or {}).items()):
        if not isinstance(segs, dict):
            continue
        for seg, v in sorted(segs.items()):
            if isinstance(v, (int, float)):
                yield f"critpath.{wl}.{seg}", float(v)


def hbm_leaves(extras: Dict) -> Iterator[Tuple[str, float]]:
    """(metric path, bytes) for the device-memory record a round
    carried (the obs/memledger ``memory`` evidence block): the
    attributed peak plus each owner kind's peak."""
    mem = extras.get("memory")
    if not isinstance(mem, dict):
        return
    v = mem.get("peak_bytes")
    if isinstance(v, (int, float)):
        yield "memory.peak_bytes", float(v)
    for kind, pv in sorted((mem.get("peak_by_owner") or {}).items()):
        if isinstance(pv, (int, float)):
            yield f"memory.peak.{kind}", float(pv)


def degraded_round(doc: Optional[Dict]) -> bool:
    """True when a round's evidence records degraded-mode dispatches —
    quarantine-driven oracle fallbacks, admission sheds, or plan
    quarantines from the device fault domain (the per-round
    ``device_faults`` evidence block, exec/devicefault) — or
    correctness-plane findings: shadow-oracle parity divergences or
    scrub repairs (the ``parity_audit`` block, exec/audit). A chaos or
    diverged round measures the ladder, not the fast path:
    ``bench._last_good_round`` skips these so one can never become the
    regression baseline."""
    ex = (doc or {}).get("extras") or {}
    df = ex.get("device_faults")
    if isinstance(df, dict) and any(
        int(df.get(k) or 0) > 0
        for k in ("oracle_served", "sheds", "quarantines")
    ):
        return True
    pa = ex.get("parity_audit")
    if isinstance(pa, dict) and any(
        int(pa.get(k) or 0) > 0
        for k in ("diverged", "scrub_corruptions", "scrub_repairs")
    ):
        return True
    return False


def diff(
    base: Dict,
    cur: Dict,
    tol: float = 0.55,
    ms_tol: float = 0.85,
    overlap_tol: float = 0.2,
    ms_floor: float = 0.5,
    hbm_tol: float = 1.5,
    hbm_floor: float = float(1 << 16),
) -> Dict:
    """The comparison document (pure function — tests drive it on
    synthetic rounds)."""
    b_ex, c_ex = base.get("extras") or {}, cur.get("extras") or {}
    b_q = dict(qps_leaves(b_ex))
    c_q = dict(qps_leaves(c_ex))
    b_q["headline"] = float(base.get("value") or 0.0)
    c_q["headline"] = float(cur.get("value") or 0.0)
    qps_reg: List[Dict] = []
    qps_imp: List[Dict] = []
    compared = 0
    for name, bv in sorted(b_q.items()):
        cv = c_q.get(name)
        if cv is None or bv <= 0:
            continue
        compared += 1
        row = {
            "metric": name,
            "base": bv,
            "cur": cv,
            "ratio": round(cv / bv, 3),
        }
        if cv < bv * tol:
            qps_reg.append(row)
        elif bv < cv * tol:  # the same envelope, in the other direction
            qps_imp.append(row)
    b_ms = dict(ms_leaves(b_ex.get("phase_split_ms_per_query") or {}))
    c_ms = dict(ms_leaves(c_ex.get("phase_split_ms_per_query") or {}))
    ms_reg: List[Dict] = []
    ms_imp: List[Dict] = []
    for name, bv in sorted(b_ms.items()):
        cv = c_ms.get(name)
        if cv is None or bv < ms_floor:
            continue
        compared += 1
        row = {
            "metric": name,
            "base": bv,
            "cur": cv,
            "ratio": round(cv / bv, 3),
        }
        if cv > bv / ms_tol:
            ms_reg.append(row)
        elif cv < bv * ms_tol:
            ms_imp.append(row)
    b_ov = dict(overlap_leaves(b_ex))
    c_ov = dict(overlap_leaves(c_ex))
    ov_reg: List[Dict] = []
    ov_deltas: Dict[str, Dict] = {}
    for name in sorted(set(b_ov) & set(c_ov)):
        bv, cv = b_ov[name], c_ov[name]
        delta = round(cv - bv, 4)
        ov_deltas[name] = {"base": bv, "cur": cv, "delta": delta}
        worse = (
            delta > overlap_tol
            if name.endswith("device_idle_fraction")
            else delta < -overlap_tol
        )
        if worse:
            ov_reg.append(
                {"metric": name, "base": bv, "cur": cv, "delta": delta}
            )
    b_seg = dict(segment_leaves(b_ex))
    c_seg = dict(segment_leaves(c_ex))
    seg_reg: List[Dict] = []
    seg_imp: List[Dict] = []
    for name, bv in sorted(b_seg.items()):
        cv = c_seg.get(name)
        if cv is None or bv < ms_floor:
            continue
        compared += 1
        row = {
            "metric": name,
            "base": bv,
            "cur": cv,
            "ratio": round(cv / bv, 3),
        }
        if cv > bv / ms_tol:
            seg_reg.append(row)
        elif cv < bv * ms_tol:
            seg_imp.append(row)
    b_hbm = dict(hbm_leaves(b_ex))
    c_hbm = dict(hbm_leaves(c_ex))
    hbm_reg: List[Dict] = []
    hbm_imp: List[Dict] = []
    for name, bv in sorted(b_hbm.items()):
        cv = c_hbm.get(name)
        if cv is None or bv < hbm_floor:
            continue
        compared += 1
        row = {
            "metric": name,
            "base": bv,
            "cur": cv,
            "ratio": round(cv / bv, 3),
        }
        if cv > bv * hbm_tol:
            hbm_reg.append(row)
        elif cv < bv / hbm_tol:
            hbm_imp.append(row)
    regressions = (
        [dict(r, kind="qps") for r in qps_reg]
        + [dict(r, kind="ms") for r in ms_reg]
        + [dict(r, kind="overlap") for r in ov_reg]
        + [dict(r, kind="segment") for r in seg_reg]
        + [dict(r, kind="hbm") for r in hbm_reg]
    )
    hb, hc = b_q["headline"], c_q["headline"]
    return {
        "headline": {
            "base": hb,
            "cur": hc,
            "ratio": round(hc / hb, 3) if hb else None,
        },
        "compared": compared,
        "qps": {"regressions": qps_reg, "improvements": qps_imp},
        "ms": {"regressions": ms_reg, "improvements": ms_imp},
        "overlap": {"deltas": ov_deltas, "regressions": ov_reg},
        "segments": {"regressions": seg_reg, "improvements": seg_imp},
        "hbm": {"regressions": hbm_reg, "improvements": hbm_imp},
        "regressions": regressions,
        "verdict": "regression" if regressions else "pass",
        "thresholds": {
            "tol": tol,
            "ms_tol": ms_tol,
            "overlap_tol": overlap_tol,
            "hbm_tol": hbm_tol,
        },
    }


def _human(rep: Dict, base_path: str, cur_path: str) -> None:
    h = rep["headline"]
    print(
        f"perfdiff {base_path} -> {cur_path}: headline "
        f"{h['base']} -> {h['cur']} "
        f"({h['ratio'] if h['ratio'] is not None else 'n/a'}x), "
        f"{rep['compared']} metrics compared",
        file=sys.stderr,
    )
    for r in rep["regressions"]:
        print(
            f"  REGRESSION [{r['kind']}] {r['metric']}: "
            f"{r['base']} -> {r['cur']}",
            file=sys.stderr,
        )
    for kind in ("qps", "ms", "segments", "hbm"):
        for r in rep[kind]["improvements"]:
            print(
                f"  improvement [{kind}] {r['metric']}: "
                f"{r['base']} -> {r['cur']}",
                file=sys.stderr,
            )
    print(f"verdict: {rep['verdict']}", file=sys.stderr)


_USAGE = (
    "usage: python -m orientdb_tpu.tools.perfdiff "
    "BASE_DETAIL.json CUR_DETAIL.json [--json] [--tol X] "
    "[--ms-tol X] [--overlap-tol X] [--hbm-tol X]"
)


def main(argv: List[str]) -> int:
    vals = {"tol": 0.55, "ms-tol": 0.85, "overlap-tol": 0.2, "hbm-tol": 1.5}
    pos: List[str] = []
    as_json = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--json":
            as_json = True
        elif a.startswith("--"):
            name, _, raw = a[2:].partition("=")
            if not raw and i + 1 < len(argv):
                i += 1
                raw = argv[i]
            if name not in vals:
                print(_USAGE, file=sys.stderr)
                return 1
            try:
                vals[name] = float(raw)
            except ValueError:
                print(_USAGE, file=sys.stderr)
                return 1
        else:
            pos.append(a)
        i += 1
    if len(pos) != 2:
        print(_USAGE, file=sys.stderr)
        return 1
    base = _load(pos[0])
    cur = _load(pos[1])
    if base is None or cur is None:
        return 1
    rep = diff(
        base,
        cur,
        tol=vals["tol"],
        ms_tol=vals["ms-tol"],
        overlap_tol=vals["overlap-tol"],
        hbm_tol=vals["hbm-tol"],
    )
    rep["base"] = pos[0]
    rep["cur"] = pos[1]
    if not as_json:
        _human(rep, pos[0], pos[1])
    print(json.dumps(rep, indent=1, sort_keys=True))
    return 2 if rep["verdict"] == "regression" else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
