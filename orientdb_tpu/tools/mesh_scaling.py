"""Shard-count scaling probe for the ring-compacted expansion merge.

Run as a subprocess per shard count (the CPU device count is fixed at
process start):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=S \
        python -m orientdb_tpu.tools.mesh_scaling S

Builds a demodb-shaped graph with one planted SUPERNODE (the §5.7 skew
case the merge design is judged on), runs a row-returning 1-hop MATCH
through the supernode on an S-shard mesh, and prints one JSON line:

    {"shards": S, "merge_rows": N, "allgather_rows": M, "wall_s": T}

``merge_rows`` is what the ring-compacted merge shipped per recording
(O(pow2 global total)); ``allgather_rows`` is what the previous
all_gather-of-cap-blocks design would have shipped (O(S·pow2 local
max)) — the bench records the pair per S so the curve shows merge bytes
sublinear in S under skew (VERDICT r3 #6)."""

from __future__ import annotations

import json
import sys
import time


def main(shards: int) -> None:
    from orientdb_tpu.parallel.sharded import make_mesh
    from orientdb_tpu.storage.ingest import generate_demodb
    from orientdb_tpu.storage.snapshot import attach_fresh_snapshot
    from orientdb_tpu.utils.metrics import metrics

    db = generate_demodb(n_profiles=2000, avg_friends=5, seed=11)
    # plant a supernode: profile 0 follows 1500 others — one shard's
    # local expansion max is ~1500 while the balanced share is ~10
    docs = {d["uid"]: d for d in db.browse_class("Profiles")}
    hub, n = docs[0], len(docs)
    for k in range(1, 1501):
        db.new_edge("HasFriend", hub, docs[k % (n - 1) + 1])
    mesh = make_mesh(shards, replicas=1)
    attach_fresh_snapshot(db, mesh=mesh)
    sql = (
        "MATCH {class:Profiles, as:p, where:(uid < 40)}"
        "-HasFriend->{as:f} RETURN p.uid AS p, f.uid AS f"
    )
    before = metrics.snapshot()["counters"]
    t0 = time.perf_counter()
    rows = db.query(sql, engine="tpu", strict=True).to_dicts()
    wall = time.perf_counter() - t0
    after = metrics.snapshot()["counters"]
    assert rows, "probe query returned nothing"
    print(
        json.dumps(
            {
                "shards": shards,
                "merge_rows": after.get("mesh.merge_rows", 0)
                - before.get("mesh.merge_rows", 0),
                "allgather_rows": after.get("mesh.allgather_rows", 0)
                - before.get("mesh.allgather_rows", 0),
                "wall_s": round(wall, 2),
                "result_rows": len(rows),
            }
        )
    )


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
