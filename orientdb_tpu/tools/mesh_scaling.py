"""Shard-count scaling probe for the frontier-sparse sharded MATCH path.

Run as a subprocess per shard count (the CPU device count is fixed at
process start):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=S \
        python -m orientdb_tpu.tools.mesh_scaling S

or standalone across a sweep (each shard count in its own subprocess,
for bisection without a full bench round):

    python -m orientdb_tpu.tools.mesh_scaling --sweep 2,4,8 --json

Builds a demodb-shaped graph with one planted SUPERNODE (the §5.7 skew
case the merge design is judged on), runs a row-returning 1-hop MATCH
through the supernode on an S-shard mesh, and prints one JSON record per
shard count (the same record shape bench.py's ``mesh_scaling`` block
stores):

    {"shards": S, "merge_rows": N, "allgather_rows": M, "wall_s": T,
     "replay_s": R, "collective_kb": C, "frontier_occupancy": F,
     "empty_shard_skips": K, "kernel_builds": J, "result_rows": n,
     "overlap": {"records": d, "device_idle_fraction": i,
                 "transfer_hidden_fraction": h, "paths": {...}}}

``overlap`` is the flight recorder's verdict over the probe's own
dispatches (obs/timeline): how idle the devices sat between them and
how many transferred bytes hid behind compute.

``merge_rows`` is what the ring-compacted merge shipped per recording
(O(pow2 global total)); ``allgather_rows`` is what the pre-rework
all_gather-of-cap-blocks design would have shipped (O(S·pow2 local
max)). ``collective_kb`` counts the packed psum segment bytes per hop,
``frontier_occupancy`` is live expansion rows over dense slot rows
(how sparse the frontier the collectives no longer pay for), and
``empty_shard_skips`` counts shards whose gather/scatter was
cond-skipped outright. ``wall_s`` is the cold first query
(record + kernel compiles), ``replay_s`` the median sync-free replay —
the steady-state serving cost chips actually scale. ``kernel_builds``
reads the mesh.kernel_builds counter (memoized kernel wrappers built —
the trace-cache roots): revisiting a geometry must add zero (the
recompile-free contract tests/test_sharded.py pins)."""

from __future__ import annotations

import json
import sys
import time


def main(shards: int) -> None:
    from orientdb_tpu.parallel.sharded import make_mesh
    from orientdb_tpu.storage.ingest import generate_demodb
    from orientdb_tpu.storage.snapshot import attach_fresh_snapshot
    from orientdb_tpu.utils.metrics import metrics

    db = generate_demodb(n_profiles=2000, avg_friends=5, seed=11)
    # plant a supernode: profile 0 follows 1500 others — one shard's
    # local expansion max is ~1500 while the balanced share is ~10
    docs = {d["uid"]: d for d in db.browse_class("Profiles")}
    hub, n = docs[0], len(docs)
    for k in range(1, 1501):
        db.new_edge("HasFriend", hub, docs[k % (n - 1) + 1])
    mesh = make_mesh(shards, replicas=1)
    attach_fresh_snapshot(db, mesh=mesh)
    sql = (
        "MATCH {class:Profiles, as:p, where:(uid < 40)}"
        "-HasFriend->{as:f} RETURN p.uid AS p, f.uid AS f"
    )
    before = metrics.snapshot()["counters"]
    t0 = time.perf_counter()
    rows = db.query(sql, engine="tpu", strict=True).to_dicts()
    wall = time.perf_counter() - t0
    after = metrics.snapshot()["counters"]
    assert rows, "probe query returned nothing"

    def delta(name):
        return after.get(name, 0) - before.get(name, 0)

    # steady state: the cached plan replays sync-free — the cost a
    # scaled-out serving fleet actually pays per query
    replays = []
    for _ in range(3):
        t1 = time.perf_counter()
        db.query(sql, engine="tpu", strict=True).to_dicts()
        replays.append(time.perf_counter() - t1)
    live = delta("mesh.frontier_live_rows")
    slots = delta("mesh.frontier_slot_rows")
    # overlap verdict for the probe's own dispatches (obs/timeline):
    # the sharded records land in THIS subprocess's flight recorder, so
    # the bench mesh_scaling block's per-S evidence carries device-idle
    # and transfer-hidden fractions next to the collective counters
    from orientdb_tpu.obs.timeline import recorder as _flight

    rep = _flight.overlap()
    overlap = {
        "records": rep.get("records", 0),
        "device_idle_fraction": rep.get("device_idle_fraction"),
        "transfer_hidden_fraction": (rep.get("transfer") or {}).get(
            "transfer_hidden_fraction"
        ),
        "paths": rep.get("paths", {}),
    }
    print(
        json.dumps(
            {
                "shards": shards,
                "merge_rows": delta("mesh.merge_rows"),
                "allgather_rows": delta("mesh.allgather_rows"),
                "wall_s": round(wall, 2),
                "replay_s": round(sorted(replays)[1], 3),
                "collective_kb": round(delta("mesh.collective_bytes") / 1024, 1),
                "frontier_occupancy": round(live / slots, 4) if slots else None,
                "empty_shard_skips": delta("mesh.empty_shard_skips"),
                "kernel_builds": delta("mesh.kernel_builds"),
                "result_rows": len(rows),
                "overlap": overlap,
            }
        )
    )


def sweep(shard_counts, as_json: bool) -> int:
    """Per-S subprocesses (the virtual CPU device count is pinned at
    process start) emitting the bench-block record shape — runnable
    standalone so a mesh regression bisects without a bench round. One
    hung or malformed shard count records an error and the sweep keeps
    going (the bench twin clamps the same way)."""
    from orientdb_tpu.tools.virtual_mesh import run_virtual_mesh_subprocess

    out = []
    rc = 0
    for S in shard_counts:
        res = run_virtual_mesh_subprocess(
            "orientdb_tpu.tools.mesh_scaling", [S], timeout=300, n_devices=S
        )
        res.setdefault("shards", S)
        if "error" in res:
            rc = 1
        out.append(res)
    if as_json:
        print(json.dumps(out))
    else:
        for rec in out:
            print(json.dumps(rec))
    return rc


if __name__ == "__main__":
    argv = sys.argv[1:]
    if "--sweep" in argv:
        i = argv.index("--sweep")
        try:
            counts = [int(s) for s in argv[i + 1].split(",") if s]
        except (IndexError, ValueError):
            print(
                "usage: python -m orientdb_tpu.tools.mesh_scaling "
                "--sweep 2,4,8 [--json]",
                file=sys.stderr,
            )
            sys.exit(2)
        sys.exit(sweep(counts, as_json="--json" in argv))
    try:
        shards = int(argv[0]) if argv else 8
    except ValueError:
        print(
            "usage: python -m orientdb_tpu.tools.mesh_scaling "
            "[SHARDS | --sweep 2,4,8 [--json]]",
            file=sys.stderr,
        )
        sys.exit(2)
    main(shards)
