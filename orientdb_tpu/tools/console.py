"""Interactive console.

Analog of [E] OConsoleDatabaseApp (`console.sh`, SURVEY.md §2 "Console"):
connect to an embedded (`embedded:<name>`) or remote
(`remote:<host>:<port>/<db>`) database, run SQL, inspect schema, and
export/import portable JSON dumps.

Commands (case-insensitive; anything unrecognized is sent as SQL):
  CONNECT <url> [user] [password]     CREATE DATABASE <name>
  LIST DATABASES                      INFO
  CLASSES                             BROWSE CLASS <name>
  LOAD RECORD <rid>                   EXPORT DATABASE <path>
  IMPORT DATABASE <path>              DISCONNECT / QUIT / EXIT
  SLOWLOG [<n>|CLEAR]                 DIAG [<path>]
  STATS QUERIES [<k>]                 STATS PROFILE / STATS RESET
  CDC LIST                            CDC LAG
  ALERTS [<n>|HISTORY]                HEALTH
  SLO                                 TIMELINE [<n>]
  MEMORY [OWNERS|WATERMARK]           CRITPATH [<k>]
"""

from __future__ import annotations

import cmd
import shlex
import sys
from typing import Optional

from orientdb_tpu.models.database import Database


class Console(cmd.Cmd):
    intro = "orientdb-tpu console — CONNECT embedded:<name> to begin; QUIT to exit."
    prompt = "orientdb-tpu> "

    def __init__(self, stdout=None) -> None:
        super().__init__(stdout=stdout or sys.stdout)
        self.db = None
        self.remote = None
        self._embedded: dict = {}

    # -- helpers ------------------------------------------------------------

    def parseline(self, line):
        # commands are case-insensitive (CONNECT == connect); the raw line
        # still reaches default() untouched so SQL keeps its case
        c, arg, ln = super().parseline(line)
        return (c.lower() if c else c), arg, ln

    def _p(self, *lines) -> None:
        for ln in lines:
            print(ln, file=self.stdout)

    def _need_db(self) -> bool:
        if self.db is None and self.remote is None:
            self._p("!! not connected; use CONNECT embedded:<name>")
            return False
        return True

    def _run_sql(self, sql: str) -> None:
        try:
            target = self.remote if self.remote is not None else self.db
            rows = target.command(sql).to_dicts()
            for i, r in enumerate(rows):
                self._p(f"# {i}: {r}")
            self._p(f"({len(rows)} rows)")
        except Exception as e:
            self._p(f"!! {type(e).__name__}: {e}")

    # -- commands ------------------------------------------------------------

    def do_connect(self, arg: str) -> None:
        """CONNECT embedded:<name> | remote:<host>:<port>/<db> [user] [pw]"""
        parts = shlex.split(arg)
        if not parts:
            self._p("!! usage: CONNECT <url> [user] [password]")
            return
        url = parts[0]
        user = parts[1] if len(parts) > 1 else "admin"
        pw = parts[2] if len(parts) > 2 else "admin"
        try:
            if url.startswith("remote:"):
                from orientdb_tpu.client.remote import connect

                self.remote = connect(url, user, pw)
                self.db = None
                self._p(f"connected to {url}")
            else:
                name = url.split(":", 1)[1] if ":" in url else url
                self.db = self._embedded.setdefault(name, Database(name))
                self.remote = None
                self._p(f"connected to embedded database '{name}'")
        except Exception as e:
            self._p(f"!! {type(e).__name__}: {e}")

    def do_disconnect(self, _arg: str) -> None:
        if self.remote is not None:
            self.remote.close()
        self.db = self.remote = None
        self._p("disconnected")

    def do_create(self, arg: str) -> None:
        """CREATE DATABASE <name> (embedded); other CREATE ... goes to SQL."""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "database":
            name = parts[1]
            self.db = self._embedded.setdefault(name, Database(name))
            self.remote = None
            self._p(f"database '{name}' created")
            return
        self.default(f"create {arg}")

    def do_list(self, arg: str) -> None:
        """LIST DATABASES"""
        if arg.lower().strip() == "databases":
            if self.remote is not None:
                self._p(*self.remote.databases())
            else:
                self._p(*sorted(self._embedded))
            return
        self.default(f"list {arg}")

    def do_info(self, _arg: str) -> None:
        if not self._need_db():
            return
        if self.remote is not None:
            self._p(f"remote database '{self.remote.name}'")
            return
        s = self.db.current_snapshot()
        self._p(
            f"database '{self.db.name}'",
            f"classes: {len(list(self.db.schema.classes()))}",
            f"mutation epoch: {self.db.mutation_epoch}",
            f"snapshot: {'attached' if s is not None else 'none'}"
            + (" (stale)" if self.db.snapshot_is_stale else ""),
        )

    def do_classes(self, _arg: str) -> None:
        if not self._need_db() or self.db is None:
            return
        for c in sorted(self.db.schema.classes(), key=lambda c: c.name):
            kind = "V" if c.is_vertex_type else "E" if c.is_edge_type else "O"
            n = 0 if c.abstract else self.db.count_class(c.name, polymorphic=False)
            self._p(f"{c.name:<24} {kind} abstract={c.abstract} records={n}")

    def do_browse(self, arg: str) -> None:
        """BROWSE CLASS <name>"""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "class":
            self._run_sql(f"SELECT FROM {parts[1]}")
            return
        self.default(f"browse {arg}")

    def do_load(self, arg: str) -> None:
        """LOAD RECORD <rid>"""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "record":
            if not self._need_db():
                return
            target = self.remote if self.remote is not None else self.db
            doc = target.load(parts[1])
            if doc is None:
                self._p(f"!! record {parts[1]} not found")
            else:
                self._p(str(doc.to_dict() if hasattr(doc, "to_dict") else doc))
            return
        self.default(f"load {arg}")

    def do_export(self, arg: str) -> None:
        """EXPORT DATABASE <path>"""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "database":
            if not self._need_db() or self.db is None:
                return
            from orientdb_tpu.storage.ingest import export_database

            export_database(self.db, parts[1])
            self._p(f"exported to {parts[1]}")
            return
        self.default(f"export {arg}")

    def do_import(self, arg: str) -> None:
        """IMPORT DATABASE <path>"""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "database":
            from orientdb_tpu.storage.ingest import import_database

            self.db = import_database(parts[1])
            self._embedded[self.db.name] = self.db
            self.remote = None
            self._p(f"imported database '{self.db.name}'")
            return
        self.default(f"import {arg}")

    def do_backup(self, arg: str) -> None:
        """BACKUP DATABASE <path> — online zip backup (frozen-window
        consistency; [E] the reference's BACKUP DATABASE)."""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "database":
            if not self._need_db() or self.db is None:
                return
            from orientdb_tpu.storage.backup import backup_database

            backup_database(self.db, parts[1])
            self._p(f"backup written to {parts[1]}")
            return
        self.default(f"backup {arg}")

    def do_restore(self, arg: str) -> None:
        """RESTORE DATABASE <path>"""
        parts = shlex.split(arg)
        if len(parts) == 2 and parts[0].lower() == "database":
            from orientdb_tpu.storage.backup import restore_database

            self.db = restore_database(parts[1])
            self._embedded[self.db.name] = self.db
            self.remote = None
            self._p(f"restored database '{self.db.name}'")
            return
        self.default(f"restore {arg}")

    def do_fsck(self, arg: str) -> None:
        """FSCK <directory> | FSCK BACKUP <zip> — verify durable-state
        integrity: WAL CRC chains + segment continuity, checkpoint/
        delta/epoch content hashes, coldstore tails; BACKUP adds the
        archive's restore-and-rehash round trip (tools/fsck)."""
        parts = shlex.split(arg)
        from orientdb_tpu.tools.fsck import (
            format_report,
            fsck_backup,
            fsck_tree,
        )

        if len(parts) == 2 and parts[0].lower() == "backup":
            self._p(format_report(fsck_backup(parts[1])))
            return
        if len(parts) == 1 and parts[0]:
            self._p(format_report(fsck_tree(parts[0])))
            return
        self.default(f"fsck {arg}")

    def do_script(self, arg: str) -> None:
        """SCRIPT <sql batch>  — LET/IF/RETURN and ';'-separated
        statements in one session ([E] the console's script command)."""
        if not self._need_db():
            return
        try:
            target = self.remote if self.remote is not None else self.db
            rows = target.execute("sql", arg).to_dicts()
            for i, r in enumerate(rows):
                self._p(f"# {i}: {r}")
            self._p(f"({len(rows)} rows)")
        except Exception as e:
            self._p(f"!! {type(e).__name__}: {e}")

    def do_slowlog(self, arg: str) -> None:
        """SLOWLOG [<n>|CLEAR] — recent slow queries (most recent
        first; threshold = config.slow_query_ms, 0 disables)."""
        from orientdb_tpu.obs.slowlog import slowlog
        from orientdb_tpu.utils.config import config

        a = arg.strip().lower()
        if a == "clear":
            slowlog.clear()
            self._p("slowlog cleared")
            return
        limit = int(a) if a.isdigit() else 20
        entries = slowlog.entries(limit)
        if not entries:
            self._p(
                "slowlog empty "
                f"(threshold {config.slow_query_ms:g} ms; 0 = disabled)"
            )
            return
        for e in entries:
            trace = f" trace={e['trace_id']}" if e.get("trace_id") else ""
            # the fingerprint is the pivot into STATS QUERIES: one slow
            # query joins its shape's cumulative cost on this id
            fp = f" fp={e['fingerprint']}" if e.get("fingerprint") else ""
            cache = f" cache={e['cache']}" if e.get("cache") else ""
            self._p(
                f"{e['ms']:>9.1f} ms  [{e['engine']}]{fp}{cache}{trace}"
                f"  {e['sql']}"
            )
        self._p(f"({len(entries)} entries)")

    def do_stats(self, arg: str) -> None:
        """STATS QUERIES [<k>] — top-k query shapes by cumulative
        latency (fingerprint, calls, errors, mean ms, device/compile
        ms, cache hits); STATS PROFILE — per-stage self-time from the
        span aggregator; STATS RESET — clear both planes."""
        from orientdb_tpu.obs.profile import profiler
        from orientdb_tpu.obs.stats import stats

        parts = arg.split()
        sub = parts[0].lower() if parts else "queries"
        if sub == "reset":
            stats.reset()
            profiler.reset()
            self._p("query stats and profile reset")
            return
        if sub == "profile":
            rows = profiler.flat(20)
            if not rows:
                self._p("profile empty")
                return
            self._p(f"{'self ms':>12} {'total ms':>12} {'count':>8}  stage")
            for r in rows:
                self._p(
                    f"{r['self_ms']:>12.1f} {r['total_ms']:>12.1f} "
                    f"{r['count']:>8}  {r['name']}"
                )
            return
        if sub != "queries":
            self._p("!! usage: STATS QUERIES [<k>] | PROFILE | RESET")
            return
        k = int(parts[1]) if len(parts) > 1 and parts[1].isdigit() else 10
        rows = stats.top(k)
        if not rows:
            self._p("no recorded queries")
            return
        self._p(
            f"{'fingerprint':<16} {'calls':>7} {'err':>5} {'mean ms':>9} "
            f"{'p50 ms':>8} {'p99 ms':>8} "
            f"{'dev ms':>9} {'compile ms':>11} {'cache':>6}  query"
        )
        for r in rows:
            self._p(
                f"{r['fingerprint']:<16} {r['calls']:>7} {r['errors']:>5} "
                f"{r['mean_ms']:>9.2f} "
                f"{r['p50_ms']:>8.1f} {r['p99_ms']:>8.1f} "
                f"{r['device_s'] * 1000:>9.1f} "
                f"{r['compile_s'] * 1000:>11.1f} "
                f"{r['plan_cache_hits'] + r['result_cache_hits']:>6}  "
                f"{r['query'][:70]}"
            )
        self._p(f"({len(rows)} shapes)")

    def do_timeline(self, arg: str) -> None:
        """TIMELINE [<n>] — the dispatch flight recorder (obs/timeline):
        the overlap verdict over the recent window (device-idle /
        transfer-hidden fractions, ring savings, lane decomposition)
        followed by the last n dispatch records (default 10). The full
        Perfetto-loadable export is GET /debug/timeline."""
        from orientdb_tpu.obs.timeline import recorder
        from orientdb_tpu.utils.config import config

        a = arg.strip()
        n = int(a) if a.isdigit() else 10
        rep = recorder.overlap(window_s=config.timeline_window_s)
        if not rep.get("records"):
            self._p(
                "timeline empty (no dispatches in the last "
                f"{config.timeline_window_s:g} s; capacity "
                f"{config.timeline_capacity})"
            )
            return
        tr = rep.get("transfer", {})
        ring = rep.get("ring", {})
        pf = rep.get("prefetch", {})
        self._p(
            f"{rep['records']} dispatches over {rep['span_s']:.2f} s  "
            f"device idle {rep['device_idle_fraction']:.1%}  "
            f"transfer hidden {tr.get('transfer_hidden_fraction', 0.0):.1%} "
            f"({tr.get('hidden_bytes', 0)}/{tr.get('bytes', 0)} B)",
            f"ring hits {ring.get('hits', 0)}/"
            f"{ring.get('hits', 0) + ring.get('uploads', 0)}  "
            f"prefetch {pf.get('hits', 0)} hit / {pf.get('misses', 0)} "
            f"miss / {pf.get('starts', 0)} started  "
            f"paths {rep.get('paths', {})}",
        )
        lane = rep.get("lane")
        if lane:
            self._p(
                f"lane: queue {lane.get('queue_ms_mean')} ms  window "
                f"{lane.get('window_ms_mean')} ms  service "
                f"{lane.get('service_ms_mean')} ms "
                f"({lane['dispatches']} drains)"
            )
        recs = recorder.records(
            window_s=config.timeline_window_s, limit=n
        )
        for r in recs:
            dev_ms = sum(b - a_ for a_, b in r.get("device", [])) * 1e3
            nbytes = sum(t[2] for t in r.get("transfers", []))
            fp = r.get("fingerprint") or "-"
            self._p(
                f"#{r['seq']:<6} {r['path']:<8} n={r['n']:<4} fp={fp:<16} "
                f"device {dev_ms:>7.2f} ms  {nbytes:>8} B  "
                f"{len(r['events'])} events"
            )
        self._p(f"({len(recs)} records)")

    def do_critpath(self, arg: str) -> None:
        """CRITPATH [<k>] — per-request critical-path attribution
        (obs/critpath): per-SLO-class segment breakdowns with the
        dominant bottleneck, then the top-k fingerprints by cumulative
        wall (default 10) with their mean per-segment split. The full
        document (catalog, recent decompositions) is
        GET /stats/critpath."""
        from orientdb_tpu.obs.critpath import plane

        a = arg.strip()
        k = int(a) if a.isdigit() else 10
        rep = plane.report(k)
        if not rep["requests"]:
            state = "enabled" if rep["enabled"] else "disabled"
            self._p(f"no decompositions recorded (critpath {state})")
            return
        self._p(f"{rep['requests']} sampled requests decomposed")
        for name, c in rep["by_class"].items():
            segs = ", ".join(
                f"{s} {ms:.2f}" for s, ms in
                list(c["segments_ms_mean"].items())[:5]
            )
            self._p(
                f"class {name}: {c['requests']} req  mean "
                f"{c['wall_ms_mean']:.2f} ms  dominant "
                f"{c['dominant'] or '-'}  [{segs}]"
            )
        self._p(
            f"{'fingerprint':<16} {'req':>6} {'mean ms':>9} "
            f"{'dominant':<16} segments (mean ms)"
        )
        for r in rep["fingerprints"]:
            segs = ", ".join(
                f"{s} {ms:.2f}" for s, ms in
                list(r["segments_ms_mean"].items())[:4]
            )
            self._p(
                f"{r['fingerprint']:<16} {r['requests']:>6} "
                f"{r['wall_ms_mean']:>9.2f} "
                f"{(r['dominant'] or '-'):<16} {segs}"
            )
        self._p(f"({len(rep['fingerprints'])} shapes)")

    def do_memory(self, arg: str) -> None:
        """MEMORY [OWNERS|WATERMARK] — the device-memory ledger
        (obs/memledger): OWNERS (the default) prints the per-kind HBM
        rollup, the reconciliation verdict against jax.live_arrays,
        and lease/refusal state; WATERMARK prints the recent
        total-bytes watermark ring. The full document is
        GET /debug/memory."""
        from orientdb_tpu.obs.memledger import memledger

        sub = (arg.strip().split() or ["owners"])[0].lower()
        if sub not in ("owners", "watermark"):
            self._p("!! usage: MEMORY [OWNERS|WATERMARK]")
            return
        if sub == "watermark":
            marks = memledger.watermarks()
            if not marks:
                self._p("watermark ring empty (no device registrations)")
                return
            for ts, b in marks:
                self._p(f"{ts:>14.3f}  {b:>14} B  ({b / (1 << 20):8.2f} MiB)")
            self._p(
                f"({len(marks)} marks, peak {memledger.peak_total()} B)"
            )
            return
        rep = memledger.report()
        for kind, row in rep["owners"].items():
            self._p(
                f"{kind:<16} {row['bytes']:>12} B  "
                f"entries={row['entries']:<5} owners={row['owners']:<4} "
                f"oldest={row['oldest_s']:g}s"
            )
        self._p(
            f"total {rep['total_bytes']} B  peak {rep['peak_bytes']} B  "
            f"pinned {rep['pinned_bytes']} B  entries {rep['entries']}"
        )
        rec = rep.get("reconcile") or {}
        if rec:
            self._p(
                f"reconcile: {'ok' if rec.get('ok') else 'RESIDUE'}  "
                f"untracked={rec.get('untracked_bytes', 0)} B  "
                f"tracked_dead={rec.get('tracked_dead_bytes', 0)} B  "
                f"reclaimed={rec.get('reclaimed_bytes', 0)} B"
            )
        leases = rep.get("leases", {})
        stale = leases.get("stale", [])
        self._p(
            f"leases: {leases.get('outstanding', 0)} outstanding, "
            f"{len(stale)} stale"
        )
        for lease in stale:
            self._p(
                f"  !! epoch {lease['epoch']} held {lease['age_s']:g}s "
                f"trace={lease['trace_id'] or '-'}"
            )
        refusals = rep.get("refusals", {})
        if refusals.get("counts"):
            last = refusals.get("last") or {}
            self._p(
                f"refusals: {refusals['counts']}"
                + (
                    f"  last={last.get('reason')}: {last.get('detail')}"
                    if last
                    else ""
                )
            )

    def do_cdc(self, arg: str) -> None:
        """CDC LIST — changefeed consumers and durable cursors per
        connected embedded database; CDC LAG — head LSN and per-consumer
        lag / queue depth / shed counts (the slow-consumer triage
        view)."""
        sub = (arg.strip().split() or ["list"])[0].lower()
        if sub not in ("list", "lag"):
            self._p("!! usage: CDC LIST | CDC LAG")
            return
        dbs = list(self._embedded.values())
        if self.db is not None and self.db not in dbs:
            dbs.append(self.db)
        feeds = [
            (db, db.__dict__.get("_cdc_feed"))
            for db in dbs
            if db.__dict__.get("_cdc_feed") is not None
        ]
        if not feeds:
            self._p("no changefeeds (no database has subscribers)")
            return
        for db, feed in feeds:
            s = feed.stats()
            if sub == "list":
                self._p(
                    f"database '{db.name}': head_lsn={s['head_lsn']} "
                    f"consumers={len(s['consumers'])} "
                    f"cursors={len(s['cursors'])}"
                )
                for c in s["consumers"]:
                    name = c["name"] or "-"
                    cls = ",".join(c["classes"] or []) or "*"
                    self._p(
                        f"  #{c['token']:<4} {name:<16} classes={cls} "
                        f"mode={c['mode']} policy={c['policy']}"
                    )
                for name, cur in sorted(s["cursors"].items()):
                    self._p(f"  cursor {name:<16} lsn={cur['lsn']}")
            else:
                self._p(f"database '{db.name}': head_lsn={s['head_lsn']}")
                for c in s["consumers"]:
                    name = c["name"] or f"#{c['token']}"
                    self._p(
                        f"  {name:<16} lag={c['lag_entries']:<6} "
                        f"queue={c['queue_depth']:<6} "
                        f"unacked={c['unacked_entries']:<6} "
                        f"shed={c['shed_events']}"
                    )

    def do_alerts(self, arg: str) -> None:
        """ALERTS [<n>|HISTORY] — the alert plane (obs/alerts): active
        pending/firing alerts with exemplar trace ids; HISTORY lists
        recently resolved ones."""
        from orientdb_tpu.obs.alerts import engine

        a = arg.strip().lower()
        if a == "history":
            items = engine.history(20)
            if not items:
                self._p("no resolved alerts")
                return
            for e in items:
                self._p(
                    f"[resolved] {e['rule']}({e['key']}) "
                    f"value={e['value']:g} thr={e['threshold']:g}"
                    + (
                        f" trace={e['exemplar_trace_id']}"
                        if e.get("exemplar_trace_id")
                        else ""
                    )
                )
            self._p(f"({len(items)} resolved)")
            return
        limit = int(a) if a.isdigit() else 20
        items = engine.active()[:limit]
        if not items:
            self._p("no active alerts")
            return
        for e in items:
            trace = (
                f" trace={e['exemplar_trace_id']}"
                if e.get("exemplar_trace_id")
                else ""
            )
            self._p(
                f"[{e['state']:<7}] {e['severity']:<8} "
                f"{e['rule']}({e['key']}) value={e['value']:g} "
                f"thr={e['threshold']:g}{trace}  {e['detail']}"
            )
        self._p(f"({len(items)} active)")

    def do_slo(self, _arg: str) -> None:
        """SLO — the last traffic-simulator run's SLO verdict
        (obs/slo): pass/fail, error-budget burn, per-class windowed
        p50/p99 vs targets, and every failure naming its rule/key."""
        from orientdb_tpu.obs.slo import engine as slo_engine

        r = slo_engine.report()
        if r.get("verdict") == "none":
            self._p("no SLO run recorded (workloads.driver.TrafficSim)")
            return
        self._p(
            f"verdict: {r['verdict'].upper()}  burn={r['burn']:g}  "
            f"calls={r['calls']} errors={r['errors']}  "
            f"window={r['window_s']:g}s"
        )
        self._p(
            f"{'class':<10} {'calls':>7} {'err':>5} {'p50 ms':>9} "
            f"{'p99 ms':>9} {'targets (p50/p99/avail)':>26}"
        )
        for c in r["classes"]:
            t = c["targets"]
            self._p(
                f"{c['class']:<10} {c['calls']:>7} {c['errors']:>5} "
                f"{c.get('p50_ms', 0.0):>9.1f} {c.get('p99_ms', 0.0):>9.1f} "
                f"{t['p50_ms']:>10g}/{t['p99_ms']:g}/{t['availability']:g}"
            )
        for f in r["failures"]:
            self._p(f"FAIL {f['rule']}({f['key']}): {f['detail']}")
        if not r["failures"]:
            self._p("(no failures)")

    def do_health(self, _arg: str) -> None:
        """HEALTH — watchdog summary (rules/ticks/lifecycle totals),
        circuit-breaker states, and per-database in-doubt 2PC counts —
        the console's answer to GET /cluster/health."""
        from orientdb_tpu.obs.alerts import engine
        from orientdb_tpu.parallel.resilience import breaker_snapshot

        s = engine.summary()
        self._p(
            f"watchdog: rules={s['rules']} ticks={s['ticks']} "
            f"firing={s['firing']} pending={s['pending']} "
            f"fired_total={s['fired_total']} "
            f"resolved_total={s['resolved_total']} "
            f"baselines={s['baselines']}"
            + (
                f" tick_age={s['tick_age_s']:g}s"
                if s["tick_age_s"] is not None
                else " (no tick yet)"
            )
        )
        breakers = breaker_snapshot()
        for name, b in sorted(breakers.items()):
            self._p(f"breaker {name}: {b['state']}")
        if not breakers:
            self._p("no circuit breakers registered")
        dbs = list(self._embedded.values())
        if self.db is not None and self.db not in dbs:
            dbs.append(self.db)
        for db in dbs:
            reg = getattr(db, "_tx2pc_registry", None)
            staged = len(reg.staged_report()) if reg is not None else 0
            if staged:
                self._p(f"database '{db.name}': {staged} in-doubt 2pc")

    def do_diag(self, arg: str) -> None:
        """DIAG [<path>] — flight-recorder debug bundle (obs/bundle):
        recent traces assembled by trace id, the slowlog, a metrics
        snapshot, and in-doubt 2PC state. With a path, the full JSON
        artifact is written there; either way a summary prints."""
        import json

        from orientdb_tpu.obs.bundle import debug_bundle

        dbs = list(self._embedded.values())
        if self.db is not None and self.db not in dbs:
            dbs.append(self.db)
        bundle = debug_bundle(dbs=dbs, member="console")
        path = arg.strip()
        if path:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(bundle, f, sort_keys=True, default=str)
            self._p(f"debug bundle written to {path}")
        traces = bundle["traces"]
        n_spans = sum(len(t["spans"]) for t in traces)
        indoubt = bundle["in_doubt_2pc"]
        staged = sum(len(v) for v in indoubt["staged"].values())
        self._p(
            f"traces: {len(traces)} ({n_spans} spans)",
            f"slowlog entries: {len(bundle['slowlog'])}",
            f"in-doubt 2pc: {staged} staged, "
            f"{len(indoubt['coordinator_reports'])} coordinator reports",
            f"metric counters: {len(bundle['metrics']['counters'])}",
        )
        for t in traces[-3:]:
            names = [s["name"] for s in t["spans"]]
            self._p(
                f"  {t['trace_id']}: "
                + " -> ".join(names[:8])
                + (" ..." if len(names) > 8 else "")
            )

    def do_quit(self, _arg: str) -> bool:
        return True

    do_exit = do_quit
    do_EOF = do_quit

    def default(self, line: str) -> None:
        if not self._need_db():
            return
        self._run_sql(line)

    def emptyline(self) -> None:
        pass


def main() -> None:  # pragma: no cover - interactive entry
    Console().cmdloop()


if __name__ == "__main__":  # pragma: no cover
    main()
