"""Multi-host (multi-process) sharded execution — the DCN analog.

The reference scales across servers with Hazelcast over TCP
(SURVEY.md §2 "Distributed", §5.8); the TPU-native control plane is the
**jax distributed runtime**: N processes, each owning a slice of the
device mesh, executing ONE logical SPMD program — collectives ride ICI
within a host and DCN (here: Gloo over loopback TCP) between hosts
(SURVEY.md:149, 352 "host/control plane + multi-slice = jax distributed
runtime / gRPC over DCN").

``main(process_id, coordinator_port, n_procs, local_devices)`` joins the
process group, builds the SAME demodb-shaped graph in every process
(deterministic seed — the ingest analog of every host reading the same
snapshot), attaches it sharded over the GLOBAL mesh, and runs the
BASELINE-shaped sharded-MATCH corpus (`tools/dryrun.QUERIES`) at oracle
parity. Each process holds only its addressable shards of adjacency and
property columns (O(V/S + E/S) per process); replicated results are
fully addressable everywhere, so materialization needs no extra
cross-host step.

Run by `tests/test_multihost.py` as 2 real processes on one machine —
the multi-server-in-one-JVM pattern of the reference's distributed tests
(SURVEY.md §4), with real inter-process collectives.
"""

from __future__ import annotations

import os
import sys


def main(
    process_id: int,
    coordinator_port: int,
    n_procs: int = 2,
    local_devices: int = 4,
) -> int:
    os.environ["JAX_PLATFORMS"] = "cpu"
    kept = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(
        kept + [f"--xla_force_host_platform_device_count={local_devices}"]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coordinator_port}",
        num_processes=n_procs,
        process_id=process_id,
    )
    import numpy as np

    from orientdb_tpu.parallel.sharded import make_mesh
    from orientdb_tpu.storage.ingest import generate_demodb
    from orientdb_tpu.storage.snapshot import attach_fresh_snapshot
    from orientdb_tpu.tools.dryrun import QUERIES

    devs = jax.devices()
    assert len(devs) == n_procs * local_devices, (
        f"expected {n_procs * local_devices} global devices, got {len(devs)}"
    )
    n_local = len(jax.local_devices())
    assert n_local == local_devices
    # 2 replicas x (n_procs*local/2) shards: the shard axis SPANS hosts,
    # so expansion all_gathers and bitmap psums cross the process boundary
    mesh = make_mesh(len(devs), replicas=2, devices=devs)
    db = generate_demodb(n_profiles=64, avg_friends=4, seed=1)
    attach_fresh_snapshot(db, mesh=mesh)

    def canon(rows):
        return sorted(tuple(sorted(r.items())) for r in rows)

    for sql, params in QUERIES:
        recorded = canon(
            db.query(sql, params=params, engine="tpu", strict=True).to_dicts()
        )
        replayed = canon(
            db.query(sql, params=params, engine="tpu", strict=True).to_dicts()
        )
        oracle = canon(db.query(sql, params=params, engine="oracle").to_dicts())
        assert recorded == oracle, f"[proc {process_id}] record parity: {sql}"
        assert replayed == oracle, f"[proc {process_id}] replay parity: {sql}"
    # per-process memory really is a slice, not a replica
    from orientdb_tpu.ops.device_graph import device_graph

    rep = device_graph(db.current_snapshot()).memory_report()
    adj_l, adj_d = rep["logical"]["adjacency"], rep["per_device"]["adjacency"]
    assert adj_d * 2 < adj_l, f"adjacency not sharded: {adj_d} vs {adj_l}"
    print(
        f"multihost ok: proc {process_id}/{n_procs}, mesh "
        f"{dict(mesh.shape)}, {len(QUERIES)} queries at oracle parity, "
        f"adjacency {adj_d}B/device of {adj_l}B logical",
        flush=True,
    )
    return 0


def probe(process_id: int, coordinator_port: int, n_procs: int = 2) -> int:
    """Backend-capability probe: join a minimal process group (one
    device per process) and run ONE cross-process collective — the
    smallest operation the full corpus depends on. Prints
    ``multihost collectives ok`` on success; a backend without
    multiprocess collectives (jaxlib's CPU backend in most containers:
    ``Multiprocess computations aren't implemented on the CPU
    backend``) fails fast instead, so ``tests/test_multihost.py`` can
    SKIP as an environment limitation rather than read red."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    kept = [
        f
        for f in os.environ.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(
        kept + ["--xla_force_host_platform_device_count=1"]
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"127.0.0.1:{coordinator_port}",
        num_processes=n_procs,
        process_id=process_id,
    )
    import numpy as np
    from jax.experimental import multihost_utils

    out = multihost_utils.broadcast_one_to_all(np.int32(41) + 1)
    assert int(out) == 42, f"collective returned {out!r}"
    print(f"multihost collectives ok: proc {process_id}", flush=True)
    return 0


if __name__ == "__main__":
    argv = sys.argv[1:]
    if argv and argv[0] == "--probe":
        sys.exit(
            probe(
                int(argv[1]),
                int(argv[2]),
                int(argv[3]) if len(argv) > 3 else 2,
            )
        )
    sys.exit(
        main(
            int(argv[0]),
            int(argv[1]),
            int(argv[2]) if len(argv) > 2 else 2,
            int(argv[3]) if len(argv) > 3 else 4,
        )
    )
