"""Sharded config-5 probe: the SNB-interactive-shaped graph executed on
a virtual S-device mesh (VERDICT r4 #2's "sharded sub-block").

Run as a subprocess so the CPU device count can be forced:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=S \
        python -m orientdb_tpu.tools.sharded_sf S N_PERSONS

Builds `storage.bigshape.build_snb_shape` (Person-knows with a
creationDate EDGE column + Message-hasCreator), shards it over the mesh
(adjacency + property columns row-sharded, O(E/S) per device —
`ops/device_graph.py`), checks the multi-pattern edge-property-WHERE
COUNT against the exact numpy reference, and prints ONE JSON line:

    {"shards": S, "persons": P, "knows_edges": E,
     "per_device_hbm": {...}, "config5_qps": Q, "wall_s": T}
"""

from __future__ import annotations

import json
import sys
import time

CONFIG5_SQL = (
    "MATCH {class:Person, as:p, where:(age > 40)}"
    ".outE('knows'){where:(creationDate > :d)}"
    ".inV(){as:f, where:(age < 30)}, "
    "{class:Message, as:m}-hasCreator->{as:f} "
    "RETURN count(*) AS n"
)


def main(shards: int, n_persons: int) -> None:
    from orientdb_tpu.ops.device_graph import device_graph
    from orientdb_tpu.parallel.sharded import make_mesh
    from orientdb_tpu.storage.bigshape import (
        build_snb_shape,
        numpy_config5_count,
    )

    db, snap = build_snb_shape(
        n_persons, msgs_per_person=2, avg_knows=10, seed=7
    )
    snap._mesh = make_mesh(shards, replicas=1)
    t0 = time.perf_counter()
    # parity gate (compiles the sharded plan as a side effect)
    d0 = 15_000
    got = db.query(
        CONFIG5_SQL, params={"d": d0}, engine="tpu", strict=True
    ).to_dicts()
    want = numpy_config5_count(snap, d0)
    if got != [{"n": want}]:
        print(
            json.dumps(
                {"shards": shards, "error": f"parity: {got} != {want}"}
            )
        )
        sys.exit(1)
    # timed replays across parameter values (plan is parameter-generic)
    n_queries = 8
    t1 = time.perf_counter()
    for i in range(n_queries):
        d = 12_000 + (i * 911) % 7000
        rows = db.query(
            CONFIG5_SQL, params={"d": d}, engine="tpu", strict=True
        ).to_dicts()
        assert rows and "n" in rows[0]
    dt = time.perf_counter() - t1
    rep = device_graph(snap).memory_report()
    print(
        json.dumps(
            {
                "shards": shards,
                "persons": int(n_persons),
                "knows_edges": int(snap.edge_classes["knows"].num_edges),
                "per_device_hbm": rep["per_device"],
                "config5_qps": round(n_queries / dt, 3),
                "wall_s": round(time.perf_counter() - t0, 1),
            }
        )
    )


if __name__ == "__main__":
    main(
        int(sys.argv[1]) if len(sys.argv) > 1 else 8,
        int(sys.argv[2]) if len(sys.argv) > 2 else 1_000_000,
    )
