"""Columnar predicate compiler: WHERE AST → fused device masks.

The reference evaluates `WHERE` as an interpreted expression tree per
candidate record inside the MATCH hot loop ([E] OExpression eval inside
MatchEdgeTraverser, SURVEY.md §3.3). Here the same AST compiles once per
query into a closure over device property columns; applied to a whole
frontier it is a handful of vectorized compares/selects that XLA fuses into
the expansion gathers ("edge-property WHERE predicates fused in" — the
north star).

Semantics contract: must agree with `orientdb_tpu/exec/eval.py` on the
columnar subset — parity tests replay the golden corpus through both
engines. Key OrientDB null rules preserved:
  - any comparison with null is false (only IS NULL sees nulls);
  - `!=` additionally needs both sides non-null;
  - AND/OR collapse null to false; NOT(null) is true;
  - type-mismatched `=` is false, `<` family is false, while `!=` of two
    non-null incomparable values is true (values_equal falls back to
    Python `==`).

String columns are dictionary-encoded with a *sorted* dictionary, so:
  - ordered compares against a literal become int32 compares versus the
    literal's bisect rank;
  - LIKE / MATCHES / CONTAINSTEXT are evaluated host-side over the (small)
    dictionary and pushed to device as a boolean code-membership table.

Anything outside the subset raises `Uncompilable`; the engine front door
falls back to the oracle interpreter, keeping behavior total.
"""

from __future__ import annotations

import bisect
import re
from typing import Callable, Dict, Optional, Sequence, Set, Tuple

import jax.numpy as jnp
import numpy as np

from orientdb_tpu.exec.eval import like_match
from orientdb_tpu.ops.device_graph import DeviceColumn
from orientdb_tpu.sql import ast as A


class Uncompilable(Exception):
    """Expression outside the columnar subset; caller falls back."""


def split_params(params: Dict) -> Tuple[Dict[object, str], Dict[object, object]]:
    """Partition query parameters into *dynamic* (numeric — become jit
    arguments of the cached plan, so one compiled plan serves every value)
    and *static* (everything else — baked into the compiled predicates, so
    their values join the plan-cache key). int32 range is the TPU-native
    integer width; out-of-range ints stay static and hit `_const_val`'s
    range gate (→ oracle fallback)."""
    dyn: Dict[object, str] = {}
    static: Dict[object, object] = {}
    for k, v in params.items():
        if isinstance(v, bool):
            dyn[k] = "bool"
        elif isinstance(v, int) and -(2**31) < v < 2**31:
            dyn[k] = "int"
        elif isinstance(v, float):
            dyn[k] = "float"
        else:
            static[k] = v
    return dyn, static


class ParamBox:
    """Mutable parameter environment shared by a solver's compiled
    predicate closures.

    Recording runs read the concrete values from ``current``; a cached
    plan's replay swaps traced jit-argument scalars into ``current`` for
    the duration of the trace, making every numeric parameter a runtime
    input of ONE compiled executable instead of a compile-time constant
    (the [E] OExecutionPlanCache caches per *statement*, not per binding
    set — this is the TPU-native equivalent)."""

    def __init__(self, params: Dict) -> None:
        self.initial = dict(params)
        self.current = dict(params)
        self.dynamic, self.static = split_params(params)
        #: dynamic keys actually referenced by some compiled predicate
        self.used: Dict[object, str] = {}

    def __contains__(self, k) -> bool:
        return k in self.initial

    def set_current(self, values: Dict) -> None:
        self.current = {**self.initial, **values}

    def reset(self) -> None:
        self.current = dict(self.initial)


class ColumnScope:
    """Resolves bare field names for one predicate scope (a vertex alias or
    an edge class' property columns).

    With ``binding_columns`` set (a second, vertex-property scope) the
    compiler also accepts ``alias.prop`` references for aliases in
    ``visible_aliases``: they emit per-slot gathers through the alias'
    binding column, which the caller provides at evaluation time via
    ``env["bindings"][alias]`` (an int32 vertex-index array aligned with
    the mask slots). This is how edge/node WHERE clauses that reference
    earlier MATCH bindings compile ([E] the reference evaluates them
    per-candidate inside MatchEdgeTraverser with the binding context)."""

    def __init__(
        self,
        columns: Dict[str, DeviceColumn],
        non_columnar: Set[str],
        reserved: Set[str] = frozenset(),
        binding_columns: Optional[Dict[str, DeviceColumn]] = None,
        binding_non_columnar: Set[str] = frozenset(),
        visible_aliases: Set[str] = frozenset(),
    ) -> None:
        self.columns = columns
        self.non_columnar = non_columnar
        #: names that are MATCH aliases / variables → binding-dependent
        self.reserved = reserved
        self.binding_columns = binding_columns
        self.binding_non_columnar = binding_non_columnar
        self.visible_aliases = visible_aliases
        #: set True by the compiler when any binding reference compiled —
        #: callers must then pass env["bindings"] at evaluation time
        self.uses_bindings = False

    def resolve(self, name: str) -> Optional[DeviceColumn]:
        if name in self.reserved:
            raise Uncompilable(f"identifier {name!r} is a bound alias/variable")
        if name.startswith("@") or name.startswith("$"):
            raise Uncompilable(f"meta field {name!r} not columnar")
        if name in self.columns:
            return self.columns[name]
        if name in self.non_columnar:
            raise Uncompilable(f"property {name!r} has no columnar encoding")
        return None  # never present → null column

    def resolve_binding(self, alias: str, prop: str) -> Optional[DeviceColumn]:
        """Column for ``alias.prop`` where alias is a visible bound alias;
        raises Uncompilable when ineligible."""
        if self.binding_columns is None or alias not in self.visible_aliases:
            raise Uncompilable(f"alias {alias!r} not visible to this predicate")
        if prop.startswith("@") or prop.startswith("$"):
            raise Uncompilable(f"meta field {prop!r} not columnar")
        if prop in self.binding_columns:
            self.uses_bindings = True
            return self.binding_columns[prop]
        if prop in self.binding_non_columnar:
            raise Uncompilable(f"property {prop!r} has no columnar encoding")
        self.uses_bindings = True
        return None  # never present → null column


# A value node: kind + emit(idx, env) -> (values, present). kind one of
# 'int' 'float' 'bool' 'str' 'null'. For 'str', `dictionary` carries the
# sorted host dictionary. A bool node: emit(idx, env) -> mask.
class _Val:
    __slots__ = ("kind", "emit", "dictionary", "column")

    def __init__(self, kind: str, emit, dictionary=None, column=None):
        self.kind = kind
        self.emit = emit
        self.dictionary = dictionary
        #: source DeviceColumn for 'str' column reads — carries the
        #: delta maintainer's dict_unsorted flag (O(1) sortedness check)
        self.column = column


BoolFn = Callable[[jnp.ndarray, dict], jnp.ndarray]


def _const_val(v) -> _Val:
    if v is None:
        return _Val("null", lambda idx, env: (jnp.zeros(idx.shape, jnp.int32), jnp.zeros(idx.shape, bool)))
    if isinstance(v, bool):
        return _Val("bool", lambda idx, env, v=v: (
            jnp.full(idx.shape, int(v), jnp.int32), jnp.ones(idx.shape, bool)))
    if isinstance(v, int):
        if not (-(2**31) < v < 2**31):
            # float32 demotion would lose precision vs the oracle's exact
            # integer compare near the boundary — fall back instead
            raise Uncompilable(f"integer literal {v} outside int32 range")
        return _Val("int", lambda idx, env, v=v: (
            jnp.full(idx.shape, v, jnp.int32), jnp.ones(idx.shape, bool)))
    if isinstance(v, float):
        return _Val("float", lambda idx, env, v=v: (
            jnp.full(idx.shape, v, jnp.float32), jnp.ones(idx.shape, bool)))
    if isinstance(v, str):
        # literal strings stay host-side; comparisons handle them specially
        return _Val("strlit", lambda idx, env: None, dictionary=v)
    raise Uncompilable(f"literal {v!r} not columnar")


def _column_val(col: DeviceColumn) -> _Val:
    def emit(idx, env, col=col):
        n = col.values.shape[0]
        if n == 0:
            return (jnp.zeros(idx.shape, col.values.dtype), jnp.zeros(idx.shape, bool))
        ok = idx >= 0
        ci = jnp.clip(idx, 0, n - 1)
        return (
            jnp.take(col.values, ci),
            jnp.take(col.present, ci) & ok,
        )

    return _Val(col.kind, emit, dictionary=col.dictionary, column=col)


def _binding_val(alias: str, col: DeviceColumn) -> _Val:
    """``alias.prop``: the per-slot vertex index comes from
    env["bindings"][alias] (same length as the mask slots), then the
    property gathers through it."""

    def emit(idx, env, alias=alias, col=col):
        rows = env["bindings"][alias]
        n = col.values.shape[0]
        if n == 0:
            return (jnp.zeros(rows.shape, col.values.dtype), jnp.zeros(rows.shape, bool))
        ok = rows >= 0
        ci = jnp.clip(rows, 0, n - 1)
        return (
            jnp.take(col.values, ci),
            jnp.take(col.present, ci) & ok,
        )

    return _Val(col.kind, emit, dictionary=col.dictionary, column=col)


_NUMERIC = ("int", "float", "bool")


def _promote(a: _Val, b: _Val):
    """Numeric promotion for arithmetic/compare: int32 unless any float."""
    return "float" if "float" in (a.kind, b.kind) else "int"


def _as_dtype(vals, present, kind):
    if kind == "float":
        return vals.astype(jnp.float32), present
    return vals.astype(jnp.int32), present


class Compiler:
    def __init__(self, scope: ColumnScope, params: Dict, allow_depth: bool = False):
        self.scope = scope
        self.params = params
        self.allow_depth = allow_depth

    # -- entry -------------------------------------------------------------

    def compile_bool(self, expr: A.Expression) -> BoolFn:
        return self._bool(expr)

    # -- value nodes -------------------------------------------------------

    def _value(self, expr: A.Expression) -> _Val:
        if isinstance(expr, A.Literal):
            return _const_val(expr.value)
        if isinstance(expr, A.Parameter):
            key = expr.name if expr.name is not None else expr.index
            if key not in self.params:
                sig = f":{expr.name}" if expr.name is not None else f"?{expr.index}"
                raise Uncompilable(f"missing parameter {sig}")
            return self._param_val(key)
        if isinstance(expr, A.Identifier):
            col = self.scope.resolve(expr.name)
            if col is None:
                return _const_val(None)
            return _column_val(col)
        if (
            isinstance(expr, A.FieldAccess)
            and isinstance(expr.base, A.Identifier)
            and self.scope.binding_columns is not None
            and expr.base.name in self.scope.visible_aliases
        ):
            alias = expr.base.name
            col = self.scope.resolve_binding(alias, expr.name)
            if col is None:
                return _const_val(None)
            return _binding_val(alias, col)
        if isinstance(expr, A.ContextVar):
            if expr.name == "depth" and self.allow_depth:
                return _Val(
                    "int",
                    lambda idx, env: (
                        jnp.full(idx.shape, env["depth"], jnp.int32),
                        jnp.ones(idx.shape, bool),
                    ),
                )
            raise Uncompilable(f"context var ${expr.name} not columnar")
        if isinstance(expr, A.Unary):
            if expr.op in ("-", "+"):
                v = self._value(expr.expr)
                if v.kind not in _NUMERIC:
                    raise Uncompilable("unary minus on non-numeric")
                if expr.op == "+":
                    return v

                def emit(idx, env, v=v):
                    vals, pres = v.emit(idx, env)
                    return -vals, pres

                return _Val("int" if v.kind in ("int", "bool") else "float", emit)
            raise Uncompilable(f"unary {expr.op} is boolean")
        if isinstance(expr, A.Binary) and expr.op in ("+", "-", "*", "/", "%"):
            return self._arith(expr)
        if (
            isinstance(expr, A.FunctionCall)
            and expr.name.lower() == "distance"
        ):
            return self._distance(expr)
        raise Uncompilable(f"expression {type(expr).__name__} not columnar")

    def _distance(self, expr: A.FunctionCall) -> _Val:
        """Device haversine ([E] OSQLFunctionDistance): spatial predicates
        like ``distance(lat, lng, :x, :y) < r`` evaluate over the float
        columns on device — all V distances in one fused elementwise pass
        instead of a per-row host loop."""
        from orientdb_tpu.utils.geo import (
            EARTH_RADIUS_KM,
            MILE_UNITS,
            MILES_PER_KM,
        )

        if len(expr.args) not in (4, 5):
            raise Uncompilable("distance() takes 4 args (+ optional unit)")
        scale = 1.0
        if len(expr.args) == 5:
            u = expr.args[4]
            if not isinstance(u, A.Literal) or str(u.value).lower() not in (
                MILE_UNITS | {"km"}
            ):
                raise Uncompilable("distance() unit must be a literal")
            if str(u.value).lower() != "km":
                scale = MILES_PER_KM
        vals = [self._value(a) for a in expr.args[:4]]
        for v in vals:
            if v.kind == "null":
                return _const_val(None)
            # bool is numeric to arithmetic but the host oracle's
            # distance() rejects it (returns null) — match by falling back
            if v.kind not in ("int", "float"):
                raise Uncompilable("non-numeric distance() operand")

        def emit(idx, env, vals=vals, scale=scale):
            rads = []
            pres = jnp.ones(idx.shape, bool)
            for v in vals:
                vv, vp = _as_dtype(*v.emit(idx, env), "float")
                rads.append(jnp.deg2rad(vv))
                pres = pres & vp
            lat1, lon1, lat2, lon2 = rads
            h = (
                jnp.sin((lat2 - lat1) / 2.0) ** 2
                + jnp.cos(lat1) * jnp.cos(lat2) * jnp.sin((lon2 - lon1) / 2.0) ** 2
            )
            d = (
                2.0
                * EARTH_RADIUS_KM
                * jnp.arcsin(jnp.sqrt(jnp.clip(h, 0.0, 1.0)))
                * scale
            )
            return d, pres

        return _Val("float", emit)

    def _param_val(self, key) -> _Val:
        """A parameter reference: dynamic numerics read the box's current
        value (a concrete number while recording, a traced jit argument on
        replay); everything else bakes as a constant."""
        box = self.params
        if not isinstance(box, ParamBox) or key not in box.dynamic:
            v = box.initial[key] if isinstance(box, ParamBox) else box[key]
            return _const_val(v)
        kind = box.dynamic[key]
        box.used[key] = kind
        dtype = jnp.float32 if kind == "float" else jnp.int32

        def emit(idx, env, box=box, key=key, dtype=dtype):
            v = jnp.asarray(box.current[key]).astype(dtype)
            return (
                jnp.broadcast_to(v, idx.shape),
                jnp.ones(idx.shape, bool),
            )

        return _Val(kind, emit)

    def _arith(self, expr: A.Binary) -> _Val:
        a = self._value(expr.left)
        b = self._value(expr.right)
        if a.kind in ("strlit", "str") or b.kind in ("strlit", "str"):
            raise Uncompilable("string arithmetic not columnar")
        if a.kind == "null" or b.kind == "null":
            return _const_val(None)
        if a.kind not in _NUMERIC or b.kind not in _NUMERIC:
            raise Uncompilable("non-numeric arithmetic")
        op = expr.op
        kind = _promote(a, b)
        if op == "/":
            kind = "float"  # exact-int division equals float division numerically

        def emit(idx, env, a=a, b=b, op=op, kind=kind):
            av, ap = _as_dtype(*a.emit(idx, env), kind)
            bv, bp = _as_dtype(*b.emit(idx, env), kind)
            pres = ap & bp
            if op == "+":
                out = av + bv
            elif op == "-":
                out = av - bv
            elif op == "*":
                out = av * bv
            elif op == "/":
                pres = pres & (bv != 0)
                out = av / jnp.where(bv != 0, bv, 1)
            else:  # %
                pres = pres & (bv != 0)
                out = jnp.mod(av, jnp.where(bv != 0, bv, 1))
            return out, pres

        return _Val(kind, emit)

    # -- boolean nodes -----------------------------------------------------

    def _bool(self, expr: A.Expression) -> BoolFn:
        if isinstance(expr, A.Binary):
            op = expr.op
            if op == "AND":
                l, r = self._bool(expr.left), self._bool(expr.right)
                return lambda idx, env: l(idx, env) & r(idx, env)
            if op == "OR":
                l, r = self._bool(expr.left), self._bool(expr.right)
                return lambda idx, env: l(idx, env) | r(idx, env)
            if op in ("=", "!=", "<", "<=", ">", ">="):
                return self._compare(op, expr.left, expr.right)
            if op in ("LIKE", "MATCHES", "CONTAINSTEXT"):
                return self._string_table_op(op, expr.left, expr.right)
            if op == "IN":
                return self._in(expr.left, expr.right)
            raise Uncompilable(f"operator {op} not columnar")
        if isinstance(expr, A.Unary) and expr.op == "NOT":
            inner = self._bool(expr.expr)
            return lambda idx, env: ~inner(idx, env)
        if isinstance(expr, A.Between):
            ge = self._compare(">=", expr.expr, expr.low)
            le = self._compare("<=", expr.expr, expr.high)
            return lambda idx, env: ge(idx, env) & le(idx, env)
        if isinstance(expr, A.IsNull):
            v = self._value(expr.expr)
            if v.kind == "strlit":
                raise Uncompilable("IS NULL on string literal")
            neg = expr.negated

            def isnull(idx, env, v=v, neg=neg):
                if v.kind == "null":
                    pres = jnp.zeros(idx.shape, bool)
                else:
                    _, pres = v.emit(idx, env)
                return pres if neg else ~pres

            return isnull
        if isinstance(expr, A.Literal) and isinstance(expr.value, bool):
            b = expr.value
            return lambda idx, env: jnp.full(idx.shape, b, bool)
        # truthiness of a bare value (where:(flag))
        try:
            v = self._value(expr)
        except Uncompilable:
            raise
        return self._truthy(v)

    def _truthy(self, v: _Val) -> BoolFn:
        if v.kind == "null":
            return lambda idx, env: jnp.zeros(idx.shape, bool)
        if v.kind == "strlit":
            b = bool(v.dictionary)
            return lambda idx, env: jnp.full(idx.shape, b, bool)
        if v.kind == "str":
            # non-empty string is truthy: host-eval over the dictionary
            table = np.array([bool(s) for s in (v.dictionary or [])], bool)
            return self._code_table_mask(v, table)

        def fn(idx, env, v=v):
            vals, pres = v.emit(idx, env)
            return pres & (vals != 0)

        return fn

    def _code_table_mask(self, v: _Val, table: np.ndarray) -> BoolFn:
        dev = jnp.asarray(table) if table.size else jnp.zeros(1, bool)

        def fn(idx, env, v=v, dev=dev, empty=not table.size):
            vals, pres = v.emit(idx, env)
            if empty:
                return jnp.zeros(idx.shape, bool)
            code = jnp.clip(vals, 0, dev.shape[0] - 1)
            return pres & jnp.take(dev, code)

        return fn

    def _string_table_op(self, op: str, left: A.Expression, right: A.Expression) -> BoolFn:
        lv = self._value(left)
        rv = self._value(right)
        if rv.kind != "strlit":
            raise Uncompilable(f"{op} needs a literal pattern")
        pat = rv.dictionary
        if lv.kind == "null":
            return lambda idx, env: jnp.zeros(idx.shape, bool)
        if lv.kind == "strlit":
            # literal op literal: host constant (oracle semantics)
            s = lv.dictionary
            if op == "LIKE":
                res = like_match(s, pat)
            elif op == "MATCHES":
                res = re.fullmatch(pat, s) is not None
            else:
                res = pat in s
            return lambda idx, env, res=res: jnp.full(idx.shape, res, bool)
        if lv.kind != "str":
            return lambda idx, env: jnp.zeros(idx.shape, bool)  # non-str LIKE → false
        d = lv.dictionary or []
        if op == "LIKE":
            table = np.array([like_match(s, pat) for s in d], bool)
        elif op == "MATCHES":
            table = np.array([re.fullmatch(pat, s) is not None for s in d], bool)
        else:  # CONTAINSTEXT
            table = np.array([pat in s for s in d], bool)
        return self._code_table_mask(lv, table)

    def _in(self, left: A.Expression, right: A.Expression) -> BoolFn:
        if not isinstance(right, A.ListExpr):
            raise Uncompilable("IN needs a literal list")
        eqs = [self._compare("=", left, item) for item in right.items]
        if not eqs:
            return lambda idx, env: jnp.zeros(idx.shape, bool)

        def fn(idx, env, eqs=eqs):
            m = eqs[0](idx, env)
            for e in eqs[1:]:
                m = m | e(idx, env)
            return m

        return fn

    # -- comparisons -------------------------------------------------------

    def _compare(self, op: str, left: A.Expression, right: A.Expression) -> BoolFn:
        a = self._value(left)
        b = self._value(right)
        # null on either side: every compare false (incl. !=)
        if a.kind == "null" or b.kind == "null":
            return lambda idx, env: jnp.zeros(idx.shape, bool)
        # string literal vs string literal: host constant
        if a.kind == "strlit" and b.kind == "strlit":
            res = _host_cmp(op, a.dictionary, b.dictionary)
            return lambda idx, env, res=res: jnp.full(idx.shape, res, bool)
        # string column vs literal (either side)
        if a.kind == "str" and b.kind == "strlit":
            return self._cmp_str_lit(op, a, b.dictionary)
        if a.kind == "strlit" and b.kind == "str":
            return self._cmp_str_lit(_flip(op), b, a.dictionary)
        # type-mismatch across order classes
        a_num = a.kind in _NUMERIC
        b_num = b.kind in _NUMERIC
        a_str = a.kind == "str"
        b_str = b.kind in ("str", "strlit")
        if (a_num and b_str) or (a_str and b_num) or (a.kind == "strlit" and b_num):
            if op == "!=":
                # non-null incomparables are "not equal" (values_equal fallback)
                def fn(idx, env, a=a, b=b):
                    ap = _presence(a, idx, env)
                    bp = _presence(b, idx, env)
                    return ap & bp

                return fn
            return lambda idx, env: jnp.zeros(idx.shape, bool)
        if a_str and b.kind == "str":
            if a.dictionary is not None and a.dictionary is b.dictionary:
                if op not in ("=", "!=") and not _dict_sorted(a):
                    raise Uncompilable(
                        "ordered string compare on a delta-appended "
                        "dictionary (compaction re-sorts)"
                    )
                # same sorted dictionary (same property column on both
                # sides): code rank order == lexicographic order, so the
                # codes compare directly as ints (codes compare by
                # identity for =/!=, which appended dictionaries keep)
                a = _Val("int", a.emit)
                b = _Val("int", b.emit)
                a_num = b_num = True
            else:
                raise Uncompilable("string column vs string column compare")
        # numeric vs numeric (bool included)
        if not (a_num and b_num):
            raise Uncompilable(f"cannot compare {a.kind} with {b.kind}")
        ordered_ok = True
        if ("bool" in (a.kind, b.kind)) and a.kind != b.kind and op not in ("=", "!="):
            # compare() yields None for bool vs non-bool → ordered ops false
            ordered_ok = False
        kind = _promote(a, b)

        def fn(idx, env, a=a, b=b, op=op, kind=kind, ordered_ok=ordered_ok):
            av, ap = _as_dtype(*a.emit(idx, env), kind)
            bv, bp = _as_dtype(*b.emit(idx, env), kind)
            pres = ap & bp
            if op not in ("=", "!=") and not ordered_ok:
                return jnp.zeros(idx.shape, bool)
            if op == "=":
                c = av == bv
            elif op == "!=":
                c = av != bv
            elif op == "<":
                c = av < bv
            elif op == "<=":
                c = av <= bv
            elif op == ">":
                c = av > bv
            else:
                c = av >= bv
            return pres & c

        return fn

    def _cmp_str_lit(self, op: str, col: _Val, lit: str) -> BoolFn:
        d: Sequence[str] = col.dictionary or []
        if not _dict_sorted(col):
            # the delta maintainer (storage/deltas) APPENDED new strings:
            # codes no longer rank-ordered, so bisect is wrong. Equality
            # still compiles (exact code lookup); ordered compares fall
            # back to the oracle until compaction re-sorts.
            if op not in ("=", "!="):
                raise Uncompilable(
                    "ordered string compare on a delta-appended "
                    "dictionary (compaction re-sorts)"
                )
            lookup = (
                col.column.dict_lookup if col.column is not None else None
            )
            if lookup is not None:
                # the maintainer's value→code map: O(1) vs the O(n)
                # dictionary rescan, on the path every dict append
                # makes hot (appends bump plan_gen → re-record)
                exact_u: Optional[int] = lookup.get(lit)
            else:  # defensive: column-less _Vals are never delta-appended
                try:
                    exact_u = list(d).index(lit)
                except ValueError:
                    exact_u = None

            def ufn(idx, env, col=col, op=op, exact=exact_u):
                vals, pres = col.emit(idx, env)
                if op == "=":
                    if exact is None:
                        return jnp.zeros(idx.shape, bool)
                    return pres & (vals == exact)
                if exact is None:
                    return pres
                return pres & (vals != exact)

            return ufn
        lo = bisect.bisect_left(d, lit)
        hi = bisect.bisect_right(d, lit)
        exact = lo if (lo < len(d) and d[lo] == lit) else None

        def fn(idx, env, col=col, op=op, exact=exact, lo=lo, hi=hi):
            vals, pres = col.emit(idx, env)
            if op == "=":
                if exact is None:
                    return jnp.zeros(idx.shape, bool)
                return pres & (vals == exact)
            if op == "!=":
                if exact is None:
                    return pres
                return pres & (vals != exact)
            if op == "<":
                return pres & (vals < lo)
            if op == "<=":
                return pres & (vals < hi)
            if op == ">":
                return pres & (vals >= hi)
            return pres & (vals >= lo)  # >=

        return fn


def _presence(v: _Val, idx, env) -> jnp.ndarray:
    if v.kind == "strlit":
        return jnp.ones(idx.shape, bool)
    if v.kind == "null":
        return jnp.zeros(idx.shape, bool)
    _, pres = v.emit(idx, env)
    return pres


def _dict_sorted(v: _Val) -> bool:
    """True while the column dictionary's code order is lexicographic —
    the build-time invariant ordered compares rely on. The delta
    maintainer appends new strings at the tail, breaking it until
    compaction; it flags the host column (``dict_unsorted``), so a
    column-backed value answers in O(1). Only a _Val with no column
    attribution pays the O(n) scan (defensive: snapshot builds always
    sort, so untracked dictionaries are sorted in practice)."""
    col = v.column
    if col is not None:
        return not col.dict_unsorted
    d = v.dictionary or []
    return all(d[i] <= d[i + 1] for i in range(len(d) - 1))


def _flip(op: str) -> str:
    return {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "=", "!=": "!="}[op]


def _host_cmp(op: str, a: str, b: str) -> bool:
    return {
        "=": a == b,
        "!=": a != b,
        "<": a < b,
        "<=": a <= b,
        ">": a > b,
        ">=": a >= b,
    }[op]


def compile_predicate(
    expr: A.Expression,
    scope: ColumnScope,
    params: Dict,
    allow_depth: bool = False,
) -> BoolFn:
    """Compile a WHERE AST into `fn(idx_array, env) -> bool mask`.

    Raises Uncompilable outside the columnar subset."""
    return Compiler(scope, params, allow_depth=allow_depth).compile_bool(expr)
