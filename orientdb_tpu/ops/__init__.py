"""TPU kernel layer: device-resident graph arrays and batched frontier
expansion primitives (SURVEY.md §1 "Pallas/XLA kernel layer")."""
