"""Batched CSR frontier-expansion primitives.

This is the TPU replacement for the reference's per-record hot loop
([E] MatchStep.syncPull → MatchEdgeTraverser.next → ORidBag iteration →
per-RID document load, SURVEY.md §3.3): one `PatternEdge` hop over the whole
frontier becomes a **count → exclusive-scan → rank-search gather** over the
CSR arrays — a handful of fused XLA ops instead of millions of interpreted
iterator pulls.

Shape discipline (XLA wants static shapes): every kernel takes sizes that
are **bucketed to powers of two** (`bucket()`), padding rows carry src=-1
and are masked out, so the jit cache holds O(log n) specializations per
kernel instead of one per distinct frontier size.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

MIN_BUCKET = 8


def bucket(n: int, minimum: int = 0) -> int:
    """Round up to a power of two (≥ minimum, default
    config.min_expansion_cap) to bound the jit cache."""
    if minimum <= 0:
        from orientdb_tpu.utils.config import config

        minimum = max(1, config.min_expansion_cap)
    if n <= minimum:
        return minimum
    return 1 << (n - 1).bit_length()


@jax.jit
def degree_counts(indptr: jnp.ndarray, srcs: jnp.ndarray) -> jnp.ndarray:
    """Per-source neighbor counts; padding (src=-1) counts 0."""
    valid = srcs >= 0
    s = jnp.where(valid, srcs, 0)
    return jnp.where(valid, jnp.take(indptr, s + 1) - jnp.take(indptr, s), 0)


@jax.jit
def exclusive_cumsum(counts: jnp.ndarray) -> jnp.ndarray:
    return jnp.concatenate(
        [jnp.zeros(1, counts.dtype), value_cumsum(counts)[:-1]]
    )


@partial(jax.jit, static_argnames=("out_size",))
def gather_expand(
    indptr: jnp.ndarray,
    neighbors: jnp.ndarray,
    srcs: jnp.ndarray,
    offsets: jnp.ndarray,
    total: jnp.ndarray,
    out_size: int,
):
    """Expand every source's CSR slice into flat (row, edge_pos, neighbor).

    `offsets` is the exclusive cumsum of `degree_counts(indptr, srcs)` and
    `total` its sum (device scalar); `out_size` is a static bucket ≥ total.
    Returns int32 arrays of length `out_size`:
      row      — index into `srcs` this output came from (-1 on padding)
      edge_pos — position in CSR edge order (edge-property gathers use this)
      neighbor — the reached vertex (dst for out-CSR, src for in-CSR)
    """
    K = srcs.shape[0]
    pos = jnp.arange(out_size, dtype=jnp.int32)
    valid = pos < total
    # rank search via scatter+cumsum (binary-search gathers serialize badly
    # on TPU; a one-hot scatter then prefix-sum stays on the VPU): mark each
    # row's start offset, then row(pos) = #starts ≤ pos − 1. Zero-count rows
    # share an offset with their successor and never own a position.
    marks = jnp.zeros(out_size, jnp.int32).at[offsets].add(1, mode="drop")
    row = jnp.clip(value_cumsum(marks) - 1, 0, K - 1).astype(jnp.int32)
    src = jnp.take(srcs, row)
    s = jnp.clip(src, 0, indptr.shape[0] - 2)
    edge_pos = jnp.take(indptr, s) + (pos - jnp.take(offsets, row))
    if neighbors.shape[0]:
        edge_pos_c = jnp.clip(edge_pos, 0, neighbors.shape[0] - 1)
        nbr = jnp.take(neighbors, edge_pos_c)
    else:
        nbr = jnp.full((out_size,), -1, jnp.int32)
    row = jnp.where(valid, row, -1)
    edge_pos = jnp.where(valid, edge_pos, -1)
    nbr = jnp.where(valid, nbr, -1)
    return row, edge_pos, nbr


_CS_BLOCK = 256


def mask_cumsum(mask: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix sum of a boolean mask, MXU-shaped.

    XLA's plain cumsum over 1M elements costs ~14 ms on TPU (log-depth
    reduce-window passes); a [n/256, 256] reshape turns the intra-block
    scan into ONE triangular matmul on the systolic array (values ≤ 256
    are exact in f32), leaving only a tiny 4k-element cumsum for the
    block offsets — sub-millisecond at graph scale."""
    n = mask.shape[0]
    B = _CS_BLOCK
    if n < 2 * B or n % B:
        return jnp.cumsum(mask.astype(jnp.int32))
    # intra-block inclusive scans on the MXU (shared with value_cumsum)
    row_cs = _block_scan_f32(mask.astype(jnp.float32)).astype(jnp.int32)
    block_tot = row_cs[:, -1]
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), value_cumsum(block_tot)[:-1]]
    )
    return (row_cs + offs[:, None]).reshape(-1)


def _block_scan_f32(vals_f32: jnp.ndarray) -> jnp.ndarray:
    """[n/B, B] per-block inclusive scans as ONE triangular matmul on
    the systolic array. Exact while every block-local partial stays
    under 2^24 (callers arrange that); cross-block offsets are the
    caller's job — f32 cannot carry graph-scale totals exactly."""
    B = _CS_BLOCK
    rows = vals_f32.reshape(-1, B)
    tri = jnp.triu(jnp.ones((B, B), jnp.float32))
    return jnp.dot(rows, tri)


def value_cumsum(vals: jnp.ndarray, force_blocked: bool = False) -> jnp.ndarray:
    """Inclusive prefix sum of int32/f32 VALUES, MXU-shaped like
    :func:`mask_cumsum` — the COUNT-pushdown weight chain runs this
    over the whole edge list (80M rows at SF100 shape), where XLA's
    log-depth plain cumsum was the measured per-query floor (~14 ms
    per 1M elements → seconds per pass; the r04 16.8 q/s two-hop
    cliff).

    int32 stays EXACT on the f32 systolic array by scanning the low
    and high 16-bit halves separately: per-block partials are
    ≤ 256·2^16 < 2^24 (f32-exact), the halves recombine per block as
    ``hi·2^16 + lo`` in int32, and the cross-block offsets accumulate
    in int32 (recursively blocked) — exact for non-negative inputs
    whose total fits int32, which callers overflow-guard already (the
    pushdown's float-twin check). f32 inputs take the matmul path with
    f32 offsets (the overflow twin tolerates its ~1e-7 error); other
    dtypes, short inputs, and the padding tail fall back to plain
    cumsum; non-multiple lengths are zero-padded to a block boundary.

    The matmul path is gated to systolic backends at trace time: CPU's
    native cumsum is linear and memory-bound, so the [n/B, B]·[B, B]
    contraction would only add FLOPs there (backends are baked per
    executable anyway — the read is a trace-time constant by design,
    like the kernel platform itself)."""
    n = vals.shape[0]
    B = _CS_BLOCK
    if n < 2 * B or (jax.default_backend() == "cpu" and not force_blocked):
        return jnp.cumsum(vals)
    if n % B:
        pad = B - (n % B)
        return value_cumsum(jnp.pad(vals, (0, pad)), force_blocked)[:n]
    if vals.dtype == jnp.float32:
        row_cs = _block_scan_f32(vals)
        block_tot = row_cs[:, -1]
        offs = jnp.concatenate(
            [
                jnp.zeros(1, jnp.float32),
                value_cumsum(block_tot, force_blocked)[:-1],
            ]
        )
        return (row_cs + offs[:, None]).reshape(-1)
    if vals.dtype != jnp.int32:
        return jnp.cumsum(vals)
    lo = (vals & 0xFFFF).astype(jnp.float32)  # [0, 2^16)
    hi = (vals >> 16).astype(jnp.float32)  # arithmetic shift: sign rides hi
    row_cs = _block_scan_f32(hi).astype(jnp.int32) * jnp.int32(
        65536
    ) + _block_scan_f32(lo).astype(jnp.int32)
    block_tot = row_cs[:, -1]
    offs = jnp.concatenate(
        [jnp.zeros(1, jnp.int32), value_cumsum(block_tot, force_blocked)[:-1]]
    )
    return (row_cs + offs[:, None]).reshape(-1)


@partial(jax.jit, static_argnames=("out_size",))
def compact_indices(mask: jnp.ndarray, out_size: int) -> jnp.ndarray:
    """Indices of True entries (ascending), -1-padded to the static
    `out_size`.

    Two regimes, chosen statically by shape:
    - selective compactions (out_size ≪ n — point-lookup roots, sparse
      emissions): blocked prefix sum (see mask_cumsum) + out_size binary
      searches. jnp.nonzero here would pay XLA's full-width TPU sort
      (~28 ms per 1M elements — it dominated every compiled plan's
      device time at SF10 scale).
    - dense compactions (out_size comparable to n): nonzero's single
      sort beats out_size·log(n) gather-bound searches."""
    n = mask.shape[0]
    if n == 0:
        return jnp.full(out_size, -1, jnp.int32)
    if out_size * 8 > n:
        (idx,) = jnp.nonzero(mask, size=out_size, fill_value=-1)
        return idx.astype(jnp.int32)
    ranks = mask_cumsum(mask)
    wanted = jnp.arange(1, out_size + 1, dtype=jnp.int32)
    pos = jnp.searchsorted(ranks, wanted, side="left").astype(jnp.int32)
    ok = (pos < n) & (wanted <= ranks[-1])
    return jnp.where(ok, pos, -1)


@jax.jit
def take_pad(values: jnp.ndarray, idx: jnp.ndarray, fill) -> jnp.ndarray:
    """`values[idx]` where idx ≥ 0, else `fill` (padding-safe gather)."""
    n = values.shape[0]
    if n == 0:
        return jnp.full(idx.shape, fill, values.dtype)
    ok = idx >= 0
    v = jnp.take(values, jnp.clip(idx, 0, n - 1))
    return jnp.where(ok, v, fill)


@jax.jit
def mask_count(mask: jnp.ndarray) -> jnp.ndarray:
    return jnp.sum(mask.astype(jnp.int32))


@partial(jax.jit, static_argnames=("out_size",))
def indptr_segment_sum(
    vals: jnp.ndarray, indptr: jnp.ndarray, out_size: int
) -> jnp.ndarray:
    """Segment sums of CSR-ordered values: cumsum + boundary gathers.

    When values are already ordered by segment (an edge list in CSR
    order), the per-vertex sum is a difference of prefix sums at the
    indptr boundaries — measured ~7x cheaper than the scatter-add
    `segment_sum` lowers to on TPU (2.8 ms vs 0.2+overhead ms at 200k
    rows), and it vmaps as a batched axis-wise scan instead of a
    batched scatter. The prefix sum itself runs MXU-blocked
    (:func:`value_cumsum`): at SF100 scale this cumsum over the 80M-row
    edge list was ~2 s/pass of XLA's log-depth reduce-window — the
    whole r04 two-hop COUNT cliff. Result is zero-padded to the static
    `out_size`."""
    tot = jnp.concatenate([jnp.zeros(1, vals.dtype), value_cumsum(vals)])
    seg = jnp.take(tot, indptr[1:]) - jnp.take(tot, indptr[:-1])
    pad = out_size - seg.shape[0]
    if pad > 0:
        seg = jnp.pad(seg, (0, pad))
    return seg[:out_size]


@partial(jax.jit, static_argnames=("vb",))
def rows_to_bitmap(rows: jnp.ndarray, vb: int) -> jnp.ndarray:
    """[C] vertex ids (-1 = none) → [C, vb] one-hot frontier bitmap."""
    C = rows.shape[0]
    ok = rows >= 0
    r = jnp.clip(rows, 0, vb - 1)
    return jnp.zeros((C, vb), bool).at[jnp.arange(C), r].max(ok)


@jax.jit
def bitmap_hop(
    act_idx: jnp.ndarray,
    emit_idx: jnp.ndarray,
    edge_mask: jnp.ndarray,
    frontier: jnp.ndarray,
) -> jnp.ndarray:
    """One frontier hop over an edge list as dense bitmaps.

    act_idx/emit_idx [E]: the edge endpoint that must be in the frontier
    and the endpoint reached (swap them to walk edges backwards);
    edge_mask [E] prefilters edges (fused edge-property WHERE);
    frontier [C, vb] per-row bitmaps. The scatter-OR is the SURVEY §5.7
    frontier-bitmap step of variable-depth traversal.
    """
    vb = frontier.shape[1]
    if act_idx.shape[0] == 0:
        return jnp.zeros_like(frontier)
    act = frontier[:, jnp.clip(act_idx, 0, vb - 1)] & edge_mask[None, :]
    emit_c = jnp.clip(emit_idx, 0, vb - 1)
    return jnp.zeros_like(frontier).at[:, emit_c].max(act)


@partial(jax.jit, static_argnames=("num_segments",))
def rows_with_matches(rows: jnp.ndarray, mask: jnp.ndarray, num_segments: int):
    """Per-source-row match counts (OPTIONAL-arm left-join bookkeeping):
    scatter-add 1 for every surviving expansion into its origin row."""
    ok = mask & (rows >= 0)
    r = jnp.where(ok, rows, 0)
    return jax.ops.segment_sum(
        ok.astype(jnp.int32), r, num_segments=num_segments
    )


