"""Device-resident graph snapshot.

The HBM form of the columnar snapshot (`orientdb_tpu/storage/snapshot.py`):
every array `jax.device_put` once per snapshot epoch and cached, so repeated
queries over the same snapshot pay zero host↔device traffic for graph data —
the TPU-native answer to the reference's per-record page-cache reads on every
hop ([E] O2QCache / OPaginatedCluster.readRecord, SURVEY.md §3.2-3.3).

All arrays live in one flat ``DeviceGraph.arrays`` dict and are read through
lightweight proxies (`DeviceColumn`, `DeviceEdgeClass`). Compiled plans pass
that dict as a jit *argument* pytree — temporarily swapping in the tracer
dict during tracing — so the (potentially multi-GB) graph is shared across
every cached plan executable instead of being baked into each one as HLO
constants.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

import jax.numpy as jnp
import numpy as np

from orientdb_tpu.chaos.faults import FaultError, fault
from orientdb_tpu.storage.snapshot import GraphSnapshot, PropertyColumn


class DeviceColumn:
    """A property column proxy: values + presence mask in ``graph.arrays``.

    `dictionary` (host-side) stays with the column so string predicates can
    be evaluated over the (small) dictionary on host and pushed to device as
    code-set membership masks.

    On a mesh, columns are row-sharded over the ``shards`` axis
    (``shard_pad`` rows per padded column, R per device) instead of
    replicated — per-device property memory is O(V/S) (vertices) or
    O(E/S) (edges), the SURVEY.md §7 SF100 per-chip budget. Predicate
    gathers read them in jit global view; XLA's SPMD partitioner inserts
    the cross-shard collectives (all_gather / all_to_all, its choice).
    """

    __slots__ = ("name", "kind", "dictionary", "_g", "_kv", "_kp", "_src")

    def __init__(
        self,
        col: PropertyColumn,
        g: "DeviceGraph",
        prefix: str,
        shard_pad: Optional[int] = None,
    ):
        self.name = col.name
        self.kind = col.kind
        self.dictionary = col.dictionary
        self._src = col
        self._g = g
        # LAZY upload (per-query property pruning, SURVEY.md §7's SF100
        # memory plan): the host arrays are registered but reach HBM only
        # when a compiled plan first reads the column — columns no query
        # references never cost device memory
        self._kv = g._put_lazy(f"{prefix}:v", col.values, shard_pad=shard_pad)
        self._kp = g._put_lazy(
            f"{prefix}:p", col.present, shard_pad=shard_pad
        )

    @property
    def dict_unsorted(self) -> bool:
        """Read through to the HOST column: the delta maintainer flips
        the flag when it appends a new string, which may happen long
        after this proxy was built (predicates check it per compile —
        O(1) where rescanning the dictionary is O(n))."""
        return self._src.dict_unsorted

    @property
    def dict_lookup(self):
        """Read through to the host column's exact value→code map (the
        delta maintainer keeps it current across appends) — equality
        compiles against a delta-appended dictionary in O(1)."""
        return self._src.dict_lookup

    @property
    def values(self):
        self._g.ensure_key(self._kv)
        return self._g.arrays[self._kv]

    @property
    def present(self):
        self._g.ensure_key(self._kp)
        return self._g.arrays[self._kp]


class DeviceEdgeClass:
    """One edge class's CSR adjacency (both directions) in HBM.

    On a mesh-sharded graph the flat adjacency is NOT uploaded — every
    mesh execution path reads the ``sh:*`` shard-wise layout instead
    (`orientdb_tpu/parallel/mesh_graph.py`), and uploading both would
    leave per-device HBM at O(E·(1+1/S)) instead of O(E/S). Edge property
    columns are row-sharded by edge range on a mesh (O(E/S) per device);
    predicate gathers read them through XLA-inserted collectives."""

    __slots__ = ("class_name", "columns", "non_columnar", "num_edges", "_g", "_p")

    def __init__(self, csr, g: "DeviceGraph") -> None:
        self.class_name = csr.class_name
        self._g = g
        p = self._p = f"e:{csr.class_name}"
        if g.mesh_graph is None:
            # tiered snapshots (storage/tiering) page the four [E]
            # value arrays between a hot device pool and host-pinned
            # cold blocks — the flat uploads are what the HBM cap
            # exists to avoid. Indptrs stay resident (O(V), and every
            # paged gather sizes from them). Reading a skipped
            # property below raises KeyError by design: every consumer
            # is gated onto the paged kernels.
            tier = getattr(g.snap, "_tier", None)
            paged = tier is not None and tier.pages_dir(csr.class_name, "out")
            g._put(f"{p}:indptr_out", csr.indptr_out)
            g._put(f"{p}:indptr_in", csr.indptr_in)
            if not paged:
                g._put(f"{p}:dst", csr.dst)
                # per-edge source vertex in out-CSR order (bitmap-hop
                # kernels index edges directly instead of walking indptr)
                g._put(f"{p}:edge_src", csr.edge_src_np())
                g._put(f"{p}:src", csr.src)
                g._put(f"{p}:edge_id_in", csr.edge_id_in)
            if getattr(csr, "live", None) is not None:
                # delta-slab liveness (storage/deltas): spare slots and
                # tombstoned edges read False; the bitmap-hop and slab
                # expansion paths mask on it as a jit ARGUMENT, so
                # delta patches reach every cached plan
                g._put(f"{p}:live", csr.live)
            ov = getattr(g.snap, "_overlay", None)
            bk = getattr(ov, "bk", {}).get(csr.class_name) if ov else None
            if bk is not None:
                # bucketed slab index (storage/deltas): per-direction
                # endpoint-keyed tables of slab slots — patch-maintained
                # jit arguments like the live mask above
                g._put(f"bk:{csr.class_name}:out", bk["out"])
                g._put(f"bk:{csr.class_name}:in", bk["in"])
        e_pad = g._shard_pad_rows(int(csr.dst.shape[0]))
        self.columns: Dict[str, DeviceColumn] = {
            n: DeviceColumn(c, g, f"{p}:c:{n}", shard_pad=e_pad)
            for n, c in csr.edge_columns.items()
        }
        self.non_columnar: Set[str] = set(getattr(csr, "non_columnar", ()))
        self.num_edges = int(csr.dst.shape[0])

    @property
    def indptr_out(self):
        return self._g.arrays[f"{self._p}:indptr_out"]

    @property
    def dst(self):
        return self._g.arrays[f"{self._p}:dst"]

    @property
    def edge_src(self):
        return self._g.arrays[f"{self._p}:edge_src"]

    @property
    def indptr_in(self):
        return self._g.arrays[f"{self._p}:indptr_in"]

    @property
    def src(self):
        return self._g.arrays[f"{self._p}:src"]

    @property
    def edge_id_in(self):
        return self._g.arrays[f"{self._p}:edge_id_in"]


class _TouchTracker:
    """Recording-time view of the array store: logs every key read (the
    plan's future jit-arg subset) and faults lazy columns in on first
    read. Never reaches jax — dispatches always pass a plain dict."""

    __slots__ = ("_g", "log")

    def __init__(self, g: "DeviceGraph") -> None:
        self._g = g
        self.log: Set[str] = set()

    def __getitem__(self, key: str):
        self.log.add(key)
        g = self._g
        if key not in g._arrays:
            with g._pending_lock:
                spec = g._pending.pop(key, None)
            if spec is not None:
                arr, shard_pad, fill = spec
                g._put(key, arr, shard_pad=shard_pad, fill=fill)
        return g._arrays[key]

    def get(self, key: str, default=None):
        try:
            return self[key]
        except KeyError:
            return default

    def __contains__(self, key: str) -> bool:
        return key in self._g._arrays or key in self._g._pending

    def __iter__(self):
        return iter(self._g._arrays)

    def keys(self):
        return self._g._arrays.keys()

    def __len__(self) -> int:
        return len(self._g._arrays)


class DeviceGraph:
    """The full snapshot in HBM plus host metadata for planning/marshal.

    When the snapshot was attached with a device mesh, adjacency is
    additionally laid out shard-wise (`orientdb_tpu/parallel/mesh_graph.py`)
    and `self.mesh_graph` carries the sharding metadata; replicated arrays
    get an explicit fully-replicated NamedSharding so every jit argument
    agrees about the mesh."""

    def __init__(self, snap: GraphSnapshot) -> None:
        self.snap = snap
        self.num_vertices = snap.num_vertices
        self.mesh_graph = None
        self._replicated_spec = None
        mesh = getattr(snap, "_mesh", None)
        if mesh is not None:
            if getattr(snap, "_overlay", None) is not None:
                # the mesh layout re-partitions adjacency per shard and
                # does not upload the slab live masks — silently meshing
                # a delta-maintained snapshot would serve spare/dead
                # edges. Compact to a clean snapshot first.
                raise ValueError(
                    "delta-maintained snapshots are single-device; "
                    "compact before attaching a mesh"
                )
            from jax.sharding import NamedSharding, PartitionSpec
            from orientdb_tpu.parallel.mesh_graph import MeshGraph

            self.mesh_graph = MeshGraph(mesh)
            self._replicated_spec = NamedSharding(mesh, PartitionSpec())
        #: the single flat array store — compiled plans pass a per-plan
        #: KEY SUBSET of it as their jit-arg pytree (plans record the
        #: keys they touch, so lazily uploaded columns growing this dict
        #: never change any cached plan's pytree structure)
        self._arrays: Dict[str, jnp.ndarray] = {}
        #: device-memory ledger owner id (obs/memledger): every array
        #: this graph puts in HBM is attributed here; _free_device
        #: drops the whole owner in one call
        self._ledger_owner = (
            f"snap:{id(snap):x}:e{int(getattr(snap, 'epoch', 0) or 0)}"
        )
        #: host arrays registered but not yet uploaded (lazy columns):
        #: key -> (host_array, shard_pad, fill)
        self._pending: Dict[str, tuple] = {}
        self._pending_lock = threading.Lock()
        self._tls = threading.local()
        v_pad = self._shard_pad_rows(self.num_vertices)
        self._put("v_class", snap.v_class, shard_pad=v_pad, fill=-1)
        self.columns: Dict[str, DeviceColumn] = {
            n: DeviceColumn(c, self, f"v:{n}", shard_pad=v_pad)
            for n, c in snap.v_columns.items()
        }
        self.non_columnar: Set[str] = set(getattr(snap, "v_non_columnar", ()))
        tier = getattr(snap, "_tier", None)
        if tier is not None and self.mesh_graph is not None:
            # same composition rule as mesh + overlay below: the mesh
            # layout re-partitions adjacency shard-wise and knows
            # nothing of the hot/cold pools
            from orientdb_tpu.obs.memledger import memledger

            memledger.note_refusal(
                "mesh", "tiered snapshot built against a device mesh"
            )
            raise ValueError(
                "tiered snapshots are single-device; drop the mesh or "
                "raise tier_hbm_cap_bytes"
            )
        self.edges: Dict[str, DeviceEdgeClass] = {
            n: DeviceEdgeClass(c, self) for n, c in snap.edge_classes.items()
        }
        if tier is not None:
            # upload block indexes + pools, seed the hottest blocks
            # (storage/tiering); re-runs per DeviceGraph build, so a
            # _free_device → rebuild cycle re-establishes residency
            tier.install(self)
        # class-id sets stay OUTSIDE `arrays`: they are lazily created per
        # query, and growing the jit-arg pytree would change its structure
        # and silently retrace every cached plan. They are tiny (a few
        # int32s), so being baked into plan executables as constants is fine.
        self._class_ids: Dict[str, jnp.ndarray] = {}
        if self.mesh_graph is not None:
            self.mesh_graph.build(self)
        self.memory_report()  # publish hbm.* gauges for /metrics

    @property
    def arrays(self):
        """The array store — per-thread overridable.

        Compiled plans swap in the jit tracer pytree for the duration of a
        trace (``dg.arrays = tracers``). Replays are AOT-warmed on a
        background thread (`tpu_engine._CompiledPlan.ensure_compiled`), so
        that swap MUST be invisible to other threads: the override lives in
        thread-local storage, and assigning the canonical dict back clears
        it. Concurrent traces and eager solves on different threads each see
        their own view; `_put` writes to the canonical store directly so an
        active override can never swallow an upload.

        During a RECORDING (``start_touch_log``) this thread instead sees
        a tracking view that logs every key read — the recorded set
        becomes the plan's jit-arg subset — and faults lazy columns in
        on first read."""
        ov = getattr(self._tls, "override", None)
        if ov is not None:
            return ov
        trk = getattr(self._tls, "tracker", None)
        return self._arrays if trk is None else trk

    @arrays.setter
    def arrays(self, value) -> None:
        self._tls.override = None if value is self._arrays else value

    # -- recording touch log (per-plan jit-arg subsets) ---------------------

    def start_touch_log(self) -> None:
        self._tls.tracker = _TouchTracker(self)

    def stop_touch_log(self) -> frozenset:
        trk = getattr(self._tls, "tracker", None)
        self._tls.tracker = None
        return frozenset(trk.log) if trk is not None else frozenset()

    @property
    def mesh(self):
        return self.mesh_graph.mesh if self.mesh_graph is not None else None

    def _shard_pad_rows(self, n: int) -> Optional[int]:
        """Padded row count making ``n`` divisible by the shard count
        (None when unsharded)."""
        if self.mesh_graph is None:
            return None
        S = self.mesh_graph.n_shards
        return max(1, -(-max(n, 1) // S)) * S

    def _put_lazy(
        self,
        key: str,
        arr,
        shard_pad: Optional[int] = None,
        fill: int = 0,
    ) -> str:
        """Register a host array for on-demand upload (`ensure_key`) —
        the per-query property-pruning path. ``column_prune=False``
        restores eager uploads."""
        from orientdb_tpu.utils.config import config as _cfg

        if not _cfg.column_prune:
            return self._put(key, arr, shard_pad=shard_pad, fill=fill)
        self._pending[key] = (arr, shard_pad, fill)
        return key

    def ensure_key(self, key: str) -> None:
        """Upload a lazily registered array if it has not reached the
        device yet; logs the touch when a recording is active. The
        upload runs INSIDE the pending lock so a concurrent delta patch
        (``apply_patches``) can never land between the pop and the
        device store and be lost."""
        trk = getattr(self._tls, "tracker", None)
        if trk is not None:
            trk.log.add(key)
        if key in self._pending:
            with self._pending_lock:
                spec = self._pending.pop(key, None)
                if spec is not None:
                    arr, shard_pad, fill = spec
                    self._put(key, arr, shard_pad=shard_pad, fill=fill)

    def apply_patches(self, patches: Dict[str, tuple]) -> int:
        """Scatter one delta batch into resident device arrays:
        ``{key: (indices, values)}`` applied as a functional
        ``arr.at[idx].set(vals)`` per key. Same shape in, same shape out
        — compiled plans take these arrays as jit ARGUMENTS, so every
        cached executable sees the patch with zero retrace and the
        upload is bounded by the delta (the packed index/value
        segments), never the graph. Keys still pending lazy upload are
        skipped: their HOST arrays were already patched in place by the
        maintainer, so the eventual upload carries the delta for free.
        Returns the host→device bytes shipped."""
        import jax

        nbytes = 0
        with self._pending_lock:
            for key, (idx, vals) in patches.items():
                cur = self._arrays.get(key)
                if cur is None:
                    continue  # lazy column not yet resident
                ia = np.asarray(idx, np.int32)
                va = np.asarray(vals).astype(cur.dtype)
                try:
                    # scrub.flip chaos crossing: corrupt the DEVICE-
                    # bound copy only — the maintainer already patched
                    # host truth, so the scrub sweep provably detects
                    with fault.point("scrub.flip"):
                        pass
                except FaultError:
                    from orientdb_tpu.storage.scrub import chaos_flip

                    va = chaos_flip(va)
                # bucket the segment to a pow2 length by REPEATING the
                # last (index, value) pair — a duplicate scatter of the
                # same value is idempotent, and the bucketed shape keeps
                # the .at[].set executable jit-cache-hot (per-delta
                # shapes recompiled XLA on every batch otherwise: ~3x
                # the whole read path's cost at bench shape)
                cap = 1 << max(0, int(ia.shape[0] - 1).bit_length())
                if cap > ia.shape[0]:
                    ia = np.concatenate(
                        [ia, np.full(cap - ia.shape[0], ia[-1], ia.dtype)]
                    )
                    va = np.concatenate(
                        [va, np.full(cap - va.shape[0], va[-1], va.dtype)]
                    )
                self._arrays[key] = cur.at[jax.device_put(ia)].set(
                    jax.device_put(va)
                )
                nbytes += int(ia.nbytes) + int(va.nbytes)
                # the overlay write produced a NEW device array under
                # the same key: refresh its ledger attribution in place
                from orientdb_tpu.obs.memledger import memledger

                memledger.register_graph_array(
                    self, key, self._arrays[key]
                )
                self._scrub_mark(key)
        return nbytes

    def _scrub_mark(self, key: str) -> None:
        """Host truth changed under ``key``: the scrubber re-hashes its
        cached checksum on the next sweep (storage/scrub)."""
        d = getattr(self, "_scrub_dirty", None)
        if d is None:
            d = self._scrub_dirty = set()
        d.add(key)

    def _put(
        self,
        key: str,
        arr,
        shard_pad: Optional[int] = None,
        fill: int = 0,
    ) -> str:
        a = jnp.asarray(arr)
        if (
            self.mesh_graph is not None
            and shard_pad is not None
            and a.ndim == 1
            and a.shape[0] > 0
        ):
            # row-shard over the mesh's shard axis (vertex- or edge-range
            # ownership); padding rows carry `fill` and a False presence
            import jax
            from jax.sharding import NamedSharding, PartitionSpec

            from orientdb_tpu.utils.config import config as _cfg

            if shard_pad > a.shape[0]:
                pad = jnp.full((shard_pad - a.shape[0],), fill, a.dtype)
                a = jnp.concatenate([a, pad])
            spec = NamedSharding(
                self.mesh_graph.mesh, PartitionSpec(_cfg.mesh_shard_axis)
            )
            self._arrays[key] = jax.device_put(a, spec)
            from orientdb_tpu.obs.memledger import memledger

            memledger.register_graph_array(self, key, self._arrays[key])
            self._scrub_mark(key)
            return key
        if self._replicated_spec is not None:
            import jax

            a = jax.device_put(a, self._replicated_spec)
        self._arrays[key] = a
        from orientdb_tpu.obs.memledger import memledger

        memledger.register_graph_array(self, key, a)
        self._scrub_mark(key)
        return key

    @property
    def v_class(self):
        return self.arrays["v_class"]

    def memory_report(self) -> Dict[str, Dict[str, int]]:
        """Per-device graph-memory accounting by category (the SURVEY.md
        §5.5 HBM-occupancy observable): for each key group, logical bytes
        and per-device bytes (= the largest addressable shard, so a
        sharded array counts V/S-ish while a replicated one counts V).
        Published to the metrics registry as ``hbm.*`` gauges."""
        cats = {
            "adjacency": 0,
            "vertex_columns": 0,
            "edge_columns": 0,
            "other": 0,
        }
        logical = dict(cats)
        for key, arr in self._arrays.items():
            if key.startswith("sh:"):
                cat = "adjacency"
            elif key.startswith("t:") or key.startswith("bk:"):
                # tier pools/indexes (storage/tiering) and overlay slab
                # bucket tables are adjacency in paged/bucketed clothing
                cat = "adjacency"
            elif key == "v_class" or key.startswith("v:"):
                cat = "vertex_columns"
            elif key.startswith("e:") and ":c:" in key:
                cat = "edge_columns"
            elif key.startswith("e:"):
                cat = "adjacency"
            else:
                cat = "other"
            logical[cat] += int(arr.nbytes)
            try:
                per_dev = max(
                    int(s.data.nbytes) for s in arr.addressable_shards
                )
            except Exception:
                per_dev = int(arr.nbytes)
            cats[cat] += per_dev
        from orientdb_tpu.utils.metrics import metrics

        for cat, b in cats.items():
            metrics.gauge(f"hbm.per_device.{cat}_bytes", b)
        metrics.gauge("hbm.per_device.total_bytes", sum(cats.values()))
        # property pruning observables: columns registered but never
        # referenced by any compiled plan stay host-side
        pruned_bytes = sum(
            int(np.asarray(a).nbytes) for a, _sp, _f in self._pending.values()
        )
        metrics.gauge("hbm.pruned_column_bytes", pruned_bytes)
        metrics.gauge("hbm.pruned_column_arrays", len(self._pending))
        return {
            "per_device": cats,
            "logical": logical,
            "pruned_bytes": pruned_bytes,
            "pruned_arrays": len(self._pending),
        }

    def class_ids(self, class_name: str) -> jnp.ndarray:
        key = class_name.lower()
        ids = self._class_ids.get(key)
        if ids is None:
            ids = self._class_ids[key] = jnp.asarray(
                self.snap.vertex_class_ids(class_name)
            )
            # baked into plan executables as constants — attributed so
            # the ledger's snapshot rollup covers the whole footprint
            from orientdb_tpu.obs.memledger import memledger

            memledger.register(
                "plan_const", self._ledger_owner, f"cls:{key}", arr=ids
            )
        return ids


_DG_BUILD_LOCK = threading.Lock()


def device_graph(snap: GraphSnapshot) -> DeviceGraph:
    """Build (or fetch the cached) device form of a snapshot.

    Construction is locked: a concurrent first-touch stampede would
    otherwise build SEVERAL DeviceGraphs for one snapshot (last writer
    wins) — wasted uploads, and threads left holding different
    instances, which breaks anything keyed on instance identity (the
    recording touch log that feeds per-plan jit-arg subsets)."""
    cached: Optional[DeviceGraph] = getattr(snap, "_device_cache", None)
    if cached is not None:
        return cached
    with _DG_BUILD_LOCK:
        cached = getattr(snap, "_device_cache", None)
        if cached is None:
            cached = snap._device_cache = DeviceGraph(snap)
    return cached
