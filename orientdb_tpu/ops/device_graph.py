"""Device-resident graph snapshot.

The HBM form of the columnar snapshot (`orientdb_tpu/storage/snapshot.py`):
every array `jax.device_put` once per snapshot epoch and cached, so repeated
queries over the same snapshot pay zero host↔device traffic for graph data —
the TPU-native answer to the reference's per-record page-cache reads on every
hop ([E] O2QCache / OPaginatedCluster.readRecord, SURVEY.md §3.2-3.3).
"""

from __future__ import annotations

from typing import Dict, Optional, Set

import jax.numpy as jnp
import numpy as np

from orientdb_tpu.storage.snapshot import GraphSnapshot, PropertyColumn


class DeviceColumn:
    """A property column on device: values + presence mask.

    `dictionary` (host-side) stays with the column so string predicates can
    be evaluated over the (small) dictionary on host and pushed to device as
    code-set membership masks.
    """

    __slots__ = ("name", "kind", "values", "present", "dictionary")

    def __init__(self, col: PropertyColumn):
        self.name = col.name
        self.kind = col.kind
        self.values = jnp.asarray(col.values)
        self.present = jnp.asarray(col.present)
        self.dictionary = col.dictionary


class DeviceEdgeClass:
    """One edge class's CSR adjacency (both directions) in HBM."""

    __slots__ = (
        "class_name",
        "indptr_out",
        "dst",
        "indptr_in",
        "src",
        "edge_id_in",
        "columns",
        "non_columnar",
        "num_edges",
    )

    def __init__(self, csr) -> None:
        self.class_name = csr.class_name
        self.indptr_out = jnp.asarray(csr.indptr_out)
        self.dst = jnp.asarray(csr.dst)
        self.indptr_in = jnp.asarray(csr.indptr_in)
        self.src = jnp.asarray(csr.src)
        self.edge_id_in = jnp.asarray(csr.edge_id_in)
        self.columns: Dict[str, DeviceColumn] = {
            n: DeviceColumn(c) for n, c in csr.edge_columns.items()
        }
        self.non_columnar: Set[str] = set(getattr(csr, "non_columnar", ()))
        self.num_edges = int(csr.dst.shape[0])


class DeviceGraph:
    """The full snapshot in HBM plus host metadata for planning/marshal."""

    def __init__(self, snap: GraphSnapshot) -> None:
        self.snap = snap
        self.num_vertices = snap.num_vertices
        self.v_class = jnp.asarray(snap.v_class)
        self.columns: Dict[str, DeviceColumn] = {
            n: DeviceColumn(c) for n, c in snap.v_columns.items()
        }
        self.non_columnar: Set[str] = set(getattr(snap, "v_non_columnar", ()))
        self.edges: Dict[str, DeviceEdgeClass] = {
            n: DeviceEdgeClass(c) for n, c in snap.edge_classes.items()
        }
        #: device-side polymorphic class-id sets (vertex classes)
        self._class_ids: Dict[str, jnp.ndarray] = {}

    def class_ids(self, class_name: str) -> jnp.ndarray:
        key = class_name.lower()
        ids = self._class_ids.get(key)
        if ids is None:
            ids = jnp.asarray(self.snap.vertex_class_ids(class_name))
            self._class_ids[key] = ids
        return ids


def device_graph(snap: GraphSnapshot) -> DeviceGraph:
    """Build (or fetch the cached) device form of a snapshot."""
    cached: Optional[DeviceGraph] = getattr(snap, "_device_cache", None)
    if cached is not None:
        return cached
    dg = DeviceGraph(snap)
    snap._device_cache = dg
    return dg
