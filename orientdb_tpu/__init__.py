"""orientdb_tpu — a TPU-native multi-model graph/document engine.

A brand-new framework with the capabilities of OrientDB's (reference:
AnsonT/orientdb, an OrientDB 3.x-era fork) document/graph model and SQL
MATCH/TRAVERSE query layer, redesigned TPU-first:

- host-side record store (documents, vertices, edges, schema, RIDs) that
  plays the role of OrientDB's record/metadata layer (SURVEY.md §1 layers 6-7),
- immutable columnar graph *snapshots* (CSR adjacency + property columns)
  bulk-loaded into TPU HBM (the plocal-cluster -> HBM ingest of the north star),
- a MATCH compiler that turns pattern ASTs into staged, batched frontier
  expansions executed under jit/shard_map instead of OrientDB's per-record
  interpreted ``MatchEdgeTraverser`` DFS,
- sharded multi-chip execution over a ``jax.sharding.Mesh`` with XLA
  collectives (psum / all_gather / ppermute) in place of Hazelcast + TCP
  channels.

Reference citations in docstrings use the ``[E] <path>`` convention from
SURVEY.md: the reference mount was empty during the survey, so paths are
expected upstream OrientDB 3.x Maven paths, to be re-verified when the
reference source appears.
"""

from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import Schema, SchemaClass, Property, PropertyType
from orientdb_tpu.models.record import Document, Vertex, Edge, Direction
from orientdb_tpu.models.database import Database, ConcurrentModificationError
from orientdb_tpu.exec.result import Result, ResultSet

__version__ = "0.3.0"

__all__ = [
    "RID",
    "Schema",
    "SchemaClass",
    "Property",
    "PropertyType",
    "Document",
    "Vertex",
    "Edge",
    "Direction",
    "Database",
    "ConcurrentModificationError",
    "Result",
    "ResultSet",
]
