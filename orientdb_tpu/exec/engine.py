"""Query engine front door.

Analog of the reference's query dispatch ([E]
ODatabaseDocumentEmbedded.query/command → OStatementCache →
planner → step chain; SURVEY.md §3.2): parses (with a statement cache),
routes idempotent statements to an execution engine, and wraps rows in a
ResultSet.

Engine selection (the north star's per-session ``TRAVERSE_ENGINE`` switch):
- ``engine="oracle"`` — the pure-Python reference interpreter (parity oracle);
- ``engine="tpu"`` — the compiled batched engine over the attached snapshot
  (MATCH/TRAVERSE/SELECT subset); falls back to the oracle for statements it
  cannot compile unless ``strict=True``;
- ``engine="auto"`` (default, from config.traverse_engine) — tpu when a
  fresh snapshot is attached, oracle otherwise.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Optional

from orientdb_tpu.exec.result import ResultSet
from orientdb_tpu.sql import ast as A
from orientdb_tpu.sql.parser import parse
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger

log = get_logger("engine")

_ENGINES = ("auto", "tpu", "oracle")

# statement cache ([E] OStatementCache): sql text → AST. AST nodes are
# frozen dataclasses, so sharing across threads is safe; the cache dict
# itself needs the lock.
_stmt_cache: "OrderedDict[str, A.Statement]" = OrderedDict()
_stmt_cache_lock = threading.Lock()


def parse_cached(sql: str) -> A.Statement:
    with _stmt_cache_lock:
        stmt = _stmt_cache.get(sql)
        if stmt is not None:
            _stmt_cache.move_to_end(sql)
            return stmt
    stmt = parse(sql)
    with _stmt_cache_lock:
        _stmt_cache[sql] = stmt
        while len(_stmt_cache) > config.statement_cache_size:
            _stmt_cache.popitem(last=False)
    return stmt


def _normalize_params(params) -> Dict:
    if params is None:
        return {}
    if isinstance(params, dict):
        return params
    # positional list → {0: v0, 1: v1, …}
    return {i: v for i, v in enumerate(params)}


def _choose_engine(db, stmt: A.Statement, engine: Optional[str]) -> str:
    eng = engine or config.traverse_engine
    if eng not in _ENGINES:
        raise ValueError(f"unknown engine {eng!r}; expected one of {_ENGINES}")
    if eng == "auto":
        if (
            db.tx is None
            and db.current_snapshot(require_fresh=True) is not None
            and isinstance(
                stmt,
                (A.MatchStatement, A.TraverseStatement, A.SelectStatement),
            )
        ):
            return "tpu"
        return "oracle"
    return eng


def _run(
    db,
    stmt: A.Statement,
    params,
    engine: Optional[str],
    strict: bool,
    sql: Optional[str] = None,
):
    from orientdb_tpu.utils.metrics import metrics

    eng = _choose_engine(db, stmt, engine)
    if eng == "tpu":
        from orientdb_tpu.exec import tpu_engine
        from orientdb_tpu.exec.devicefault import domain as _fault_domain

        try:
            # device fault quarantine gate: a fingerprint whose plan
            # exhausted the escalation ladder serves the oracle until
            # its TTL expires; "probe" admits exactly this dispatch as
            # the re-admission trial (success inside execute() clears
            # the entry, a fault re-quarantines with a doubled TTL)
            if _fault_domain.admit(sql) == "quarantined":
                raise tpu_engine.Uncompilable(
                    "plan quarantined by device fault domain"
                )
            # an active tx means the snapshot no longer reflects this
            # session's view (tx-created/-deleted records) — the oracle is
            # the only engine that applies the tx overlay
            if db.tx is not None:
                raise tpu_engine.Uncompilable("active transaction on this thread")
            rows = tpu_engine.execute(db, stmt, params, sql=sql)
            from orientdb_tpu.exec import audit as _audit

            # audit.mismatch chaos crossing: corrupts SERVED rows only,
            # so the shadow-oracle auditor provably detects them
            rows = _audit.corrupt_point(rows)
            metrics.incr("query.tpu")
            return rows, "tpu"
        except tpu_engine.Uncompilable as e:
            if strict:
                raise
            metrics.incr("query.tpu.fallback")
            log.info("tpu engine fallback to oracle: %s", e)
    metrics.incr("query.oracle")
    import orientdb_tpu.obs.critpath as CP
    import orientdb_tpu.obs.timeline as TL
    from orientdb_tpu.exec.oracle import execute_statement

    # the oracle is a dispatch path too: its flight record carries no
    # device intervals (host interpreter), but its wall time shows up
    # in the timeline next to the compiled paths it is compared against
    rec = TL.recorder.begin("oracle")
    with TL.active(rec), CP.segment("host_compute"):
        rows = execute_statement(db, stmt, params)
    TL.recorder.commit(rec)
    return rows, "oracle"


def _result_set(rows, engine_used: str) -> ResultSet:
    rs = ResultSet(rows)
    rs.engine = engine_used  # type: ignore[attr-defined]
    return rs


def _observe_query(
    sql: str, t0: float, engine_used: str, trace_id, acc
) -> None:
    """Per-query accounting shared by query()/command(): the duration
    stat + histogram feed /metrics, the per-fingerprint stats table
    aggregates cost by query shape, and the slowlog keeps the tail —
    stamped with the fingerprint so slowlog ↔ stats ↔ trace join on
    one id."""
    import time

    import orientdb_tpu.obs.stats as S  # noqa: F401 (module)
    from orientdb_tpu.obs.registry import obs as _obs
    from orientdb_tpu.obs.slowlog import slowlog

    dur = time.perf_counter() - t0
    _obs.observe("query.latency_s", dur)
    plan_cache = None
    if acc is not None:
        if acc.plan_cache_hits:
            plan_cache = "hit"
        elif acc.plan_cache_misses:
            plan_cache = "miss"
        elif acc.result_cache_hits:
            plan_cache = "result-cache"
    rows = getattr(acc, "_rows", None) if acc is not None else None
    fid = S.stats.finish(acc, dur, engine=engine_used, rows=rows)
    slowlog.record(
        sql,
        dur,
        engine=engine_used,
        trace_id=trace_id,
        fingerprint=fid,
        cache=plan_cache,
    )


def _observe_error(sql: str, t0: float, acc, exc: BaseException) -> None:
    """A failing query still counts: calls + errors per fingerprint."""
    import time

    import orientdb_tpu.obs.stats as S

    S.stats.finish(acc, time.perf_counter() - t0, engine="?", error=exc)


def execute_query(
    db,
    sql: str,
    params=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> ResultSet:
    """Idempotent statements only ([E] ODatabaseSession.query contract).
    PROFILE executes its inner statement, so a PROFILE of a write is
    rejected here too."""
    import time

    import orientdb_tpu.obs.critpath as CP
    import orientdb_tpu.obs.stats as S
    from orientdb_tpu.obs.trace import span

    t0 = time.perf_counter()
    acc = S.stats.begin(sql)
    with CP.request("engine", sql) as cp:
        seg0 = cp.total() if cp is not None else 0.0
        try:
            with span("query", sql=sql[:120]) as sp:
                rs = _execute_query(db, sql, params, engine, strict)
                sp.set("engine", getattr(rs, "engine", None))
                rows = getattr(rs, "_rows", None)
                if hasattr(rows, "__len__"):
                    sp.set("rows", len(rows))
                    if acc is not None:
                        acc._rows = len(rows)  # type: ignore[attr-defined]
        except BaseException as e:
            _observe_error(sql, t0, acc, e)
            CP.fold_query(cp, time.perf_counter() - t0, acc, seg0)
            raise
        _observe_query(sql, t0, getattr(rs, "engine", "?"), sp.trace_id, acc)
        CP.fold_query(cp, time.perf_counter() - t0, acc, seg0)
        # shadow-oracle parity audit: rides the stats sampling decision
        # (acc) so stats/slowlog/timeline/audit cover the same subset.
        # One attribute read when auditing is off — the serving path
        # must not pay normalize/submit costs for a disabled auditor.
        if config.audit_sample_rate > 0.0:
            from orientdb_tpu.exec import audit as _audit

            _audit.auditor.maybe_submit(
                db, sql, _normalize_params(params), rs, sp.trace_id,
                acc is not None,
            )
    return rs


def _execute_query(
    db,
    sql: str,
    params=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> ResultSet:
    stmt = parse_cached(sql)
    if isinstance(stmt, A.ExplainStatement):
        inner_writes = stmt.profile and not stmt.inner.is_idempotent
        if inner_writes:
            raise ValueError(
                "cannot PROFILE a non-idempotent statement via query(); use command()"
            )
        return explain_statement(db, stmt, _normalize_params(params))
    if not stmt.is_idempotent:
        raise ValueError(
            f"cannot run non-idempotent {type(stmt).__name__} via query(); use command()"
        )
    norm = _normalize_params(params)
    # result cache ([E] OCommandCache, off by default): idempotent
    # queries outside a tx, keyed incl. engine AND strict (a cached
    # fallback result must not mask strict=True's Uncompilable contract)
    from orientdb_tpu.exec.command_cache import cache_for

    cache = cache_for(db) if db.tx is None else None
    key = cache.key(sql, norm, engine, strict) if cache is not None else None
    # capture the epoch BEFORE running: a write landing mid-query must
    # make the cache entry stale (not stamp post-write freshness onto
    # pre-write rows) and must block view admission (the CDC callback
    # cannot invalidate a view that is not registered yet)
    epoch = db.mutation_epoch
    if key is not None:
        hit = cache.get(key, epoch)
        if hit is not None:
            return _result_set(hit[0], hit[1])
    # materialized continuous views (exec/views): hot fingerprints'
    # results kept resident with CDC-EXACT invalidation — unlike the
    # epoch-keyed command cache, an unrelated write does not kill them
    vm = None
    if db.tx is None:
        from orientdb_tpu.exec.views import views_for

        vm = views_for(db)
        if vm is not None:
            view = vm.lookup(sql, norm, engine, strict)
            if view is not None:
                return _result_set(view.rows, view.engine)
    rows, used = _run(db, stmt, norm, engine, strict, sql=sql)
    if key is not None:
        cache.put(key, rows, used, epoch)
    if vm is not None:
        vm.observe(sql, norm, engine, strict, rows, used, epoch=epoch)
    return _result_set(rows, used)


def execute_command(
    db,
    sql: str,
    params=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> ResultSet:
    import time

    import orientdb_tpu.obs.critpath as CP
    import orientdb_tpu.obs.stats as S
    from orientdb_tpu.obs.trace import span

    t0 = time.perf_counter()
    acc = S.stats.begin(sql)
    with CP.request("command", sql) as cp:
        seg0 = cp.total() if cp is not None else 0.0
        try:
            with span("command", sql=sql[:120]) as sp:
                rs = _execute_command(db, sql, params, engine, strict)
                sp.set("engine", getattr(rs, "engine", None))
                rows = getattr(rs, "_rows", None)
                if acc is not None and hasattr(rows, "__len__"):
                    acc._rows = len(rows)  # type: ignore[attr-defined]
        except BaseException as e:
            _observe_error(sql, t0, acc, e)
            CP.fold_query(cp, time.perf_counter() - t0, acc, seg0)
            raise
        _observe_query(sql, t0, getattr(rs, "engine", "?"), sp.trace_id, acc)
        CP.fold_query(cp, time.perf_counter() - t0, acc, seg0)
        if config.audit_sample_rate > 0.0:
            from orientdb_tpu.exec import audit as _audit

            _audit.auditor.maybe_submit(
                db, sql, _normalize_params(params), rs, sp.trace_id,
                acc is not None,
            )
    return rs


def _execute_command(
    db,
    sql: str,
    params=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> ResultSet:
    stmt = parse_cached(sql)
    if isinstance(stmt, A.ExplainStatement):
        return explain_statement(db, stmt, _normalize_params(params))
    if stmt.is_idempotent:
        rows, used = _run(
            db, stmt, _normalize_params(params), engine, strict, sql=sql
        )
        return _result_set(rows, used)
    from orientdb_tpu.exec.oracle import execute_statement

    return _result_set(
        execute_statement(db, stmt, _normalize_params(params)), "oracle"
    )


def execute_query_batch(
    db,
    sqls,
    params_list=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> List[ResultSet]:
    """Run a batch of idempotent statements in ~one device round trip.

    The TPU-engine members dispatch together and overlap their
    device→host transfers (``tpu_engine.execute_batch``) — the DP-axis
    answer to the tunneled-TPU's fixed per-transfer RTT. Per-statement
    Uncompilable failures fall back to the oracle (unless ``strict``).
    """
    import time

    import orientdb_tpu.obs.critpath as CP
    import orientdb_tpu.obs.stats as S
    from orientdb_tpu.obs.trace import span

    t0 = time.perf_counter()
    # a failing batch records NO per-statement stats: which statements
    # actually executed is unknowable here, and charging calls+errors
    # to all N shapes would fabricate exactly the aggregate evidence
    # this table exists to make trustworthy (the failure still lands in
    # query.latency_s / the caller's error path)
    import orientdb_tpu.obs.timeline as TL

    # one flight record for the whole in-frame batch (refined to
    # "group" when a vmapped group dispatch forms inside it)
    rec = TL.recorder.begin(
        "batch", sql=sqls[0] if sqls else None, n=len(sqls)
    )
    with CP.request("batch", sqls[0] if sqls else None) as cp:
        seg0 = cp.total() if cp is not None else 0.0
        with span("query_batch", n=len(sqls)) as bsp:
            # the capture collects the batch's device/transfer/compile
            # attribution (no per-query accumulator runs on a batch)
            with S.capture() as cap, TL.active(rec):
                out = _execute_query_batch(
                    db, sqls, params_list, engine, strict
                )
        TL.recorder.commit(rec)
        dur = time.perf_counter() - t0
        # per-statement stats with the batch's amortized wall clock:
        # device time overlaps across the whole batch, so per-item
        # attribution would be fiction — calls/rows/engine are what
        # aggregate honestly
        n = max(len(sqls), 1)
        per = dur / n
        per_segs = _amortized_segs(cp, dur, cap, seg0, n)
        auditing = config.audit_sample_rate > 0.0
        if auditing:
            from orientdb_tpu.exec import audit as _audit

        plist = params_list if params_list is not None else [None] * n
        for sql, p, rs in zip(sqls, plist, out):
            rows = getattr(rs, "_rows", None)
            S.stats.record_external(
                sql,
                per,
                engine=getattr(rs, "engine", "?"),
                rows=len(rows) if hasattr(rows, "__len__") else None,
            )
            if per_segs:
                S.stats.record_segments(sql, per_segs)
            # batch paths carry no per-query accumulator: the batch
            # capture is always on, so every member is audit-eligible
            if auditing:
                _audit.auditor.maybe_submit(
                    db, sql, _normalize_params(p), rs, bsp.trace_id, True
                )
    return out


def _amortized_segs(cp, dur: float, cap, seg0: float, n: int):
    """Fold one batch execution into the active critical-path record
    and return the per-statement amortized segment split for the stats
    table. The record takes the FULL batch cost (its segment sum must
    match the request's wall — the caller waited for the whole batch);
    the stats columns take the 1/n share next to record_external's
    amortized wall, and the record is marked so commit does not write
    the full-batch split over the amortized one."""
    import orientdb_tpu.obs.critpath as CP

    if cp is None:
        return None
    CP.fold_query(cp, dur, cap, seg0)
    cp.stats_recorded = True
    return {
        k: v / n
        for k, v in (
            ("queue", cap.queue_s),
            ("plan_resolve", cap.compile_s),
            ("device_compute", cap.device_s),
            ("result_transfer", cap.transfer_s),
            ("host_compute", max(
                0.0,
                dur - cap.queue_s - cap.compile_s - cap.device_s
                - cap.transfer_s,
            )),
        )
        if v > 0.0
    }


def _execute_query_batch(
    db,
    sqls,
    params_list=None,
    engine: Optional[str] = None,
    strict: bool = False,
) -> List[ResultSet]:
    n = len(sqls)
    if params_list is None:
        params_list = [None] * n
    if len(params_list) != n:
        raise ValueError("params_list length must match sqls length")
    items = []
    for sql, p in zip(sqls, params_list):
        stmt = parse_cached(sql)
        if isinstance(stmt, A.ExplainStatement) or not stmt.is_idempotent:
            raise ValueError(
                f"cannot run non-idempotent {type(stmt).__name__} via query_batch()"
            )
        items.append((stmt, _normalize_params(p)))
    engines = [_choose_engine(db, s, engine) for s, _ in items]
    out: List[Optional[ResultSet]] = [None] * n
    tpu_idx = [i for i, e in enumerate(engines) if e == "tpu"]
    if tpu_idx and db.tx is None:
        from orientdb_tpu.exec import tpu_engine
        from orientdb_tpu.exec.devicefault import domain as _fault_domain

        # per-item quarantine gate: quarantined fingerprints drop to
        # the oracle loop below; "probe" items ride the batch and clear
        # their entry on a clean result
        gates = {i: _fault_domain.admit(sqls[i]) for i in tpu_idx}
        if strict and any(g == "quarantined" for g in gates.values()):
            raise tpu_engine.Uncompilable(
                "plan quarantined by device fault domain"
            )
        run_idx = [i for i in tpu_idx if gates[i] != "quarantined"]
        if run_idx:
            batch = tpu_engine.execute_batch(
                db,
                [items[i] for i in run_idx],
                sqls=[sqls[i] for i in run_idx],
            )
            for i, res in zip(run_idx, batch):
                if isinstance(res, tpu_engine.Uncompilable):
                    if strict:
                        raise res
                    log.info("tpu batch fallback to oracle: %s", res)
                else:
                    out[i] = _result_set(res, "tpu")
                    if gates[i] == "probe":
                        _fault_domain.note_success(sqls[i])
    elif tpu_idx:  # active tx: snapshot cannot see the tx overlay
        if strict:
            from orientdb_tpu.exec.tpu_engine import Uncompilable

            raise Uncompilable("active transaction on this thread")
    from orientdb_tpu.exec.oracle import execute_statement

    for i in range(n):
        if out[i] is None:
            stmt, p = items[i]
            out[i] = _result_set(execute_statement(db, stmt, p), "oracle")
    return out


def dispatch_lane_batch(
    db,
    sqls,
    params_list=None,
    ring_state=None,
    enqueue_ts=None,
    window_s=None,
    min_epoch=None,
):
    """Lane front door (server/coalesce): NON-BLOCKING dispatch of one
    fingerprint lane's homogeneous micro-batch. Returns a handle whose
    ``collect()`` yields the ResultSets (folding per-item stats
    attribution — amortized wall/device/transfer plus each item's
    queue wait), or None when the lane fast path does not apply; the
    caller then runs ``execute_query_batch``, which also records first
    executions and oracle statements.

    ``ring_state`` is the lane's opaque per-plan staging state (a plain
    dict the engine keeps its :class:`tpu_engine.ParamRing` in), so the
    coalescer never has to import the device stack. ``enqueue_ts``
    (monotonic: the first rider's lane entry) and ``window_s`` (the
    collection window that formed this batch) stamp the dispatch's
    flight record (obs/timeline) so overlap accounting can decompose
    lane wait vs service."""
    n = len(sqls)
    if params_list is None:
        params_list = [None] * n
    items = []
    for sql, p in zip(sqls, params_list):
        stmt = parse_cached(sql)
        if isinstance(stmt, A.ExplainStatement) or not stmt.is_idempotent:
            return None
        items.append((stmt, _normalize_params(p)))
    if not items or _choose_engine(db, items[0][0], None) != "tpu":
        return None
    from orientdb_tpu.exec import tpu_engine
    from orientdb_tpu.exec.devicefault import domain as _fault_domain

    if _fault_domain.admit(sqls[0]) == "quarantined":
        # homogeneous lane, one fingerprint: the whole drain degrades
        # to the generic path, whose gate serves the oracle ("probe"
        # proceeds — the lane dispatch IS the re-admission trial)
        return None
    ring = None
    if ring_state is not None:
        ring = ring_state.get("ring")
        if ring is None:
            ring = ring_state["ring"] = tpu_engine.ParamRing()
    import orientdb_tpu.obs.critpath as CP

    # detached worker-side harvest record: ring staging stamps its
    # param_upload/ring_hit timing here (this lane worker thread has no
    # per-request record); collect() amortizes the harvest across the
    # batch members, whose dicts travel back to the submitting sessions
    harvest = CP.CritPath("lane") if config.critpath_enabled else None
    with CP.active(harvest):
        h = tpu_engine.dispatch_lane(
            db,
            items,
            ring=ring,
            sql=sqls[0],
            enqueue_ts=enqueue_ts,
            window_s=window_s,
            min_epoch=min_epoch,
        )
    if h is None:
        return None
    return _LaneHandle(
        sqls, h, harvest.segs if harvest else None,
        db=db, params_list=params_list,
    )


class _LaneHandle:
    """Wraps an in-flight ``tpu_engine.LaneDispatch``: ``collect()``
    blocks on the fetch, wraps rows in ResultSets, and attributes the
    batch's amortized cost to each member fingerprint."""

    __slots__ = (
        "sqls", "_h", "_stage_segs", "item_segs", "_db", "_params_list",
    )

    def __init__(
        self, sqls, h, stage_segs=None, db=None, params_list=None
    ) -> None:
        self.sqls = sqls
        self._h = h
        self._db = db
        self._params_list = params_list
        #: worker-side staging stamps (param_upload / ring_hit seconds
        #: for the whole batch) harvested by dispatch_lane_batch
        self._stage_segs = stage_segs
        #: per-item critical-path splits built by collect(), read by
        #: the coalescer and folded into each submitter's record
        self.item_segs: Optional[List[Dict[str, float]]] = None

    def collect(self, queue_waits=None) -> List[ResultSet]:
        import time

        import orientdb_tpu.obs.stats as S

        t0 = time.perf_counter()
        with S.capture() as cap:
            outs = self._h.collect()
        wall = time.perf_counter() - t0
        n = max(len(outs), 1)
        per = wall / n
        host_per = max(0.0, wall - cap.device_s - cap.transfer_s) / n
        stage = self._stage_segs or {}
        results = []
        self.item_segs = []
        for k, (sql, rows) in enumerate(zip(self.sqls, outs)):
            rs = _result_set(rows, "tpu")
            S.stats.record_external(
                sql,
                per,
                engine="tpu",
                rows=len(rows) if hasattr(rows, "__len__") else None,
                queue_s=queue_waits[k] if queue_waits else 0.0,
                device_s=cap.device_s / n,
                transfer_s=cap.transfer_s / n,
                bytes_fetched=cap.bytes_fetched // n,
            )
            segs = {
                "queue": queue_waits[k] if queue_waits else 0.0,
                "device_compute": cap.device_s / n,
                "result_transfer": cap.transfer_s / n,
                "host_compute": host_per,
            }
            for name, v in stage.items():
                segs[name] = segs.get(name, 0.0) + v / n
            self.item_segs.append(
                {k2: v for k2, v in segs.items() if v > 0.0}
            )
            if self._db is not None and config.audit_sample_rate > 0.0:
                from orientdb_tpu.exec import audit as _audit

                p = (
                    self._params_list[k]
                    if self._params_list is not None
                    and k < len(self._params_list)
                    else None
                )
                _audit.auditor.maybe_submit(
                    self._db, sql, _normalize_params(p), rs, None, True
                )
            results.append(rs)
        return results


def explain(db, sql: str, params=None) -> ResultSet:
    stmt = parse_cached(sql)
    if not isinstance(stmt, A.ExplainStatement):
        stmt = A.ExplainStatement(stmt, profile=False)
    return explain_statement(db, stmt, _normalize_params(params))


def explain_statement(db, stmt: A.ExplainStatement, params) -> ResultSet:
    from orientdb_tpu.exec.planner import explain_plan

    return explain_plan(db, stmt, params)
