"""Query engine entry points (wired from Database.query/command/explain).

Placeholder until the SQL front door (parser + oracle + TPU engine) lands;
keeping the module importable gives a clear error instead of an import crash.
"""

from __future__ import annotations


def execute_query(db, sql, params, **kw):
    raise NotImplementedError(
        "the SQL engine is not built yet (parser/oracle land next milestone)"
    )


def execute_command(db, sql, params, **kw):
    raise NotImplementedError(
        "the SQL engine is not built yet (parser/oracle land next milestone)"
    )


def explain(db, sql, params):
    raise NotImplementedError(
        "the SQL engine is not built yet (parser/oracle land next milestone)"
    )
