"""Device fault domain: contain, degrade, and recover from device-side
failures on every dispatch path.

PR 3 hardened every *host-side* channel (chaos points, retries,
breakers, admission shedding) but the device itself stayed a single
point of failure: an XLA runtime error, a device OOM, or a failed D2H
transfer mid-dispatch escaped as an unclassified exception — no retry,
no degradation, no quarantine. This module closes that hole with one
**escalation ladder** wrapped around every dispatch path (compiled
single, vmapped group, coalesce lanes, sharded mesh, tiered prefetch,
delta apply — compaction is this ladder's *actuator*, reached through
the overlay poison machinery):

1. **classify** — every exception crossing a device boundary becomes
   ``oom`` / ``transient`` / ``persistent`` (``device.fault.*``
   counters; ``SimulatedCrash`` and the engines' own control-flow
   exceptions pass through untouched);
2. **retry** — transients re-dispatch under the PR-3
   :class:`~orientdb_tpu.parallel.resilience.RetryPolicy` (bounded
   attempts + budget);
3. **relieve** — an OOM actuates memory-pressure relief before its
   retry, memledger-guided by owner taxonomy: evict tier-pool blocks
   (PR 16), poison the delta overlay so the maintainer compacts its
   slabs (PR 15), and drop the coalesce lanes' device param rings
   (PR 12);
4. **quarantine** — a plan whose faults survive the retries is
   quarantined by stats-plane fingerprint: the engine front doors
   route it to the oracle (riding the coalesce poison-fallback
   machinery) for a TTL, then admit ONE probe; a clean probe
   re-admits, a failed one doubles the TTL;
5. **shed** — when relief leaves the memledger total above the
   headroom fraction of ``tier_hbm_cap_bytes`` (or an OOM survives
   relief), the admission plane (``server/admission.db_pressure``)
   sheds writes with 503 + Retry-After for ``devicefault_shed_s`` —
   the server degrades loudly instead of OOM-crashing.

Injectable end to end: the ``tpu.dispatch`` / ``tpu.transfer`` /
``tpu.oom`` chaos points cross inside the wrapped sections, so a
seeded :class:`~orientdb_tpu.chaos.faults.FaultPlan` drives the whole
ladder deterministically in tests. Observable end to end: the
``devicefault.escalate`` span, the ``device_fault_storm`` alert rule,
quarantine state in ``/cluster/health`` and the debug bundle, fault
events on the flight-recorder timeline, and a per-round
``device_faults`` bench evidence record.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from orientdb_tpu.chaos.faults import fault
from orientdb_tpu.ops.predicates import Uncompilable
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("devicefault")

#: classification kinds (the ``device.fault.<kind>`` counter suffixes)
OOM = "oom"
TRANSIENT = "transient"
PERSISTENT = "persistent"
#: parity conviction (exec/audit): the plan ran fine but served rows
#: the shadow oracle disagrees with — wrong answers, not crashes
PARITY = "parity"


class DeviceFaultError(OSError):
    """A classified device-side failure.

    OSError on purpose: the PR-3 retry surfaces (client failover, the
    guard's own policy) already treat OSError as the retryable family.
    ``retry_after`` is set when the quarantine/shed machinery knows how
    long degraded mode lasts — the binary server forwards it as a
    503-style hint and :class:`client.remote.DeviceTransientError`
    honors it."""

    def __init__(
        self, msg: str, kind: str = TRANSIENT,
        retry_after: Optional[float] = None,
    ) -> None:
        super().__init__(msg)
        self.kind = kind
        self.retry_after = retry_after


class DeviceOomError(DeviceFaultError):
    """Device memory exhaustion (classified ``oom``)."""

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg, kind=OOM, retry_after=retry_after)


class _PersistentFault(DeviceFaultError):
    """Internal: a fault classified persistent — retrying cannot help,
    the policy gives up immediately and escalation quarantines."""


class DeviceQuarantined(Uncompilable):
    """Raised out of a guarded dispatch path when the ladder exhausted
    its rungs. Subclasses ``Uncompilable`` deliberately: every engine
    front door already converts that into a per-statement oracle
    fallback, and the coalesce lanes' batch-failure machinery re-runs
    members through those front doors — so degraded mode rides the
    existing poison-fallback plumbing instead of a parallel one."""

    def __init__(self, msg: str, retry_after: Optional[float] = None):
        super().__init__(msg)
        self.retry_after = retry_after


# -- classification ----------------------------------------------------------

#: message fragments (lowercased) that mark device memory exhaustion —
#: XLA's RESOURCE_EXHAUSTED family plus the chaos point's own name, so
#: a plain ``error`` rule at ``tpu.oom`` classifies without a custom
#: error factory
_OOM_MARKERS = (
    "resource_exhausted",
    "resource exhausted",
    "out of memory",
    "allocat",
    "hbm",
    "tpu.oom",
)

#: fragments that mark a *structurally* broken executable — retrying
#: the same program cannot succeed, so the ladder skips straight to
#: quarantine
_PERSISTENT_MARKERS = (
    "invalid_argument",
    "invalid argument",
    "unimplemented",
    "failed_precondition",
)


def classify(exc: BaseException) -> str:
    """``oom`` / ``persistent`` / ``transient`` for an exception caught
    at a device dispatch/fetch boundary. Callers only hand this
    exceptions that crossed such a boundary — position, not type, is
    what makes them device-side — so the default is ``transient``:
    retry is the cheapest rung, and a persistent conviction also
    arrives via retry exhaustion."""
    if isinstance(exc, DeviceFaultError):
        return exc.kind
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(m in msg for m in _OOM_MARKERS):
        return OOM
    if any(m in msg for m in _PERSISTENT_MARKERS):
        return PERSISTENT
    return TRANSIENT


# -- quarantine entries ------------------------------------------------------


class _Quarantine:
    __slots__ = (
        "fid", "sql", "kind", "reason", "since", "until", "strikes",
        "probe_ts",
    )

    def __init__(self, fid, sql, kind, reason, now, ttl) -> None:
        self.fid = fid
        self.sql = sql
        self.kind = kind
        self.reason = reason
        self.since = now
        self.until = now + ttl
        self.strikes = 1
        #: monotonic ts of the in-flight probe (None = no probe out);
        #: a probe that never reports back expires after one TTL so a
        #: lost probe cannot wedge the entry in quarantine forever
        self.probe_ts: Optional[float] = None

    def row(self, now: float) -> Dict:
        return {
            "fingerprint": self.fid,
            "sql": (self.sql or "")[:120],
            "kind": self.kind,
            "reason": self.reason[:200],
            "age_s": round(now - self.since, 3),
            "ttl_s": round(max(0.0, self.until - now), 3),
            "strikes": self.strikes,
            "probing": self.probe_ts is not None,
        }


# -- the domain --------------------------------------------------------------


class DeviceFaultDomain:
    """Process-wide device fault state (mirrors ``metrics``/``stats``):
    the guard (:meth:`run`), the quarantine registry the engine front
    doors consult (:meth:`admit`), and the admission-plane shed latch
    (:meth:`shed_state`)."""

    def __init__(self) -> None:
        self._mu = threading.RLock()
        self._q: Dict[int, _Quarantine] = {}
        #: classified fault counts by kind (process lifetime)
        self._faults: Dict[str, int] = {}
        self._reliefs: Dict[str, int] = {}
        self._retries = 0
        self._quarantines = 0
        self._readmitted = 0
        self._oracle_served = 0
        self._probes = 0
        self._sheds = 0
        self._shed_until = 0.0
        self._shed_reason: Optional[str] = None

    # -- admission (engine front doors) --------------------------------------

    def _fid(self, sql: Optional[str]) -> Optional[int]:
        if not sql:
            return None
        from orientdb_tpu.obs.stats import fingerprint_cached

        return fingerprint_cached(sql).fid

    def admit(self, sql: Optional[str]) -> Optional[str]:
        """Gate one statement's compiled dispatch: ``None`` = clear,
        ``"quarantined"`` = serve the oracle, ``"probe"`` = THIS call
        holds the re-admission probe (report back via
        :meth:`note_success`, or the next fault re-quarantines). The
        no-quarantine fast path is one attribute read."""
        if not self._q:
            return None
        fid = self._fid(sql)
        if fid is None:
            return None
        now = time.monotonic()
        with self._mu:
            e = self._q.get(fid)
            if e is None:
                return None
            if now < e.until or (
                e.probe_ts is not None
                and now - e.probe_ts < self._ttl()
            ):
                # still serving time, or another probe is in flight
                self._oracle_served += 1
                metrics.incr("device.fault.quarantine.oracle")
                return "quarantined"
            e.probe_ts = now
            self._probes += 1
            metrics.incr("device.fault.probe")
            return "probe"

    def note_success(self, sql: Optional[str]) -> None:
        """A probe dispatch completed cleanly: re-admit the plan."""
        if not self._q:
            return
        fid = self._fid(sql)
        with self._mu:
            e = self._q.get(fid) if fid is not None else None
            if e is None or e.probe_ts is None:
                return
            del self._q[fid]
            self._readmitted += 1
        metrics.incr("device.fault.readmitted")
        metrics.gauge("device.fault.quarantined", float(len(self._q)))
        log.info("device fault quarantine lifted (probe ok): %s", sql)

    # -- the guard -----------------------------------------------------------

    def run(
        self,
        fn: Callable,
        *,
        db=None,
        sql: Optional[str] = None,
        stage: str = "dispatch",
        passthrough: Tuple[type, ...] = (),
        tier=None,
    ):
        """Run one device dispatch/fetch section under the escalation
        ladder. ``passthrough`` names the caller's control-flow
        exceptions (``ScheduleOverflow``); ``Uncompilable`` and
        ``SimulatedCrash`` always pass through. Exhaustion raises
        :class:`DeviceQuarantined` (an ``Uncompilable``) — zero
        unclassified device exceptions escape."""
        import time as _time

        from orientdb_tpu.parallel.resilience import (
            RetryBudgetExceeded,
            RetryPolicy,
        )

        give_up = (Uncompilable,) + tuple(passthrough)
        relief_done: List[str] = []
        # fault_retry attribution: retry backoff sleep + failed attempts
        # must not masquerade as device-compute growth in the critical-
        # path blame diff, so everything run() spends beyond the single
        # SUCCESSFUL attempt is stamped as its own segment
        t_run0 = _time.perf_counter()
        last_attempt_s = [0.0]
        n_attempts = [0]

        def _attempt():
            n_attempts[0] += 1
            t_a = _time.perf_counter()
            try:
                out = fn()
                last_attempt_s[0] = _time.perf_counter() - t_a
                return out
            except give_up:
                raise
            except Exception as e:
                # SimulatedCrash is a BaseException: it unwinds through
                # here untouched, like a real SIGKILL would
                kind = classify(e)
                self._record_fault(kind, stage, e)
                if kind == OOM and not relief_done:
                    # relief BEFORE the retry, once per guarded section
                    relief_done.extend(self.relieve(db, tier=tier))
                if kind == PERSISTENT:
                    raise _PersistentFault(
                        f"{stage}: {type(e).__name__}: {e}", kind=kind
                    ) from e
                with self._mu:
                    self._retries += 1
                raise DeviceFaultError(
                    f"{stage}: {type(e).__name__}: {e}", kind=kind
                ) from e

        policy = RetryPolicy(
            attempts=max(1, int(config.devicefault_retry_attempts)),
            base_s=0.01,
            cap_s=0.25,
            budget_s=float(config.devicefault_retry_budget_s),
        )
        try:
            out = policy.call(
                _attempt,
                retry_on=(DeviceFaultError,),
                give_up_on=give_up + (_PersistentFault,),
            )
        except give_up:
            raise
        except (_PersistentFault, RetryBudgetExceeded) as e:
            import orientdb_tpu.obs.critpath as _CP

            # exhaustion: the whole guarded section was retry churn
            _CP.add_segment(
                "fault_retry", _time.perf_counter() - t_run0
            )
            cause = e if isinstance(e, DeviceFaultError) else e.__cause__
            kind = cause.kind if isinstance(
                cause, DeviceFaultError
            ) else TRANSIENT
            self._escalate(kind, cause, db=db, sql=sql, stage=stage,
                           relief_done=relief_done)
        else:
            if n_attempts[0] > 1:
                overhead = (
                    _time.perf_counter() - t_run0
                ) - last_attempt_s[0]
                if overhead > 0.0:
                    import orientdb_tpu.obs.critpath as _CP

                    _CP.add_segment("fault_retry", overhead)
            if sql and self._q:
                self.note_success(sql)
            return out

    def _record_fault(self, kind: str, stage: str, exc) -> None:
        with self._mu:
            self._faults[kind] = self._faults.get(kind, 0) + 1
        metrics.incr(f"device.fault.{kind}")
        metrics.incr("device.fault.total")
        from orientdb_tpu.obs.timeline import note_fault

        note_fault(kind)
        log.warning(
            "device fault (%s) at %s: %s: %s",
            kind, stage, type(exc).__name__, exc,
        )

    def _escalate(
        self, kind, cause, *, db, sql, stage, relief_done
    ) -> None:
        """Retries exhausted (or the fault is persistent): quarantine
        the fingerprint, arm the shed latch when memory stayed tight,
        and degrade to the oracle. Always raises."""
        from orientdb_tpu.obs.trace import span

        ttl = self._ttl()
        with span(
            "devicefault.escalate", stage=stage, kind=kind,
            relief=",".join(relief_done) or None,
        ):
            retry_after = ttl
            if sql is not None:
                retry_after = self._quarantine(sql, kind, str(cause))
            if kind == OOM:
                # the device said OOM and relief + retry did not clear
                # it: degrade admission loudly instead of OOM-crashing
                self._arm_shed(f"device OOM survived relief at {stage}")
            elif self._ledger_over_headroom():
                self._arm_shed("memledger total over headroom fraction")
        raise DeviceQuarantined(
            f"device fault domain: {kind} fault at {stage} exhausted "
            f"retries ({cause}); serving oracle",
            retry_after=retry_after,
        ) from cause

    def quarantine_parity(self, sql: str, reason: str) -> float:
        """Parity-divergence conviction (exec/audit): the compiled plan
        executed cleanly but served rows the shadow oracle disagrees
        with. Quarantine its fingerprint so the engine front doors
        serve degraded-but-correct oracle traffic; the existing probe
        machinery re-admits after a clean (re-audited) trial. Returns
        the TTL, like :meth:`_quarantine`."""
        return self._quarantine(sql, PARITY, reason)

    def parity_quarantined(self) -> int:
        """Active quarantine entries convicted by the parity auditor
        (the ``parity_divergence`` alert rule's active-state signal)."""
        with self._mu:
            return sum(1 for e in self._q.values() if e.kind == PARITY)

    # -- quarantine ----------------------------------------------------------

    def _ttl(self) -> float:
        return max(0.1, float(config.devicefault_quarantine_ttl_s))

    def _quarantine(self, sql: str, kind: str, reason: str) -> float:
        """Register/extend the fingerprint's quarantine; returns the
        TTL the caller advertises as Retry-After."""
        fid = self._fid(sql)
        if fid is None:
            return self._ttl()
        now = time.monotonic()
        ttl = self._ttl()
        with self._mu:
            e = self._q.get(fid)
            if e is None:
                self._q[fid] = _Quarantine(fid, sql, kind, reason, now, ttl)
            else:
                # a failed probe (or a second path convicting the same
                # plan): strike and back off the TTL exponentially
                e.strikes += 1
                e.kind = kind
                e.reason = reason
                e.probe_ts = None
                ttl = ttl * min(2 ** (e.strikes - 1), 8)
                e.until = now + ttl
            self._quarantines += 1
        metrics.incr("device.fault.quarantine")
        metrics.gauge("device.fault.quarantined", float(len(self._q)))
        log.warning(
            "plan quarantined (%s, ttl %.1fs): %s", kind, ttl, sql
        )
        return ttl

    # -- relief --------------------------------------------------------------

    def relieve(self, db=None, tier=None) -> List[str]:
        """Actuate memory-pressure relief, memledger-guided: the owner
        taxonomy (PR 17) says where the bytes are, the PR-16 tier pool
        / PR-15 delta plane / PR-12 param rings are the actuators.
        Returns the actions taken (also counted as
        ``device.fault.relief.<action>``)."""
        from orientdb_tpu.obs.memledger import memledger

        totals = memledger.totals()
        actions: List[str] = []
        # actuate in descending attributed-bytes order so the relief
        # chases where the ledger says the memory actually is; rings
        # and transient pages are always worth dropping (cheap, purely
        # a cache)
        candidates = sorted(
            ("tier_pool", "delta_slab"),
            key=lambda k: totals.get(k, 0),
            reverse=True,
        )
        # each actuator independently guarded: relief runs UNDER a
        # faulting dispatch — a second failure here must degrade the
        # relief, never replace the classified fault being handled
        for kind in candidates:
            try:
                if kind == "tier_pool":
                    t = tier
                    if t is None and db is not None:
                        snap = db.current_snapshot()
                        t = getattr(snap, "_tier", None)
                    if t is not None and self._evict_tier(t):
                        actions.append("tier_evict")
                elif kind == "delta_slab" and totals.get(kind, 0) > 0:
                    if db is not None and self._poison_overlay(db):
                        actions.append("delta_compact")
            except Exception as e:  # noqa: BLE001 - relief best-effort
                log.warning("relief actuator %s failed: %s", kind, e)
        try:
            if self._drop_rings():
                actions.append("ring_drop")
        except Exception as e:  # noqa: BLE001 - relief best-effort
            log.warning("relief actuator ring_drop failed: %s", e)
        for a in actions:
            with self._mu:
                self._reliefs[a] = self._reliefs.get(a, 0) + 1
            metrics.incr(f"device.fault.relief.{a}")
        memledger.note_event(
            "devicefault_relief",
            ",".join(actions) if actions else "no actuator available",
        )
        log.warning("device fault relief actuated: %s", actions or "none")
        return actions

    @staticmethod
    def _evict_tier(tier, max_blocks: int = 8) -> bool:
        """Evict up to ``max_blocks`` resident, unpinned LRU blocks.
        Pool pages are recycled (not freed) — the relief is working-set
        pressure off the pinned hot set, and the observable signal the
        acceptance tests assert (``tier.evictions``)."""
        evicted = 0
        with tier.lock:
            for part in tier.parts.values():
                resident = [
                    b for b in range(part.B)
                    if part.page_of[b] >= 0
                    and part.pins.get(b, 0) <= 0
                ]
                resident.sort(key=lambda b: part.lru.get(b, -1))
                for b in resident[:max_blocks - evicted]:
                    tier._evict(part, b)
                    evicted += 1
                if evicted >= max_blocks:
                    break
        return evicted > 0

    @staticmethod
    def _poison_overlay(db) -> bool:
        """Poison the delta overlay so the maintainer folds its slabs
        on the next catch-up — compaction rides the existing rebuild
        machinery rather than running on the faulting thread (which may
        hold dispatch leases the compaction swap would wait on)."""
        m = getattr(db, "_snapshot_maintainer", None)
        ov = m.overlay if m is not None else None
        if ov is None or ov.poisoned is not None:
            return False
        ov.poison("device fault relief: compact slabs")
        return True

    @staticmethod
    def _drop_rings() -> bool:
        from orientdb_tpu.exec import tpu_engine

        return tpu_engine.drop_param_rings() > 0

    def _ledger_over_headroom(self) -> bool:
        cap = int(config.tier_hbm_cap_bytes)
        frac = float(config.devicefault_headroom_fraction)
        if cap <= 0 or frac <= 0:
            return False
        from orientdb_tpu.obs.memledger import memledger

        return memledger.total_bytes() > cap * frac

    # -- admission shed ------------------------------------------------------

    def _arm_shed(self, reason: str) -> None:
        with self._mu:
            self._sheds += 1
            self._shed_reason = reason
            self._shed_until = time.monotonic() + max(
                0.1, float(config.devicefault_shed_s)
            )
        metrics.incr("device.fault.shed")
        metrics.gauge("device.fault.shedding", 1.0)
        log.warning("device fault admission shed armed: %s", reason)

    def shed_state(self) -> Tuple[Optional[str], float]:
        """(reason or None, Retry-After seconds) — consulted by
        ``server/admission.db_pressure``. The latch is a half-open
        window: after ``devicefault_shed_s`` it clears on its own, so
        a recovered device re-admits without an operator."""
        if self._shed_until <= 0.0:
            return None, 0.0
        now = time.monotonic()
        with self._mu:
            if now >= self._shed_until:
                if self._shed_reason is not None:
                    self._shed_reason = None
                    metrics.gauge("device.fault.shedding", 0.0)
                return None, 0.0
            return self._shed_reason, round(self._shed_until - now, 3)

    # -- views ---------------------------------------------------------------

    def fault_total(self) -> int:
        """Classified device faults this process lifetime (the
        ``device_fault_storm`` rule's rate source)."""
        with self._mu:
            return sum(self._faults.values())

    def snapshot(self) -> Dict:
        """The ``/cluster/health`` + debug-bundle block."""
        now = time.monotonic()
        shed_reason, shed_after = self.shed_state()
        with self._mu:
            return {
                "classified": dict(self._faults),
                "retries": self._retries,
                "reliefs": dict(self._reliefs),
                "quarantined": [e.row(now) for e in self._q.values()],
                "quarantines_total": self._quarantines,
                "readmitted": self._readmitted,
                "oracle_served": self._oracle_served,
                "probes": self._probes,
                "sheds": self._sheds,
                "shedding": shed_reason,
                "shed_retry_after_s": shed_after,
            }

    def reset(self) -> None:
        """Test isolation (mirrors ``metrics.reset``)."""
        with self._mu:
            self._q.clear()
            self._faults.clear()
            self._reliefs.clear()
            self._retries = 0
            self._quarantines = 0
            self._readmitted = 0
            self._oracle_served = 0
            self._probes = 0
            self._sheds = 0
            self._shed_until = 0.0
            self._shed_reason = None


#: the process-wide domain (mirrors metrics/stats/tracer singletons)
domain = DeviceFaultDomain()


# -- chaos crossings ---------------------------------------------------------


def dispatch_point() -> None:
    """Cross the device-dispatch chaos points. ``tpu.oom`` first so a
    plan targeting it fires before a generic ``tpu.dispatch`` rule —
    its injected error carries the point name and classifies ``oom``
    without a custom error factory."""
    with fault.point("tpu.oom"):
        pass
    with fault.point("tpu.dispatch"):
        pass


def transfer_point() -> None:
    """Cross the device-transfer chaos points (H2D uploads and the
    blocking D2H result drains)."""
    with fault.point("tpu.oom"):
        pass
    with fault.point("tpu.transfer"):
        pass


# -- bench evidence ----------------------------------------------------------


def bench_device_faults_summary() -> Dict:
    """One per-round ``device_faults`` evidence record (the watchdog /
    memory blocks' sibling): classified counts, quarantines, sheds,
    relief actuations. ``tools/perfdiff.degraded_round`` reads it to
    keep chaos rounds out of the regression baseline."""
    s = domain.snapshot()
    return {
        "total": sum(s["classified"].values()),
        "classified": s["classified"],
        "retries": s["retries"],
        "reliefs": s["reliefs"],
        "quarantines": s["quarantines_total"],
        "quarantined_now": len(s["quarantined"]),
        "readmitted": s["readmitted"],
        "oracle_served": s["oracle_served"],
        "sheds": s["sheds"],
        "shedding": bool(s["shedding"]),
    }
