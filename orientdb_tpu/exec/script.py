"""SQL batch scripts.

Analog of the reference's script executor ([E] OCommandScript /
OSqlScriptExecutor behind ``ODatabaseSession.execute("sql", script)``
and the REST ``/batch`` command): a semicolon/newline-separated
sequence of statements running in ONE session context, with

- ``LET $name = <statement or expression>`` binding the result set (or
  scalar) into the script context — later statements reference ``$name``
- ``IF (<expr>) { <statements> }`` conditional blocks
- ``RETURN <expr> | $var | [list]`` ending the script with a value
- ``BEGIN / COMMIT / ROLLBACK`` spanning statements (the per-thread tx
  the statements already share)
- ``SLEEP <ms>`` (the reference's script-only sleep statement)

The splitter is quote-aware and brace-aware, so ``;`` inside string
literals and MATCH pattern braces do not split statements.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.exec.eval import EvalContext, evaluate, truthy
from orientdb_tpu.exec.result import Result


class ScriptError(Exception):
    pass


#: script-level directives (not parser statements) — a newline after a
#: line starting with one of these always separates
_DIRECTIVE_HEADS = ("LET", "RETURN", "SLEEP")


def _complete_statement(buf: str) -> bool:
    """Newline-separation test: the buffer is a finished statement.
    Script directives (LET/RETURN/SLEEP) are line-oriented; anything
    else must parse as a complete SQL statement."""
    s = buf.strip()
    if not s:
        return False
    head = s.split(None, 1)[0].upper()
    if head in _DIRECTIVE_HEADS:
        return True
    from orientdb_tpu.sql.parser import parse

    try:
        parse(s)
        return True
    except Exception:  # ParseError or lexer errors: keep accumulating
        return False


def split_script(text: str) -> List[str]:
    """Split on ``;`` and statement-separating newlines, respecting
    string literals and brace/bracket/paren nesting (MATCH patterns,
    IF blocks, embedded collections). A newline separates only when
    the accumulated text already forms a complete statement — so a
    statement may span lines, and one-statement-per-line scripts (the
    reference's console/Studio batch form) split correctly."""
    out: List[str] = []
    buf: List[str] = []
    depth = 0
    quote: Optional[str] = None
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if quote is not None:
            buf.append(ch)
            if ch == "\\" and i + 1 < n:
                buf.append(text[i + 1])
                i += 2
                continue
            if ch == quote:
                quote = None
            i += 1
            continue
        if ch in "'\"":
            quote = ch
            buf.append(ch)
        elif ch in "{[(":
            depth += 1
            buf.append(ch)
        elif ch in "}])":
            depth -= 1
            buf.append(ch)
        elif ch == ";" and depth == 0:
            out.append("".join(buf))
            buf = []
        elif ch == "\n" and depth == 0 and _complete_statement("".join(buf)):
            out.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
        i += 1
    if buf:
        out.append("".join(buf))
    return [s.strip() for s in out if s.strip()]


def _split_if(raw: str) -> Tuple[str, str]:
    """``IF (<cond>) { <body> }`` → (cond_text, body_text). Raises
    ScriptError on malformed shapes (shared by runner + authorizer)."""
    open_paren = raw.find("(")
    if open_paren < 0:
        raise ScriptError(f"malformed IF: {raw!r}")
    depth = 0
    close = -1
    quote: Optional[str] = None
    for i in range(open_paren, len(raw)):
        ch = raw[i]
        if quote is not None:
            if ch == quote:
                quote = None
            continue
        if ch in "'\"":
            quote = ch
        elif ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                close = i
                break
    if close < 0:
        raise ScriptError(f"unbalanced IF condition: {raw!r}")
    body = raw[close + 1 :].strip()
    if not (body.startswith("{") and body.endswith("}")):
        raise ScriptError("IF body must be a { … } block")
    return raw[open_paren + 1 : close], body[1:-1]


def _expr_permissions(expr_text: str) -> set:
    """Permissions an EXPRESSION needs: expressions read data only via
    embedded subqueries (SELECT/MATCH/TRAVERSE), so their presence
    requires the read grant; pure arithmetic needs nothing. Keyword
    scan is deliberately conservative — a string literal containing
    'select' over-requires, never under-requires."""
    from orientdb_tpu.models.security import READ, RES_RECORD

    t = expr_text.lower()
    if "select" in t or "match" in t or "traverse" in t:
        return {(RES_RECORD, READ)}
    return set()


def script_permissions(text: str) -> set:
    """Every (resource, op) pair the script needs, for callers that
    authorize before executing ([E] the per-command checks the server
    applies to single statements): walks top-level statements, LET
    right-hand sides, IF conditions AND bodies, and RETURN expressions
    recursively — a subquery anywhere still needs the read grant."""
    from orientdb_tpu.models.security import classify_sql
    from orientdb_tpu.sql.parser import parse

    needed: set = set()
    for raw in split_script(text):
        head = raw.split(None, 1)[0].upper() if raw.split() else ""
        if head == "LET":
            eq = raw.find("=")
            if eq > 0:
                rhs = raw[eq + 1 :].strip()
                try:
                    parse(rhs)
                    needed.add(classify_sql(rhs))
                except Exception:
                    # expression RHS: subqueries inside still read
                    needed |= _expr_permissions(rhs)
        elif head == "IF":
            try:
                cond, body = _split_if(raw)
            except ScriptError:
                continue  # the runner raises the real error
            needed |= _expr_permissions(cond)
            needed |= script_permissions(body)
        elif head == "RETURN":
            needed |= _expr_permissions(raw[6:])
        elif head in ("SLEEP", ""):
            continue
        else:
            needed.add(classify_sql(raw))
    return needed


def _parse_expr_via_select(expr_text: str):
    """The parser has no public expression entry point; wrap the text
    as a single-projection SELECT (the StoredFunction trick)."""
    from orientdb_tpu.sql.parser import parse

    sel = parse(f"SELECT {expr_text} AS __v")
    return sel.projections[0].expr


def _let_value(rows: List[Result]):
    """LET binding shape: a statement's full row list; a 1-row
    single-projection result collapses to the scalar (so
    ``LET $n = SELECT count(*) as c FROM V`` then ``IF ($n.c > 0)``
    and plain ``$n`` both behave)."""
    if len(rows) == 1 and not rows[0].is_element:
        props = rows[0].to_dict()
        if len(props) == 1:
            return next(iter(props.values()))
    return [r.element if r.is_element else r.to_dict() for r in rows]


class _ScriptRunner:
    def __init__(self, db, params: Optional[Dict]) -> None:
        self.db = db
        self.params = params or {}
        self.ctx = EvalContext(db, params=self.params)

    def run(self, text: str) -> List[Result]:
        done, rows = self._run_block(split_script(text))
        return rows

    # -- execution -----------------------------------------------------------

    def _run_block(self, statements: List[str]) -> Tuple[bool, List[Result]]:
        """Returns (returned, rows): ``returned`` True when a RETURN
        ended the script (propagates out of nested IF blocks)."""
        from orientdb_tpu.exec.oracle import execute_statement
        from orientdb_tpu.sql.parser import parse

        last: List[Result] = []
        for raw in statements:
            head = raw.split(None, 1)[0].upper() if raw.split() else ""
            if head == "LET":
                self._let(raw)
            elif head == "IF":
                done, rows = self._if(raw)
                if done:
                    return True, rows
            elif head == "RETURN":
                return True, self._return(raw)
            elif head == "SLEEP":
                ms = int(raw.split(None, 1)[1])
                time.sleep(ms / 1000.0)
            else:
                last = execute_statement(
                    self.db, parse(raw), self.params, parent_ctx=self.ctx
                )
        return False, last

    def _let(self, raw: str) -> None:
        body = raw[3:].strip()
        eq = body.find("=")
        if eq < 0:
            raise ScriptError(f"malformed LET: {raw!r}")
        name = body[:eq].strip()
        if name.startswith("$"):
            name = name[1:]
        rhs = body[eq + 1 :].strip()
        from orientdb_tpu.exec.oracle import execute_statement
        from orientdb_tpu.sql.parser import ParseError, parse

        try:
            stmt = parse(rhs)
            rows = execute_statement(
                self.db, stmt, self.params, parent_ctx=self.ctx
            )
            self.ctx.variables[name] = _let_value(rows)
        except ParseError:
            # expression RHS: LET $x = $y.size() + 1
            expr = _parse_expr_via_select(rhs)
            self.ctx.variables[name] = evaluate(self.ctx, expr)

    def _if(self, raw: str) -> Tuple[bool, List[Result]]:
        # IF (<expr>) { <statements> }
        cond_text, body = _split_if(raw)
        cond = evaluate(self.ctx, _parse_expr_via_select(cond_text))
        if not truthy(cond):
            return False, []
        return self._run_block(split_script(body))

    def _return(self, raw: str) -> List[Result]:
        rest = raw[6:].strip()
        if not rest:
            return []
        if rest.startswith("$"):
            val = self.ctx.variables.get(rest[1:])
            if isinstance(val, list):
                return [
                    r
                    if isinstance(r, Result)
                    else Result(
                        props=r if isinstance(r, dict) else {"value": r}
                    )
                    if not hasattr(r, "rid")
                    else Result(element=r)
                    for r in val
                ]
            return [Result(props={"value": val})]
        val = evaluate(self.ctx, _parse_expr_via_select(rest))
        return [Result(props={"value": val})]


def execute_script(db, text: str, params: Optional[Dict] = None) -> List[Result]:
    """Run a SQL batch script; returns the RETURN value's rows, or the
    last statement's rows ([E] ODatabaseSession.execute contract)."""
    return _ScriptRunner(db, params).run(text)
