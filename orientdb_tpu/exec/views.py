"""Materialized continuous MATCH views — CDC-exact result caching.

The epoch-keyed command cache (``exec/command_cache.py``) dies on EVERY
write: any committed mutation moves ``mutation_epoch`` and all entries
stop matching, however unrelated. This plane keeps hot query results
alive across writes by invalidating **CDC-exactly**:

- **admission by heat**: a query becomes a view candidate once the PR-4
  stats table has recorded ``config.view_min_calls`` calls for its
  fingerprint — the same normalized-SQL id the coalesce lanes key on,
  so the hottest lanes earn resident results first.
- **class-footprint invalidation**: each view remembers the classes its
  MATCH pattern can read (vertex classes + edge classes,
  subclass-closed; a bare ``{as:x}`` target widens the footprint to
  any VERTEX class). A callback-mode CDC consumer
  (``cdc/feed.py``) checks every committed event against each view's
  footprint — an insert into ``SimAudit`` leaves a ``Person`` view
  serving at cache speed; only events that could change the result kill
  it. An event with no class attribution conservatively kills
  everything.
- **incremental count maintenance**: views of single-node lone-COUNT
  shape (``MATCH {class:C, where:(...)} RETURN count(*)``) do not die
  on a matching insert/delete — the count adjusts by ±1 from the event
  itself (``cdc.feed.event_matches`` evaluates the view's WHERE against
  the event record), the delta sibling of the snapshot maintainer's
  scatter patches. Updates and preimage-less deletes invalidate
  (conservative: the old value is unknown).

Rows are shared between hits (the command-cache convention: results are
read-only by convention). The plane is bounded per database by
``config.view_cache_size`` (LRU) and disabled at 0.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Set, Tuple

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("views")


class _View:
    __slots__ = (
        "key",
        "rows",
        "engine",
        "classes",
        "vertex_wildcard",
        "count_shape",
        "count_name",
        "count_classes",
        "where",
        "valid",
        "hits",
        "refreshes",
    )

    def __init__(self, key, rows, engine, classes, vertex_wildcard) -> None:
        self.key = key
        self.rows = rows
        self.engine = engine
        #: lowered class names the statement can read
        self.classes: Set[str] = classes
        #: True when the pattern binds a BARE target (`{as:q}` with no
        #: class): any VERTEX event can change the result (reached
        #: vertices are unconstrained, and a vertex delete cascades
        #: edge removals that produce no per-edge events) — but plain
        #: DOCUMENT writes still cannot, which is the workload's noise
        self.vertex_wildcard = vertex_wildcard
        #: single-node lone-COUNT shape: maintained incrementally
        self.count_shape = False
        self.count_name: Optional[str] = None
        self.count_classes: Optional[List[str]] = None
        self.where = None
        self.valid = True
        self.hits = 0
        self.refreshes = 0


def _local_expr(e) -> bool:
    """True when the expression reads ONLY the current record's own
    values (plus literals/parameters/context vars). Graph functions
    (``out()``/``in()``/``both()``…), method chains, and field
    dereference all reach records OUTSIDE the node's class — a write
    to those would never intersect the view's footprint, so a filter
    using them would serve stale results forever. Conservative by
    design: an unrecognized node shape refuses."""
    from orientdb_tpu.sql import ast as A

    if e is None:
        return True
    if isinstance(
        e, (A.Literal, A.Parameter, A.ContextVar, A.RIDLiteral, A.Identifier)
    ):
        return True
    if isinstance(e, A.Unary):
        return _local_expr(e.expr)
    if isinstance(e, A.Binary):
        return _local_expr(e.left) and _local_expr(e.right)
    if isinstance(e, A.Between):
        return all(_local_expr(x) for x in (e.expr, e.low, e.high))
    if isinstance(e, (A.IsNull, A.IsDefined)):
        return _local_expr(e.expr)
    if isinstance(e, A.ListExpr):
        return all(_local_expr(x) for x in e.items)
    return False  # FieldAccess / FunctionCall / MethodCall / IndexAccess…


def _local_filter(f) -> bool:
    return f is None or (
        _local_expr(f.where) and _local_expr(f.while_cond)
    )


def _statement_classes(db, stmt):
    """``(lowered class names, vertex_wildcard)`` describing what the
    statement can read — the event check intersects the record class's
    superclass closure with the names, so storing the named classes
    suffices. ``(None, False)`` = cannot bound the footprint (no
    admission). A classless node makes the footprint vertex-wildcard:
    any vertex event invalidates, document events never do. Node/edge
    filters must be LOCAL (``_local_expr``): a WHERE hopping through
    ``out('X')`` reads class X without naming it in the pattern."""
    from orientdb_tpu.sql import ast as A

    names: Set[str] = set()
    wildcard = False
    try:
        if not isinstance(stmt, A.MatchStatement):
            return None, False
        for path in stmt.paths:
            if not _local_filter(path.first):
                return None, False
            if path.first.class_name is None:
                wildcard = True
            else:
                names.add(path.first.class_name.lower())
            for it in path.items:
                if not it.edge_classes:
                    return None, False  # any-edge-class hop: unbounded
                if not (
                    _local_filter(it.target)
                    and _local_filter(it.edge_filter)
                ):
                    return None, False
                names.update(c.lower() for c in it.edge_classes)
                if it.target.class_name is not None:
                    names.add(it.target.class_name.lower())
                else:
                    wildcard = True
        if not names and not wildcard:
            return None, False
        return names, wildcard
    except Exception:
        return None, False


#: aggregate / pure functions a view's RETURN may call; anything else
#: (sequence(), date(), uuid(), format()...) may be impure or
#: time-dependent — serving it from cache would change semantics
_PURE_FUNCTIONS = frozenset({"count", "sum", "min", "max", "avg"})


def _safe_projections(stmt) -> bool:
    """True when every RETURN expression is a plain field access /
    identifier or a pure aggregate — the shapes a cached result can
    answer without re-evaluating anything impure."""
    from orientdb_tpu.sql import ast as A

    def safe(e) -> bool:
        if isinstance(e, (A.Identifier, A.Star)):
            return True
        if isinstance(e, A.FieldAccess):
            return isinstance(e.base, A.Identifier)
        if isinstance(e, A.FunctionCall):
            return e.name.lower() in _PURE_FUNCTIONS and all(
                safe(a) for a in e.args
            )
        return False

    try:
        return all(safe(p.expr) for p in stmt.returns)
    except Exception:
        return False


class ViewManager:
    """Per-database registry of materialized views."""

    def __init__(self, db) -> None:
        self.db = db
        self._lock = threading.Lock()
        self._map: "OrderedDict[Tuple, _View]" = OrderedDict()
        self._consumer_token: Optional[int] = None
        # registration-only mutex: never taken by _on_event, so holding
        # it across feed.register can't deadlock against the feed
        # delivering on another thread
        self._consumer_mu = threading.Lock()

    # -- CDC wiring ---------------------------------------------------------

    def _ensure_consumer(self) -> None:
        if self._consumer_token is not None:
            return
        from orientdb_tpu.cdc.feed import feed_of

        with self._consumer_mu:
            if self._consumer_token is not None:
                # lost the registration race: one consumer is enough
                # (two would deliver every event twice, and a count-
                # shape view would adjust by ±2 per matching write)
                return
            feed = feed_of(self.db, create=True)
            c = feed.register(callback=self._on_event)
            self._consumer_token = c.token

    def _on_event(self, ev: Dict) -> None:
        """Inline from the write path: MUST stay cheap. Footprint check
        per view + flag flips; the count adjustment is host arithmetic."""
        op = ev.get("op")
        if op not in ("create", "update", "delete"):
            return
        cname = ev.get("class")
        cls = (
            self.db.schema.get_class(cname) if cname is not None else None
        )
        closure = None
        if cls is not None:
            # the record's class plus every superclass, lowered: a view
            # footprinting any of them is affected (case-insensitive —
            # query text and schema may disagree on case)
            closure = {cls.name.lower()} | {
                s.lower() for s in cls.all_superclass_names()
            }
        with self._lock:
            views = list(self._map.values())
        for v in views:
            if not v.valid:
                continue
            if closure is None:
                self._invalidate(v)  # classless event: assume the worst
                continue
            affected = bool(closure & v.classes) or (
                v.vertex_wildcard
                and cls is not None
                and cls.is_vertex_type
            )
            if not affected:
                continue  # the CDC-exact win: unrelated write, view lives
            if v.count_shape and op in ("create", "delete"):
                self._adjust_count(v, ev, op)
            else:
                self._invalidate(v)

    def _adjust_count(self, v: _View, ev: Dict, op: str) -> None:
        """±1 maintenance for single-node COUNT views; falls back to
        invalidation when the event cannot be judged (no preimage)."""
        from orientdb_tpu.cdc.feed import event_matches

        if op == "delete" and not ev.get("record"):
            self._invalidate(v)  # preimage unknown: cannot judge
            return
        try:
            hit = event_matches(
                self.db,
                {**ev, "op": "create"},  # judge the record against WHERE
                classes=v.count_classes,
                where=v.where,
            )
        except Exception:
            self._invalidate(v)
            return
        if not hit:
            return
        delta = 1 if op == "create" else -1
        rows = v.rows
        try:
            from orientdb_tpu.exec.result import Result

            row = rows[0]
            cur = (
                row.get(v.count_name)
                if isinstance(row, dict)
                else row.get_property(v.count_name)
            )
            if len(rows) != 1 or cur is None:
                raise ValueError("not a count row")
            # REPLACE the row (never mutate: hits share row objects)
            v.rows = [Result(props={v.count_name: max(0, int(cur) + delta)})]
            v.refreshes += 1
            metrics.incr("views.incremental")
        except Exception:
            self._invalidate(v)

    def _invalidate(self, v: _View) -> None:
        if v.valid:
            v.valid = False
            metrics.incr("views.invalidated")

    def invalidate_all(self, reason: str = "") -> None:
        """Kill every view: schema mutations that bypass the CDC stream
        (class rename/drop rewrite records in place) leave the class
        footprints keyed by names that no longer exist — no future
        event would ever match them. Called under ``db._lock`` (same
        order as the CDC callback path: db._lock → our lock)."""
        with self._lock:
            views = list(self._map.values())
            self._map.clear()
        for v in views:
            self._invalidate(v)
        if views:
            log.info("all %d views invalidated: %s", len(views), reason)

    # -- serving ------------------------------------------------------------

    @staticmethod
    def _key(sql: str, params, engine, strict) -> Optional[Tuple]:
        try:
            pk = (
                tuple(sorted((str(k), repr(v)) for k, v in params.items()))
                if params
                else ()
            )
        except Exception:
            return None
        return (sql, pk, engine or "", bool(strict))

    def lookup(self, sql: str, params, engine, strict) -> Optional[_View]:
        key = self._key(sql, params, engine, strict)
        if key is None:
            return None
        with self._lock:
            v = self._map.get(key)
            if v is None:
                return None
            if not v.valid:
                self._map.pop(key, None)
                metrics.incr("views.refresh_needed")
                return None
            self._map.move_to_end(key)
            v.hits += 1
        metrics.incr("views.hit")
        from orientdb_tpu.obs.stats import note_result_cache_hit

        note_result_cache_hit()
        return v

    def observe(
        self, sql: str, params, engine, strict, rows, used, epoch=None
    ) -> None:
        """Post-execution admission: materialize the result once the
        fingerprint is hot enough. ``engine`` is the REQUESTED engine
        (the lookup key); ``used`` is the engine that actually served
        (the label a hit reports). ``epoch`` is ``db.mutation_epoch``
        captured BEFORE the query ran: a write committing between the
        run and this admission fires its CDC callback before the view
        is registered, so nothing would ever invalidate the pre-write
        rows — the epoch re-check under the lock closes that window
        (writes bump the epoch before their hooks fire, both under
        ``db._lock``)."""
        cap = config.view_cache_size
        if cap <= 0:
            return
        key = self._key(sql, params, engine, strict)
        if key is None:
            return
        from orientdb_tpu.obs.stats import fingerprint_cached, stats

        try:
            from orientdb_tpu.exec.engine import parse_cached
            from orientdb_tpu.sql import ast as A

            stmt = parse_cached(sql)
        except Exception:
            return
        # MATCH only (the plane's name is literal): SELECT projections
        # can hide side effects (sequence('s').next()) and TRAVERSE
        # footprints are unbounded — neither may be served from cache
        if not isinstance(stmt, A.MatchStatement):
            return
        if not _safe_projections(stmt):
            return
        fid = fingerprint_cached(sql).fid
        if stats.calls_of(fid) < max(1, config.view_min_calls):
            return
        classes, wildcard = _statement_classes(self.db, stmt)
        if classes is None:
            return  # unbounded footprint: every write would kill it
        v = _View(key, rows, used, classes, wildcard)
        self._mark_count_shape(v, stmt, params)
        self._ensure_consumer()
        with self._lock:
            if epoch is not None and self.db.mutation_epoch != epoch:
                metrics.incr("views.admission_raced")
                return
            while len(self._map) >= cap:
                self._map.popitem(last=False)
            self._map[key] = v
        metrics.incr("views.materialized")

    def _mark_count_shape(self, v: _View, stmt, params) -> None:
        """Single-node lone-COUNT MATCH with a literal WHERE → eligible
        for ±1 incremental maintenance."""
        from orientdb_tpu.sql import ast as A

        if params:
            return  # parameterized WHEREs re-derive per value: skip
        if not isinstance(stmt, A.MatchStatement):
            return
        if (
            stmt.group_by
            or stmt.order_by
            or stmt.skip
            or stmt.limit
            or stmt.unwind
        ):
            return
        if len(stmt.paths) != 1 or stmt.paths[0].items:
            return
        node = stmt.paths[0].first
        if node.class_name is None or node.optional or node.rid is not None:
            return
        r = stmt.returns
        if not (
            len(r) == 1
            and isinstance(r[0].expr, A.FunctionCall)
            and r[0].expr.name.lower() == "count"
            and len(r[0].expr.args) == 1
            and isinstance(r[0].expr.args[0], A.Star)
        ):
            return
        from orientdb_tpu.exec.oracle import expr_name

        # resolve to the SCHEMA's casing: event_matches compares via
        # is_subclass_of, which is case-sensitive
        cls = self.db.schema.get_class(node.class_name)
        v.count_shape = True
        v.count_name = r[0].alias or expr_name(r[0].expr, 0)
        v.count_classes = [cls.name if cls is not None else node.class_name]
        v.where = node.where

    # -- observability ------------------------------------------------------

    def stats(self) -> Dict:
        with self._lock:
            views = list(self._map.values())
        return {
            "views": len(views),
            "valid": sum(1 for v in views if v.valid),
            "incremental": sum(1 for v in views if v.count_shape),
            "hits": sum(v.hits for v in views),
        }

    def close(self) -> None:
        if self._consumer_token is not None:
            from orientdb_tpu.cdc.feed import feed_of

            feed = feed_of(self.db, create=False)
            if feed is not None:
                feed.unregister(self._consumer_token)
            self._consumer_token = None
        with self._lock:
            self._map.clear()


_VM_CREATE_MU = threading.Lock()


def views_for(db) -> Optional[ViewManager]:
    """The database's view manager, created on first use; None when the
    plane is disabled (``view_cache_size`` = 0)."""
    if config.view_cache_size <= 0:
        return None
    vm = getattr(db, "_view_manager", None)
    if vm is None:
        with _VM_CREATE_MU:
            vm = getattr(db, "_view_manager", None)
            if vm is None:
                vm = db._view_manager = ViewManager(db)
    return vm
