"""Query result (command) cache.

Analog of the reference's command cache ([E] OCommandCache /
OCommandCacheSoftRefs: caches idempotent query result sets per database,
invalidated on writes; DISABLED by default upstream and here —
``config.command_cache_enabled``). Redesign: instead of per-cluster
invalidation bookkeeping, entries are stamped with the database's
mutation epoch — any write moves the epoch, so stale entries simply stop
matching and age out of the LRU. Rows are shared between hits (results
are read-only by convention; mutating a cached Result would be visible
to later hits, same trade the reference documents)."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.metrics import metrics


class CommandCache:
    """Per-database LRU of (sql, params, engine, strict) → (rows, engine,
    epoch); thread-safe (server request threads share one database)."""

    def __init__(self, max_entries: Optional[int] = None) -> None:
        self.max_entries = max_entries or config.command_cache_size
        self._map: "OrderedDict[Tuple, Tuple]" = OrderedDict()
        self._lock = threading.Lock()

    @staticmethod
    def key(
        sql: str, params, engine: Optional[str], strict: bool = False
    ) -> Optional[Tuple]:
        try:
            pk = (
                tuple(sorted((str(k), repr(v)) for k, v in params.items()))
                if params
                else ()
            )
        except Exception:
            return None  # unhashable/odd params: skip caching
        return (sql, pk, engine or "", bool(strict))

    def get(self, key: Tuple, epoch: int):
        with self._lock:
            hit = self._map.get(key)
            if hit is None:
                metrics.incr("command_cache.miss")
                return None
            rows, used, at_epoch = hit
            if at_epoch != epoch:
                # a write moved the epoch: the entry is stale — drop it
                self._map.pop(key, None)
                metrics.incr("command_cache.invalidated")
                return None
            self._map.move_to_end(key)
        metrics.incr("command_cache.hit")
        # per-fingerprint accounting (obs/stats): a cached execution
        # still counts as a call; this marks it served without running
        from orientdb_tpu.obs.stats import note_result_cache_hit

        note_result_cache_hit()
        return rows, used

    def put(self, key: Tuple, rows: List, used: str, epoch: int) -> None:
        with self._lock:
            while len(self._map) >= self.max_entries:
                self._map.popitem(last=False)
            self._map[key] = (rows, used, epoch)

    def clear(self) -> None:
        with self._lock:
            self._map.clear()

    def __len__(self) -> int:
        return len(self._map)


def cache_for(db) -> Optional[CommandCache]:
    """The database's command cache, or None when the feature is off."""
    if not config.command_cache_enabled:
        return None
    cache = getattr(db, "_command_cache", None)
    if cache is None:
        cache = db._command_cache = CommandCache()
    return cache
