"""Optimistic MVCC transactions.

Analog of [E] OTransactionOptimistic (SURVEY.md §3.4): changes buffer in a
tx-local workspace; ``commit()`` takes the storage lock once, re-checks
every touched record's version against the store (MVCC), then applies
creates → edges → updates → deletes. A version mismatch raises
``ConcurrentModificationError`` before any mutation (the reference's
OConcurrentModificationException), and a mid-apply failure (e.g. a unique
index violation) triggers compensating rollback of already-applied ops so
the store never holds a half-committed transaction.

Tx-local visibility: ``load``/``browse_class``/queries inside the tx see
tx-created records, tx-updated field values, and hide tx-deleted records
(read-your-writes). New records carry temporary RIDs ``#-1:-N`` (the
reference's negative temp RIDs) remapped to real RIDs at commit.
Divergence from the reference, documented: adjacency bags of *existing*
vertices do not show uncommitted edges until commit.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.exec.result import Result
from orientdb_tpu.models.record import Direction, Document, Edge, Vertex
from orientdb_tpu.models.rid import NEW_RID, RID
from orientdb_tpu.sql import ast as A
from orientdb_tpu.utils.logging import get_logger

log = get_logger("tx")


class TxError(Exception):
    pass


def _clone(doc: Document) -> Document:
    """Tx-local copy: same identity/version, independent fields/bags."""
    from orientdb_tpu.models.record import Blob

    if isinstance(doc, Blob):
        # Blob.__init__ takes only the payload; from_fields keeps any
        # metadata fields riding alongside `data`
        c: Document = Blob.from_fields(dict(doc.fields()))
        c.rid = doc.rid
        c.version = doc.version
        c._db = doc._db
        return c
    c = type(doc)(doc.class_name, dict(doc.fields()))
    c.rid = doc.rid
    c.version = doc.version
    c._db = doc._db
    if isinstance(doc, Vertex) and isinstance(c, Vertex):
        c._out_edges = {k: list(v) for k, v in doc._out_edges.items()}
        c._in_edges = {k: list(v) for k, v in doc._in_edges.items()}
    if isinstance(doc, Edge) and isinstance(c, Edge):
        c.out_rid = doc.out_rid
        c.in_rid = doc.in_rid
    return c


class Transaction:
    """One optimistic transaction bound to a Database session."""

    def __init__(self, db) -> None:
        self.db = db
        self._temp_seq = itertools.count(2)
        #: rid → tx-local doc (updates and tx-loaded copies)
        self.workspace: Dict[RID, Document] = {}
        #: rids written through the tx → base version for the MVCC check
        self.dirty: Dict[RID, int] = {}
        #: pre-images for store-shared objects mutated in place
        self._preimages: Dict[RID, Tuple[Dict, int]] = {}
        self.created: List[Document] = []  # temp-RID docs in creation order
        self.deleted: Dict[RID, Document] = {}
        #: (edge_doc, src_rid, dst_rid) — rids may be temporary
        self.edge_ops: List[Tuple[Edge, RID, RID]] = []
        #: cross-owner sub-batches (parallel/twophase 2PC): MEMBER
        #: identity (forwarding.member_key) → {"owner", "ops",
        #: "created" {temp: (doc, op)}, "updated" {ridstr: doc}} — ops
        #: for classes OTHER members own buffer here and 2-phase-commit
        #: at their owners. Keyed by member, not WriteOwner object id:
        #: assign_class_owner mints one route object per class, and two
        #: sub-batches of one txid landing at the SAME member collided
        #: in TwoPhaseRegistry.prepare ("already prepared here")
        self._foreign: Dict[str, Dict] = {}
        self._foreign_deleted: set = set()
        self.active = True

    # -- tx-local operations ------------------------------------------------

    def _temp_rid(self) -> RID:
        return RID(-1, -next(self._temp_seq))

    def _foreign_batch(self, class_name: str):
        """The cross-owner sub-batch this class's ops buffer into, or
        None when this member owns the class (the op commits locally).
        A transaction spanning both commits via 2PC at commit time
        ([E] the reference's distributed tx, SURVEY.md:126)."""
        owner = self.db._owner_for(class_name)
        if owner is None:
            return None
        from orientdb_tpu.parallel.forwarding import member_key

        key = member_key(owner)
        batch = self._foreign.get(key)
        if batch is None:
            batch = self._foreign[key] = {
                "owner": owner,
                "ops": [],
                "created": {},
                "updated": {},
            }
        return batch

    @staticmethod
    def _enc_fields(doc: Document) -> Dict:
        from orientdb_tpu.storage.durability import _enc_fields

        return _enc_fields(doc)

    def _foreign_save(self, batch, doc: Document) -> Document:
        from orientdb_tpu.models.record import Blob

        if not doc.rid.is_persistent and str(doc.rid) not in batch["created"]:
            doc.rid = self._temp_rid()
            doc.version = 0
            doc._db = self.db
            op = {
                "kind": "create",
                "type": "vertex"
                if isinstance(doc, Vertex)
                else "blob" if isinstance(doc, Blob) else "document",
                "class": doc.class_name,
                "temp": str(doc.rid),
                "fields": self._enc_fields(doc),
            }
            batch["ops"].append(op)
            batch["created"][str(doc.rid)] = (doc, op)
            self.workspace[doc.rid] = doc
            return doc
        key = str(doc.rid)
        if key in batch["created"]:
            batch["created"][key][1]["fields"] = self._enc_fields(doc)
            return doc
        if key in batch["updated"]:
            for o in batch["ops"]:
                if o.get("kind") == "update" and o["rid"] == key:
                    o["fields"] = self._enc_fields(doc)
                    break
            batch["updated"][key] = doc
            return doc
        batch["ops"].append(
            {
                "kind": "update",
                "rid": key,
                # the MVCC base is the version this tx READ: for a shared
                # store object mutated in place that is the touch()-time
                # preimage version — a replication apply bumping the
                # object between read and save must conflict at the
                # owner, not silently win (ADVICE r5; mirrors
                # ForwardedTransaction.save)
                "base_version": self._preimages.get(
                    doc.rid, (None, doc.version)
                )[1],
                "fields": self._enc_fields(doc),
            }
        )
        batch["updated"][key] = doc
        self.workspace[doc.rid] = doc
        return doc

    def save(self, doc: Document) -> Document:
        fb = self._foreign_batch(doc.class_name)
        if fb is not None:
            if doc.rid in self.deleted or doc.rid in self._foreign_deleted:
                raise TxError(f"{doc.rid} deleted in this transaction")
            return self._foreign_save(fb, doc)
        if doc.rid in self.deleted:
            raise TxError(f"{doc.rid} deleted in this transaction")
        if not doc.rid.is_persistent:
            if doc.rid not in self.workspace:
                cls = self.db.schema.get_class(doc.class_name)
                if cls is None:
                    cls = self.db.schema.create_class(doc.class_name)
                cls.validate(doc.fields())
                doc.rid = self._temp_rid()
                doc.version = 0
                doc._db = self.db
                self.created.append(doc)
                self.workspace[doc.rid] = doc
            # already temp-registered: fields live on the doc itself
            return doc
        if doc.rid not in self.dirty:
            stored = self.db._load_raw(doc.rid)
            if stored is None:
                raise TxError(f"{doc.rid} not found")
            # base = the version THIS tx read (clone keeps it from load
            # time); using the store's current version here would silently
            # swallow concurrent commits between tx.load and tx.save
            self.dirty[doc.rid] = doc.version
            if stored is doc and doc.rid not in self._preimages:
                # mutating the shared store object in place: capture the
                # pre-image so rollback can restore it (touch() may already
                # have captured it BEFORE the first field mutation)
                self._preimages[doc.rid] = (dict(stored.fields()), stored.version)
        self.workspace[doc.rid] = doc
        return doc

    def touch(self, doc: Document) -> None:
        """Capture a pre-image for a shared store object about to be
        mutated in place (called from Document.set before the write)."""
        rid = doc.rid
        if rid in self._preimages or rid in self.deleted:
            return
        stored = self.db._load_raw(rid)
        if stored is doc:
            self._preimages[rid] = (dict(stored.fields()), stored.version)

    def load(self, rid: RID) -> Optional[Document]:
        if rid in self.deleted or rid in self._foreign_deleted:
            return None
        hit = self.workspace.get(rid)
        if hit is not None:
            return hit
        stored = self.db._load_raw(rid)
        if stored is None:
            return None
        copy = _clone(stored)
        self.workspace[rid] = copy
        return copy

    def delete(self, doc: Document) -> None:
        rid = doc.rid
        fb = self._foreign_batch(doc.class_name)
        if fb is not None:
            key = str(rid)
            if key in fb["created"]:
                # deleting an uncommitted foreign record: drop its op
                _d, op = fb["created"].pop(key)
                fb["ops"] = [o for o in fb["ops"] if o is not op]
                self.workspace.pop(rid, None)
                return
            # the delete ships the version this tx read so the owner's
            # execute_tx_ops MVCC-checks it — matching the local
            # _commit_locked path (ADVICE r5)
            fb["ops"].append(
                {
                    "kind": "delete",
                    "rid": key,
                    "base_version": self._preimages.get(
                        rid, (None, doc.version)
                    )[1],
                }
            )
            self._foreign_deleted.add(rid)
            self.workspace.pop(rid, None)
            return
        if not rid.is_persistent:
            # deleting an uncommitted record: drop it from the tx, and (for
            # a vertex) cascade-drop uncommitted edges touching it — the
            # tx-buffered mirror of the store's cascade delete
            self.created = [d for d in self.created if d.rid != rid]
            self.edge_ops = [
                op
                for op in self.edge_ops
                if op[0].rid != rid and op[1] != rid and op[2] != rid
            ]
            self.workspace.pop(rid, None)
            return
        stored = self.db._load_raw(rid)
        if stored is None:
            return
        self.dirty.setdefault(rid, stored.version)
        self.deleted[rid] = stored
        self.workspace.pop(rid, None)

    def new_edge(self, class_name: str, src: Vertex, dst: Vertex, **fields) -> Edge:
        fb = self._foreign_batch(class_name)
        if fb is not None:
            e = Edge(class_name, fields)
            e._db = self.db
            e.rid = self._temp_rid()
            e.out_rid = src.rid
            e.in_rid = dst.rid
            op = {
                "kind": "edge",
                "class": class_name,
                "temp": str(e.rid),
                "from": str(src.rid),
                "to": str(dst.rid),
                "fields": self._enc_fields(e),
            }
            fb["ops"].append(op)
            fb["created"][str(e.rid)] = (e, op)
            self.workspace[e.rid] = e
            return e
        cls = self.db.schema.get_class(class_name)
        if cls is None:
            cls = self.db.schema.create_edge_class(class_name)
        if not cls.is_edge_type:
            raise ValueError(f"class '{class_name}' is not an edge class")
        e = Edge(cls.name, fields)
        e._db = self.db
        e.rid = self._temp_rid()
        e.out_rid = src.rid
        e.in_rid = dst.rid
        self.workspace[e.rid] = e
        self.edge_ops.append((e, src.rid, dst.rid))
        return e

    # -- visibility ----------------------------------------------------------

    def browse_extra(self, class_name: str, polymorphic: bool):
        """Tx-created docs visible to scans (read-your-writes)."""
        def _member(doc):
            cls = self.db.schema.get_class(doc.class_name)
            if cls is None:
                # foreign-owned class unknown locally (the owner creates
                # it at 2PC commit): exact name match only
                return doc.class_name.lower() == class_name.lower()
            if cls.name.lower() == class_name.lower():
                return True
            return polymorphic and cls.is_subclass_of(class_name)

        for doc in self.created:
            if _member(doc):
                yield doc
        for e, _s, _d in self.edge_ops:
            if _member(e):
                yield e
        for batch in self._foreign.values():
            for doc, _op in batch["created"].values():
                if _member(doc):
                    yield doc

    def overlay(self, doc: Document) -> Optional[Document]:
        """Committed doc → tx view (updated copy, or None if tx-deleted)."""
        if doc.rid in self.deleted or doc.rid in self._foreign_deleted:
            return None
        return self.workspace.get(doc.rid, doc)

    # -- terminal operations -------------------------------------------------

    def commit(self) -> Dict[RID, RID]:
        """Apply the tx atomically; returns the temp→real RID map."""
        if not self.active:
            raise TxError("transaction no longer active")
        db = self.db
        if getattr(db, "_write_owner", None) is not None:
            # a forwarding member still commits locally when every
            # locally-buffered op's class is one it OWNS (per-class
            # owner streams; twophase.execute_tx_ops drives this path) —
            # foreign classes were routed to 2PC sub-batches at buffer
            # time, so anything local here must resolve to None
            for doc in list(self.created) + [
                e for e, _s, _d in self.edge_ops
            ]:
                if db._owner_for(doc.class_name) is not None:
                    raise TxError(
                        f"class '{doc.class_name}' is owned by another "
                        "member; buffered locally by mistake"
                    )
        from orientdb_tpu.obs.trace import span

        if self._foreign:
            with span(
                "tx.commit",
                distributed=True,
                owners=len(self._foreign),
            ):
                return self._commit_distributed(db)
        try:
            # quorum pushes deferred during the locked apply (the
            # atomic tx entry) ship once the db-wide lock is free
            with span(
                "tx.commit",
                creates=len(self.created),
                edges=len(self.edge_ops),
                updates=len(self.dirty),
                deletes=len(self.deleted),
            ):
                with db._quorum_deferral():
                    with db._lock:
                        return self._commit_locked(db)
        except Exception:
            # a failed commit invalidates the tx (the reference rolls the
            # whole transaction back on OConcurrentModificationException /
            # ORecordDuplicatedException)
            self.rollback()
            raise

    def _commit_distributed(self, db) -> Dict[RID, RID]:
        """Cross-owner 2PC ([E] the reference's 2-phase distributed tx,
        SURVEY.md:126), driven by twophase.run_coordinator: the LOCAL
        write set participates via validate+lock at prepare and the
        ordinary ``_commit_locked`` at phase 2; each foreign sub-batch
        is a RemoteParticipant at its owner."""
        import uuid

        from orientdb_tpu.parallel import twophase as tp

        txid = uuid.uuid4().hex
        LOCAL = "local"
        local_creates = {str(d.rid) for d in self.created} | {
            str(e.rid) for e, _s, _d in self.edge_ops
        }
        local_refs = set()
        for _e, s, d in self.edge_ops:
            for r in (s, d):
                rs = str(r)
                if tp._is_temp(rs) and rs not in local_creates:
                    local_refs.add(rs)
        rows = [(LOCAL, local_creates, local_refs)]
        mapping: Dict[RID, RID] = {}
        outer = self

        class _LocalTx(tp.Participant):
            """The coordinator's own buffered ops as a participant."""

            def __init__(self) -> None:
                self.locked: List[RID] = []

            def prepare(self, txid: str) -> None:
                import time as _t

                from orientdb_tpu.chaos import fault
                from orientdb_tpu.obs.trace import span as _span

                deadline = _t.time() + tp.DEFAULT_TTL
                # same span names as TwoPhaseRegistry's: the assembled
                # trace shows every participant uniformly, local or not
                with _span(
                    "tx2pc.participant.prepare",
                    txid=txid,
                    ops=len(outer.dirty) + len(local_creates),
                ), fault.point("tx2pc.prepare"), db._lock:
                    for rid, base in outer.dirty.items():
                        db._check_2pc_lock(rid)
                        stored = db._load_raw(rid)
                        if rid in outer.deleted:
                            if (
                                stored is not None
                                and stored.version != base
                            ):
                                outer._fail_conflict(
                                    rid, stored.version, base
                                )
                        elif stored is None:
                            raise TxError(f"{rid} vanished before commit")
                        elif stored.version != base:
                            outer._fail_conflict(rid, stored.version, base)
                    for rid in set(outer.dirty) | set(outer.deleted):
                        db._tx2pc_locks[rid] = (txid, deadline)
                        self.locked.append(rid)

            def _unlock(self, txid: str) -> None:
                with db._lock:
                    for rid in self.locked:
                        held = db._tx2pc_locks.get(rid)
                        if held is not None and held[0] == txid:
                            del db._tx2pc_locks[rid]
                    self.locked = []

            def commit(self, txid: str, rid_map: Dict[str, str]) -> None:
                from orientdb_tpu.chaos import fault
                from orientdb_tpu.obs.trace import span as _span

                db._tx_local.tx2pc_commit = txid
                try:
                    with _span(
                        "tx2pc.participant.commit", txid=txid
                    ), fault.point("tx2pc.commit"):
                        outer._substitute_local_edges(db, rid_map)
                        with db._quorum_deferral():
                            with db._lock:
                                local_map = outer._commit_locked(db)
                finally:
                    db._tx_local.tx2pc_commit = None
                    self._unlock(txid)
                mapping.update(local_map)
                rid_map.update(
                    {str(k): str(v) for k, v in local_map.items()}
                )

            def abort(self, txid: str) -> None:
                self._unlock(txid)

        parts: Dict[object, tp.Participant] = {LOCAL: _LocalTx()}
        for key, batch in self._foreign.items():
            c, r = tp.batch_temp_sets(batch["ops"])
            rows.append((key, c, r))

            def _adopt(ops, results, batch=batch):
                for op, res in zip(ops, results):
                    if op["kind"] in ("create", "edge") and res:
                        doc, _ = batch["created"].get(
                            op["temp"], (None, None)
                        )
                        if doc is None:
                            continue
                        old = doc.rid
                        doc.rid = RID.parse(res["@rid"])
                        doc.version = res.get("@version", 1)
                        mapping[old] = doc.rid
                    elif op["kind"] == "update" and res:
                        d = batch["updated"].get(op["rid"])
                        if d is not None:
                            d.version = res.get("@version", d.version)

            parts[key] = tp.RemoteParticipant(
                batch["owner"], batch["ops"], _adopt
            )
        try:
            tp.run_coordinator(txid, parts, rows, coord_db=db)
        except tp.TxInDoubtError:
            # some participants applied: the tx is spent either way
            if self.active:
                self.active = False
                db._end_tx(self)
            raise
        except Exception:
            # clean abort: nothing applied anywhere
            self.rollback()
            raise
        if self.active:
            self.active = False
            db._end_tx(self)
        return mapping

    def _substitute_local_edges(self, db, rid_map_str: Dict[str, str]) -> None:
        """Rewrite local edge endpoints through rids other participants
        assigned; a record committed at another owner arrives HERE via
        async replication — poll briefly for it."""
        import time as _time

        if not rid_map_str:
            return
        deadline = _time.time() + 10.0
        new_ops: List[Tuple[Edge, RID, RID]] = []
        for e, s, d in self.edge_ops:
            for end in ("out", "in"):
                rid = s if end == "out" else d
                real = rid_map_str.get(str(rid))
                if real is not None:
                    r = RID.parse(real)
                    while (
                        db._load_raw(r) is None
                        and _time.time() < deadline
                    ):
                        _time.sleep(0.02)
                    if end == "out":
                        s = r
                        e.out_rid = r
                    else:
                        d = r
                        e.in_rid = r
            new_ops.append((e, s, d))
        self.edge_ops = new_ops

    def _commit_locked(self, db) -> Dict[RID, RID]:
            # phase 1: MVCC checks before any mutation (atomic fail-fast)
            for rid, base in self.dirty.items():
                db._check_2pc_lock(rid)
                stored = db._load_raw(rid)
                if rid in self.deleted:
                    if stored is not None and stored.version != base:
                        self._fail_conflict(rid, stored.version, base)
                    continue
                if stored is None:
                    raise TxError(f"{rid} vanished before commit")
                if stored.version != base:
                    self._fail_conflict(rid, stored.version, base)
            # phase 2: apply, with compensating rollback on failure.
            # AFTER hooks (and live-query delivery built on them) are
            # buffered for the duration of the apply and flushed only once
            # the whole commit has succeeded — a mid-apply failure discards
            # them, so subscribers never observe compensated-away ops (the
            # reference's post-commit-only OLiveQueryHookV2 delivery).
            applied: List[Tuple[str, object]] = []
            rid_map: Dict[RID, RID] = {}
            db._tx_suspended = True
            after_events: List = []
            db._tx_local.hook_buffer = after_events
            # WAL ops buffer during apply and flush as ONE atomic entry
            # only on success — compensation discards them, so the log
            # never shows a half-commit (the [E] tx-boundary WAL records)
            wal_ops: List = []
            db._tx_local.wal_buffer = wal_ops
            try:
                for doc in self.created:
                    temp = doc.rid
                    doc.rid = NEW_RID
                    db.save(doc)
                    rid_map[temp] = doc.rid
                    applied.append(("create", doc))
                for e, src_rid, dst_rid in self.edge_ops:
                    sr = rid_map.get(src_rid, src_rid)
                    dr = rid_map.get(dst_rid, dst_rid)
                    src = db._load_raw(sr)
                    dst = db._load_raw(dr)
                    if not isinstance(src, Vertex) or not isinstance(dst, Vertex):
                        raise TxError("edge endpoint is not a vertex")
                    real = db.new_edge(e.class_name, src, dst, **e.fields())
                    rid_map[e.rid] = real.rid
                    applied.append(("edge", real))
                for rid in list(self.dirty):
                    if rid in self.deleted:
                        continue
                    doc = self.workspace.get(rid)
                    stored = db._load_raw(rid)
                    if doc is None or stored is None or stored is doc:
                        if doc is not None and stored is doc:
                            # in-place mutation of the shared object: commit
                            # it through save for indexes/hooks/epoch
                            pre = (dict(self._preimages[rid][0]), self._preimages[rid][1])
                            db.save(doc)
                            applied.append(("update_pre", (rid, pre)))
                        continue
                    pre_clone = _clone(stored)
                    doc.version = stored.version  # save() re-checks MVCC
                    db.save(doc)
                    applied.append(("update", pre_clone))
                for rid in list(self.deleted):
                    live = db._load_raw(rid)
                    if live is not None:
                        # capture incident edges BEFORE the cascade so a
                        # compensating restore can re-wire them
                        edges = (
                            list(live.edges(Direction.BOTH))
                            if isinstance(live, Vertex)
                            else []
                        )
                        db.delete(live)
                        applied.append(("delete", (live, edges)))
            except Exception:
                self._compensate(applied)
                raise
            finally:
                db._tx_suspended = False
                db._tx_local.hook_buffer = None
                db._tx_local.wal_buffer = None
            if db._wal is not None and wal_ops and not db._wal.replaying:
                tx_entry = {"op": "tx", "ops": wal_ops}
                txid2pc = getattr(db._tx_local, "tx2pc_commit", None)
                if txid2pc:
                    # stamp the distributed txid: recovery classifies
                    # this txid as decided-commit (parallel/twophase.
                    # recover_from_wal) instead of re-staging it
                    tx_entry["txid2pc"] = txid2pc
                lsn = db._wal.append(tx_entry)
                db._mark_ckpt_dirty(tx_entry)
                # changefeed tap: the committed tx is ONE atomic entry —
                # consumers see its ops share an LSN (seq-ordered)
                from orientdb_tpu.cdc.feed import notify_commit

                notify_commit(db, tx_entry, lsn)
                # quorum mode: the whole tx ships as ONE atomic entry and
                # the commit blocks until a majority holds it
                db._quorum_push(tx_entry, lsn)
            # adopt real rids onto buffered edge objects (created docs
            # were saved in place; edges are re-created, so the caller's
            # handle would otherwise keep its temp rid forever)
            for e, _s, _d in self.edge_ops:
                if not e.rid.is_persistent:
                    e.rid = rid_map.get(e.rid, e.rid)
                e.out_rid = rid_map.get(e.out_rid, e.out_rid)
                e.in_rid = rid_map.get(e.in_rid, e.in_rid)
            from orientdb_tpu.utils.metrics import metrics

            metrics.incr("tx.commit")
            self.active = False
            db._end_tx(self)
            if db._hooks is not None:
                for ev, doc in after_events:
                    # best-effort: the commit is already durable — a raising
                    # subscriber must not make a persisted commit look failed
                    # or starve later subscribers
                    try:
                        db._hooks.fire(ev, doc)
                    except Exception:
                        log.exception("post-commit %s hook failed", ev)
            return rid_map

    def _fail_conflict(self, rid, stored_v, base_v):
        from orientdb_tpu.models.database import ConcurrentModificationError
        from orientdb_tpu.utils.metrics import metrics

        metrics.incr("tx.conflict")
        raise ConcurrentModificationError(
            f"{rid}: stored v{stored_v} != tx base v{base_v}"
        )

    def _compensate(self, applied) -> None:
        """Undo already-applied ops after a mid-commit failure.

        Every restore routes through the index manager too — writing a
        pre-image straight into the cluster would leave unique indexes
        mapping the compensated-away values forever (a phantom
        DuplicateKeyError on every future insert of that key).
        """
        db = self.db
        idx = db._indexes
        for kind, payload in reversed(applied):
            try:
                if kind in ("create", "edge"):
                    db.delete(payload)
                elif kind == "update":
                    pre: Document = payload
                    cur = db._load_raw(pre.rid)
                    if idx is not None and cur is not None:
                        idx.on_delete(cur)
                    db._cluster(pre.rid.cluster).records[pre.rid.position] = pre
                    if idx is not None:
                        idx.on_save(pre)
                    if db._cold_tier is not None:
                        db._cold_tier.on_save(pre)  # compensations bypass save()
                elif kind == "update_pre":
                    rid, (fields, version) = payload
                    live = db._load_raw(rid)
                    if live is not None:
                        if idx is not None:
                            idx.on_delete(live)
                        live._fields = dict(fields)
                        live.version = version
                        if idx is not None:
                            idx.on_save(live)
                        if db._cold_tier is not None:
                            db._cold_tier.on_save(live)
                elif kind == "delete":
                    doc, edges = payload
                    self._restore_deleted(doc)
                    for e in edges:
                        self._restore_deleted(e)
            except Exception:  # pragma: no cover - best effort
                log.exception("compensation failed for %s", kind)

    def _restore_deleted(self, doc: Document) -> None:
        """Resurrect a deleted record: cluster slot, index entries, and (for
        edges) both endpoint adjacency bags."""
        db = self.db
        if db._load_raw(doc.rid) is None:
            db._cluster(doc.rid.cluster).records[doc.rid.position] = doc
        doc._deleted = False
        if db._indexes is not None:
            db._indexes.on_save(doc)
        if db._cold_tier is not None:
            db._cold_tier.on_save(doc)  # compensations bypass save()
        if isinstance(doc, Edge):
            src = db._load_raw(doc.out_rid)
            dst = db._load_raw(doc.in_rid)
            if isinstance(src, Vertex):
                bag = src._bag(Direction.OUT, doc.class_name)
                if doc.rid not in bag:
                    bag.append(doc.rid)
            if isinstance(dst, Vertex):
                bag = dst._bag(Direction.IN, doc.class_name)
                if doc.rid not in bag:
                    bag.append(doc.rid)

    def rollback(self) -> None:
        if not self.active:
            return
        for rid, (fields, version) in self._preimages.items():
            live = self.db._load_raw(rid)
            if live is not None:
                live._fields = dict(fields)
                live.version = version
        self.active = False
        self.db._end_tx(self)


# ---------------------------------------------------------------------------
# SQL surface (BEGIN / COMMIT / ROLLBACK)
# ---------------------------------------------------------------------------


def execute_tx_statement(db, stmt) -> List[Result]:
    if isinstance(stmt, A.BeginStatement):
        db.begin()
        return [Result(props={"operation": "begin"})]
    if isinstance(stmt, A.CommitStatement):
        rid_map = db.commit()
        return [
            Result(
                props={
                    "operation": "commit",
                    "created": {str(k): str(v) for k, v in rid_map.items()},
                }
            )
        ]
    if isinstance(stmt, A.RollbackStatement):
        db.rollback()
        return [Result(props={"operation": "rollback"})]
    raise TxError(f"not a tx statement: {type(stmt).__name__}")
