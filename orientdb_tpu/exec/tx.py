"""Transaction statements (BEGIN/COMMIT/ROLLBACK).

Placeholder until the optimistic transaction manager lands (analog of [E]
OTransactionOptimistic, SURVEY.md §3.4); the host store currently
auto-commits every statement.
"""

from __future__ import annotations

from typing import List

from orientdb_tpu.exec.result import Result
from orientdb_tpu.sql import ast as A


def execute_tx_statement(db, stmt) -> List[Result]:
    raise NotImplementedError(
        "explicit transactions are not implemented yet; statements auto-commit"
    )
