"""Optimistic MVCC transactions.

Analog of [E] OTransactionOptimistic (SURVEY.md §3.4): changes buffer in a
tx-local workspace; ``commit()`` takes the storage lock once, re-checks
every touched record's version against the store (MVCC), then applies
creates → edges → updates → deletes. A version mismatch raises
``ConcurrentModificationError`` before any mutation (the reference's
OConcurrentModificationException), and a mid-apply failure (e.g. a unique
index violation) triggers compensating rollback of already-applied ops so
the store never holds a half-committed transaction.

Tx-local visibility: ``load``/``browse_class``/queries inside the tx see
tx-created records, tx-updated field values, and hide tx-deleted records
(read-your-writes). New records carry temporary RIDs ``#-1:-N`` (the
reference's negative temp RIDs) remapped to real RIDs at commit.
Divergence from the reference, documented: adjacency bags of *existing*
vertices do not show uncommitted edges until commit.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.exec.result import Result
from orientdb_tpu.models.record import Direction, Document, Edge, Vertex
from orientdb_tpu.models.rid import NEW_RID, RID
from orientdb_tpu.sql import ast as A
from orientdb_tpu.utils.logging import get_logger

log = get_logger("tx")


class TxError(Exception):
    pass


def _clone(doc: Document) -> Document:
    """Tx-local copy: same identity/version, independent fields/bags."""
    c = type(doc)(doc.class_name, dict(doc.fields()))
    c.rid = doc.rid
    c.version = doc.version
    c._db = doc._db
    if isinstance(doc, Vertex) and isinstance(c, Vertex):
        c._out_edges = {k: list(v) for k, v in doc._out_edges.items()}
        c._in_edges = {k: list(v) for k, v in doc._in_edges.items()}
    if isinstance(doc, Edge) and isinstance(c, Edge):
        c.out_rid = doc.out_rid
        c.in_rid = doc.in_rid
    return c


class Transaction:
    """One optimistic transaction bound to a Database session."""

    def __init__(self, db) -> None:
        self.db = db
        self._temp_seq = itertools.count(2)
        #: rid → tx-local doc (updates and tx-loaded copies)
        self.workspace: Dict[RID, Document] = {}
        #: rids written through the tx → base version for the MVCC check
        self.dirty: Dict[RID, int] = {}
        #: pre-images for store-shared objects mutated in place
        self._preimages: Dict[RID, Tuple[Dict, int]] = {}
        self.created: List[Document] = []  # temp-RID docs in creation order
        self.deleted: Dict[RID, Document] = {}
        #: (edge_doc, src_rid, dst_rid) — rids may be temporary
        self.edge_ops: List[Tuple[Edge, RID, RID]] = []
        self.active = True

    # -- tx-local operations ------------------------------------------------

    def _temp_rid(self) -> RID:
        return RID(-1, -next(self._temp_seq))

    def _check_ownership(self, class_name: str) -> None:
        """A LOCAL transaction must not buffer writes to a class another
        member owns (per-class owner streams): committing them here
        would fork the class's stream — rid collisions and divergence.
        Cross-owner transactions need 2PC (documented delta); run the tx
        against the owning member instead."""
        if self.db._owner_for(class_name) is not None:
            raise TxError(
                f"class '{class_name}' is owned by another member; run "
                "this transaction there (cross-owner tx needs 2PC)"
            )

    def save(self, doc: Document) -> Document:
        self._check_ownership(doc.class_name)
        if doc.rid in self.deleted:
            raise TxError(f"{doc.rid} deleted in this transaction")
        if not doc.rid.is_persistent:
            if doc.rid not in self.workspace:
                cls = self.db.schema.get_class(doc.class_name)
                if cls is None:
                    cls = self.db.schema.create_class(doc.class_name)
                cls.validate(doc.fields())
                doc.rid = self._temp_rid()
                doc.version = 0
                doc._db = self.db
                self.created.append(doc)
                self.workspace[doc.rid] = doc
            # already temp-registered: fields live on the doc itself
            return doc
        if doc.rid not in self.dirty:
            stored = self.db._load_raw(doc.rid)
            if stored is None:
                raise TxError(f"{doc.rid} not found")
            # base = the version THIS tx read (clone keeps it from load
            # time); using the store's current version here would silently
            # swallow concurrent commits between tx.load and tx.save
            self.dirty[doc.rid] = doc.version
            if stored is doc and doc.rid not in self._preimages:
                # mutating the shared store object in place: capture the
                # pre-image so rollback can restore it (touch() may already
                # have captured it BEFORE the first field mutation)
                self._preimages[doc.rid] = (dict(stored.fields()), stored.version)
        self.workspace[doc.rid] = doc
        return doc

    def touch(self, doc: Document) -> None:
        """Capture a pre-image for a shared store object about to be
        mutated in place (called from Document.set before the write)."""
        rid = doc.rid
        if rid in self._preimages or rid in self.deleted:
            return
        stored = self.db._load_raw(rid)
        if stored is doc:
            self._preimages[rid] = (dict(stored.fields()), stored.version)

    def load(self, rid: RID) -> Optional[Document]:
        if rid in self.deleted:
            return None
        hit = self.workspace.get(rid)
        if hit is not None:
            return hit
        stored = self.db._load_raw(rid)
        if stored is None:
            return None
        copy = _clone(stored)
        self.workspace[rid] = copy
        return copy

    def delete(self, doc: Document) -> None:
        rid = doc.rid
        if not rid.is_persistent:
            # deleting an uncommitted record: drop it from the tx, and (for
            # a vertex) cascade-drop uncommitted edges touching it — the
            # tx-buffered mirror of the store's cascade delete
            self.created = [d for d in self.created if d.rid != rid]
            self.edge_ops = [
                op
                for op in self.edge_ops
                if op[0].rid != rid and op[1] != rid and op[2] != rid
            ]
            self.workspace.pop(rid, None)
            return
        stored = self.db._load_raw(rid)
        if stored is None:
            return
        self.dirty.setdefault(rid, stored.version)
        self.deleted[rid] = stored
        self.workspace.pop(rid, None)

    def new_edge(self, class_name: str, src: Vertex, dst: Vertex, **fields) -> Edge:
        self._check_ownership(class_name)
        cls = self.db.schema.get_class(class_name)
        if cls is None:
            cls = self.db.schema.create_edge_class(class_name)
        if not cls.is_edge_type:
            raise ValueError(f"class '{class_name}' is not an edge class")
        e = Edge(cls.name, fields)
        e._db = self.db
        e.rid = self._temp_rid()
        e.out_rid = src.rid
        e.in_rid = dst.rid
        self.workspace[e.rid] = e
        self.edge_ops.append((e, src.rid, dst.rid))
        return e

    # -- visibility ----------------------------------------------------------

    def browse_extra(self, class_name: str, polymorphic: bool):
        """Tx-created docs visible to scans (read-your-writes)."""
        def _member(doc):
            cls = self.db.schema.get_class(doc.class_name)
            if cls is None:
                return False
            if cls.name.lower() == class_name.lower():
                return True
            return polymorphic and cls.is_subclass_of(class_name)

        for doc in self.created:
            if _member(doc):
                yield doc
        for e, _s, _d in self.edge_ops:
            if _member(e):
                yield e

    def overlay(self, doc: Document) -> Optional[Document]:
        """Committed doc → tx view (updated copy, or None if tx-deleted)."""
        if doc.rid in self.deleted:
            return None
        return self.workspace.get(doc.rid, doc)

    # -- terminal operations -------------------------------------------------

    def commit(self) -> Dict[RID, RID]:
        """Apply the tx atomically; returns the temp→real RID map."""
        if not self.active:
            raise TxError("transaction no longer active")
        db = self.db
        if getattr(db, "_write_owner", None) is not None:
            raise TxError(
                "transactions commit on the cluster's write owner; run "
                "the tx against the primary (per-record forwarding is "
                "not atomic)"
            )
        try:
            # quorum pushes deferred during the locked apply (the
            # atomic tx entry) ship once the db-wide lock is free
            with db._quorum_deferral():
                with db._lock:
                    return self._commit_locked(db)
        except Exception:
            # a failed commit invalidates the tx (the reference rolls the
            # whole transaction back on OConcurrentModificationException /
            # ORecordDuplicatedException)
            self.rollback()
            raise

    def _commit_locked(self, db) -> Dict[RID, RID]:
            # phase 1: MVCC checks before any mutation (atomic fail-fast)
            for rid, base in self.dirty.items():
                stored = db._load_raw(rid)
                if rid in self.deleted:
                    if stored is not None and stored.version != base:
                        self._fail_conflict(rid, stored.version, base)
                    continue
                if stored is None:
                    raise TxError(f"{rid} vanished before commit")
                if stored.version != base:
                    self._fail_conflict(rid, stored.version, base)
            # phase 2: apply, with compensating rollback on failure.
            # AFTER hooks (and live-query delivery built on them) are
            # buffered for the duration of the apply and flushed only once
            # the whole commit has succeeded — a mid-apply failure discards
            # them, so subscribers never observe compensated-away ops (the
            # reference's post-commit-only OLiveQueryHookV2 delivery).
            applied: List[Tuple[str, object]] = []
            rid_map: Dict[RID, RID] = {}
            db._tx_suspended = True
            after_events: List = []
            db._tx_local.hook_buffer = after_events
            # WAL ops buffer during apply and flush as ONE atomic entry
            # only on success — compensation discards them, so the log
            # never shows a half-commit (the [E] tx-boundary WAL records)
            wal_ops: List = []
            db._tx_local.wal_buffer = wal_ops
            try:
                for doc in self.created:
                    temp = doc.rid
                    doc.rid = NEW_RID
                    db.save(doc)
                    rid_map[temp] = doc.rid
                    applied.append(("create", doc))
                for e, src_rid, dst_rid in self.edge_ops:
                    sr = rid_map.get(src_rid, src_rid)
                    dr = rid_map.get(dst_rid, dst_rid)
                    src = db._load_raw(sr)
                    dst = db._load_raw(dr)
                    if not isinstance(src, Vertex) or not isinstance(dst, Vertex):
                        raise TxError("edge endpoint is not a vertex")
                    real = db.new_edge(e.class_name, src, dst, **e.fields())
                    rid_map[e.rid] = real.rid
                    applied.append(("edge", real))
                for rid in list(self.dirty):
                    if rid in self.deleted:
                        continue
                    doc = self.workspace.get(rid)
                    stored = db._load_raw(rid)
                    if doc is None or stored is None or stored is doc:
                        if doc is not None and stored is doc:
                            # in-place mutation of the shared object: commit
                            # it through save for indexes/hooks/epoch
                            pre = (dict(self._preimages[rid][0]), self._preimages[rid][1])
                            db.save(doc)
                            applied.append(("update_pre", (rid, pre)))
                        continue
                    pre_clone = _clone(stored)
                    doc.version = stored.version  # save() re-checks MVCC
                    db.save(doc)
                    applied.append(("update", pre_clone))
                for rid in list(self.deleted):
                    live = db._load_raw(rid)
                    if live is not None:
                        # capture incident edges BEFORE the cascade so a
                        # compensating restore can re-wire them
                        edges = (
                            list(live.edges(Direction.BOTH))
                            if isinstance(live, Vertex)
                            else []
                        )
                        db.delete(live)
                        applied.append(("delete", (live, edges)))
            except Exception:
                self._compensate(applied)
                raise
            finally:
                db._tx_suspended = False
                db._tx_local.hook_buffer = None
                db._tx_local.wal_buffer = None
            if db._wal is not None and wal_ops and not db._wal.replaying:
                tx_entry = {"op": "tx", "ops": wal_ops}
                lsn = db._wal.append(tx_entry)
                db._mark_ckpt_dirty(tx_entry)
                # quorum mode: the whole tx ships as ONE atomic entry and
                # the commit blocks until a majority holds it
                db._quorum_push(tx_entry, lsn)
            from orientdb_tpu.utils.metrics import metrics

            metrics.incr("tx.commit")
            self.active = False
            db._end_tx(self)
            if db._hooks is not None:
                for ev, doc in after_events:
                    # best-effort: the commit is already durable — a raising
                    # subscriber must not make a persisted commit look failed
                    # or starve later subscribers
                    try:
                        db._hooks.fire(ev, doc)
                    except Exception:
                        log.exception("post-commit %s hook failed", ev)
            return rid_map

    def _fail_conflict(self, rid, stored_v, base_v):
        from orientdb_tpu.models.database import ConcurrentModificationError
        from orientdb_tpu.utils.metrics import metrics

        metrics.incr("tx.conflict")
        raise ConcurrentModificationError(
            f"{rid}: stored v{stored_v} != tx base v{base_v}"
        )

    def _compensate(self, applied) -> None:
        """Undo already-applied ops after a mid-commit failure.

        Every restore routes through the index manager too — writing a
        pre-image straight into the cluster would leave unique indexes
        mapping the compensated-away values forever (a phantom
        DuplicateKeyError on every future insert of that key).
        """
        db = self.db
        idx = db._indexes
        for kind, payload in reversed(applied):
            try:
                if kind in ("create", "edge"):
                    db.delete(payload)
                elif kind == "update":
                    pre: Document = payload
                    cur = db._load_raw(pre.rid)
                    if idx is not None and cur is not None:
                        idx.on_delete(cur)
                    db._cluster(pre.rid.cluster).records[pre.rid.position] = pre
                    if idx is not None:
                        idx.on_save(pre)
                    if db._cold_tier is not None:
                        db._cold_tier.on_save(pre)  # compensations bypass save()
                elif kind == "update_pre":
                    rid, (fields, version) = payload
                    live = db._load_raw(rid)
                    if live is not None:
                        if idx is not None:
                            idx.on_delete(live)
                        live._fields = dict(fields)
                        live.version = version
                        if idx is not None:
                            idx.on_save(live)
                        if db._cold_tier is not None:
                            db._cold_tier.on_save(live)
                elif kind == "delete":
                    doc, edges = payload
                    self._restore_deleted(doc)
                    for e in edges:
                        self._restore_deleted(e)
            except Exception:  # pragma: no cover - best effort
                log.exception("compensation failed for %s", kind)

    def _restore_deleted(self, doc: Document) -> None:
        """Resurrect a deleted record: cluster slot, index entries, and (for
        edges) both endpoint adjacency bags."""
        db = self.db
        if db._load_raw(doc.rid) is None:
            db._cluster(doc.rid.cluster).records[doc.rid.position] = doc
        doc._deleted = False
        if db._indexes is not None:
            db._indexes.on_save(doc)
        if db._cold_tier is not None:
            db._cold_tier.on_save(doc)  # compensations bypass save()
        if isinstance(doc, Edge):
            src = db._load_raw(doc.out_rid)
            dst = db._load_raw(doc.in_rid)
            if isinstance(src, Vertex):
                bag = src._bag(Direction.OUT, doc.class_name)
                if doc.rid not in bag:
                    bag.append(doc.rid)
            if isinstance(dst, Vertex):
                bag = dst._bag(Direction.IN, doc.class_name)
                if doc.rid not in bag:
                    bag.append(doc.rid)

    def rollback(self) -> None:
        if not self.active:
            return
        for rid, (fields, version) in self._preimages.items():
            live = self.db._load_raw(rid)
            if live is not None:
                live._fields = dict(fields)
                live.version = version
        self.active = False
        self.db._end_tx(self)


# ---------------------------------------------------------------------------
# SQL surface (BEGIN / COMMIT / ROLLBACK)
# ---------------------------------------------------------------------------


def execute_tx_statement(db, stmt) -> List[Result]:
    if isinstance(stmt, A.BeginStatement):
        db.begin()
        return [Result(props={"operation": "begin"})]
    if isinstance(stmt, A.CommitStatement):
        rid_map = db.commit()
        return [
            Result(
                props={
                    "operation": "commit",
                    "created": {str(k): str(v) for k, v in rid_map.items()},
                }
            )
        ]
    if isinstance(stmt, A.RollbackStatement):
        db.rollback()
        return [Result(props={"operation": "rollback"})]
    raise TxError(f"not a tx statement: {type(stmt).__name__}")
