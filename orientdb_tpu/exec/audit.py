"""Sampled shadow-oracle parity auditor.

The north star is 50x MATCH throughput **at result-set parity**, but
until this module parity was asserted only inside ``bench.py``'s dryrun
gate — never in production serving. PRs 15-18 stacked mutable device
state under every cached plan (delta slab scatters, tier paging, epoch
compaction swaps, OOM-relief evictions), so a single mis-applied patch
could serve wrong rows at full speed with zero signal. This module
makes the parity claim continuously verified:

- at ``audit_sample_rate``, the engine front doors (query/command,
  query_batch, the coalesce lanes' harvest) capture a served compiled
  result together with an epoch lease on the snapshot it was computed
  against (``GraphSnapshot.retain`` — the PR-15 lease keeps the
  compared epoch's device state alive until the audit retires);
- a bounded background worker re-executes the statement on the pure
  Python oracle and compares canonical result digests — the SAME
  canonicalization bench's parity gates use (``exec/result``
  helpers), so the two parity definitions cannot drift;
- a divergence emits a structured, replayable divergence record
  (fingerprint, trace id, epoch, row-level diff sample), bumps
  ``parity.diverged``, and convicts the fingerprint through the PR-18
  quarantine ladder (``devicefault.domain.quarantine_parity``) so the
  oracle serves degraded-but-correct traffic until a clean probe
  re-admits; the ``parity_divergence`` alert rule fires with the
  divergent request's trace id as exemplar.

Shadow execution is strictly off the serving thread: the submit fast
path is one config read, one sampling roll, an epoch capture, and a
non-blocking queue put (drops count ``parity.audit_dropped`` when the
queue is full). A store mutation between capture and shadow execution
invalidates the compare (the oracle reads the LIVE host store) — those
audits retire as ``parity.audit_stale`` instead of false divergences.

Deterministically provable: the ``audit.mismatch`` chaos point
(:func:`corrupt_point`, crossed by ``exec/engine._run`` after every
compiled execute) corrupts the SERVED rows — never the oracle's — so a
seeded :class:`~orientdb_tpu.chaos.faults.FaultPlan` drives detect →
quarantine → alert → re-admission end to end in tests.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from orientdb_tpu.chaos.faults import FaultError, fault
from orientdb_tpu.exec.result import (
    ColumnarRows,
    Result,
    result_digest,
    rows_diff_sample,
)
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("audit")


def _to_dicts(rows) -> List[Dict]:
    """Plain-dict rows from a raw row container (the ``_rows`` of a
    ResultSet: a list of Result or a ColumnarRows) WITHOUT consuming
    any caller-visible stream."""
    if isinstance(rows, ColumnarRows):
        return rows.to_dicts()
    return [r.to_dict() if isinstance(r, Result) else dict(r) for r in rows]


class _Capture:
    """One sampled serving-path result awaiting shadow execution."""

    __slots__ = (
        "db", "sql", "params", "rows", "trace_id", "epoch", "snap",
        "ts",
    )

    def __init__(self, db, sql, params, rows, trace_id, epoch, snap):
        self.db = db
        self.sql = sql
        self.params = params
        self.rows = rows
        self.trace_id = trace_id
        self.epoch = epoch
        self.snap = snap
        self.ts = time.time()


class ParityAuditor:
    """Process-wide auditor (mirrors the metrics/stats singletons): a
    bounded queue + one daemon worker."""

    def __init__(self) -> None:
        self._mu = threading.Lock()
        # SimpleQueue (C-implemented) keeps the serving-thread put at
        # sub-µs; the bound is enforced by a qsize() check in
        # maybe_submit (approximate under races — a shed valve, not an
        # invariant)
        self._q: "queue.SimpleQueue[_Capture]" = queue.SimpleQueue()
        self._qmax = max(1, int(config.audit_queue_max))
        self._worker: Optional[threading.Thread] = None
        self._inflight = 0
        self._retired = 0
        self._submitted = 0
        self._audited = 0
        self._diverged = 0
        self._dropped = 0
        self._stale = 0
        self._errors = 0
        self._divergences: deque = deque()
        #: coalesced epoch leases: id(snap) -> [snap, refcount]. Every
        #: in-flight capture of the same snapshot shares ONE real
        #: ``retain()`` — the retain/ledger bookkeeping is the dominant
        #: serving-thread cost at high sample rates, and a thousand
        #: one-query leases tell the hbm_epoch_leak scan nothing a
        #: single audit-plane lease doesn't
        self._leases: Dict[int, list] = {}

    # -- serving-thread side -------------------------------------------------

    def maybe_submit(
        self, db, sql: str, params, rs, trace_id, sampled_in: bool
    ) -> bool:
        """The front-door hook: enqueue a shadow audit for a COMPILED
        result when the auditor's sampling roll admits it. Rides the
        PR-4 stats decision (``sampled_in`` = the query's stats
        accumulator ran, or the always-captured batch paths) so stats /
        slowlog / timeline / audit cover the same query subset. Never
        blocks and never raises into the serving path."""
        rate = config.audit_sample_rate
        if rate <= 0 or not sampled_in:
            return False
        if getattr(rs, "engine", None) != "tpu":
            return False
        rows = getattr(rs, "_rows", None)
        if rows is None or not hasattr(rows, "__len__"):
            return False
        from orientdb_tpu.obs.stats import sampled

        if not sampled(rate):
            return False
        try:
            snap = db.current_snapshot()
            cap = _Capture(
                db, sql, params, rows, trace_id, db.mutation_epoch, snap
            )
            with self._mu:
                if snap is not None:
                    # epoch lease: the compared epoch's device state
                    # stays alive until the audit retires (dropped in
                    # _release); captures of the same snapshot share
                    # one refcounted retain
                    sid = id(snap)
                    e = self._leases.get(sid)
                    if e is None:
                        snap.retain()
                        self._leases[sid] = [snap, 1]
                    else:
                        e[1] += 1
                self._submitted += 1
            if self._q.qsize() >= self._qmax:
                self._release(cap)
                with self._mu:
                    self._submitted -= 1
                    self._dropped += 1
                metrics.incr("parity.audit_dropped")
                return False
            self._q.put(cap)
            self._ensure_worker()
            return True
        except Exception:  # the audit plane must never fail a query
            log.exception("parity audit submit failed")
            return False

    # -- worker side ---------------------------------------------------------

    def _ensure_worker(self) -> None:
        w = self._worker
        if w is not None and w.is_alive():
            return
        with self._mu:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._loop, name="parity-audit", daemon=True
            )
            self._worker.start()

    def _loop(self) -> None:
        while True:
            cap = self._q.get()
            with self._mu:
                self._inflight += 1
            try:
                self._audit_one(cap)
            except Exception:
                with self._mu:
                    self._errors += 1
                log.exception("parity audit failed: %s", cap.sql)
            finally:
                self._release(cap)
                with self._mu:
                    self._inflight -= 1
                    self._retired += 1

    def _release(self, cap: _Capture) -> None:
        snap = cap.snap
        if snap is None:
            return
        cap.snap = None
        real = None
        with self._mu:
            e = self._leases.get(id(snap))
            if e is not None:
                e[1] -= 1
                if e[1] <= 0:
                    del self._leases[id(snap)]
                    real = e[0]
        if real is not None:
            try:
                real.release()
            except Exception:
                log.exception("audit epoch lease release failed")

    def _audit_one(self, cap: _Capture) -> None:
        from orientdb_tpu.obs.trace import span

        with span("audit.shadow", sql=cap.sql[:120]) as sp:
            if cap.db.mutation_epoch != cap.epoch:
                # the oracle reads the LIVE host store; a write landed
                # since capture, so the compare is no longer valid at
                # the captured epoch — retire without a verdict
                with self._mu:
                    self._stale += 1
                metrics.incr("parity.audit_stale")
                sp.set("verdict", "stale")
                return
            from orientdb_tpu.exec.engine import parse_cached
            from orientdb_tpu.exec.oracle import execute_statement

            served = _to_dicts(cap.rows)
            oracle_rows = execute_statement(
                cap.db, parse_cached(cap.sql), cap.params or {}
            )
            oracle = _to_dicts(oracle_rows)
            d_served = result_digest(served)
            d_oracle = result_digest(oracle)
            with self._mu:
                self._audited += 1
            metrics.incr("parity.audited")
            if d_served == d_oracle:
                sp.set("verdict", "parity")
                return
            sp.set("verdict", "diverged")
            self._diverge(cap, served, oracle, d_served, d_oracle)

    def _diverge(self, cap, served, oracle, d_served, d_oracle) -> None:
        from orientdb_tpu.exec.devicefault import domain as _fault_domain
        from orientdb_tpu.obs.stats import fingerprint_cached

        rec = {
            "fingerprint": fingerprint_cached(cap.sql).fid,
            "sql": cap.sql[:200],
            "trace_id": cap.trace_id,
            "epoch": cap.epoch,
            "digest_served": d_served,
            "digest_oracle": d_oracle,
            "rows_served": len(served),
            "rows_oracle": len(oracle),
            "diff": rows_diff_sample(
                served, oracle, limit=max(1, int(config.audit_diff_rows))
            ),
            "ts": round(time.time(), 3),
        }
        with self._mu:
            self._diverged += 1
            self._divergences.append(rec)
            capacity = max(1, int(config.audit_history_capacity))
            while len(self._divergences) > capacity:
                self._divergences.popleft()
        metrics.incr("parity.diverged")
        # quarantine the fingerprint through the PR-18 ladder: the
        # front doors serve the oracle (degraded but correct) until a
        # clean probe — which this auditor re-audits — re-admits
        _fault_domain.quarantine_parity(
            cap.sql,
            f"parity divergence: served {d_served} != oracle {d_oracle} "
            f"at epoch {cap.epoch}",
        )
        log.error(
            "PARITY DIVERGENCE (epoch %s, trace %s): %s — served %s "
            "(%d rows) vs oracle %s (%d rows)",
            cap.epoch, cap.trace_id, cap.sql[:120], d_served,
            len(served), d_oracle, len(oracle),
        )

    # -- views ---------------------------------------------------------------

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Drain every queued audit (tests and bench settle): True when
        every submitted capture has retired — exact accounting, immune
        to the dequeue-to-inflight handoff window."""
        deadline = time.monotonic() + timeout_s
        with self._mu:
            drained = self._retired >= self._submitted
        if not drained:
            self._ensure_worker()
        while True:
            with self._mu:
                if self._retired >= self._submitted:
                    return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.005)

    def divergences(self) -> List[Dict]:
        """Replayable divergence records, oldest first."""
        with self._mu:
            return list(self._divergences)

    def last_divergence_trace(self) -> Optional[str]:
        with self._mu:
            for rec in reversed(self._divergences):
                if rec.get("trace_id"):
                    return rec["trace_id"]
        return None

    def snapshot(self) -> Dict:
        with self._mu:
            return {
                "submitted": self._submitted,
                "audited": self._audited,
                "diverged": self._diverged,
                "dropped": self._dropped,
                "stale": self._stale,
                "errors": self._errors,
                "queued": self._q.qsize(),
                "divergences": list(self._divergences),
            }

    def reset(self) -> None:
        """Test isolation (mirrors ``metrics.reset``)."""
        self.flush(timeout_s=1.0)
        with self._mu:
            self._retired = 0
            self._submitted = 0
            self._audited = 0
            self._diverged = 0
            self._dropped = 0
            self._stale = 0
            self._errors = 0
            self._divergences.clear()


#: the process-wide auditor (mirrors metrics/stats/tracer singletons)
auditor = ParityAuditor()


# -- chaos crossing ----------------------------------------------------------


def corrupt_point(rows):
    """The ``audit.mismatch`` chaos crossing: an armed plan's ``error``
    rule here deterministically corrupts the SERVED compiled rows —
    never the oracle's — so the auditor's digest compare must diverge.
    Crossed by ``exec/engine._run`` after every compiled execute."""
    try:
        with fault.point("audit.mismatch"):
            return rows
    except FaultError:
        metrics.incr("parity.chaos_corrupted")
        if hasattr(rows, "__len__") and len(rows) > 0:
            return rows[1:]  # drop the first served row
        return [Result(props={"__corrupt__": True})]


# -- bench evidence ----------------------------------------------------------


def bench_parity_audit_summary() -> Dict:
    """One per-round ``parity_audit`` evidence record (the
    device_faults block's sibling): audit volume, divergences, scrub
    findings. ``tools/perfdiff.degraded_round`` reads it to keep
    diverged/repaired rounds out of the regression baseline."""
    from orientdb_tpu.storage.scrub import scrubber

    auditor.flush(timeout_s=2.0)
    s = auditor.snapshot()
    sc = scrubber.snapshot()
    return {
        "submitted": s["submitted"],
        "audited": s["audited"],
        "diverged": s["diverged"],
        "dropped": s["dropped"],
        "stale": s["stale"],
        "scrub_corruptions": sc["corruptions"],
        "scrub_repairs": sum(sc["repairs"].values()),
    }
