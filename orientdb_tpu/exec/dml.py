"""Write-path and DDL statement execution (host side).

Analog of the reference's Insert/Update/Delete execution planners ([E]
core/.../sql/executor/OInsertExecutionPlanner etc.) and DDL statements.
Writes always run on the host record store; the TPU snapshot is invalidated
via Database.mutation_epoch (north-star design: the TPU path is a read
accelerator, writes stay host-side — SURVEY.md §7 design stance).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from orientdb_tpu.exec.eval import EvalContext, as_list, evaluate, resolve_links, truthy
from orientdb_tpu.exec.result import Result
from orientdb_tpu.models.record import Document, Edge, Vertex
from orientdb_tpu.models.rid import RID
from orientdb_tpu.models.schema import PropertyType
from orientdb_tpu.sql import ast as A


class CommandError(Exception):
    pass


def execute(db, stmt: A.Statement, params, parent_ctx=None) -> List[Result]:
    ctx = EvalContext(db, params=params, parent=parent_ctx)
    if isinstance(stmt, A.InsertStatement):
        return _insert(db, stmt, ctx, params)
    if isinstance(stmt, A.CreateVertexStatement):
        return _create_vertex(db, stmt, ctx)
    if isinstance(stmt, A.CreateEdgeStatement):
        return _create_edge(db, stmt, ctx, params)
    if isinstance(stmt, A.UpdateStatement):
        return _update(db, stmt, ctx, params)
    if isinstance(stmt, A.DeleteStatement):
        return _delete(db, stmt, ctx, params)
    if isinstance(stmt, A.CreateClassStatement):
        return _create_class(db, stmt)
    if isinstance(stmt, A.CreatePropertyStatement):
        return _create_property(db, stmt)
    if isinstance(stmt, A.CreateIndexStatement):
        return _create_index(db, stmt)
    if isinstance(stmt, A.DropClassStatement):
        return _drop_class(db, stmt)
    if isinstance(stmt, A.DropPropertyStatement):
        cls = db.schema.get_class_or_raise(stmt.class_name)
        cls.properties.pop(stmt.property_name, None)
        return [Result(props={"operation": "drop property"})]
    if isinstance(stmt, A.DropIndexStatement):
        db.indexes.drop_index(stmt.name)
        return [Result(props={"operation": "drop index"})]
    if isinstance(stmt, A.AlterPropertyStatement):
        return _alter_property(db, stmt, ctx)
    if isinstance(stmt, A.CreateSequenceStatement):
        s = db.sequences.create(
            stmt.name, stmt.seq_type, stmt.start, stmt.increment, stmt.cache
        )
        return [Result(props={"operation": "create sequence", "name": s.name})]
    if isinstance(stmt, A.AlterSequenceStatement):
        s = db.sequences.alter(stmt.name, stmt.start, stmt.increment, stmt.cache)
        return [Result(props={"operation": "alter sequence", "name": s.name})]
    if isinstance(stmt, A.DropSequenceStatement):
        db.sequences.drop(stmt.name)
        return [Result(props={"operation": "drop sequence"})]
    if isinstance(stmt, A.CreateFunctionStatement):
        f = db.functions.create(
            stmt.name,
            stmt.body,
            stmt.parameters,
            language=stmt.language,
            idempotent=stmt.idempotent,
        )
        return [Result(props={"operation": "create function", "name": f.name})]
    if isinstance(stmt, A.DropFunctionStatement):
        db.functions.drop(stmt.name)
        return [Result(props={"operation": "drop function"})]
    if isinstance(stmt, A.TruncateClassStatement):
        return _truncate_class(db, stmt)
    if isinstance(stmt, A.TruncateRecordStatement):
        n = 0
        for rid_s in stmt.rids:
            doc = db.load(RID.parse(rid_s))
            if doc is not None:
                db.delete(doc)
                n += 1
        return [Result(props={"operation": "truncate record", "count": n})]
    if isinstance(stmt, A.AlterClassStatement):
        return _alter_class(db, stmt)
    if isinstance(stmt, A.MoveVertexStatement):
        return _move_vertex(db, stmt, params)
    if isinstance(stmt, A.RebuildIndexStatement):
        return _rebuild_index(db, stmt)
    if isinstance(stmt, (A.GrantStatement, A.RevokeStatement)):
        return _grant_revoke(db, stmt)
    if isinstance(stmt, A.CreateUserStatement):
        sec = _security_of(db)
        roles = list(stmt.roles) or ["reader"]
        for r in roles:
            if sec.get_role(r) is None:
                raise CommandError(f"role '{r}' not found")
        sec.create_user(stmt.name, stmt.password, roles)
        return [Result(props={"operation": "create user", "name": stmt.name})]
    if isinstance(stmt, A.DropUserStatement):
        if not _security_of(db).drop_user(stmt.name):
            raise CommandError(f"user '{stmt.name}' not found")
        return [Result(props={"operation": "drop user", "name": stmt.name})]
    if isinstance(stmt, A.FindReferencesStatement):
        return _find_references(db, stmt)
    if isinstance(stmt, (A.BeginStatement, A.CommitStatement, A.RollbackStatement)):
        from orientdb_tpu.exec import tx as _tx

        return _tx.execute_tx_statement(db, stmt)
    if isinstance(stmt, A.LiveSelectStatement):
        from orientdb_tpu.exec import live

        return live.subscribe(db, stmt, params)
    raise CommandError(f"unsupported statement {type(stmt).__name__}")


# -- DDL / admin ------------------------------------------------------------


def _security_of(db):
    """The security manager SQL GRANT/REVOKE/CREATE USER mutate: a
    server-hosted database shares its server's manager (wired by
    Server.create_database); an embedded database gets its own on first
    use ([E] OSecurityShared lives inside the database)."""
    sec = getattr(db, "_security", None)
    if sec is None:
        from orientdb_tpu.models.security import SecurityManager

        sec = db._security = SecurityManager()
    return sec


def _truncate_class(db, stmt: A.TruncateClassStatement) -> List[Result]:
    """[E] OTruncateClassStatement: delete every record of the class.
    Deletes route through Database.delete so vertices cascade their
    incident edges and indexes/WAL/hooks stay consistent (the
    reference's UNSAFE skips the graph checks; here the graph-safe
    path is the only one, so UNSAFE only waives the vertex/edge-class
    warning)."""
    cls = db.schema.get_class(stmt.class_name)
    if cls is None:
        raise CommandError(f"class '{stmt.class_name}' not found")
    n = 0
    names = (
        [c.name for c in cls.subclasses(include_self=True)]
        if stmt.polymorphic
        else [cls.name]
    )
    for name in names:
        for doc in list(db.browse_class(name, polymorphic=False)):
            if not doc._deleted:
                db.delete(doc)
                n += 1
    return [Result(props={"operation": "truncate class", "count": n})]


def _alter_class(db, stmt: A.AlterClassStatement) -> List[Result]:
    attr = stmt.attribute.upper()
    if attr == "ADDCLUSTER":
        # [E] ALTER CLASS x ADDCLUSTER: widen the class's cluster set
        # (round-robin insertion spreads across them). Clusters here
        # are numeric-only; a NAMED cluster must fail loudly, not be
        # silently created anonymous
        if stmt.value is not None:
            raise CommandError(
                "named clusters are not supported; use ALTER CLASS "
                f"{stmt.class_name} ADDCLUSTER (ids are numeric)"
            )
        cid = db.schema.add_cluster(stmt.class_name)
        return [
            Result(
                props={"operation": "alter class", "cluster": cid}
            )
        ]
    if attr == "NAME":
        db.rename_class(stmt.class_name, str(stmt.value))
        return [
            Result(
                props={"operation": "alter class", "name": str(stmt.value)}
            )
        ]
    if attr == "ABSTRACT" and stmt.value:
        cls = db.schema.get_class_or_raise(stmt.class_name)
        if any(True for _ in db.browse_class(cls.name, polymorphic=False)):
            raise CommandError(
                f"cannot make class '{cls.name}' abstract: it has records"
            )
    try:
        cls = db.schema.alter_class(stmt.class_name, attr, stmt.value)
    except ValueError as e:
        raise CommandError(str(e)) from None
    db.mutation_epoch += 1
    return [Result(props={"operation": "alter class", "name": cls.name})]


def _move_vertex(db, stmt: A.MoveVertexStatement, params) -> List[Result]:
    """[E] OMoveVertexStatement: re-create each source vertex in the
    target class and rewire every incident edge to the new rid; the
    old record is deleted. Returns one row per move with old/new rids
    (the reference's result shape)."""
    from orientdb_tpu.models.record import Direction, Vertex

    cls = db.schema.get_class(stmt.target_class)
    if cls is None:
        cls = db.schema.create_vertex_class(stmt.target_class)
    if not cls.is_vertex_type:
        raise CommandError(
            f"MOVE VERTEX target '{stmt.target_class}' is not a vertex class"
        )
    sources: List[Vertex] = []
    if isinstance(stmt.source, str):
        doc = db.load(RID.parse(stmt.source))
        if doc is None:
            raise CommandError(f"record {stmt.source} not found")
        sources.append(doc)
    else:  # subquery
        from orientdb_tpu.exec.oracle import execute_select

        for r in execute_select(db, stmt.source, params or {}):
            if r.is_element:
                sources.append(r.element)
    rows = []
    for src in sources:
        if not isinstance(src, Vertex):
            raise CommandError(f"{src.rid} is not a vertex")
        old_rid = src.rid
        moved = db.new_vertex(cls.name, **dict(src.fields()))
        # rewire: every incident edge re-created against the new rid,
        # preserving direction, class, and fields; endpoints equal to
        # the moving vertex map to `moved` (a self-loop re-created
        # against old_rid would be cascaded away by the delete below)
        for e in list(src.edges(Direction.OUT)):
            dst = (
                moved if e.in_rid == old_rid else db.load(e.in_rid)
            )
            if dst is not None:
                db.new_edge(e.class_name, moved, dst, **dict(e.fields()))
        for e in list(src.edges(Direction.IN)):
            if e.out_rid == old_rid:
                continue  # self-loop: already re-created in the OUT pass
            s2 = db.load(e.out_rid)
            if s2 is not None:
                db.new_edge(e.class_name, s2, moved, **dict(e.fields()))
        db.delete(src)  # cascades the old edges
        rows.append(
            Result(
                props={"old": str(old_rid), "new": str(moved.rid)},
                element=moved,
            )
        )
    return rows


def _rebuild_index(db, stmt: A.RebuildIndexStatement) -> List[Result]:
    """[E] ORebuildIndexStatement: clear and re-populate from a full
    class scan — the recovery tool for an index that drifted."""
    if db._indexes is None:
        # the manager is created lazily with the first index
        if stmt.name == "*":
            return [
                Result(
                    props={
                        "operation": "rebuild index",
                        "indexes": 0,
                        "records": 0,
                    }
                )
            ]
        raise CommandError(f"index '{stmt.name}' not found")
    if stmt.name == "*":
        targets = db._indexes.all()  # may be empty: rebuild nothing
    else:
        ix = db._indexes.get_index(stmt.name)
        if ix is None:
            raise CommandError(f"index '{stmt.name}' not found")
        targets = [ix]
    total = 0
    for ix in targets:
        ix.clear()
        # re-populate through the index's own per-doc path so every
        # index type (unique/fulltext/spatial) rebuilds identically
        seen = 0
        for doc in db.browse_class(ix.class_name, polymorphic=True):
            ix.index_doc(doc)
            seen += 1
        total += seen
    return [
        Result(
            props={
                "operation": "rebuild index",
                "indexes": len(targets),
                "records": total,
            }
        )
    ]


def _grant_revoke(db, stmt) -> List[Result]:
    from orientdb_tpu.models.security import ALL

    sec = _security_of(db)
    role = sec.get_role(stmt.role)
    if role is None:
        raise CommandError(f"role '{stmt.role}' not found")
    op = stmt.permission.lower()
    # ALL expands to the four CRUD ops — Role stores op names, so the
    # literal 'all' would never match a permission check
    ops = ALL if op == "all" else (op,)
    if isinstance(stmt, A.GrantStatement):
        role.grant(stmt.resource, *ops)
        return [
            Result(
                props={
                    "operation": "grant",
                    "role": role.name,
                    "resource": stmt.resource,
                }
            )
        ]
    role.revoke(stmt.resource, *ops)
    return [
        Result(
            props={
                "operation": "revoke",
                "role": role.name,
                "resource": stmt.resource,
            }
        )
    ]


def _find_references(db, stmt: A.FindReferencesStatement) -> List[Result]:
    """[E] OFindReferencesStatement: scan link-bearing fields (and edge
    endpoints) for records pointing at the rid."""
    target = RID.parse(stmt.rid)
    classes = {c.lower() for c in stmt.classes}
    referers = []
    for cls in db.schema.classes():
        if cls.abstract:
            continue
        if classes and cls.name.lower() not in classes:
            continue
        for doc in db.browse_class(cls.name, polymorphic=False):
            found = False
            if isinstance(doc, Edge) and (
                doc.out_rid == target or doc.in_rid == target
            ):
                found = True
            if not found:
                for v in doc.fields().values():
                    if v == target or (
                        isinstance(v, (list, tuple, set)) and target in v
                    ):
                        found = True
                        break
            if found:
                referers.append(doc.rid)
    return [
        Result(props={"rid": stmt.rid, "referredBy": [str(r) for r in referers]})
    ]


# -- INSERT / CREATE --------------------------------------------------------


def _field_map(ctx, set_fields) -> Dict[str, object]:
    return {name: evaluate(ctx, e) for name, e in set_fields}


def _insert(db, stmt: A.InsertStatement, ctx, params) -> List[Result]:
    class_name = stmt.class_name
    if class_name is None and stmt.cluster is not None:
        cls = db.schema.get_class(stmt.cluster)
        if cls is None:
            raise CommandError(f"cluster '{stmt.cluster}' not found")
        class_name = cls.name
    assert class_name is not None
    cls = db.schema.get_class(class_name)
    if cls is not None and cls.is_edge_type:
        raise CommandError("cannot INSERT INTO an edge class; use CREATE EDGE")
    rows_fields: List[Dict[str, object]] = []
    if stmt.set_fields:
        rows_fields.append(_field_map(ctx, stmt.set_fields))
    elif stmt.content is not None:
        content = evaluate(ctx, stmt.content)
        for m in as_list(content):
            if not isinstance(m, dict):
                raise CommandError("INSERT CONTENT expects map(s)")
            rows_fields.append(dict(m))
    elif stmt.from_select is not None:
        from orientdb_tpu.exec.oracle import execute_statement

        for r in execute_statement(db, stmt.from_select, params, parent_ctx=ctx):
            if r.is_element:
                rows_fields.append(r.element.fields())  # type: ignore[union-attr]
            else:
                rows_fields.append({k: r.get_property(k) for k in r.property_names()})
    else:
        rows_fields.append({})
    out = []
    for fields in rows_fields:
        if cls is not None and cls.is_vertex_type:
            doc: Document = db.new_vertex(class_name, **fields)
        else:
            doc = db.new_element(class_name, **fields)
        if stmt.return_expr is not None:
            rctx = EvalContext(db, current=doc, params=ctx.params, parent=ctx)
            out.append(Result(props={"result": evaluate(rctx, stmt.return_expr)}))
        else:
            out.append(Result(element=doc))
    return out


def _create_vertex(db, stmt: A.CreateVertexStatement, ctx) -> List[Result]:
    fields = _field_map(ctx, stmt.set_fields)
    if stmt.content is not None:
        c = evaluate(ctx, stmt.content)
        if not isinstance(c, dict):
            raise CommandError("CREATE VERTEX CONTENT expects a map")
        fields.update(c)
    v = db.new_vertex(stmt.class_name, **fields)
    return [Result(element=v)]


def _resolve_vertices(db, ctx, expr: A.Expression) -> List[Vertex]:
    val = evaluate(ctx, expr)
    out = []
    for item in as_list(resolve_links(ctx, val)):
        from orientdb_tpu.exec.result import Result as _R

        if isinstance(item, _R) and item.is_element:
            item = item.element
        if isinstance(item, Vertex):
            out.append(item)
        elif isinstance(item, RID):
            d = db.load(item)
            if isinstance(d, Vertex):
                out.append(d)
    return out


def _create_edge(db, stmt: A.CreateEdgeStatement, ctx, params) -> List[Result]:
    sources = _resolve_vertices(db, ctx, stmt.from_expr)
    targets = _resolve_vertices(db, ctx, stmt.to_expr)
    if not sources or not targets:
        raise CommandError("CREATE EDGE: FROM/TO resolved to no vertices")
    fields = _field_map(ctx, stmt.set_fields)
    if stmt.content is not None:
        c = evaluate(ctx, stmt.content)
        if not isinstance(c, dict):
            raise CommandError("CREATE EDGE CONTENT expects a map")
        fields.update(c)
    out = []
    for s in sources:
        for t in targets:
            e = db.new_edge(stmt.class_name, s, t, **fields)
            out.append(Result(element=e))
    return out


# -- UPDATE / DELETE --------------------------------------------------------


def _target_docs(db, target: A.Target, where, limit, ctx, params) -> List[Document]:
    from orientdb_tpu.exec.oracle import resolve_target_rows

    docs = []
    for row in resolve_target_rows(db, target, ctx):
        doc = row if isinstance(row, Document) else (
            row.element if isinstance(row, Result) and row.is_element else None
        )
        if doc is None:
            continue
        if where is not None:
            rctx = EvalContext(db, current=doc, params=params, parent=ctx)
            if not truthy(evaluate(rctx, where)):
                continue
        docs.append(doc)
    if limit is not None:
        n = int(evaluate(ctx, limit))
        docs = docs[:n]
    return docs


def _update(db, stmt: A.UpdateStatement, ctx, params) -> List[Result]:
    docs = _target_docs(db, stmt.target, stmt.where, stmt.limit, ctx, params)
    if not docs and stmt.upsert:
        # derive fields from a conjunction of equality predicates, as the
        # reference's UPSERT does
        fields = {}
        _collect_eq_fields(stmt.where, fields, ctx)
        if not isinstance(stmt.target, A.ClassTarget):
            raise CommandError("UPSERT requires a class target")
        doc = db.new_element(stmt.target.name, **fields)
        docs = [doc]
    before = []
    if stmt.return_mode == "BEFORE":
        before = [Result(props=d.to_dict()) for d in docs]
    for doc in docs:
        rctx = EvalContext(db, current=doc, params=params, parent=ctx)
        for op in stmt.ops:
            _apply_op(db, doc, op, rctx)
        db.save(doc)
    if stmt.return_mode == "BEFORE":
        return before
    if stmt.return_mode == "AFTER":
        return [Result(element=d) for d in docs]
    return [Result(props={"count": len(docs)})]


def _collect_eq_fields(where, fields: Dict[str, object], ctx) -> None:
    if isinstance(where, A.Binary):
        if where.op == "AND":
            _collect_eq_fields(where.left, fields, ctx)
            _collect_eq_fields(where.right, fields, ctx)
        elif where.op == "=" and isinstance(where.left, A.Identifier):
            fields[where.left.name] = evaluate(ctx, where.right)


def _apply_op(db, doc: Document, op: A.UpdateOp, rctx) -> None:
    if op.kind == "SET":
        for name, e in op.items:
            doc.set(name, evaluate(rctx, e))
    elif op.kind == "INCREMENT":
        for name, e in op.items:
            cur = doc.get(name) or 0
            doc.set(name, cur + evaluate(rctx, e))
    elif op.kind == "REMOVE":
        for name, e in op.items:
            val = evaluate(rctx, e)
            if val is None:
                doc.remove_field(name)
            else:
                lst = as_list(doc.get(name))
                doc.set(name, [x for x in lst if x != val])
    elif op.kind == "CONTENT":
        new = evaluate(rctx, op.items[0][1])
        if not isinstance(new, dict):
            raise CommandError("UPDATE CONTENT expects a map")
        for name in list(doc.field_names()):
            doc.remove_field(name)
        doc.update(**new)
    elif op.kind == "MERGE":
        new = evaluate(rctx, op.items[0][1])
        if not isinstance(new, dict):
            raise CommandError("UPDATE MERGE expects a map")
        doc.update(**new)
    else:
        raise CommandError(f"unsupported UPDATE op {op.kind}")


def _delete(db, stmt: A.DeleteStatement, ctx, params) -> List[Result]:
    where = stmt.where
    if stmt.kind == "EDGE" and (stmt.edge_from is not None or stmt.edge_to is not None):
        docs = _edge_endpoint_docs(db, stmt, ctx)
        if where is not None:
            docs = [
                d
                for d in docs
                if truthy(
                    evaluate(EvalContext(db, current=d, params=params, parent=ctx), where)
                )
            ]
        if stmt.limit is not None:
            docs = docs[: int(evaluate(ctx, stmt.limit))]
    else:
        docs = _target_docs(db, stmt.target, where, stmt.limit, ctx, params)
    count = 0
    for doc in docs:
        if stmt.kind == "VERTEX" and not isinstance(doc, Vertex):
            continue
        if stmt.kind == "EDGE" and not isinstance(doc, Edge):
            continue
        db.delete(doc)
        count += 1
    return [Result(props={"count": count})]


def _edge_endpoint_docs(db, stmt: A.DeleteStatement, ctx) -> List[Edge]:
    src_rids = {
        v.rid for v in _resolve_vertices(db, ctx, stmt.edge_from)
    } if stmt.edge_from is not None else None
    dst_rids = {
        v.rid for v in _resolve_vertices(db, ctx, stmt.edge_to)
    } if stmt.edge_to is not None else None
    cls = stmt.target.name if isinstance(stmt.target, A.ClassTarget) else "E"
    out = []
    for doc in db.browse_class(cls):
        if not isinstance(doc, Edge):
            continue
        if src_rids is not None and doc.out_rid not in src_rids:
            continue
        if dst_rids is not None and doc.in_rid not in dst_rids:
            continue
        out.append(doc)
    return out


# -- DDL --------------------------------------------------------------------


def _create_class(db, stmt: A.CreateClassStatement) -> List[Result]:
    if db.schema.exists_class(stmt.name):
        if stmt.if_not_exists:
            return [Result(props={"operation": "create class", "existed": True})]
        raise CommandError(f"class '{stmt.name}' already exists")
    db.schema.create_class(stmt.name, superclasses=stmt.superclasses, abstract=stmt.abstract)
    return [Result(props={"operation": "create class", "name": stmt.name})]


def _create_property(db, stmt: A.CreatePropertyStatement) -> List[Result]:
    cls = db.schema.get_class_or_raise(stmt.class_name)
    if stmt.property_name in cls.properties:
        if stmt.if_not_exists:
            return [Result(props={"operation": "create property", "existed": True})]
        raise CommandError(f"property '{stmt.property_name}' already exists")
    try:
        ptype = PropertyType[stmt.property_type]
    except KeyError:
        raise CommandError(f"unknown property type {stmt.property_type}")
    cls.create_property(stmt.property_name, ptype, linked_class=stmt.linked_class)
    return [Result(props={"operation": "create property"})]


def _create_index(db, stmt: A.CreateIndexStatement) -> List[Result]:
    if stmt.class_name is None:
        raise CommandError("CREATE INDEX needs a class (use name ON class (fields) or Class.field)")
    metadata = None
    if stmt.metadata is not None:
        from orientdb_tpu.exec.eval import EvalContext, evaluate

        metadata = evaluate(EvalContext(db), stmt.metadata)
        if not isinstance(metadata, dict):
            raise CommandError("CREATE INDEX METADATA must be a map literal")
    db.indexes.create_index(
        stmt.name, stmt.class_name, list(stmt.fields), stmt.index_type,
        engine=stmt.engine, metadata=metadata,
    )
    return [Result(props={"operation": "create index", "name": stmt.name})]


def _drop_class(db, stmt: A.DropClassStatement) -> List[Result]:
    if not db.schema.exists_class(stmt.name):
        if stmt.if_exists:
            return [Result(props={"operation": "drop class", "existed": False})]
        raise CommandError(f"class '{stmt.name}' not found")
    db.drop_class(stmt.name)
    return [Result(props={"operation": "drop class"})]


def _alter_property(db, stmt: A.AlterPropertyStatement, ctx) -> List[Result]:
    cls = db.schema.get_class_or_raise(stmt.class_name)
    prop = cls.get_property(stmt.property_name)
    if prop is None:
        raise CommandError(f"property '{stmt.property_name}' not found")
    value = evaluate(ctx, stmt.value)
    attr = stmt.attribute.upper()
    if attr == "MANDATORY":
        prop.mandatory = bool(value)
    elif attr == "NOTNULL":
        prop.not_null = bool(value)
    elif attr == "READONLY":
        prop.read_only = bool(value)
    elif attr == "MIN":
        prop.min_value = value
    elif attr == "MAX":
        prop.max_value = value
    else:
        raise CommandError(f"unsupported ALTER PROPERTY attribute {attr}")
    if db.schema.on_ddl is not None:
        db.schema.on_ddl(
            {
                "op": "alter_property",
                "class": cls.name,
                "name": prop.name,
                "attribute": attr,
                "value": value,
            }
        )
    return [Result(props={"operation": "alter property"})]
