"""Expression evaluator (host side).

Analog of the reference's interpreted expression tree ([E]
core/.../sql/executor + OSQLFunction* / OSQLMethod* registries): evaluates
the AST of `orientdb_tpu/sql/ast.py` against one record/row at a time. This
is the *oracle* semantics definition — the TPU predicate compiler
(`orientdb_tpu/ops/predicates.py`) must agree with it on the columnar subset
(numeric/string comparisons, boolean logic, arithmetic), which parity tests
enforce.

Null semantics follow OrientDB: any comparison with null is false (only
IS NULL / IS NOT NULL see nulls); arithmetic with null yields null;
AND/OR use three-valued-ish collapse where null acts as false.
"""

from __future__ import annotations

import math
import re
from typing import Dict, Iterable, List, Optional

from orientdb_tpu.models.record import Document, Edge, Vertex, Direction
from orientdb_tpu.models.rid import RID
from orientdb_tpu.sql import ast as A


class EvalError(Exception):
    pass


class EvalContext:
    """Evaluation scope: current record/row, query params, $variables.

    `current` may be a Document, a dict-like row, or a plain value (inside
    method chains). `variables` holds LET results and MATCH context
    ($matched, $depth, $path…). `parent` chains nested scopes (subqueries,
    traversal)."""

    __slots__ = ("db", "current", "params", "variables", "parent")

    def __init__(self, db, current=None, params=None, variables=None, parent=None):
        self.db = db
        self.current = current
        self.params = params or {}
        self.variables: Dict[str, object] = variables or {}
        self.parent: Optional[EvalContext] = parent

    def child(self, current=None, variables=None) -> "EvalContext":
        return EvalContext(
            self.db,
            current=current if current is not None else self.current,
            params=self.params,
            variables=variables if variables is not None else {},
            parent=self,
        )

    def lookup_var(self, name: str):
        ctx: Optional[EvalContext] = self
        while ctx is not None:
            if name in ctx.variables:
                return ctx.variables[name]
            ctx = ctx.parent
        return None

    def has_var(self, name: str) -> bool:
        ctx: Optional[EvalContext] = self
        while ctx is not None:
            if name in ctx.variables:
                return True
            ctx = ctx.parent
        return False


# ---------------------------------------------------------------------------
# value helpers
# ---------------------------------------------------------------------------


def get_prop(obj, name: str):
    """Property access on whatever the executor passes around."""
    if obj is None:
        return None
    if isinstance(obj, Document):
        v = obj.get(name)
        # OrientDB resolves link fields transparently on chained access.
        return v
    if isinstance(obj, dict):
        if name in obj:
            return obj[name]
        if name.startswith("@"):
            return obj.get(name)
        return None
    # Result rows
    from orientdb_tpu.exec.result import Result

    if isinstance(obj, Result):
        return obj.get_property(name)
    if isinstance(obj, (list, tuple)):
        # field access over a collection maps over items (OrientDB behavior
        # for e.g. out('E').name)
        out = []
        for item in obj:
            v = get_prop(item, name)
            if isinstance(v, (list, tuple)):
                out.extend(v)
            elif v is not None:
                out.append(v)
        return out
    return None


def resolve_links(ctx: EvalContext, value):
    """RIDs → records, lists thereof (for chained navigation)."""
    if isinstance(value, RID):
        return ctx.db.load(value)
    if isinstance(value, (list, tuple)):
        return [resolve_links(ctx, v) for v in value]
    return value


def is_collection(v) -> bool:
    return isinstance(v, (list, tuple, set))


def as_list(v) -> List[object]:
    if v is None:
        return []
    if isinstance(v, (list, tuple, set)):
        return list(v)
    return [v]


def _numeric(v) -> bool:
    return isinstance(v, (int, float)) and not isinstance(v, bool)


def compare(a, b) -> Optional[int]:
    """3-way compare; None if incomparable (null or type mismatch)."""
    if a is None or b is None:
        return None
    if isinstance(a, Document):
        a = a.rid
    if isinstance(b, Document):
        b = b.rid
    if isinstance(a, RID) and isinstance(b, RID):
        return (a > b) - (a < b)
    if isinstance(a, bool) or isinstance(b, bool):
        if isinstance(a, bool) and isinstance(b, bool):
            return (a > b) - (a < b)
        return None
    if _numeric(a) and _numeric(b):
        return (a > b) - (a < b)
    if isinstance(a, str) and isinstance(b, str):
        return (a > b) - (a < b)
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        for x, y in zip(a, b):
            c = compare(x, y)
            if c is None:
                return None
            if c != 0:
                return c
        return (len(a) > len(b)) - (len(a) < len(b))
    return None


def values_equal(a, b) -> bool:
    if a is None or b is None:
        return False
    c = compare(a, b)
    if c is not None:
        return c == 0
    return a == b


def like_match(value, pattern) -> bool:
    if not isinstance(value, str) or not isinstance(pattern, str):
        return False
    rx = re.escape(pattern).replace("%", ".*").replace("_", ".")
    return re.fullmatch(rx, value, flags=re.DOTALL) is not None


# ---------------------------------------------------------------------------
# graph navigation helpers (shared with oracle)
# ---------------------------------------------------------------------------

_DIRS = {"out": Direction.OUT, "in": Direction.IN, "both": Direction.BOTH}


def nav_vertices(ctx: EvalContext, base, direction: str, classes) -> List[Vertex]:
    out: List[Vertex] = []
    for item in as_list(resolve_links(ctx, base)):
        if isinstance(item, Vertex):
            if classes:
                for cname in classes:
                    out.extend(item.vertices(_DIRS[direction], cname))
            else:
                out.extend(item.vertices(_DIRS[direction]))
        elif isinstance(item, Edge):
            # out()/in() on an edge → endpoint vertex (outV/inV semantics)
            if direction == "out":
                out.append(item.from_vertex())
            elif direction == "in":
                out.append(item.to_vertex())
            else:
                out.extend([item.from_vertex(), item.to_vertex()])
    return out


def nav_edges(ctx: EvalContext, base, direction: str, classes) -> List[Edge]:
    out: List[Edge] = []
    for item in as_list(resolve_links(ctx, base)):
        if isinstance(item, Vertex):
            if classes:
                for cname in classes:
                    out.extend(item.edges(_DIRS[direction], cname))
            else:
                out.extend(item.edges(_DIRS[direction]))
    return out


# ---------------------------------------------------------------------------
# SQL functions & methods
# ---------------------------------------------------------------------------

AGGREGATE_FUNCTIONS = {"count", "sum", "min", "max", "avg"}


def _fn_coalesce(args):
    for a in args:
        if a is not None:
            return a
    return None


_MATH_FNS = {
    "abs": abs,
    "sqrt": math.sqrt,
    "floor": math.floor,
    "ceil": math.ceil,
    "exp": math.exp,
    "log": math.log,
}


def _as_vertex(ctx: EvalContext, v) -> Optional[Vertex]:
    """Resolve a graph-function endpoint argument: a Vertex, a RID, a
    rid string, or a single-element result list (``$a`` bindings)."""
    # RID is a NamedTuple: test it BEFORE the list/tuple unwrap
    if not isinstance(v, RID) and isinstance(v, (list, tuple)):
        v = v[0] if len(v) == 1 else None
    if isinstance(v, Vertex):
        return v
    if isinstance(v, Document):
        return None
    rid = None
    if isinstance(v, RID):
        rid = v
    elif isinstance(v, str) and v.startswith("#"):
        try:
            rid = RID.parse(v)
        except ValueError:
            return None
    if rid is not None and ctx.db is not None:
        doc = ctx.db.load(rid)
        return doc if isinstance(doc, Vertex) else None
    return None


def _path_direction(arg) -> Direction:
    d = str(arg or "BOTH").upper()
    return {
        "OUT": Direction.OUT,
        "IN": Direction.IN,
    }.get(d, Direction.BOTH)


def _shortest_path(ctx: EvalContext, args) -> List[RID]:
    """[E] OSQLFunctionShortestPath: unweighted BFS source→target.
    ``shortestPath(v1, v2 [, direction [, edgeClass [, {maxDepth}]]])``
    → list of rids INCLUDING both endpoints; [] when unreachable."""
    if len(args) < 2:
        return []
    src = _as_vertex(ctx, args[0])
    dst = _as_vertex(ctx, args[1])
    if src is None or dst is None:
        return []
    if src.rid == dst.rid:
        return [src.rid]
    direction = _path_direction(args[2] if len(args) > 2 else None)
    edge_class = args[3] if len(args) > 3 else None
    # the reference accepts a single class name OR a collection of them
    if isinstance(edge_class, str) or edge_class is None:
        edge_classes: List[Optional[str]] = [edge_class]
    else:
        edge_classes = list(edge_class) or [None]
    max_depth = None
    if len(args) > 4 and isinstance(args[4], dict):
        max_depth = args[4].get("maxDepth")
    parent: Dict[RID, RID] = {src.rid: src.rid}
    frontier = [src]
    depth = 0
    while frontier:
        depth += 1
        if max_depth is not None and depth > max_depth:
            return []
        nxt: List[Vertex] = []
        for v in frontier:
            for ec in edge_classes:
                for w in v.vertices(direction, ec):
                    if w.rid in parent:
                        continue
                    parent[w.rid] = v.rid
                    if w.rid == dst.rid:
                        path = [w.rid]
                        while path[-1] != src.rid:
                            path.append(parent[path[-1]])
                        path.reverse()
                        return path
                    nxt.append(w)
        frontier = nxt
    return []


def _dijkstra(ctx: EvalContext, args) -> List[Vertex]:
    """[E] OSQLFunctionDijkstra: cheapest path by a numeric EDGE field.
    ``dijkstra(v1, v2, weightField [, direction])`` → vertex list
    including both endpoints; [] when unreachable. Edges missing the
    weight field cost 1."""
    import heapq
    import itertools

    if len(args) < 3:
        return []
    src = _as_vertex(ctx, args[0])
    dst = _as_vertex(ctx, args[1])
    weight_field = str(args[2])
    if src is None or dst is None:
        return []
    direction = _path_direction(args[3] if len(args) > 3 else "OUT")
    tie = itertools.count()  # heap tiebreaker: vertices don't compare
    dist: Dict[RID, float] = {src.rid: 0.0}
    parent: Dict[RID, RID] = {}
    heap = [(0.0, next(tie), src)]
    done: set = set()
    while heap:
        d, _t, v = heapq.heappop(heap)
        if v.rid in done:
            continue
        done.add(v.rid)
        if v.rid == dst.rid:
            path = [v]
            cur = v.rid
            while cur != src.rid:
                cur = parent[cur]
                path.append(ctx.db.load(cur))
            path.reverse()
            return path
        for e in v.edges(direction):
            if direction is Direction.BOTH:
                other = e.in_rid if e.out_rid == v.rid else e.out_rid
            elif direction is Direction.OUT:
                if e.out_rid != v.rid:
                    continue
                other = e.in_rid
            else:
                if e.in_rid != v.rid:
                    continue
                other = e.out_rid
            w = e.get(weight_field)
            cost = float(w) if isinstance(w, (int, float)) else 1.0
            nd = d + cost
            if nd < dist.get(other, float("inf")):
                dist[other] = nd
                parent[other] = v.rid
                nv = ctx.db.load(other)
                if isinstance(nv, Vertex):
                    heapq.heappush(heap, (nd, next(tie), nv))
    return []


def eval_function(ctx: EvalContext, name: str, arg_exprs, evaluator) -> object:
    """Non-aggregate function dispatch ([E] OSQLFunctionFactory)."""
    name = name.lower()
    if name in ("out", "in", "both", "oute", "ine", "bothe", "outv", "inv"):
        classes = [evaluator(ctx, a) for a in arg_exprs]
        base = ctx.current
        if name in ("out", "in", "both"):
            return nav_vertices(ctx, base, name, classes)
        if name in ("oute", "ine", "bothe"):
            return nav_edges(ctx, base, name[:-1], classes)
        # outV/inV on edges
        items = as_list(resolve_links(ctx, base))
        res = []
        for e in items:
            if isinstance(e, Edge):
                res.append(e.from_vertex() if name == "outv" else e.to_vertex())
        return res
    args = [evaluator(ctx, a) for a in arg_exprs]
    if name == "coalesce" or name == "ifnull":
        return _fn_coalesce(args)
    if name == "if":
        return args[1] if args[0] else (args[2] if len(args) > 2 else None)
    if name == "format":
        return str(args[0]) % tuple(args[1:]) if len(args) > 1 else str(args[0])
    if name == "concat":
        return "".join("" if a is None else str(a) for a in args)
    if name == "first":
        lst = as_list(args[0])
        return lst[0] if lst else None
    if name == "last":
        lst = as_list(args[0])
        return lst[-1] if lst else None
    if name == "size":
        return len(as_list(args[0]))
    if name in ("search_index", "search_class"):
        # [E] the Lucene module's SEARCH_INDEX('Name', 'q') /
        # SEARCH_CLASS('q') WHERE functions: true when the current
        # record is in the fulltext query's match set (boolean/phrase/
        # prefix syntax handled by models/fulltext for Lucene-grade
        # indexes; plain token AND-match on the legacy index)
        cur = ctx.current
        if not isinstance(cur, Document):
            return False
        if name == "search_index":
            idx = ctx.db.indexes.get_index(str(args[0]))
            q = args[1]
        else:
            idx = next(
                (
                    i
                    for i in ctx.db.indexes.for_class(cur.class_name)
                    if getattr(i, "type", "").upper() == "FULLTEXT"
                ),
                None,
            )
            q = args[0]
        if idx is None or getattr(idx, "type", "").upper() != "FULLTEXT":
            raise ValueError(
                f"{name}: no fulltext index "
                f"({args[0] if name == 'search_index' else cur.class_name})"
            )
        # the WHERE evaluator calls this once PER ROW: memoize the match
        # set per (index, query) so the boolean query runs once per
        # statement, not once per candidate record. The cache lives on
        # the index object and is dropped on any (un)index mutation.
        cache = idx.__dict__.setdefault("_search_memo", {})
        key = str(q)
        rids = cache.get(key)
        if rids is None:
            matcher = getattr(idx, "match", None) or idx.search_all
            rids = frozenset(matcher(key))
            if len(cache) >= 64:
                cache.clear()
            cache[key] = rids
        return cur.rid in rids
    if name == "distinct":
        seen, out = set(), []
        for v in as_list(args[0]):
            k = str(v.rid) if isinstance(v, Document) else repr(v)
            if k not in seen:
                seen.add(k)
                out.append(v)
        return out
    if name == "unionall":
        out = []
        for a in args:
            out.extend(as_list(a))
        return out
    if name == "intersect":
        sets = [as_list(a) for a in args]
        if not sets:
            return []
        out = sets[0]
        for s in sets[1:]:
            out = [v for v in out if any(values_equal(v, w) or v is w for w in s)]
        return out
    if name == "difference":
        if not args:
            return []
        out = as_list(args[0])
        for s in args[1:]:
            sl = as_list(s)
            out = [v for v in out if not any(values_equal(v, w) or v is w for w in sl)]
        return out
    if name in ("list", "set"):
        vals = []
        for a in args:
            vals.extend(as_list(a))
        if name == "set":
            seen, out = set(), []
            for v in vals:
                k = str(v.rid) if isinstance(v, Document) else repr(v)
                if k not in seen:
                    seen.add(k)
                    out.append(v)
            return out
        return vals
    if name == "map":
        return {str(args[i]): args[i + 1] for i in range(0, len(args) - 1, 2)}
    if name == "distance":
        # [E] OSQLFunctionDistance: haversine over (lat1, lon1, lat2,
        # lon2); optional unit 'km'|'mi' (constants in utils/geo.py)
        from orientdb_tpu.utils.geo import MILE_UNITS, MILES_PER_KM, haversine_km

        if len(args) < 4 or any(not _numeric(a) for a in args[:4]):
            return None
        d = haversine_km(*args[:4])
        if len(args) > 4 and str(args[4]).lower() in MILE_UNITS:
            d *= MILES_PER_KM
        return d
    if name in _MATH_FNS:
        return None if args[0] is None else _MATH_FNS[name](args[0])
    if name == "shortestpath":
        return _shortest_path(ctx, args)
    if name == "dijkstra":
        return _dijkstra(ctx, args)
    if name == "astar":
        # [E] OSQLFunctionAstar — without coordinate heuristics the
        # honest admissible heuristic is 0, which IS Dijkstra; the
        # option map (4th arg) is accepted for direction
        d_args = list(args[:3])
        if len(args) > 3 and isinstance(args[3], dict):
            d_args.append(args[3].get("direction", "OUT"))
        return _dijkstra(ctx, d_args)
    if name == "date":
        # [E] OSQLFunctionDate: no args → now; 1 arg → parse/passthrough
        # (format args beyond that are passthrough too)
        if not args:
            import datetime

            return datetime.datetime.now().isoformat()
        return args[0]
    if name == "sysdate":
        import datetime

        return datetime.datetime.now().isoformat()
    if name == "uuid":
        import uuid as _uuid

        return str(_uuid.uuid4())
    if name == "expand":
        # expand() outside projections behaves as identity on the collection
        return args[0]
    if name == "sequence":
        # sequence('s').next()/.current()/.reset() ([E] OSequence in SQL)
        if ctx.db is None or not args:
            raise EvalError("sequence() needs a database and a name")
        return ctx.db.sequences.get_or_raise(str(args[0]))
    if ctx.db is not None and ctx.db._functions is not None:
        fn = ctx.db._functions.get(name)
        if fn is not None:
            return fn.invoke(ctx.db, args, parent_ctx=ctx)
    raise EvalError(f"unknown function '{name}'")


from orientdb_tpu.models.metadata import Sequence


def eval_method(ctx: EvalContext, base, name: str, args) -> object:
    """`value.method(args)` dispatch ([E] OSQLMethodFactory subset)."""
    m = name.lower()
    if isinstance(base, Sequence):
        if m == "next":
            return base.next()
        if m == "current":
            return base.current()
        if m == "reset":
            return base.reset()
        raise EvalError(f"sequence has no method '{name}'")
    if m in ("out", "in", "both"):
        return nav_vertices(ctx, base, m, args)
    if m in ("oute", "ine", "bothe"):
        return nav_edges(ctx, base, m[:-1], args)
    if m == "outv":
        return [e.from_vertex() for e in as_list(base) if isinstance(e, Edge)]
    if m == "inv":
        return [e.to_vertex() for e in as_list(base) if isinstance(e, Edge)]
    if m == "size":
        if base is None:
            return 0
        return len(as_list(base)) if not isinstance(base, (str, dict)) else len(base)
    if m == "length":
        return len(base) if isinstance(base, (str, list, tuple)) else None
    if base is None:
        return None
    if m == "tolowercase":
        return str(base).lower()
    if m == "touppercase":
        return str(base).upper()
    if m == "trim":
        return str(base).strip()
    if m == "asstring":
        return str(base.rid) if isinstance(base, Document) else str(base)
    if m == "asinteger":
        try:
            return int(float(base))
        except (TypeError, ValueError):
            return None
    if m == "asfloat":
        try:
            return float(base)
        except (TypeError, ValueError):
            return None
    if m == "asboolean":
        if isinstance(base, str):
            return base.lower() == "true"
        return bool(base)
    if m == "aslist":
        return as_list(base)
    if m == "asset":
        return list(dict.fromkeys(as_list(base)))
    if m == "substring":
        s = str(base)
        if len(args) == 1:
            return s[int(args[0]) :]
        return s[int(args[0]) : int(args[1])]
    if m == "left":
        return str(base)[: int(args[0])]
    if m == "right":
        return str(base)[-int(args[0]) :]
    if m == "charat":
        s = str(base)
        i = int(args[0])
        return s[i] if 0 <= i < len(s) else None
    if m == "indexof":
        return str(base).find(str(args[0]))
    if m == "split":
        return str(base).split(str(args[0]))
    if m == "replace":
        return str(base).replace(str(args[0]), str(args[1]))
    if m == "append":
        return str(base) + str(args[0])
    if m == "prefix":
        return str(args[0]) + str(base)
    if m == "keys":
        return list(base.keys()) if isinstance(base, dict) else (
            base.field_names() if isinstance(base, Document) else None
        )
    if m == "values":
        return list(base.values()) if isinstance(base, dict) else None
    if m == "type":
        return type(base).__name__
    if m == "javatype":
        return type(base).__name__
    if m == "field":
        return get_prop(base, str(args[0]))
    if m == "format":
        return format(base, str(args[0])) if args else str(base)
    if m == "include":
        if isinstance(base, Document):
            return {k: base.get(k) for k in map(str, args)}
        return base
    if m == "exclude":
        if isinstance(base, Document):
            d = base.to_dict()
            for k in map(str, args):
                d.pop(k, None)
            return d
        return base
    raise EvalError(f"unknown method '{name}'")


# ---------------------------------------------------------------------------
# the evaluator
# ---------------------------------------------------------------------------


def evaluate(ctx: EvalContext, expr: A.Expression):
    if isinstance(expr, A.Literal):
        return expr.value
    if isinstance(expr, A.Star):
        return ctx.current
    if isinstance(expr, A.RIDLiteral):
        return RID(expr.cluster, expr.position)
    if isinstance(expr, A.Parameter):
        if expr.name is not None:
            if expr.name not in ctx.params:
                raise EvalError(f"missing parameter :{expr.name}")
            return ctx.params[expr.name]
        try:
            return ctx.params[expr.index]
        except (KeyError, IndexError):
            raise EvalError(f"missing positional parameter ?{expr.index}")
    if isinstance(expr, A.ContextVar):
        name = expr.name
        if name == "current":
            # nearest non-None current up the scope chain (a subquery's
            # FROM $current resolves before the subquery has rows)
            c: Optional[EvalContext] = ctx
            while c is not None:
                if c.current is not None:
                    return c.current
                c = c.parent
            return None
        if name == "parent":
            return ctx.parent
        if ctx.has_var(name):
            return ctx.lookup_var(name)
        return None
    if isinstance(expr, A.Identifier):
        name = expr.name
        # identifier resolution order: bound variable (MATCH alias / LET),
        # then field of current record
        if ctx.has_var(name):
            return ctx.lookup_var(name)
        return get_prop(ctx.current, name)
    if isinstance(expr, A.ListExpr):
        return [evaluate(ctx, e) for e in expr.items]
    if isinstance(expr, A.MapExpr):
        return {k: evaluate(ctx, v) for k, v in expr.pairs}
    if isinstance(expr, A.FieldAccess):
        base = evaluate(ctx, expr.base)
        base = resolve_links(ctx, base)
        return get_prop(base, expr.name)
    if isinstance(expr, A.IndexAccess):
        base = evaluate(ctx, expr.base)
        idx = evaluate(ctx, expr.index)
        if base is None:
            return None
        try:
            if isinstance(base, dict):
                return base.get(idx)
            return as_list(base)[int(idx)]
        except (IndexError, TypeError, ValueError):
            return None
    if isinstance(expr, A.MethodCall):
        base = evaluate(ctx, expr.base)
        args = [evaluate(ctx, a) for a in expr.args]
        return eval_method(ctx, resolve_links(ctx, base), expr.name, args)
    if isinstance(expr, A.FunctionCall):
        if expr.name == "$subquery":
            from orientdb_tpu.exec.oracle import execute_statement

            sub = expr.args[0].value  # type: ignore[union-attr]
            rows = execute_statement(ctx.db, sub, ctx.params, parent_ctx=ctx)
            out = []
            for r in rows:
                out.append(r.element if r.is_element else r)
            return out
        if expr.name in AGGREGATE_FUNCTIONS:
            raise EvalError(
                f"aggregate {expr.name}() outside aggregation context"
            )
        return eval_function(ctx, expr.name, expr.args, evaluate)
    if isinstance(expr, A.Unary):
        v = evaluate(ctx, expr.expr)
        if expr.op == "NOT":
            return not truthy(v)
        if v is None:
            return None
        return -v if expr.op == "-" else +v
    if isinstance(expr, A.Between):
        v = evaluate(ctx, expr.expr)
        lo = evaluate(ctx, expr.low)
        hi = evaluate(ctx, expr.high)
        c1 = compare(v, lo)
        c2 = compare(v, hi)
        return c1 is not None and c2 is not None and c1 >= 0 and c2 <= 0
    if isinstance(expr, A.IsNull):
        v = evaluate(ctx, expr.expr)
        return (v is not None) if expr.negated else (v is None)
    if isinstance(expr, A.IsDefined):
        defined = False
        e = expr.expr
        if isinstance(e, A.Identifier) and isinstance(ctx.current, Document):
            defined = e.name in ctx.current or e.name.startswith("@")
        elif isinstance(e, A.FieldAccess):
            base = resolve_links(ctx, evaluate(ctx, e.base))
            if isinstance(base, Document):
                defined = e.name in base
            elif isinstance(base, dict):
                defined = e.name in base
        else:
            defined = evaluate(ctx, e) is not None
        return (not defined) if expr.negated else defined
    if isinstance(expr, A.Binary):
        return eval_binary(ctx, expr)
    raise EvalError(f"cannot evaluate {expr!r}")


def truthy(v) -> bool:
    if v is None:
        return False
    if isinstance(v, bool):
        return v
    # OrientDB: non-boolean where results are not truthy-coerced; be strict
    # for numbers/strings but allow non-empty collection semantics for IN-ish
    if isinstance(v, (list, tuple, set)):
        return len(v) > 0
    return bool(v)


def eval_binary(ctx: EvalContext, expr: A.Binary):
    op = expr.op
    if op == "AND":
        return truthy(evaluate(ctx, expr.left)) and truthy(evaluate(ctx, expr.right))
    if op == "OR":
        return truthy(evaluate(ctx, expr.left)) or truthy(evaluate(ctx, expr.right))
    left = evaluate(ctx, expr.left)
    right = evaluate(ctx, expr.right)
    if op == "=":
        if isinstance(left, Document) or isinstance(right, Document) or isinstance(
            left, RID
        ) or isinstance(right, RID):
            lr = left.rid if isinstance(left, Document) else left
            rr = right.rid if isinstance(right, Document) else right
            return lr == rr
        return values_equal(left, right)
    if op == "!=":
        if left is None or right is None:
            return False
        lr = left.rid if isinstance(left, Document) else left
        rr = right.rid if isinstance(right, Document) else right
        if isinstance(lr, RID) or isinstance(rr, RID):
            return lr != rr
        return not values_equal(left, right)
    if op in ("<", "<=", ">", ">="):
        c = compare(left, right)
        if c is None:
            return False
        return {"<": c < 0, "<=": c <= 0, ">": c > 0, ">=": c >= 0}[op]
    if op == "LIKE":
        return like_match(left, right)
    if op == "MATCHES":
        if not isinstance(left, str) or not isinstance(right, str):
            return False
        return re.fullmatch(right, left) is not None
    if op == "IN":
        items = as_list(right)
        if isinstance(left, Document) or isinstance(left, RID):
            lrid = left.rid if isinstance(left, Document) else left
            for it in items:
                irid = it.rid if isinstance(it, Document) else it
                if irid == lrid:
                    return True
            return False
        return any(values_equal(left, it) for it in items)
    if op == "CONTAINS":
        items = as_list(left)
        if isinstance(right, Document) or isinstance(right, RID):
            rrid = right.rid if isinstance(right, Document) else right
            return any(
                (it.rid if isinstance(it, Document) else it) == rrid for it in items
            )
        return any(values_equal(it, right) for it in items)
    if op == "CONTAINSANY":
        items = as_list(left)
        return any(any(values_equal(it, r) for it in items) for r in as_list(right))
    if op == "CONTAINSALL":
        items = as_list(left)
        return all(any(values_equal(it, r) for it in items) for r in as_list(right))
    if op == "CONTAINSKEY":
        return isinstance(left, dict) and right in left
    if op == "CONTAINSVALUE":
        return isinstance(left, dict) and any(
            values_equal(v, right) for v in left.values()
        )
    if op == "CONTAINSTEXT":
        return isinstance(left, str) and isinstance(right, str) and right in left
    if op == "INSTANCEOF":
        name = right if isinstance(right, str) else str(right)
        if isinstance(left, Document):
            cls = ctx.db.schema.get_class(left.class_name)
            return cls is not None and cls.is_subclass_of(name)
        return False
    if op in ("+", "-", "*", "/", "%", "||"):
        if op == "||" or (op == "+" and (isinstance(left, str) or isinstance(right, str))):
            if left is None or right is None:
                return None
            return str(left) + str(right)
        if op == "+" and is_collection(left):
            return as_list(left) + as_list(right)
        if left is None or right is None:
            return None
        if not (_numeric(left) and _numeric(right)):
            raise EvalError(f"non-numeric operands for {op}: {left!r}, {right!r}")
        if op == "+":
            return left + right
        if op == "-":
            return left - right
        if op == "*":
            return left * right
        if op == "/":
            if right == 0:
                return None
            # OrientDB integer division stays integral
            if isinstance(left, int) and isinstance(right, int):
                return left // right if left % right == 0 else left / right
            return left / right
        if op == "%":
            return left % right if right != 0 else None
    raise EvalError(f"unknown operator {op}")


def contains_aggregate(expr: A.Expression) -> bool:
    if isinstance(expr, A.FunctionCall):
        if expr.name in AGGREGATE_FUNCTIONS:
            return True
        return any(contains_aggregate(a) for a in expr.args)
    if isinstance(expr, A.Binary):
        return contains_aggregate(expr.left) or contains_aggregate(expr.right)
    if isinstance(expr, A.Unary):
        return contains_aggregate(expr.expr)
    if isinstance(expr, (A.FieldAccess,)):
        return contains_aggregate(expr.base)
    if isinstance(expr, A.MethodCall):
        return contains_aggregate(expr.base) or any(
            contains_aggregate(a) for a in expr.args
        )
    if isinstance(expr, A.IndexAccess):
        return contains_aggregate(expr.base) or contains_aggregate(expr.index)
    return False
