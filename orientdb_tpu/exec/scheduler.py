"""Scheduled events.

Analog of the reference's scheduler ([E] core/.../schedule/OScheduler +
OScheduledEvent: events are ``OSchedule`` RECORDS — name, a Quartz-like
cron ``rule``, the stored ``function`` to invoke, ``arguments`` — and a
scheduler thread fires each event when its rule matches). Events here
live as documents of the ``OSchedule`` class, so they replicate,
survive restarts with the WAL, and are managed with plain SQL
(``INSERT INTO OSchedule SET name='x', rule='0/5 * * * * ?',
function='f'``) exactly like the reference.

Rules are 6-field seconds-resolution cron (sec min hour dom mon dow),
with ``*``, ``?``, lists ``a,b``, ranges ``a-b``, and steps ``*/n`` /
``a/n``. The scheduler thread ticks once per second; a tick runs every
enabled event whose rule matches that second (at-most-once per second,
the reference's semantics). Execution = invoking the named stored
function (models/metadata.StoredFunction) with the event's arguments.

Divergence, documented: the thread is started explicitly
(``db.scheduler.start()``) rather than with database open — tests and
embedded uses stay thread-free by default.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics

log = get_logger("scheduler")

SCHEDULE_CLASS = "OSchedule"


class CronError(Exception):
    pass


def _parse_field(spec: str, lo: int, hi: int) -> Optional[frozenset]:
    """One cron field → matching set, or None for the wildcard."""
    if spec in ("*", "?"):
        return None
    out = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronError(f"bad step {step_s!r}") from None
            if step <= 0:
                raise CronError(f"bad step {step}")
        if part in ("*", "?", ""):
            lo2, hi2 = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            try:
                lo2, hi2 = int(a), int(b)
            except ValueError:
                raise CronError(f"bad range {part!r}") from None
        else:
            try:
                lo2 = hi2 = int(part)
            except ValueError:
                raise CronError(f"bad value {part!r}") from None
            if step != 1:
                hi2 = hi  # Quartz 'a/n': from a to max, every n
        if lo2 < lo or hi2 > hi:
            raise CronError(f"{part!r} outside [{lo}, {hi}]")
        if lo2 > hi2:
            # a reversed range matches nothing: the event would
            # validate eagerly yet sit latent forever
            raise CronError(f"reversed range {part!r}")
        out.update(range(lo2, hi2 + 1, step))
    return frozenset(out)


class CronRule:
    """Six-field seconds cron: sec min hour day-of-month month
    day-of-week (0=Sunday, like the reference's Quartz 1=SUN shifted
    to the Python convention; both 0 and 7 mean Sunday)."""

    __slots__ = ("text", "_fields")

    _BOUNDS = [(0, 59), (0, 59), (0, 23), (1, 31), (1, 12), (0, 7)]

    def __init__(self, text: str) -> None:
        parts = text.split()
        if len(parts) == 5:
            # classic 5-field cron: implicit seconds-0
            parts = ["0"] + parts
        if len(parts) != 6:
            raise CronError(
                f"rule {text!r}: expected 5 or 6 cron fields"
            )
        self.text = text
        self._fields = [
            _parse_field(p, lo, hi)
            for p, (lo, hi) in zip(parts, self._BOUNDS)
        ]

    def matches(self, t: Optional[float] = None) -> bool:
        lt = time.localtime(t if t is not None else time.time())
        dow = (lt.tm_wday + 1) % 7  # Python Mon=0 → cron Sun=0
        for field, v in zip(
            self._fields[:3], (lt.tm_sec, lt.tm_min, lt.tm_hour)
        ):
            if field is not None and v not in field:
                return False
        if self._fields[4] is not None and lt.tm_mon not in self._fields[4]:
            return False
        # Vixie-cron semantics: when BOTH day-of-month and day-of-week
        # are restricted, the rule fires when EITHER matches ('0 9 1 * 1'
        # = 09:00 on the 1st OR on Mondays); a single restricted field
        # applies alone
        f_dom, f_dow = self._fields[3], self._fields[5]
        dom_ok = f_dom is None or lt.tm_mday in f_dom
        dow_ok = f_dow is None or dow in f_dow or (dow == 0 and 7 in f_dow)
        if f_dom is not None and f_dow is not None:
            return dom_ok or dow_ok
        return dom_ok and dow_ok


class Scheduler:
    """Per-database event scheduler reading ``OSchedule`` documents.

    Fields per event record ([E] OScheduledEvent's properties): ``name``
    (unique), ``rule`` (cron), ``function`` (stored function name),
    ``arguments`` (list, optional), ``enabled`` (default true). Runtime
    state (last fire second, run counter) stays off-record so the
    documents replicate cleanly.
    """

    TICK = 0.25  # seconds between wakeups; fires are per-second exact

    def __init__(self, db) -> None:
        self.db = db
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        #: event name → last fired epoch second (at-most-once/second)
        self._last_fired: Dict[str, int] = {}
        #: event name → executions (introspection + tests)
        self.run_counts: Dict[str, int] = {}
        self._rules: Dict[str, CronRule] = {}
        #: rule texts already reported as unparseable (log once)
        self._bad_rules: set = set()
        #: last epoch second the event set was evaluated for — ticks
        #: within one second return early, and a tick arriving LATE
        #: evaluates every second it slept through (a slow function or
        #: GC pause must not silently skip a sparse rule's one second)
        self._last_scan_sec: Optional[int] = None

    # -- management ----------------------------------------------------------

    def _ensure_class(self) -> None:
        if not self.db.schema.exists_class(SCHEDULE_CLASS):
            self.db.schema.create_class(SCHEDULE_CLASS)

    def schedule(
        self,
        name: str,
        rule: str,
        function: str,
        arguments: Optional[List] = None,
    ):
        """Create (or replace) an event record; the rule validates
        eagerly so a bad cron never sits latent in the store."""
        CronRule(rule)
        self._ensure_class()
        for doc in list(self.db.browse_class(SCHEDULE_CLASS)):
            if doc.get("name") == name:
                self.db.delete(doc)
        return self.db.new_element(
            SCHEDULE_CLASS,
            name=name,
            rule=rule,
            function=function,
            arguments=list(arguments or []),
            enabled=True,
        )

    def unschedule(self, name: str) -> bool:
        """Remove EVERY event record with the name — SQL inserts may
        have created duplicates schedule() would have replaced."""
        if not self.db.schema.exists_class(SCHEDULE_CLASS):
            return False
        found = False
        for doc in list(self.db.browse_class(SCHEDULE_CLASS)):
            if doc.get("name") == name:
                self.db.delete(doc)
                found = True
        return found

    def events(self) -> List[dict]:
        if not self.db.schema.exists_class(SCHEDULE_CLASS):
            return []
        return [
            {
                "name": d.get("name"),
                "rule": d.get("rule"),
                "function": d.get("function"),
                "enabled": d.get("enabled", True),
                "runs": self.run_counts.get(d.get("name"), 0),
            }
            for d in self.db.browse_class(SCHEDULE_CLASS)
        ]

    # -- the loop ------------------------------------------------------------

    def start(self) -> "Scheduler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="ot-scheduler", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _rule_for(self, text: str) -> Optional[CronRule]:
        r = self._rules.get(text)
        if r is None:
            try:
                r = self._rules[text] = CronRule(text)
            except CronError as e:
                # SQL-inserted events bypass schedule()'s eager
                # validation: a bad rule must be visible, once
                if text not in self._bad_rules:
                    self._bad_rules.add(text)
                    log.warning("unparseable cron rule %r: %s", text, e)
                return None
        return r

    def _run(self) -> None:
        while not self._stop.wait(self.TICK):
            try:
                self.tick()
            except Exception:  # pragma: no cover - keep the loop alive
                log.exception("scheduler tick failed")

    #: longest catch-up window after a stall; a longer gap logs and
    #: skips (a laptop resume must not replay a day of minutely fires)
    MAX_CATCHUP_S = 300

    def tick(self, now: Optional[float] = None) -> int:
        """Evaluate every second since the previous tick (catch-up: a
        slow function or pause spanning a rule's one matching second
        still fires it) and fire matching events. Split from the
        thread loop so tests drive time explicitly."""
        now = time.time() if now is None else now
        cur = int(now)
        last = self._last_scan_sec
        if last is not None and cur <= last:
            return 0  # this second was already evaluated
        start = cur if last is None else last + 1
        if cur - start > self.MAX_CATCHUP_S:
            log.warning(
                "scheduler stalled %ds; skipping to now (misses are "
                "not replayed past %ds)",
                cur - start,
                self.MAX_CATCHUP_S,
            )
            start = cur - self.MAX_CATCHUP_S
        self._last_scan_sec = cur
        if not self.db.schema.exists_class(SCHEDULE_CLASS):
            return 0
        docs = list(self.db.browse_class(SCHEDULE_CLASS))
        fired = 0
        fired_events: set = set()
        for sec in range(start, cur + 1):
            for doc in docs:
                name = doc.get("name")
                if not name or not doc.get("enabled", True):
                    continue
                if name in fired_events:
                    # at most ONE catch-up fire per event per tick: a
                    # dense rule behind a slow function must not spiral
                    # into a back-to-back replay burst — its backlog is
                    # dropped (the scan cursor advanced), a sparse rule
                    # still gets its one missed fire
                    continue
                rule = self._rule_for(doc.get("rule") or "")
                if rule is None or not rule.matches(float(sec)):
                    continue
                if self._last_fired.get(name) == sec:
                    continue  # at-most-once per matching second
                self._last_fired[name] = sec
                fired_events.add(name)
                fired += 1
                self._fire(name, doc)
        return fired

    def _fire(self, name: str, doc) -> None:
        fn_name = doc.get("function")
        fn = self.db.functions.get(fn_name) if fn_name else None
        if fn is None:
            log.warning(
                "scheduled event %r: function %r not found", name, fn_name
            )
            return
        try:
            fn.invoke(self.db, list(doc.get("arguments") or []))
            metrics.incr("scheduler.fired")
            self.run_counts[name] = self.run_counts.get(name, 0) + 1
        except Exception:
            metrics.incr("scheduler.failed")
            log.exception("scheduled event %r failed", name)


