"""Record-level hooks.

Analog of the reference's trigger SPI ([E] ORecordHook / ORecordHookAbstract,
SURVEY.md §2 "Live queries / hooks"): callbacks fire around every record
create/update/delete on the host store. BEFORE hooks may mutate the record
or veto by raising; AFTER hooks observe the committed state (live queries
are implemented on top of AFTER hooks — `orientdb_tpu/exec/live.py`).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

BEFORE_CREATE = "before_create"
AFTER_CREATE = "after_create"
BEFORE_UPDATE = "before_update"
AFTER_UPDATE = "after_update"
BEFORE_DELETE = "before_delete"
AFTER_DELETE = "after_delete"

EVENTS = (
    BEFORE_CREATE,
    AFTER_CREATE,
    BEFORE_UPDATE,
    AFTER_UPDATE,
    BEFORE_DELETE,
    AFTER_DELETE,
)


class HookManager:
    """Registry of (event, class filter) → callbacks."""

    def __init__(self, db) -> None:
        self._db = db
        self._lock = threading.Lock()
        self._next_id = 1
        #: token → (event or None=all, class_name or None=all, fn)
        self._hooks: Dict[int, Tuple[Optional[str], Optional[str], Callable]] = {}

    def register(
        self,
        fn: Callable,
        event: Optional[str] = None,
        class_name: Optional[str] = None,
    ) -> int:
        """Register `fn(event, doc)`; returns an unregister token."""
        if event is not None and event not in EVENTS:
            raise ValueError(f"unknown hook event {event!r}; one of {EVENTS}")
        with self._lock:
            token = self._next_id
            self._next_id += 1
            self._hooks[token] = (event, class_name, fn)
            return token

    def unregister(self, token: int) -> bool:
        with self._lock:
            return self._hooks.pop(token, None) is not None

    def _matches_class(self, class_name: Optional[str], doc) -> bool:
        if class_name is None:
            return True
        cls = self._db.schema.get_class(doc.class_name)
        return cls is not None and cls.is_subclass_of(class_name)

    def fire(self, event: str, doc) -> None:
        # During a tx commit apply, AFTER events are buffered (flushed by
        # the tx only once the whole commit succeeds, dropped if it is
        # compensated away); BEFORE hooks still fire inline so they can
        # veto the op that is about to apply.
        buf = getattr(self._db._tx_local, "hook_buffer", None)
        if buf is not None and event.startswith("after_"):
            buf.append((event, doc))
            return
        with self._lock:
            snapshot = list(self._hooks.values())
        for ev, cname, fn in snapshot:
            if ev is not None and ev != event:
                continue
            if not self._matches_class(cname, doc):
                continue
            fn(event, doc)  # BEFORE hooks veto by raising

    def __len__(self) -> int:
        return len(self._hooks)
