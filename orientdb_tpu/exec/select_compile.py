"""SELECT → single-node MATCH rewrite: the TPU compilation of SELECT.

The reference plans SELECT with its own executor ([E] OSelectStatement →
OSelectExecutionPlanner → fetch-from-class + filter steps; SURVEY.md §1
layer 5, §2 "SQL execution planner"). This engine already compiles MATCH
node filters to device predicate scans with hull-restricted root
candidates, COUNT pushdown, columnar RETURN marshalling, and the
parameter-generic plan cache — and a class-target SELECT is exactly a
single-node MATCH:

    SELECT <proj> FROM C WHERE <pred> [GROUP/ORDER/SKIP/LIMIT]
      ≡ MATCH {class:C, as:s, where:(<pred>)} RETURN <proj'>

so instead of a second compiled executor the rewrite translates the
statement and reuses the whole MATCH machinery. Field references in
projections/ORDER BY/GROUP BY become ``s.field`` accesses; the WHERE
moves into the node filter verbatim (node-filter WHERE already evaluates
with record fields in scope). `expr_name` is shared between SELECT and
MATCH, so unaliased projection names match the oracle's exactly.

Projection-less ``SELECT FROM C`` returns *element* rows; the rewrite
flags ``element_alias`` so the solver unwraps the binding back into a
record row after ORDER/SKIP/LIMIT run.

Ineligible statements raise `Uncompilable`, and the engine front door
falls back to the oracle interpreter — exactly the fallback contract the
MATCH path uses for its own unsupported shapes.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

from orientdb_tpu.exec.oracle import expr_name
from orientdb_tpu.ops.predicates import Uncompilable
from orientdb_tpu.sql import ast as A

#: the binding alias the rewritten root node carries; double-underscore
#: keeps it clear of user aliases, and it is NOT a `$` context var
ALIAS = "__sel__"

#: top-level functions that implicitly operate on the current record
#: (graph accessors) — their meaning does not survive the rewrite
_GRAPH_FUNCS = frozenset(
    ["out", "in", "both", "oute", "ine", "bothe", "outv", "inv", "expand"]
)


def _rewrite_expr(e: A.Expression) -> A.Expression:
    """Record-relative references become accesses on the bound alias."""
    if isinstance(e, A.Identifier):
        return A.FieldAccess(A.Identifier(ALIAS), e.name)
    if isinstance(e, A.ContextVar):
        raise Uncompilable(f"context var ${e.name} in SELECT")
    if isinstance(e, A.FunctionCall) and e.name.lower() in _GRAPH_FUNCS:
        raise Uncompilable(f"graph function {e.name}() in SELECT")
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, A.Expression):
                nv = _rewrite_expr(v)
                if nv is not v:
                    changes[f.name] = nv
            elif isinstance(v, tuple):
                # recurse through NESTED tuples too — map literals hold
                # (key, Expression) pairs a flat scan would miss
                nv = _rewrite_tuple(v)
                if nv != v:
                    changes[f.name] = nv
        if changes:
            return dataclasses.replace(e, **changes)
    return e


def _rewrite_tuple(v: tuple) -> tuple:
    return tuple(
        _rewrite_expr(x)
        if isinstance(x, A.Expression)
        else _rewrite_tuple(x)
        if isinstance(x, tuple)
        else x
        for x in v
    )


def rewrite_select(
    stmt: A.SelectStatement,
) -> Tuple[A.MatchStatement, Optional[str]]:
    """Translate an eligible class-target SELECT; returns the MATCH
    statement and the element alias (set when the SELECT returns whole
    records). Raises Uncompilable for shapes the MATCH engine cannot
    honor with oracle parity."""
    if not isinstance(stmt.target, A.ClassTarget) or not stmt.target.polymorphic:
        raise Uncompilable("SELECT target is not a polymorphic class scan")
    if stmt.lets:
        raise Uncompilable("SELECT LET is not compiled")
    if stmt.unwind:
        raise Uncompilable("SELECT UNWIND is not compiled")

    element_alias: Optional[str] = None
    if not stmt.projections and stmt.group_by:
        # oracle semantics: grouping without projections yields empty
        # rows, not representative records — no MATCH equivalent
        raise Uncompilable("GROUP BY on whole-record SELECT")
    if stmt.projections:
        returns = tuple(
            A.Projection(
                _rewrite_expr(p.expr),
                # pin the oracle's SELECT column name so unaliased
                # projections keep identical keys after the rewrite
                p.alias or expr_name(p.expr, i),
            )
            for i, p in enumerate(stmt.projections)
        )
        if any(isinstance(p.expr, A.Star) for p in stmt.projections):
            raise Uncompilable("SELECT * projection is not compiled")
    else:
        # whole-record SELECT: bind the node and unwrap to element rows
        # after the finalize tail
        if stmt.distinct:
            raise Uncompilable("DISTINCT on whole-record SELECT")
        element_alias = ALIAS
        returns = (A.Projection(A.Identifier(ALIAS), ALIAS),)

    node = A.MatchFilter(
        alias=ALIAS, class_name=stmt.target.name, where=stmt.where
    )
    match = A.MatchStatement(
        paths=(A.MatchPath(first=node, items=()),),
        returns=returns,
        distinct=stmt.distinct,
        group_by=tuple(_rewrite_expr(g) for g in stmt.group_by),
        order_by=tuple(
            dataclasses.replace(
                o, expr=_rewrite_order_expr(o.expr, stmt, element_alias)
            )
            for o in stmt.order_by
        ),
        skip=stmt.skip,
        limit=stmt.limit,
    )
    return match, element_alias


def _rewrite_order_expr(
    e: A.Expression, stmt: A.SelectStatement, element_alias: Optional[str]
):
    """ORDER BY resolution differs by mode. In element mode every field
    rides on the bound record, so expressions rewrite to alias accesses
    like any other. In projection mode the MATCH finalize tail sees only
    the projected row (no record fallback, unlike oracle SELECT's
    ordering), so the expression is kept VERBATIM and every identifier in
    it must name a projected column — anything else is Uncompilable, not
    silently None-sorted."""
    if element_alias is not None:
        return _rewrite_expr(e)
    projected = {p.alias for p in stmt.projections if p.alias} | {
        expr_name(p.expr, i)
        for i, p in enumerate(stmt.projections)
        if p.alias is None
    }
    _check_order_resolvable(e, projected)
    return e


def _check_order_resolvable(e: A.Expression, projected) -> None:
    if isinstance(e, A.Identifier):
        if e.name not in projected:
            raise Uncompilable(f"ORDER BY non-projected field {e.name}")
        return
    if isinstance(e, A.ContextVar):
        raise Uncompilable(f"context var ${e.name} in ORDER BY")
    if isinstance(e, A.FunctionCall) and e.name.lower() in _GRAPH_FUNCS:
        raise Uncompilable(f"graph function {e.name}() in ORDER BY")
    if dataclasses.is_dataclass(e) and not isinstance(e, type):
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, A.Expression):
                _check_order_resolvable(v, projected)
            elif isinstance(v, tuple):
                _check_order_tuple(v, projected)


def _check_order_tuple(v: tuple, projected) -> None:
    for x in v:
        if isinstance(x, A.Expression):
            _check_order_resolvable(x, projected)
        elif isinstance(x, tuple):
            _check_order_tuple(x, projected)
