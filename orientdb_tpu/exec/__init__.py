from orientdb_tpu.exec.result import Result, ResultSet

__all__ = ["Result", "ResultSet"]
