"""Execution planning + EXPLAIN/PROFILE.

Analog of [E] OSelectExecutionPlanner / OMatchExecutionPlanner +
OExecutionStepInternal.prettyPrint (SURVEY.md §5.1: the EXPLAIN plan dump is
the parity debugging tool). The host oracle executes the AST directly; this
module renders the plan the engines follow — the MATCH expansion order
computed here is ALSO the order `exec/tpu_engine.py` compiles, so EXPLAIN
reflects the real TPU schedule.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from orientdb_tpu.exec.result import Result, ResultSet
from orientdb_tpu.sql import ast as A


class PlanStep:
    """[E] OExecutionStepInternal surface: name, detail, children, cost."""

    def __init__(self, name: str, detail: str = "", cost: float = -1.0) -> None:
        self.name = name
        self.detail = detail
        self.cost = cost  # microseconds when profiled; -1 unknown
        self.children: List["PlanStep"] = []

    def add(self, child: "PlanStep") -> "PlanStep":
        self.children.append(child)
        return child

    def pretty(self, depth: int = 0) -> str:
        pad = "  " * depth
        cost = f" (cost≈{self.cost:.0f}µs)" if self.cost >= 0 else ""
        line = f"{pad}+ {self.name}{': ' + self.detail if self.detail else ''}{cost}"
        return "\n".join([line] + [c.pretty(depth + 1) for c in self.children])


# ---------------------------------------------------------------------------
# MATCH planning (shared with the TPU compiler)
# ---------------------------------------------------------------------------


def order_match_edges(db, stmt: A.MatchStatement):
    """Greedy smallest-candidate-first expansion order ([E]
    OMatchExecutionPlanner.createExecutionPlan): returns (pattern,
    ordered edges, root alias)."""
    from orientdb_tpu.exec.oracle import MatchInterpreter

    interp = MatchInterpreter(db, stmt, {})
    pattern = interp.pattern
    edges = [e for e in pattern.edges]
    if not edges:
        roots = [n.alias for n in pattern.nodes.values() if n.filters]
        return pattern, [], roots
    # estimate each alias, pick cheapest as root, BFS outward
    est = {a: interp.estimate(n) for a, n in pattern.nodes.items()}
    ordered = []
    bound = set()
    remaining = list(edges)
    roots: List[str] = []
    while remaining:
        candidates = [
            e for e in remaining if e.from_alias in bound or e.to_alias in bound
        ]
        if not candidates:
            # new component: root at the smallest-estimate alias in it
            comp_aliases = {a for e in remaining for a in (e.from_alias, e.to_alias)}
            root = min(comp_aliases, key=lambda a: est.get(a, 1 << 60))
            roots.append(root)
            bound.add(root)
            continue
        # prefer edges whose unbound endpoint is cheapest
        def rank(e):
            fb, tb = e.from_alias in bound, e.to_alias in bound
            if fb and tb:
                return (0, 0)
            other = e.to_alias if fb else e.from_alias
            return (1, est.get(other, 1 << 60))

        e = min(candidates, key=rank)
        remaining.remove(e)
        ordered.append(e)
        bound.add(e.from_alias)
        bound.add(e.to_alias)
    return pattern, ordered, roots


# ---------------------------------------------------------------------------
# plan rendering
# ---------------------------------------------------------------------------


def build_plan(db, stmt: A.Statement, engine: str = "oracle") -> PlanStep:
    if isinstance(stmt, A.MatchStatement):
        return _match_plan(db, stmt, engine)
    if isinstance(stmt, A.SelectStatement):
        return _select_plan(db, stmt)
    if isinstance(stmt, A.TraverseStatement):
        root = PlanStep("TRAVERSE", f"strategy={stmt.strategy}")
        root.add(PlanStep("FetchTargets", _target_str(stmt.target)))
        if stmt.while_cond is not None:
            root.add(PlanStep("While", "gate traversal on condition"))
        if stmt.max_depth is not None:
            root.add(PlanStep("MaxDepth", str(stmt.max_depth)))
        return root
    return PlanStep(type(stmt).__name__.replace("Statement", "").upper())


def _target_str(target: Optional[A.Target]) -> str:
    if target is None:
        return "(none)"
    if isinstance(target, A.ClassTarget):
        return f"class {target.name}"
    if isinstance(target, A.ClusterTarget):
        return f"cluster {target.name_or_id}"
    if isinstance(target, A.RidTarget):
        return ",".join(f"#{r.cluster}:{r.position}" for r in target.rids)
    if isinstance(target, A.IndexTarget):
        return f"index {target.name}"
    if isinstance(target, A.SubQueryTarget):
        return "(subquery)"
    return "(expression)"


def _select_plan(db, stmt: A.SelectStatement) -> PlanStep:
    root = PlanStep("SELECT")
    fetch = PlanStep("FetchFromTarget", _target_str(stmt.target))
    # index-accelerated scan detection ([E] the planner's index-vs-scan
    # choice, SURVEY.md §3.2)
    if isinstance(stmt.target, A.ClassTarget) and stmt.where is not None:
        idx_field = _indexable_eq_field(db, stmt.target.name, stmt.where)
        if idx_field:
            fetch = PlanStep("FetchFromIndex", f"{stmt.target.name}.{idx_field}")
    root.add(fetch)
    if stmt.lets:
        root.add(PlanStep("Let", ", ".join(f"${l.name}" for l in stmt.lets)))
    if stmt.where is not None:
        root.add(PlanStep("Filter", "WHERE"))
    if stmt.group_by:
        root.add(PlanStep("Aggregate", f"group by {len(stmt.group_by)} key(s)"))
    if stmt.projections:
        root.add(PlanStep("Projection", f"{len(stmt.projections)} column(s)"))
    for u in stmt.unwind:
        root.add(PlanStep("Unwind", u))
    if stmt.order_by:
        root.add(PlanStep("OrderBy", f"{len(stmt.order_by)} key(s)"))
    if stmt.skip is not None:
        root.add(PlanStep("Skip"))
    if stmt.limit is not None:
        root.add(PlanStep("Limit"))
    return root


def _indexable_eq_field(db, class_name: str, where: A.Expression) -> Optional[str]:
    if isinstance(where, A.Binary):
        if where.op == "=" and isinstance(where.left, A.Identifier):
            idx = db.indexes.best_for(class_name, where.left.name)
            if idx is not None:
                return where.left.name
        if where.op == "AND":
            return _indexable_eq_field(db, class_name, where.left) or _indexable_eq_field(
                db, class_name, where.right
            )
    return None


def _match_plan(db, stmt: A.MatchStatement, engine: str) -> PlanStep:
    pattern, ordered, roots = order_match_edges(db, stmt)
    root = PlanStep("MATCH", f"engine={engine}")
    if roots:
        root.add(PlanStep("MatchFirst", f"root alias(es): {', '.join(roots)}"))
    for e in ordered:
        item = e.item
        arrow = {"out": "-[{}]->", "in": "<-[{}]-", "both": "-[{}]-"}.get(
            item.direction, ".{}()"
        )
        label = arrow.format(",".join(item.edge_classes) or "*")
        detail = f"{e.from_alias} {label} {e.to_alias}"
        extras = []
        if item.target.while_cond is not None:
            extras.append("while")
        if item.target.max_depth is not None:
            extras.append(f"maxDepth={item.target.max_depth}")
        if item.target.optional:
            extras.append("optional")
        if item.edge_filter is not None and item.edge_filter.where is not None:
            extras.append("edge-where")
        if extras:
            detail += f" [{', '.join(extras)}]"
        name = "TpuBatchExpand" if engine == "tpu" else "MatchStep"
        root.add(PlanStep(name, detail))
    if any(p.negated for p in stmt.paths):
        root.add(PlanStep("NotPatternFilter"))
    if stmt.distinct:
        root.add(PlanStep("Distinct"))
    if stmt.group_by:
        root.add(PlanStep("Aggregate"))
    root.add(PlanStep("ReturnProjection", f"{len(stmt.returns)} column(s)"))
    if stmt.order_by:
        root.add(PlanStep("OrderBy"))
    if stmt.limit is not None:
        root.add(PlanStep("Limit"))
    return root


def explain_plan(db, stmt: A.ExplainStatement, params) -> ResultSet:
    from orientdb_tpu.exec.engine import _choose_engine

    inner = stmt.inner
    engine = _choose_engine(db, inner, None)
    plan = build_plan(db, inner, engine)
    # per-plan cost accounting (obs/stats): the EXPLAIN/PROFILE's own
    # fingerprint entry carries the plan it rendered, so the stats
    # table shows WHAT plan a query shape runs, not just how much
    from orientdb_tpu.obs.stats import note_plan

    note_plan(plan.pretty())
    props: Dict[str, object] = {
        "executionPlan": plan.pretty(),
        "engine": engine,
        "statement": type(inner).__name__,
    }
    if stmt.profile:
        t0 = time.perf_counter()
        if engine == "tpu":
            # compiled-path PROFILE: per-phase timings + schedule stats
            # (SURVEY.md §5.1 — this is the tool for dispatch-overhead work)
            from orientdb_tpu.exec import tpu_engine

            try:
                rows, phases = tpu_engine.profile_execute(db, inner, params)
                props["tpuPhases"] = phases
            except tpu_engine.Uncompilable as e:
                props["fallback"] = str(e)
                engine = "oracle"
        if engine != "tpu":
            from orientdb_tpu.exec.oracle import execute_statement

            rows = execute_statement(db, inner, params)
        elapsed = (time.perf_counter() - t0) * 1e6
        plan.cost = elapsed
        props["engine"] = engine
        props["executionPlan"] = plan.pretty()
        props["elapsedUs"] = elapsed
        props["rows"] = len(rows)
    rs = ResultSet([Result(props=props)])
    rs.plan = plan
    return rs
