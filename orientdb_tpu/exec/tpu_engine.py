"""Compiled TPU execution engine (placeholder — lands with the snapshot
layer; see `orientdb_tpu/ops/` and SURVEY.md §7 step 3)."""

from __future__ import annotations


class Uncompilable(Exception):
    """Statement (or feature) the TPU engine cannot compile; the front door
    falls back to the oracle unless strict."""


def execute(db, stmt, params):
    raise Uncompilable("TPU engine not built yet")
