"""Compiled TPU MATCH engine — batched binding-table execution.

The reference executes MATCH as a per-record interpreted DFS
([E] OMatchExecutionPlanner → MatchStep → MatchEdgeTraverser,
SURVEY.md §3.3): one RidBag walk, N document loads and an interpreted WHERE
per candidate edge. This engine replaces that hot loop wholesale:

- the pattern graph compiles to a **static plan** of steps (root scan,
  edge expansion, optional left-join) whose ordering replicates the
  oracle's greedy smallest-candidate-first choice ([E]
  OMatchExecutionPlanner's ordering) — the order is data-independent given
  host-side class counts, so the whole plan is known before launch;
- intermediate state is a **binding table**: one int32 device column per
  alias (dense vertex index, -1 = null), plus (class, edge-pos) column
  pairs for edge aliases and int32 columns for depth aliases;
- each pattern-edge hop is a batched CSR **count → scan → rank-search
  gather** (`orientdb_tpu/ops/csr.py`) with node/edge WHERE predicates
  applied as fused columnar masks (`orientdb_tpu/ops/predicates.py`);
- results marshal back through the SAME RETURN/DISTINCT/ORDER path as the
  oracle (`oracle.match_rows_from_bindings`), so result semantics are
  defined once and parity is structural.

Anything outside the compiled subset raises `Uncompilable` and the front
door falls back to the oracle — behavior stays total while the compiled
surface grows.
"""

from __future__ import annotations

import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from orientdb_tpu.exec import devicefault
from orientdb_tpu.exec.eval import EvalContext
from orientdb_tpu.exec.oracle import (
    MatchInterpreter,
    Pattern,
    PatternEdge,
    PatternNode,
    finalize_match_rows,
    match_rows_from_bindings,
    _expr_uses_bindings,
    _match_proj_name,
    _order_rows,
    _skip_limit,
    _REVERSE_DIR,
)
from orientdb_tpu.exec.result import ColumnarRows, Result
from orientdb_tpu.models.record import Document
from orientdb_tpu.models.rid import RID
from orientdb_tpu.ops import csr as K
from orientdb_tpu.ops.device_graph import DeviceGraph, device_graph
from orientdb_tpu.storage import tiering
from orientdb_tpu.ops.predicates import (
    ColumnScope,
    ParamBox,
    Uncompilable,
    compile_predicate,
    split_params,
)
from orientdb_tpu.sql import ast as A
from orientdb_tpu.utils.config import config
from orientdb_tpu.utils.logging import get_logger
from orientdb_tpu.utils.metrics import metrics, timed

log = get_logger("tpu_engine")


def _block_until_ready(d) -> None:
    """Device sync; host-resident numpy results (the CPU-backend fast
    paths) lack the method and need none."""
    fn = getattr(d, "block_until_ready", None)
    if fn is not None:
        fn()


def _copy_to_host_async(d) -> None:
    """Start an async device→host copy; host-resident numpy results
    lack the method and need none."""
    fn = getattr(d, "copy_to_host_async", None)
    if fn is not None:
        fn()


def _fetch_profiled(devs: List, split_sync: bool = True) -> List[np.ndarray]:
    """Fetch dispatched device results with the 3-way accounting the
    perf work aims by: device-sync time, transfer time, bytes moved
    (`tpu.device_s` / `tpu.transfer_s` / `tpu.bytes_fetched`; host
    marshalling is timed by callers as `tpu.host_s`). Execution is
    in-order per device, so blocking on the LAST dispatched result
    covers the whole batch with one sync instead of N. ``split_sync=
    False`` skips the separate sync wave — a lone query must not pay an
    extra round trip just for the split (the tunnel charges ~1 RTT per
    wave); profile_execute decomposes singles instead."""
    import time as _time

    devicefault.transfer_point()
    t0 = _time.perf_counter()
    if split_sync and len(devs) > 1:
        _block_until_ready(devs[-1])
    t1 = _time.perf_counter()
    for d in devs:
        _copy_to_host_async(d)
    arrs = [np.asarray(d) for d in devs]
    t2 = _time.perf_counter()
    if devs:
        metrics.observe("tpu.device_s", t1 - t0)
        metrics.observe("tpu.transfer_s", t2 - t1)
        nbytes = sum(int(a.nbytes) for a in arrs)
        metrics.incr("tpu.bytes_fetched", nbytes)
        # per-fingerprint attribution (obs/stats): one thread-local add
        # when a query accumulator is active, a no-op otherwise
        from orientdb_tpu.obs.stats import add_device

        add_device(t1 - t0, t2 - t1, nbytes)
        # flight-recorder intervals (obs/timeline): same thread-local
        # discipline — the active dispatch record gets this wave's
        # device-busy and transfer intervals for overlap accounting
        from orientdb_tpu.obs.timeline import add_phase

        add_phase(t1 - t0, t2 - t1, nbytes)
    return arrs


#: smallest page (rows) a batched result fetch transfers; pow2 rounding
#: up from here bounds the distinct sliced shapes per buffer to log2(W)
_PAGE_MIN = 1024
#: rows-group page sizes round up to this so `group_page`'s jit cache
#: stays small (width/2048 distinct shapes at most) while waste stays
#: ≤ 2048 rows per group
_GROUP_PAGE_ROUND = 2048



# ---------------------------------------------------------------------------
# binding table
# ---------------------------------------------------------------------------


class Table:
    """Device binding table: padded columns + a host-known valid count."""

    def __init__(self, count: int = 1, width: int = 0) -> None:
        #: alias → int32 [B] dense vertex index (-1 null / padding)
        self.cols: Dict[str, jnp.ndarray] = {}
        #: edge alias → (class_idx int32 [B], edge_pos int32 [B])
        self.edge_cols: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]] = {}
        #: depth alias → int32 [B]
        self.depth_cols: Dict[str, jnp.ndarray] = {}
        self.count = count  # valid rows; starts at 1 (the empty binding)
        self.width = width  # bucketed column length (0 = no columns yet)
        #: device-side twin of `count` (threaded so COUNT(*) plans can fetch
        #: one scalar instead of the whole table); None until a step sets it
        self.count_dev = None
        #: device-side per-slot liveness (int32 1/0). On a recording run the
        #: first `count` slots are exactly the live ones, so None ≡
        #: arange(width) < count; a parameter-generic REPLAY can have live
        #: rows interleaved with recorded-size padding, and this mask is
        #: what lets materialization pick the true rows.
        self.valid = None

    @property
    def count_device(self):
        if self.count_dev is None:
            return jnp.int32(self.count)
        return self.count_dev

    @property
    def valid_device(self):
        if self.valid is not None:
            return self.valid
        pos = jnp.arange(max(self.width, 1), dtype=jnp.int32)
        return (pos < self.count_device).astype(jnp.int32)

    def empty(self) -> bool:
        return self.count == 0

    def has(self, alias: str) -> bool:
        return alias in self.cols or alias in self.edge_cols

    def gather(self, rows: jnp.ndarray) -> "Table":
        """New table selecting `rows` (padded with -1) from this one."""
        t = Table(count=self.count, width=int(rows.shape[0]))
        for a, c in self.cols.items():
            t.cols[a] = K.take_pad(c, rows, jnp.int32(-1))
        for a, (ci, pos) in self.edge_cols.items():
            t.edge_cols[a] = (
                K.take_pad(ci, rows, jnp.int32(-1)),
                K.take_pad(pos, rows, jnp.int32(-1)),
            )
        for a, c in self.depth_cols.items():
            t.depth_cols[a] = K.take_pad(c, rows, jnp.int32(-1))
        t.valid = K.take_pad(self.valid_device, rows, jnp.int32(0))
        return t


def _concat_tables(parts: List[Table], counts: List[int]) -> Table:
    """Concatenate gathered part-tables (same column sets) and re-bucket.

    Parts keep their FULL bucketed capacity (not just the recorded live
    prefix): a parameter-generic replay can have up to bucket(recorded)
    live rows per part, so slicing at the recorded count would silently
    truncate them. Liveness flows through the per-slot valid mask; the
    recorded host count is bookkeeping only."""
    total = sum(counts)
    # parts are already bucket-sized; their sum is deterministic given the
    # schedule, so no re-bucketing (it would only double the padding)
    cap = sum(p.width for p in parts)
    out = Table(count=total, width=max(cap, K.bucket(0)))
    if not parts:
        out.count = 0
        out.count_dev = jnp.int32(0)
        return out
    out.count_dev = parts[0].count_device
    for p in parts[1:]:
        out.count_dev = out.count_dev + p.count_device
    keys = parts[0].cols.keys()
    for a in keys:
        out.cols[a] = _pad_concat([p.cols[a] for p in parts], out.width)
    for a in parts[0].edge_cols.keys():
        ci = _pad_concat([p.edge_cols[a][0] for p in parts], out.width)
        ps = _pad_concat([p.edge_cols[a][1] for p in parts], out.width)
        out.edge_cols[a] = (ci, ps)
    for a in parts[0].depth_cols.keys():
        out.depth_cols[a] = _pad_concat(
            [p.depth_cols[a] for p in parts], out.width
        )
    out.valid = _pad_concat([p.valid_device for p in parts], out.width, pad=0)
    return out


def _pad_concat(segs: List[jnp.ndarray], width: int, pad: int = -1) -> jnp.ndarray:
    cat = jnp.concatenate(segs) if segs else jnp.zeros(0, jnp.int32)
    n = width - cat.shape[0]
    if n > 0:
        cat = jnp.concatenate([cat, jnp.full(n, pad, jnp.int32)])
    return cat


# ---------------------------------------------------------------------------
# size schedule (the compiled-plan-cache mechanism)
# ---------------------------------------------------------------------------


def _cap_of(n: int) -> int:
    """Replay-tolerant buffer capacity for an observed count: bucketed
    with ``config.schedule_headroom`` growth, so parameter-generic replays
    whose live sizes land within the headroom run without an overflow
    re-record."""
    if n <= 0:
        return K.bucket(0)
    # deliberate trace-time read: capacities are frozen per RECORDED
    # plan by design — retuning the headroom applies to the next
    # (re-)recording, never to a live executable
    return K.bucket(max(1, int(n * config.schedule_headroom)))  # lint: allow(jaxlint)


def _observe_compact(sched: "SizeSchedule", mask, min_capacity: int = 0):
    """Shared compaction protocol: surviving-row indices sized via the
    schedule (one blocking sync on the recording run, free on replay).
    Returns (indices, host count, device count)."""
    count_dev = K.mask_count(mask)
    count = sched.observe(count_dev, min_capacity=min_capacity)
    return (
        K.compact_indices(mask, max(min_capacity, _cap_of(count))),
        count,
        count_dev,
    )


class SizeSchedule:
    """Records every host-observed device scalar (frontier totals, compact
    counts) on the first execution; replays them sync-free afterwards.

    XLA needs static shapes, frontiers are dynamic — the first run pays one
    blocking device→host sync per observation to learn the shape schedule.
    Sizes are deterministic given (snapshot epoch, statement, params), so a
    replay under `jit` executes the whole multi-hop solve as a single
    device dispatch with zero syncs — the TPU-native analog of the
    reference's prepared-plan reuse ([E] OExecutionPlanCache).

    Parameter-generic replay: numeric parameters are jit ARGUMENTS, so a
    replay may see different live sizes than were recorded. Every non-free
    observation therefore accumulates a device-side ``overflow`` flag —
    live count exceeding the recorded bucket capacity (or any liveness
    where the recording saw zero and structurally skipped work) means the
    replay's buffers were too small and its result must be discarded; the
    caller re-records with the new parameters (buckets grow monotonically,
    so re-records converge). Live counts *under* the recorded capacity are
    handled exactly via the table's device valid mask + count."""

    def __init__(self) -> None:
        self.values: List[int] = []
        self.pos = 0
        self.recording = True
        self.overflow = None  # traced bool scalar during replay

    def observe(self, dev_scalar, free: bool = False, min_capacity: int = 0) -> int:
        """``free=True`` marks a value that sizes no buffer and gates no
        control flow (e.g. the COUNT(*) pushdown total) — exempt from the
        overflow check. ``min_capacity`` is the buffer floor the call site
        allocates even for a recorded zero (kept-empty parts): replays may
        fill it without flagging."""
        if self.recording:
            v = int(dev_scalar)
            self.values.append(v)
            return v
        v = self.values[self.pos]
        self.pos += 1
        if not free:
            cap = max(min_capacity, _cap_of(v) if v > 0 else 0)
            flag = dev_scalar > cap
            self.overflow = flag if self.overflow is None else (self.overflow | flag)
        return v

    def note_flag(self, dev_flag) -> None:
        """OR an externally computed device-side failure bit into the
        overflow surface (the tiered cold-miss flag: a replay whose
        frontier wandered onto a non-resident block must discard and
        re-record — the re-record faults the block in). No-op while
        recording: the recording run ensures residency eagerly."""
        if self.recording:
            return
        self.overflow = (
            dev_flag if self.overflow is None else (self.overflow | dev_flag)
        )

    def overflow_flag(self):
        if self.overflow is None:
            return jnp.zeros((), bool)
        return self.overflow

    def start_replay(self) -> None:
        self.recording = False
        self.pos = 0
        self.overflow = None


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------


class _SeedBox:
    """Index-seeded root candidates as replay inputs.

    ``spec`` (alias → padded capacity) is fixed at record time; ``current``
    holds the live arrays — concrete during recording, tracers during a
    replay trace (set by _CompiledPlan._replay from the dyn pytree)."""

    __slots__ = ("spec", "current")

    def __init__(self) -> None:
        self.spec: Dict[str, int] = {}
        self.current: Dict[str, object] = {}


def _eq_conjuncts(e):
    """Top-level `lhs = rhs` pairs of an AND tree."""
    if isinstance(e, A.Binary):
        if e.op == "AND":
            yield from _eq_conjuncts(e.left)
            yield from _eq_conjuncts(e.right)
        elif e.op == "=":
            yield e.left, e.right


class PlanStep:
    __slots__ = ("kind", "alias", "edge", "reverse", "close")

    def __init__(self, kind, alias=None, edge=None, reverse=False, close=False):
        self.kind = kind  # 'root' | 'expand' | 'optional'
        self.alias = alias
        self.edge: Optional[PatternEdge] = edge
        self.reverse = reverse
        self.close = close

    def describe(self) -> str:
        if self.kind == "root":
            return f"ROOT {self.alias}"
        e = self.edge
        arrow = "<-" if self.reverse else "->"
        return f"{self.kind.upper()} {e.from_alias}{arrow}{e.to_alias}"


def build_plan(pattern: Pattern, interp: MatchInterpreter) -> List[PlanStep]:
    """Static replay of the oracle's dynamic edge ordering (the bound-alias
    set evolves data-independently, so the greedy choice is a compile-time
    computation here; [E] OMatchExecutionPlanner does the analogous
    estimate-driven ordering once per query)."""
    steps: List[PlanStep] = []
    bound: set = set()
    required = [e for e in pattern.edges if not interp._edge_is_optional(e)]
    optionals = [e for e in pattern.edges if interp._edge_is_optional(e)]
    edges = list(required)
    while edges:
        def rank(e: PatternEdge) -> int:
            fb, tb = e.from_alias in bound, e.to_alias in bound
            if fb and tb:
                return 0
            if fb:
                return 1
            if tb:
                return 2
            return 3

        order = sorted(range(len(edges)), key=lambda i: rank(edges[i]))
        i = order[0]
        e = edges.pop(i)
        r = rank(e)
        if r == 3:
            fn, tn = pattern.nodes[e.from_alias], pattern.nodes[e.to_alias]
            root = fn if interp.estimate(fn) <= interp.estimate(tn) else tn
            steps.append(PlanStep("root", alias=root.alias))
            bound.add(root.alias)
            edges.insert(0, e)
            continue
        if r == 0:
            steps.append(PlanStep("expand", edge=e, close=True))
        elif r == 1:
            steps.append(PlanStep("expand", edge=e))
        else:
            steps.append(PlanStep("expand", edge=e, reverse=True))
        bound.add(e.from_alias)
        bound.add(e.to_alias)
        f = e.item.edge_filter
        if f is not None and f.alias:
            bound.add(f.alias)
    # isolated nodes: the shared admission rule lives in
    # MatchInterpreter.enumerable_isolated so both engines stay in lockstep
    for n in interp.enumerable_isolated(required, optionals):
        if n.alias in bound:
            continue
        if n.is_edge_alias:
            raise Uncompilable("unbound edge alias would scan all edges")
        steps.append(PlanStep("root", alias=n.alias))
        bound.add(n.alias)
    # optional edges: oracle picks (in list order) the first with a decided
    # endpoint; replay statically
    opts = list(optionals)
    while opts:
        pick = None
        for i, e in enumerate(opts):
            if e.from_alias in bound or e.to_alias in bound:
                pick = i
                break
        if pick is None:
            # fully detached optional arms bind null; no step needed (their
            # aliases marshal as None)
            for e in opts:
                bound.add(e.from_alias)
                bound.add(e.to_alias)
            break
        e = opts.pop(pick)
        fb = e.from_alias in bound
        tb = e.to_alias in bound
        steps.append(
            PlanStep("optional", edge=e, reverse=not fb, close=(fb and tb))
        )
        bound.add(e.from_alias)
        bound.add(e.to_alias)
    return steps


# ---------------------------------------------------------------------------
# shared bitmap-hop construction (variable-depth MATCH and TRAVERSE)
# ---------------------------------------------------------------------------


def _var_emit_mask(reached, node_mask_vec, bound_chunk, vb: int):
    """One var-depth level's emission mask: reached ∧ target node mask,
    restricted to the already-bound endpoint on cyclic (close) arms.
    Shared by the row-emitting and count-only paths so their semantics
    cannot drift."""
    emit = reached & node_mask_vec[None, :]
    if bound_chunk is not None:
        vcol = jnp.arange(vb, dtype=jnp.int32)
        emit = emit & (vcol[None, :] == bound_chunk[:, None])
    return emit


def build_bitmap_hops(dg: DeviceGraph, items, sched=None, tier=None,
                      touched=None) -> List:
    """Frontier-hop closures for ``(class, direction, emask)`` items.

    Each closure maps a ``[C, vb]`` frontier bitmap to the bitmap of
    vertices reached over that class+direction. Mesh-sharded graphs hop
    via the sharded edge-list slices with a psum-OR merge over the shards
    axis (SURVEY.md §5.7); single-device graphs scatter over the flat
    edge list. ``emask`` is an optional [E] per-edge prefilter in
    out-CSR order (fused edge WHERE).

    Tiered snapshots (``tier`` set, storage/tiering) hop over the paged
    pool instead of the flat edge list: the recording run faults every
    frontier-touched block resident (accumulating the plan's
    ``touched`` footprint), replays read the pools through ``dg.arrays``
    — jit arguments, so residency changes reach cached plans — and fold
    a device-side cold-miss bit into ``sched`` so an off-footprint
    replay re-records rather than dropping edges."""
    mg = dg.mesh_graph
    armed = getattr(dg.snap, "_overlay", None) is not None
    hops = []
    for cname, d, emask in items:
        dec = dg.edges[cname]
        if mg is None and tier is not None and tier.pages_dir(cname, d):

            def tiered_hop(fr, cname=cname, d=d, emask=emask):
                if sched is None or sched.recording:
                    tier.ensure_frontier(cname, d, fr, touched)
                arrays = dg.arrays
                out = tiering.paged_hop(arrays, cname, d, emask, fr)
                # computed on the recording run too (and discarded):
                # the touch log must see the miss path's keys or the
                # replay's jit-arg subset would lack them
                miss = tiering.paged_hop_miss(arrays, cname, d, fr)
                if sched is not None:
                    sched.note_flag(miss)
                return out

            hops.append(tiered_hop)
            continue
        m = emask if emask is not None else jnp.ones(dec.num_edges, bool)
        if mg is None:
            if armed:
                # delta-maintained edge list: slab slots (appended
                # edges) and tombstones flow through ONE liveness mask,
                # read via dg.arrays so replays take it as a jit
                # argument — a delta patch reaches every cached plan
                lv = dg.arrays[f"e:{cname}:live"]
                m = lv if emask is None else (emask & lv)
            if d == "out":
                a, em = dec.edge_src, dec.dst
            else:  # follow edges backwards: activate dst, emit src
                a, em = dec.dst, dec.edge_src
            hops.append(
                lambda fr, a=a, em=em, m=m: K.bitmap_hop(a, em, m, fr)
            )
        else:
            from orientdb_tpu.parallel.mesh_graph import sharded_bitmap_hop

            p = mg.edge[cname].prefix
            src_sh = dg.arrays[f"{p}:el:src"]
            dst_sh = dg.arrays[f"{p}:el:dst"]
            eid_sh = dg.arrays[f"{p}:el:eid"]
            a_sh, e_sh = (src_sh, dst_sh) if d == "out" else (dst_sh, src_sh)
            hops.append(
                lambda fr, a=a_sh, em=e_sh, i=eid_sh, m=m, mesh=mg.mesh: (
                    sharded_bitmap_hop(mesh, a, em, i, m, fr)
                )
            )
    return hops


# ---------------------------------------------------------------------------
# the solver
# ---------------------------------------------------------------------------


class TpuMatchSolver:
    def __init__(
        self,
        db,
        stmt: A.MatchStatement,
        params: Dict,
        element_alias: Optional[str] = None,
    ) -> None:
        self.db = db
        self.stmt = stmt
        self.params = params
        #: set for rewritten whole-record SELECTs (select_compile): rows
        #: unwrap from {alias: doc} props back into element rows
        self.element_alias = element_alias
        # numeric parameters compile to reads of this box so one cached
        # plan replays for any value (predicates.ParamBox)
        self.param_box = ParamBox(params)
        snap = db.current_snapshot(require_fresh=True)
        if snap is None:
            raise Uncompilable("no fresh snapshot attached")
        self.snap = snap
        self.dg: DeviceGraph = device_graph(snap)
        #: delta-slab overlay (storage/deltas) when the snapshot is
        #: incrementally maintained; plans record its generation and
        #: overflow-fail when the structure moves under them
        self.overlay = getattr(snap, "_overlay", None)
        self.delta_gen = (
            self.overlay.plan_gen if self.overlay is not None else 0
        )
        #: hot/cold tier manager (storage/tiering) when the snapshot's
        #: adjacency exceeds the HBM cap; the recording run accumulates
        #: every faulted block into tier_touched — frozen at plan
        #: construction as the plan's dispatch-prefetch footprint
        self.tier = getattr(snap, "_tier", None)
        self.tier_touched: set = set()
        #: slab-scan capacity floor (host-read here, NOT inside the
        #: traced replay): recordings pre-allocate this many slab
        #: window/match slots even when the slab is near-empty, so a
        #: growing slab crosses far fewer pow2 buckets — each crossing
        #: is a full plan re-record (the r-mixed churn that collapsed
        #: read q/s under sustained writes)
        self._slab_floor = max(8, int(config.delta_slab_edge_slots) // 16)
        self.sched = SizeSchedule()
        # reuse the oracle's pattern build + estimates (host planning data)
        self.interp = MatchInterpreter(db, stmt, params)
        self.pattern = self.interp.pattern
        self.not_paths = self.interp.not_paths
        self.edge_class_list = sorted(self.dg.edges.keys())
        self.edge_class_idx = {n: i for i, n in enumerate(self.edge_class_list)}
        self._vertex_scope_cache: Optional[ColumnScope] = None
        self._check_supported()
        self.plan = build_plan(self.pattern, self.interp)
        # binding visibility: which (vertex) aliases are bound BEFORE each
        # alias' first bind / each step — this is the scope a
        # binding-referencing WHERE may see (mirrors the oracle, whose
        # check_node/edge-where run with the bindings accumulated so far)
        self._vertex_aliases = {
            a for a, n in self.pattern.nodes.items() if not n.is_edge_alias
        }
        self._alias_visible: Dict[str, set] = {}
        self._step_visible: Dict[int, set] = {}
        bound_so_far: set = set()
        for step in self.plan:
            if step.kind == "root":
                self._alias_visible.setdefault(step.alias, set())
                bound_so_far.add(step.alias)
                continue
            e = step.edge
            src = e.to_alias if step.reverse else e.from_alias
            dst = e.from_alias if step.reverse else e.to_alias
            vis = bound_so_far & self._vertex_aliases
            self._step_visible[id(step)] = vis
            self._alias_visible.setdefault(dst, vis)
            bound_so_far.add(src)
            bound_so_far.add(dst)
            f = e.item.edge_filter
            if f is not None and f.alias:
                bound_so_far.add(f.alias)
        # pre-compile all node/edge predicates (fail fast → fallback);
        # edge-alias nodes carry EDGE-scope filters, which the
        # edge-binding expansion compiles per concrete class itself
        self._node_masks: Dict[str, object] = {}
        for alias, node in self.pattern.nodes.items():
            if not node.is_edge_alias:
                self._node_masks[alias] = self._compile_node(node)
        # WHILE conditions compile with $depth as a per-level scalar
        self._while_fns: Dict[int, object] = {}
        for e in self.pattern.edges:
            w = e.item.target.while_cond
            if w is not None:
                self._while_fns[id(e)] = compile_predicate(
                    w, self._vertex_scope(), self.param_box, allow_depth=True
                )
        # NOT arms: per-path (aliases, admission masks, path items) for the
        # bitmap anti-join — compiled here so an unsupported arm fails
        # fast into the oracle fallback
        self._not_compiled = []
        for path in self.not_paths:
            sub = Pattern()
            prev = sub.node(path.first)
            aliases = [prev.alias]
            for it in path.items:
                aliases.append(sub.node(it.target).alias)
            masks = [self._compile_node(sub.nodes[a]) for a in aliases]
            self._not_compiled.append((aliases, masks, list(path.items)))
        # index-seeded roots ([E] the planner's index-vs-scan choice,
        # SURVEY.md §3.2): a root whose WHERE carries `field = :param` (or
        # a literal) over an indexed field seeds its candidates from the
        # host index — O(hits) instead of an O(|class|) hull scan, the
        # difference between V-independent and V-linear point lookups.
        # Seeds enter replays as jit inputs (see _SeedBox / _dyn_args).
        self.seed_box = _SeedBox()
        self._root_seeds: Dict[str, tuple] = {}
        if config.index_root_seed and self.db._indexes is not None:
            for st in self.plan:
                if st.kind == "root":
                    probe = self._root_seed_probe(st.alias)
                    if probe is not None:
                        self._root_seeds[st.alias] = probe

    def _root_seed_probe(self, alias: str):
        """(rhs expr, index) when the root's WHERE has an AND-conjunct
        `field = <param|literal>` over a single-field index covering the
        node's class; None otherwise."""
        node = self.pattern.nodes[alias]
        for f in node.filters:
            if not f.class_name or f.where is None:
                continue
            for lhs, rhs in _eq_conjuncts(f.where):
                if not isinstance(lhs, A.Identifier):
                    lhs, rhs = rhs, lhs
                if not isinstance(lhs, A.Identifier):
                    continue
                if not isinstance(rhs, (A.Parameter, A.Literal)):
                    continue
                idx = self.db._indexes.best_for(f.class_name, lhs.name)
                if idx is not None:
                    return (rhs, idx)
        return None

    def compute_seed(self, alias: str, params) -> np.ndarray:
        """Host-side index probe: snapshot vertex indices whose indexed
        field equals the (current) value — a SUPERSET filter input; the
        admission mask still applies the full node check."""
        rhs, index = self._root_seeds[alias]
        if isinstance(rhs, A.Parameter):
            key = rhs.name if rhs.name is not None else rhs.index
            value = (params or {}).get(key)
        else:
            value = rhs.value
        hits: List[int] = []
        if value is not None:
            for rid in index.get(value):
                i = self.snap.idx_of(rid)
                if i is not None:
                    hits.append(i)
        hits.sort()  # deterministic candidate order across replays
        return np.asarray(hits, np.int32)

    # -- compile-time gating ------------------------------------------------

    def _check_supported(self) -> None:
        if self.tier is not None:
            # tiered snapshots page the flat edge arrays out of HBM —
            # the method-form expansions (_expand_bind_edge /
            # _expand_edge_endpoint) still read them directly, so those
            # arms fall back to the oracle until they learn the paged
            # gather. Plain arrows, var-depth, NOT arms and TRAVERSE
            # all route through the paged kernels.
            for e in self.pattern.edges:
                if (e.item.method or "").lower() in (
                    "oute", "ine", "bothe", "outv", "inv", "bothv"
                ):
                    raise Uncompilable(
                        "method-form arm on a tiered snapshot"
                    )
        for path in self.not_paths:
            # NOT arms compile to a bitmap anti-join (see
            # _apply_not_path); the chain subset mirrors what that
            # machinery evaluates — no variable depth, methods, optional
            # flags, edge aliases, or binding references inside the arm
            flts = [path.first] + [it.target for it in path.items]
            for flt in flts:
                if flt is None:
                    continue
                if flt.while_cond is not None or flt.max_depth is not None:
                    raise Uncompilable("variable-depth NOT arm")
                if flt.optional or flt.depth_alias or flt.path_alias:
                    raise Uncompilable("optional/depth/path alias in NOT arm")
                if flt.where is not None and _expr_uses_bindings(
                    flt.where, self.pattern.nodes
                ):
                    raise Uncompilable("NOT-arm WHERE references bindings")
            for it in path.items:
                if (it.method or "").lower() in (
                    "outv", "inv", "bothv", "oute", "ine", "bothe"
                ):
                    raise Uncompilable("method form in NOT arm")
                f = it.edge_filter
                if f is not None and f.alias:
                    raise Uncompilable("edge alias in NOT arm")
                if f is not None and f.where is not None and _expr_uses_bindings(
                    f.where, self.pattern.nodes
                ):
                    raise Uncompilable("NOT-arm edge WHERE references bindings")
        reserved = set(self.pattern.nodes.keys())
        for e in self.pattern.edges:
            item = e.item
            m = (item.method or "").lower()
            if m in ("oute", "ine", "bothe") and item.edge_filter is None:
                # bare edge-binding arm (.outE(){as:e}) — compiled by
                # _expand_bind_edge; an edge target with a rid filter has
                # no device analog, and variable depth on an edge binding
                # has no compiled form
                if any(f.rid is not None for f in self.pattern.nodes[e.to_alias].filters):
                    raise Uncompilable("rid filter on an edge-binding target")
                if (
                    item.target.while_cond is not None
                    or item.target.max_depth is not None
                ):
                    raise Uncompilable("variable-depth edge-binding arm")
            if m in ("outv", "inv", "bothv") and (
                item.target.while_cond is not None
                or item.target.max_depth is not None
            ):
                raise Uncompilable("variable-depth endpoint arm")
            var_depth = (
                item.target.while_cond is not None
                or item.target.max_depth is not None
            )
            if item.target.path_alias:
                raise Uncompilable("pathAlias not compiled (per-path state)")
            if item.negated:
                raise Uncompilable("negated path item")
            f = item.edge_filter
            if var_depth:
                # variable-depth arms evaluate masks vertex-wise (no
                # per-row env), so binding references stay interpreted
                if f is not None and f.where is not None and _expr_uses_bindings(
                    f.where, self.pattern.nodes
                ):
                    raise Uncompilable("edge WHERE references bindings (WHILE arm)")
                if item.target.where is not None and _expr_uses_bindings(
                    item.target.where, self.pattern.nodes
                ):
                    raise Uncompilable("node WHERE references bindings (WHILE arm)")
                if f is not None and f.alias:
                    raise Uncompilable(
                        "edge alias on a WHILE arrow (discovery-edge binding)"
                    )
                w = item.target.while_cond
                if w is not None and _expr_uses_bindings(w, self.pattern.nodes):
                    raise Uncompilable("WHILE condition references bindings")
        # edge-alias nodes are fine when bound by an edge-filter alias or
        # as the target of a bare edge-binding arm (.outE(){as:e}); a bare
        # edge-alias root is not
        edge_filter_aliases = {
            e.item.edge_filter.alias
            for e in self.pattern.edges
            if e.item.edge_filter is not None and e.item.edge_filter.alias
        }
        edge_bind_targets = {
            e.to_alias
            for e in self.pattern.edges
            if (e.item.method or "").lower() in ("oute", "ine", "bothe")
            and e.item.edge_filter is None
        }
        for node in self.pattern.nodes.values():
            if (
                node.is_edge_alias
                and node.alias not in edge_filter_aliases
                and node.alias not in edge_bind_targets
            ):
                raise Uncompilable("edge-alias pattern nodes not compiled yet")

    # -- predicate compilation ---------------------------------------------

    def _vertex_scope(self) -> ColumnScope:
        if self._vertex_scope_cache is None:
            self._vertex_scope_cache = ColumnScope(
                self.dg.columns,
                self.dg.non_columnar,
                reserved=set(self.pattern.nodes.keys()),
            )
        return self._vertex_scope_cache

    def _compile_node(self, node: PatternNode):
        """Node admission mask: fn(idx_array) -> bool mask over vertex ids.

        Mirrors oracle.check_node: class closure ∧ rid ∧ WHERE. A WHERE
        referencing earlier bindings (``alias.prop``) compiles against the
        alias-visibility set at this node's first bind; the mask then
        needs env["bindings"] at evaluation (``mask.uses_bindings``)."""
        parts = []
        uses_bindings = False
        has_class = any(f.class_name for f in node.filters)
        if self.overlay is not None and not has_class:
            # delta-maintained universe: spare slab rows and deleted
            # vertices carry class -1 — a class filter excludes them via
            # isin, but a bare node needs an explicit liveness conjunct
            parts.append(
                lambda idx, env: K.take_pad(
                    self.dg.v_class, idx, jnp.int32(-1)
                )
                >= 0
            )
        for f in node.filters:
            if f.class_name:
                ids = self.dg.class_ids(f.class_name)
                parts.append(self._class_mask_fn(ids))
            if f.rid is not None:
                want = self.snap.idx_of(RID(f.rid.cluster, f.rid.position))
                wi = -2 if want is None else want  # -2 matches nothing (≠ -1 pad)
                parts.append(lambda idx, env, wi=wi: idx == wi)
            if f.where is not None:
                if _expr_uses_bindings(f.where, self.pattern.nodes):
                    scope = ColumnScope(
                        self.dg.columns,
                        self.dg.non_columnar,
                        reserved=set(self.pattern.nodes.keys()),
                        binding_columns=self.dg.columns,
                        binding_non_columnar=self.dg.non_columnar,
                        visible_aliases=self._alias_visible.get(
                            node.alias, set()
                        ),
                    )
                    fn = compile_predicate(f.where, scope, self.param_box)
                    uses_bindings = uses_bindings or scope.uses_bindings
                else:
                    fn = compile_predicate(
                        f.where, self._vertex_scope(), self.param_box
                    )
                parts.append(fn)

        def mask(idx, env=None, parts=parts):
            env = env or {}
            m = idx >= 0
            for p in parts:
                m = m & p(idx, env)
            return m

        mask.uses_bindings = uses_bindings
        return mask

    def _class_mask_fn(self, ids: jnp.ndarray):
        def fn(idx, env, ids=ids):
            cls = K.take_pad(self.dg.v_class, idx, jnp.int32(-1))
            if ids.shape[0] == 0:
                return jnp.zeros(idx.shape, bool)
            return jnp.isin(cls, ids)

        return fn

    def _edge_where(
        self, concrete: str, where: A.Expression, visible: Optional[set] = None
    ):
        """Edge-property predicate over edge ids; with ``visible`` given,
        ``alias.prop`` references to those (vertex) aliases compile too —
        the returned fn then carries ``uses_bindings`` and needs
        env["bindings"] arrays aligned with its idx slots."""
        dec = self.dg.edges[concrete]
        scope = ColumnScope(
            dec.columns,
            dec.non_columnar,
            reserved=set(self.pattern.nodes.keys()),
            binding_columns=self.dg.columns if visible else None,
            binding_non_columnar=self.dg.non_columnar,
            visible_aliases=visible or set(),
        )
        fn = compile_predicate(where, scope, self.param_box)
        try:
            fn.uses_bindings = scope.uses_bindings
        except AttributeError:  # pragma: no cover - plain closures accept attrs
            pass
        return fn

    # -- execution ----------------------------------------------------------

    @staticmethod
    def _binding_env(table: Table, row: jnp.ndarray, visible: set) -> Dict:
        """env for binding-referencing predicates: per-slot vertex-index
        arrays for each visible alias, aligned with ``row`` (the source
        binding-table row per expansion slot; pass None for identity
        row mapping on width-aligned masks)."""
        def col(a):
            if a not in table.cols:
                shape = row.shape if row is not None else (table.width or 1,)
                return jnp.full(shape, -1, jnp.int32)
            if row is None:
                return table.cols[a]
            return K.take_pad(table.cols[a], row, jnp.int32(-1))

        return {"bindings": {a: col(a) for a in visible}}

    def _compact(self, mask):
        return _observe_compact(self.sched, mask)

    def _expand_csr(self, indptr, nbrs, srcs):
        counts = K.degree_counts(indptr, srcs)
        offsets = K.exclusive_cumsum(counts)
        total_dev = counts.sum()
        total = self.sched.observe(total_dev)
        row, edge_pos, nbr = K.gather_expand(
            indptr, nbrs, srcs, offsets, total_dev, _cap_of(total)
        )
        if self.overlay is not None:
            # delta-tombstoned base edges keep their CSR slot but carry
            # a -1 endpoint: turn those slots into padding so the dead
            # edge can never bind (matches gather_expand's own padding)
            dead = nbr < 0
            row = jnp.where(dead, -1, row)
            edge_pos = jnp.where(dead, -1, edge_pos)
        return row, edge_pos, nbr, total

    def _expand_paged(self, dec, d: str, srcs, part):
        """CSR expansion over a tiered (paged) partition: row/edge_pos
        come from the resident indptr exactly as the flat path; the
        neighbor (and, reverse, the out-order edge id) gather from the
        hot pool through the block→page indirection. The recording run
        faults every touched block resident first (and logs it into the
        plan's tier footprint); replays fold the device-side cold-miss
        bit into the overflow surface instead of syncing."""
        if self.sched.recording:
            # eager run inside the allowlisted _record boundary: the
            # host read of the frontier is the intentional fault path
            self.tier.ensure_vertices(
                dec.class_name, d, np.asarray(srcs), self.tier_touched
            )
        arrays = self.dg.arrays
        indptr = arrays[
            f"e:{dec.class_name}:indptr_{'out' if d == 'out' else 'in'}"
        ]
        counts = K.degree_counts(indptr, srcs)
        offsets = K.exclusive_cumsum(counts)
        total_dev = counts.sum()
        total = self.sched.observe(total_dev)
        row, eid, nbr, miss = tiering.paged_expand(
            arrays, dec.class_name, d, srcs, offsets, total_dev,
            _cap_of(total), part.Wp,
        )
        self.sched.note_flag(miss)
        return row, eid, nbr, total

    def _expand_slab(self, dec, d: str, srcs):
        """Append-slab expansion for one (class, direction): scan the
        slab tail of the padded edge list for live edges whose active
        endpoint is in ``srcs``. The scan window is sized by the
        OBSERVED used-slot count (SizeSchedule), so replays overflow —
        and re-record with a wider window — when the slab outgrows the
        recording; compaction folds the slab away entirely."""
        ov = self.overlay
        base = ov.edge_base(dec.class_name)
        cap = dec.num_edges
        if cap <= base:
            return None
        if (
            dec.class_name in getattr(ov, "bk", {})
            and dec.class_name not in ov.bucket_overflow
        ):
            # O(touched buckets) path — falls back to the window scan
            # below only when a bucket overflowed (plan_gen bumps then,
            # so recorded plans never switch paths mid-replay)
            return self._expand_slab_bucketed(dec, d, srcs, base)
        arrays = self.dg.arrays
        p = f"e:{dec.class_name}"
        tail_src = arrays[f"{p}:edge_src"][base:cap]
        tail_dst = arrays[f"{p}:dst"][base:cap]
        tail_live = arrays[f"{p}:live"][base:cap]
        # used slots are append-only: edge_src >= 0 marks them even
        # after a tombstone (live=False), so the window bound survives
        # deletes. The _slab_floor keeps both buckets generous: a slab
        # filling write-by-write must not re-record the plan at every
        # pow2 crossing.
        floor = min(cap - base, self._slab_floor)
        used = self.sched.observe(
            jnp.sum((tail_src >= 0).astype(jnp.int32)),
            min_capacity=floor,
        )
        W = min(cap - base, max(_cap_of(max(used, 1)), floor))
        a = tail_src[:W] if d == "out" else tail_dst[:W]
        e = tail_dst[:W] if d == "out" else tail_src[:W]
        m = (
            (a[None, :] == srcs[:, None])
            & tail_live[:W][None, :]
            & (srcs >= 0)[:, None]
        )
        total_dev = m.sum(dtype=jnp.int32)
        total = self.sched.observe(total_dev, min_capacity=floor)
        out = max(_cap_of(max(total, 1)), floor)
        idx = K.compact_indices(m.reshape(-1), out)
        ok = idx >= 0
        row = jnp.where(ok, idx // W, -1).astype(jnp.int32)
        j = jnp.where(ok, idx % W, 0).astype(jnp.int32)
        eid = jnp.where(ok, base + j, -1).astype(jnp.int32)
        nbr = jnp.where(ok, jnp.take(e, j), -1).astype(jnp.int32)
        return row, eid, nbr, total

    def _expand_slab_bucketed(self, dec, d: str, srcs, base: int):
        """Bucket-indexed slab expansion: probe each active endpoint's
        BK-slot bucket instead of scanning the whole used window —
        O(rows × BK) work per expansion however full the slab gets
        (the r15 scan was O(rows × used slots): ~2× read cost at
        500-edge occupancy). Same contract as the scan: (row, global
        edge id, neighbor, host total)."""
        ov = self.overlay
        NB, BK = ov.bk_nb, ov.bk_bk
        arrays = self.dg.arrays
        p = f"e:{dec.class_name}"
        tab = arrays[f"bk:{dec.class_name}:{'out' if d == 'out' else 'in'}"]
        own_a = arrays[f"{p}:{'edge_src' if d == 'out' else 'dst'}"]
        nbr_a = arrays[f"{p}:{'dst' if d == 'out' else 'edge_src'}"]
        live = arrays[f"{p}:live"]
        # int32 two's complement: -1 & (NB-1) is a valid (masked) bucket
        b = srcs & jnp.int32(NB - 1)
        slots = b[:, None] * BK + jnp.arange(BK, dtype=jnp.int32)[None, :]
        rel = jnp.take(tab, slots)  # [R, BK] relative slab slots
        ok = (rel >= 0) & (srcs >= 0)[:, None]
        abs_ = base + jnp.clip(rel, 0)
        m = (
            ok
            & (jnp.take(own_a, abs_) == srcs[:, None])
            & jnp.take(live, abs_)
        )
        floor = min(dec.num_edges - base, self._slab_floor)
        total = self.sched.observe(m.sum(dtype=jnp.int32), min_capacity=floor)
        out = max(_cap_of(max(total, 1)), floor)
        idx = K.compact_indices(m.reshape(-1), out)
        okk = idx >= 0
        row = jnp.where(okk, idx // BK, -1).astype(jnp.int32)
        rel_sel = jnp.take(rel.reshape(-1), jnp.clip(idx, 0))
        eid = jnp.where(okk, base + rel_sel, -1).astype(jnp.int32)
        nbr = jnp.where(
            okk, jnp.take(nbr_a, jnp.clip(base + rel_sel, 0)), -1
        ).astype(jnp.int32)
        return row, eid, nbr, total

    def _expand_one_dir_chunked(self, dec, d: str, srcs):
        """Expansion slabs for one (class, direction): usually ONE
        ``(row, eid, nbr, total)``, but when the output would exceed
        config.max_expansion_cap rows, the binding table splits into
        contiguous row ranges expanded separately — intermediate buffers
        stay bounded however large the fan-out (the SURVEY.md §7
        binding-table-blowup mitigation). The chunk count derives from
        the RECORDED total, so replays keep the structure; per-chunk
        observes catch parameter-driven growth."""
        mg = self.dg.mesh_graph
        cap = max(1, config.max_expansion_cap)
        if mg is not None:
            return [self._expand_one_dir(dec, d, srcs)]
        if d == "out":
            indptr = dec.indptr_out
        else:
            indptr = dec.indptr_in
        counts = K.degree_counts(indptr, srcs)
        total = self.sched.observe(counts.sum(), free=True)
        n_chunks = max(1, -(-_cap_of(total) // cap))
        if n_chunks == 1:
            return [self._expand_one_dir(dec, d, srcs)]
        width = int(srcs.shape[0])
        step = -(-width // n_chunks)
        slabs = []
        for a in range(0, width, step):
            sub = srcs[a : a + step]
            row, eid, nbr, t = self._expand_one_dir(dec, d, sub)
            row = jnp.where(row >= 0, row + a, row)  # local → table rows
            slabs.append((row, eid, nbr, t))
        return slabs

    def _expand_one_dir(self, dec, d: str, srcs):
        """One (edge class, direction) expansion → (row, global edge id,
        neighbor, host total), on the single-device or mesh-sharded path."""
        mg = self.dg.mesh_graph
        if mg is None:
            if self.tier is not None:
                part = self.tier.parts.get((dec.class_name, d))
                if part is not None:
                    return self._expand_paged(dec, d, srcs, part)
            if d == "out":
                indptr, nbrs = dec.indptr_out, dec.dst
            else:
                indptr, nbrs = dec.indptr_in, dec.src
            row, edge_pos, nbr, total = self._expand_csr(indptr, nbrs, srcs)
            if d == "out":
                eid = edge_pos
            else:
                eid = K.take_pad(dec.edge_id_in, edge_pos, jnp.int32(-1))
            if self.overlay is not None and self.overlay.topology_dirty:
                # append-slab edges live outside the base CSR: merge the
                # slab scan's slots in (padding interleaves — downstream
                # masks key on row >= 0, not prefix contiguity)
                slab = self._expand_slab(dec, d, srcs)
                if slab is not None:
                    row = jnp.concatenate([row, slab[0]])
                    eid = jnp.concatenate([eid, slab[1]])
                    nbr = jnp.concatenate([nbr, slab[2]])
                    total = total + slab[3]
            return row, eid, nbr, total
        from orientdb_tpu.parallel.mesh_graph import expand_gather, expand_totals

        arrays = self.dg.arrays
        p = mg.edge[dec.class_name].prefix
        ind_sh = arrays[f"{p}:{d}:indptr"]
        nbr_sh = arrays[f"{p}:{d}:nbr"]
        span_sh = arrays["sh:rowspan"]
        extra_sh = (
            arrays[f"{p}:out:ebase"] if d == "out" else arrays[f"{p}:in:eid"]
        )
        tots = expand_totals(mg.mesh, ind_sh, span_sh, srcs)
        total = self.sched.observe(tots.sum())
        max_local = self.sched.observe(tots.max())
        cap = _cap_of(max(max_local, 1))
        # merged segment sized by the GLOBAL total, not S x local max:
        # the ring-compacted merge in expand_gather keeps skewed shards
        # (supernodes) from inflating every shard's block
        cap_total = _cap_of(max(total, 1))
        if self.sched.recording:
            # merge-traffic observability (tools/mesh_scaling.py plots
            # the S-curve): rows actually merged vs what the old
            # all_gather-of-blocks design would have shipped, per-hop
            # collective bytes (3 packed int32 psum segments), live-
            # frontier occupancy of the expansion slots, and how many
            # shards cond-skipped their gather/scatter outright
            S = mg.mesh.devices.size // (
                mg.mesh.shape.get(config.mesh_replica_axis, 1)
            )
            metrics.incr("mesh.merge_rows", cap_total)
            metrics.incr("mesh.allgather_rows", S * cap)
            metrics.incr("mesh.collective_bytes", 12 * cap_total)
            metrics.incr("mesh.frontier_live_rows", total)
            metrics.incr("mesh.frontier_slot_rows", S * cap)
            # recording runs inside the allowlisted _record boundary,
            # so this tiny [S] fetch is an intentional transfer
            metrics.incr(
                "mesh.empty_shard_skips", int((np.asarray(tots) == 0).sum())
            )
        row, eid, nbr = expand_gather(
            mg.mesh,
            ind_sh,
            nbr_sh,
            extra_sh,
            span_sh,
            srcs,
            cap,
            cap_total,
            is_out=(d == "out"),
        )
        return row, eid, nbr, total

    def solve_table(self) -> Table:
        pushdown = self._count_pushdown_steps()
        var_count = None if pushdown else self._var_count_step()
        if pushdown:
            steps = self.plan[: len(self.plan) - len(pushdown)]
        elif var_count is not None:
            steps = self.plan[:-1]
        else:
            steps = self.plan
        from contextlib import nullcontext

        from orientdb_tpu.obs.registry import obs as _obs
        from orientdb_tpu.obs.trace import span as _span

        # spans/histograms only on the eager RECORDING execution: replay
        # re-traces this body under jax.jit (compile time, recorded-size
        # padding) — observing there would record tracing artifacts as if
        # they were query execution
        rec = self.sched.recording
        table = Table(count=1, width=0)
        for step in steps:
            if table.empty():
                # required-edge pipeline already empty → no rows; optional
                # steps cannot resurrect rows
                return table
            # one span per plan step (root seed / PatternEdge hop): the
            # per-hop stage timings PROFILE surfaces; frontier sizes feed
            # the tpu.frontier_rows histogram on /metrics
            sp = _span("tpu.step", step=step.describe()) if rec else None
            with sp if sp is not None else nullcontext():
                if step.kind == "root":
                    table = self._root(table, step.alias)
                elif step.kind == "expand":
                    table = self._expand(table, step, optional=False)
                else:
                    table = self._expand(table, step, optional=True)
                if sp is not None:
                    sp.set("frontier_rows", table.count)
            if rec:
                _obs.observe_size("tpu.frontier_rows", table.count)
        if self._not_compiled and not table.empty():
            with _span("tpu.step", step="NOT anti-join") if rec else (
                nullcontext()
            ):
                table = self._apply_not_paths(table)
        if pushdown and not table.empty():
            return self._apply_count_pushdown(table, pushdown)
        if var_count is not None and not table.empty():
            return self._expand_var_depth(
                table, var_count, optional=False, count_only=True
            )
        return table

    # -- COUNT(*) aggregate pushdown ----------------------------------------

    # -- NOT patterns: bitmap anti-join -------------------------------------

    def _apply_not_paths(self, table: Table) -> Table:
        """Reject rows for which any NOT arm is satisfiable — the [E]
        NOT-pattern filter of OMatchStatement, evaluated as a chunked
        bitmap chain: candidates for the arm's first position (one-hot of
        the shared binding, or its admission mask over all vertices), one
        frontier hop per arm item, target masks/bindings ANDed in; a row
        with any survivor at the chain's end matched the NOT arm."""
        for aliases, masks, items in self._not_compiled:
            if table.empty():
                return table
            table = self._apply_not_path(table, aliases, masks, items)
        return table

    def _apply_not_path(self, table: Table, aliases, masks, items) -> Table:
        width = table.width or 1
        V = self.dg.num_vertices
        vb = K.bucket(max(V, 1))
        univ = jnp.arange(vb, dtype=jnp.int32)
        univ = jnp.where(univ < V, univ, -1)
        node_vecs = [m(univ) for m in masks]
        hops_per_item = []
        for it in items:
            hop_items = []
            f = it.edge_filter
            for cname in self._resolve_edge_classes(it):
                dec = self.dg.edges[cname]
                emask = None
                if f is not None and f.where is not None:
                    eids = jnp.arange(dec.num_edges, dtype=jnp.int32)
                    emask = self._edge_where(cname, f.where)(eids, {})
                dirs = ("out", "in") if it.direction == "both" else (it.direction,)
                for d in dirs:
                    hop_items.append((cname, d, emask))
            hops_per_item.append(
                build_bitmap_hops(
                    self.dg, hop_items, sched=self.sched, tier=self.tier,
                    touched=self.tier_touched,
                )
            )
        vcol = jnp.arange(vb, dtype=jnp.int32)
        valid_dev = table.valid_device
        exists_chunks = []
        C = self._var_chunk_rows(width, vb)
        for cs in range(0, width, C):
            chunk_rows = jnp.arange(cs, cs + C, dtype=jnp.int32)
            in_range = jnp.where(chunk_rows < valid_dev.shape[0], chunk_rows, -1)
            chunk_valid = K.take_pad(valid_dev, in_range, jnp.int32(0)) > 0
            chunk_rows = jnp.where(chunk_valid, chunk_rows, -1)
            a0 = aliases[0]
            if a0 in table.cols:
                src = K.take_pad(table.cols[a0], chunk_rows, jnp.int32(-1))
                cur = K.rows_to_bitmap(src, vb) & node_vecs[0][None, :]
            else:
                cur = node_vecs[0][None, :] & chunk_valid[:, None]
            for k, hops in enumerate(hops_per_item):
                nxt = jnp.zeros_like(cur)
                for hop in hops:
                    nxt = nxt | hop(cur)
                nxt = nxt & node_vecs[k + 1][None, :]
                tgt = aliases[k + 1]
                if tgt in table.cols:
                    bound = K.take_pad(
                        table.cols[tgt], chunk_rows, jnp.int32(-2)
                    )
                    nxt = nxt & (vcol[None, :] == bound[:, None])
                cur = nxt
            exists_chunks.append(cur.any(axis=1))
        exists = jnp.concatenate(exists_chunks)[:width]
        keep_mask = valid_dev[:width].astype(bool) & ~exists
        keep, kn, kn_dev = self._compact(keep_mask)
        t = table.gather(keep)
        t.count = kn
        t.count_dev = kn_dev
        return t

    def _count_pushdown_steps(self) -> List[PlanStep]:
        """Longest plan suffix of terminal chain expansions a lone COUNT(*)
        can aggregate without materializing binding tables.

        The reference counts MATCH results by draining the full traverser
        chain row by row ([E] the MatchStep pipeline under a COUNT
        projection); here every terminal hop collapses to one O(E)
        segment-sum pass — ``w_k[v] = Σ_{edges v→u} emask(e)·mask(u)·
        w_{k+1}[u]`` (a sparse matvec over the edge list) — and the count
        is ``Σ_rows w_1[src]``. This keeps the per-query device program at
        O(E + V) instead of O(result rows), which is what makes batched
        COUNT throughput independent of fan-out.
        """
        if self.count_only_name() is None or self.stmt.group_by or self._not_compiled:
            return []
        if self.overlay is not None and self.overlay.topology_dirty:
            # the weight chain sums degrees off the base CSR indptr:
            # slab edges would be missed and tombstoned edges counted.
            # Dirty-topology plans take the full (slab-aware) solve;
            # compaction restores the pushdown on the next recording.
            return []
        if self.tier is not None:
            # the weight passes read the flat [E] arrays directly —
            # paged out on a tiered snapshot. The frontier solve (paged
            # gather + bitmap hops) covers COUNT correctly, just
            # without the pushdown's O(E+V) collapse.
            return []
        suffix: List[PlanStep] = []
        # alias usage counts over all edges (from/to + edge-filter aliases)
        for step in reversed(self.plan):
            if step.kind != "expand" or step.close:
                break
            e = step.edge
            item = e.item
            if (
                item.target.while_cond is not None
                or item.target.max_depth is not None
                or item.target.depth_alias
                or (item.edge_filter is not None and item.edge_filter.alias)
            ):
                break
            # binding-referencing predicates need per-row env — the
            # pushdown's vertex-wise weight passes cannot provide one
            if (
                item.edge_filter is not None
                and item.edge_filter.where is not None
                and _expr_uses_bindings(item.edge_filter.where, self.pattern.nodes)
            ):
                break
            mm = (item.method or "").lower()
            if (mm in ("oute", "ine", "bothe") and item.edge_filter is None) or mm in (
                "outv", "inv", "bothv"
            ):
                break  # edge-binding / endpoint arms have no weight pass
            dst_alias = e.from_alias if step.reverse else e.to_alias
            if getattr(self._node_masks[dst_alias], "uses_bindings", False):
                break
            # dst must be terminal: referenced by no OTHER edge than this one
            # and (for non-last suffix members) only as the src of the next
            # pushdown step — checked by walking backwards: the "next" step
            # is already in `suffix`, and its src is this dst.
            used_elsewhere = False
            for e2 in self.pattern.edges:
                if e2 is e:
                    continue
                in_suffix_head = suffix and e2 is suffix[0].edge
                touches = dst_alias in (e2.from_alias, e2.to_alias)
                f2 = e2.item.edge_filter
                if f2 is not None and f2.alias == dst_alias:
                    used_elsewhere = True
                if touches and not in_suffix_head:
                    used_elsewhere = True
            if used_elsewhere:
                break
            if suffix:
                nxt = suffix[0]
                nxt_src = (
                    nxt.edge.to_alias if nxt.reverse else nxt.edge.from_alias
                )
                if nxt_src != dst_alias:
                    break
            suffix.insert(0, step)
        return suffix

    def _var_count_step(self) -> Optional[PlanStep]:
        """The plan's final step, when it is a terminal var-depth (WHILE /
        maxDepth) expansion a lone COUNT(*) can aggregate by per-level
        popcounts (`_expand_var_depth(count_only=True)`) — the var-depth
        sibling of `_count_pushdown_steps`, which stops at WHILE arms."""
        if (
            self.count_only_name() is None
            or self.stmt.group_by
            or self._not_compiled
            or not self.plan
        ):
            return None
        step = self.plan[-1]
        if step.kind != "expand" or step.close:
            return None  # optional arms contribute unmatched rows too
        e = step.edge
        item = e.item
        if item.target.while_cond is None and item.target.max_depth is None:
            return None  # fixed expansion — the weight pushdown covers it
        f = item.edge_filter
        if f is not None and f.alias:
            return None
        dst_alias = e.from_alias if step.reverse else e.to_alias
        if getattr(self._node_masks[dst_alias], "uses_bindings", False):
            return None
        for e2 in self.pattern.edges:
            if e2 is e:
                continue
            if dst_alias in (e2.from_alias, e2.to_alias):
                return None  # dst participates elsewhere: rows needed
            f2 = e2.item.edge_filter
            if f2 is not None and f2.alias == dst_alias:
                return None
        return step

    def _apply_count_pushdown(self, table: Table, steps: List[PlanStep]) -> Table:
        first = steps[0]
        src_alias = (
            first.edge.to_alias if first.reverse else first.edge.from_alias
        )
        srcs = table.cols.get(src_alias)
        if srcs is None:
            raise Uncompilable(f"alias {src_alias} not bound before expansion")
        w = self._pushdown_weights(steps, jnp.int32)
        per_row = K.take_pad(w, srcs, jnp.int32(0))
        total_dev = per_row.sum()
        if self.sched.recording:
            # int32 overflow guard (x64 is disabled on TPU): a float32 twin
            # of the whole weight chain detects wraps anywhere in the
            # segment sums — float32 is inexact above 2^24 but its ~1e-7
            # relative error is far below the mismatch a wrap produces.
            # Record-time only: the snapshot is immutable, so replay sees
            # the same data.
            wf = self._pushdown_weights(steps, jnp.float32)
            approx = float(K.take_pad(wf, srcs, jnp.float32(0)).sum())
            exact = int(total_dev)
            if not (
                0 <= approx < 2**31 * 0.99
                and abs(approx - exact) <= max(1e-3 * approx, 1.0)
            ):
                raise Uncompilable(
                    f"COUNT pushdown overflows int32 (≈{approx:.6g} vs {exact})"
                )
        # free observe: the count IS the device scalar result — it sizes no
        # buffer and gates no control flow, so it must not trip overflow
        total = self.sched.observe(total_dev, free=True)
        t = Table(count=int(total), width=0)
        t.count_dev = total_dev
        return t

    def _pushdown_weights(self, steps: List[PlanStep], dtype) -> jnp.ndarray:
        V = self.dg.num_vertices
        vb = K.bucket(max(V, 1))
        mg = self.dg.mesh_graph
        # vertex universe for [vb]-wide node-mask precomputes: the mesh
        # path always needs it; the single-device path uses it whenever
        # the edge list outnumbers the vertices — evaluating a node
        # predicate per EDGE emit re-gathers every referenced column
        # [E]-wide per hop (2-3 extra 80M-row gathers per pass at SF100
        # shape), where a [vb] precompute plus one bool gather does it
        univ = jnp.arange(vb, dtype=jnp.int32)
        univ = jnp.where(univ < V, univ, -1)
        from contextlib import nullcontext

        from orientdb_tpu.obs.trace import span as _span

        # recording-only spans, like solve_table: replays re-trace this
        # under jax.jit, where a span would time XLA tracing, not work
        rec = self.sched.recording
        w = None  # None ≡ all-ones (the implicit weight after the last hop)
        for step in reversed(steps):
            # one span per PatternEdge hop: the COUNT pushdown fuses all
            # hops into one weight chain, so the honest per-hop timing is
            # each hop's weight-pass build/dispatch
            with _span(
                "tpu.step", step=step.describe(), stage="count-pushdown"
            ) if rec else nullcontext():
                w = self._pushdown_weight_step(step, w, univ, mg, vb, dtype)
        return w

    def _pushdown_weight_step(self, step, w, univ, mg, vb, dtype):
        item = step.edge.item
        direction = item.direction
        if step.reverse:
            direction = _REVERSE_DIR[direction]
        dst_alias = (
            step.edge.from_alias if step.reverse else step.edge.to_alias
        )
        node_mask = self._node_masks[dst_alias]
        classes = self._resolve_edge_classes(item)
        # the [vb]-wide precompute only pays for itself where a consumer
        # exists: the mesh path always reads it, the single-device path
        # only for classes whose edge list outnumbers the vertices —
        # otherwise the eager recording would evaluate it for nothing
        ok_vec = (
            node_mask(univ)
            if mg is not None
            or any(self.dg.edges[c].num_edges >= vb for c in classes)
            else None
        )
        f = item.edge_filter
        new_w = jnp.zeros(vb, dtype)
        for cname in classes:
            dec = self.dg.edges[cname]
            E = dec.num_edges
            if E == 0:
                continue
            eids = jnp.arange(E, dtype=jnp.int32)
            emask = (
                self._edge_where(cname, f.where)(eids, {})
                if (f is not None and f.where is not None)
                else jnp.ones(E, bool)
            )
            for d in ("out", "in") if direction == "both" else (direction,):
                # scanning the full out-CSR edge list covers both
                # directions: eid == position for either walk
                if mg is not None:
                    from orientdb_tpu.parallel.mesh_graph import (
                        sharded_weight_pass,
                    )

                    p = mg.edge[cname].prefix
                    src_sh = self.dg.arrays[f"{p}:el:src"]
                    dst_sh = self.dg.arrays[f"{p}:el:dst"]
                    eid_sh = self.dg.arrays[f"{p}:el:eid"]
                    seg_sh, emit_sh = (
                        (src_sh, dst_sh) if d == "out" else (dst_sh, src_sh)
                    )
                    new_w = new_w + sharded_weight_pass(
                        mg.mesh,
                        seg_sh,
                        emit_sh,
                        eid_sh,
                        emask,
                        ok_vec,
                        w if w is not None else jnp.ones(vb, dtype),
                    )
                    continue
                # both CSR orders exist in HBM, so either direction
                # sums via cumsum+boundary-gather (indptr_segment_sum)
                # instead of the ~7x-costlier TPU scatter-add; the
                # in-direction reorders the out-order edge mask
                # through the in-CSR's edge-id map first
                if d == "out":
                    emit, ip = dec.dst, dec.indptr_out
                    em = emask
                else:
                    emit, ip = dec.src, dec.indptr_in
                    em = jnp.take(emask, dec.edge_id_in)
                if E >= vb:
                    # [vb] mask precompute + one bool gather beats
                    # re-evaluating the predicate's column gathers
                    # [E]-wide (see _pushdown_weights)
                    contrib = em & K.take_pad(ok_vec, emit, False)
                else:
                    contrib = em & node_mask(emit)
                vals = contrib.astype(dtype)
                if w is not None:
                    vals = vals * K.take_pad(w, emit, dtype(0))
                new_w = new_w + K.indptr_segment_sum(vals, ip, vb)
        return new_w

    def _root_candidates(self, alias: str):
        """Candidate scan for a root alias, restricted to the dense-index
        HULL of its class filters' polymorphic closures — the snapshot
        lays each concrete class out contiguously, so a `{class:Person}`
        root scans |Person|-ish slots instead of all V (the device analog
        of [E] FetchFromClassExecutionStep iterating only the class's
        clusters). Admission masks still run in full (the hull can
        contain foreign vertices)."""
        node = self.pattern.nodes[alias]
        if alias in self._root_seeds:
            if self.sched.recording:
                hits = self.compute_seed(alias, self.params)
                cap = max(_cap_of(len(hits)), K.bucket(1))
                self.seed_box.spec[alias] = cap
                arr = np.full(cap, -1, np.int32)
                arr[: len(hits)] = hits
                idx = jnp.asarray(arr)
            else:
                idx = self.seed_box.current[alias]  # [cap] replay input
            mask = self._node_masks[alias](idx) & (idx >= 0)
            cand, n, n_dev = self._compact(mask)
            cand = K.take_pad(idx, cand, jnp.int32(-1))
            return cand, n, n_dev
        V = self.dg.num_vertices
        start, end = 0, V
        has_class = False
        for f in node.filters:
            if f.class_name:
                has_class = True
                lo, hi = self.snap.vertex_hull(f.class_name)
                start, end = max(start, lo), min(end, hi)
        size = max(end - start, 0)
        # delta-maintained snapshots: inserted vertices land in the
        # append slab OUTSIDE every class hull — scan it as a second
        # segment (class masks stay exact; classless hulls already end
        # at the padded universe and need no extra segment)
        slo, shi = (
            self.snap.slab_vertex_range() if has_class else (0, 0)
        )
        slab = max(shi - slo, 0)
        if slab:
            width = K.bucket(max(size + slab, 1))
            pos = jnp.arange(width, dtype=jnp.int32)
            idx = jnp.where(
                pos < size,
                start + pos,
                jnp.where(pos < size + slab, slo + (pos - size), -1),
            )
        else:
            idx = start + jnp.arange(K.bucket(max(size, 1)), dtype=jnp.int32)
            idx = jnp.where(idx < end, idx, -1)
        mask = self._node_masks[alias](idx)
        cand, n, n_dev = self._compact(mask)
        cand = K.take_pad(idx, cand, jnp.int32(-1))
        return cand, n, n_dev

    def _root(self, table: Table, alias: str) -> Table:
        cand, n, n_dev = self._root_candidates(alias)
        if table.width == 0 and not table.cols:
            t = Table(count=n, width=int(cand.shape[0]))
            t.cols[alias] = cand
            t.count_dev = n_dev
            t.valid = (cand >= 0).astype(jnp.int32)
            return t
        # cartesian product with the existing table. Live rows may be
        # scattered among bucket padding (parts keep full capacity), but
        # the pairing below indexes a contiguous prefix — compact first.
        live = table.valid_device[: table.width].astype(bool)
        keep, packed_n, packed_dev = self._compact(live)
        table = table.gather(keep)
        table.count = packed_n
        table.count_dev = packed_dev
        # The pairing stride is the RECORDED new_n, so a parameter-generic
        # replay is only valid when both cardinalities match the recording
        # exactly — require it (single-component patterns, i.e. everything
        # without a cartesian, stay fully parameter-generic).
        old_n, new_n = table.count, n
        old_dev = table.count_device
        sched = self.sched
        if not sched.recording:
            flag = (old_dev != old_n) | (n_dev != new_n)
            sched.overflow = (
                flag if sched.overflow is None else (sched.overflow | flag)
            )
        total = old_n * new_n
        width = K.bucket(max(total, 1))
        pos = jnp.arange(width, dtype=jnp.int32)
        valid = pos < total
        if new_n == 0:
            rows = jnp.full(width, -1, jnp.int32)
            t = table.gather(rows)
            t.count = 0
            t.count_dev = jnp.int32(0)
            t.cols[alias] = rows
            return t
        rows = jnp.where(valid, pos // new_n, -1)
        sel = jnp.where(valid, pos % new_n, -1)
        t = table.gather(rows)
        t.count = total
        t.count_dev = old_dev * n_dev
        t.cols[alias] = K.take_pad(cand, sel, jnp.int32(-1))
        return t

    def _resolve_edge_classes(self, item: A.MatchPathItem) -> List[str]:
        """Concrete edge classes for a path item, with the edge-filter's
        class restriction applied as a host-side subclass check."""
        names = item.edge_classes or (None,)
        concrete: List[str] = []
        for nm in names:
            concrete.extend(self.snap.concrete_edge_classes(nm))
        f = item.edge_filter
        if f is not None and f.class_name:
            keep = []
            for c in concrete:
                cls = self.db.schema.get_class(c)
                if cls is not None and cls.is_subclass_of(f.class_name):
                    keep.append(c)
            concrete = keep
        return concrete

    def _expand(self, table: Table, step: PlanStep, optional: bool) -> Table:
        e = step.edge
        item = e.item
        if item.target.while_cond is not None or item.target.max_depth is not None:
            return self._expand_var_depth(table, step, optional)
        m = (item.method or "").lower()
        if m in ("oute", "ine", "bothe") and item.edge_filter is None:
            return self._expand_bind_edge(table, step, optional)
        if m in ("outv", "inv", "bothv"):
            return self._expand_edge_endpoint(table, step, optional, m)
        direction = item.direction
        reverse = step.reverse
        if reverse:
            direction = _REVERSE_DIR[direction]
        src_alias = e.to_alias if reverse else e.from_alias
        dst_alias = e.from_alias if reverse else e.to_alias
        dst_node = self.pattern.nodes[dst_alias]
        srcs = table.cols.get(src_alias)
        if srcs is None:
            raise Uncompilable(f"alias {src_alias} not bound before expansion")
        concrete = self._resolve_edge_classes(item)
        f = item.edge_filter
        sub_dirs = ("out", "in") if direction == "both" else (direction,)
        parts: List[Table] = []
        counts: List[int] = []
        matched_any = jnp.zeros(table.width or 1, jnp.int32)
        visible = self._step_visible.get(id(step), set())
        node_mask = self._node_masks[dst_alias]
        node_uses = getattr(node_mask, "uses_bindings", False)
        for cname in concrete:
            dec = self.dg.edges[cname]
            where_fn = (
                self._edge_where(cname, f.where, visible)
                if (f is not None and f.where is not None)
                else None
            )
            edge_uses = where_fn is not None and getattr(
                where_fn, "uses_bindings", False
            )
            for d in sub_dirs:
                for row, eid, nbr, total in self._expand_one_dir_chunked(
                    dec, d, srcs
                ):
                    if total == 0:
                        continue
                    env = {}
                    if node_uses or edge_uses:
                        env = self._binding_env(table, row, visible)
                    mask = row >= 0
                    if where_fn is not None:
                        mask = mask & where_fn(eid, env)
                    # destination node admission; close steps skip a
                    # binding-referencing re-check (the oracle doesn't re-run
                    # node filters when closing onto an already-bound alias,
                    # and the visibility set at first bind differs)
                    if not (step.close and node_uses):
                        mask = mask & node_mask(nbr, env)
                    if step.close:
                        bound = K.take_pad(table.cols[dst_alias], row, jnp.int32(-2))
                        mask = mask & (nbr == bound)
                    if optional:
                        matched_any = matched_any + K.rows_with_matches(
                            row, mask, table.width or 1
                        )
                    keep, kn, kn_dev = self._compact(mask)
                    if kn == 0:
                        continue
                    krow = K.take_pad(row, keep, jnp.int32(-1))
                    part = table.gather(krow)
                    part.count = kn
                    part.count_dev = kn_dev
                    part.cols[dst_alias] = K.take_pad(nbr, keep, jnp.int32(-1))
                    ecls_idx = self.edge_class_idx[cname]
                    keid = K.take_pad(eid, keep, jnp.int32(-1))
                    self._bind_edge_alias(part, item, ecls_idx, keid)
                    if item.target.depth_alias:
                        part.depth_cols[item.target.depth_alias] = jnp.where(
                            part.cols[dst_alias] >= 0, 1, -1
                        )
                    parts.append(part)
                    counts.append(kn)
        if optional:
            # left-join: rows with zero matches keep their binding, dst=null.
            # Liveness comes from the device valid mask, not the recorded
            # host count — a parameter-generic replay can have live rows
            # anywhere under the recorded capacity.
            matched = matched_any[: table.width] > 0 if table.width else matched_any[:0]
            valid_rows = table.valid_device[: table.width].astype(bool)
            unmatched = valid_rows & ~matched
            ukeep, un, un_dev = self._compact(unmatched)
            if un > 0:
                upart = table.gather(ukeep)
                upart.count = un
                upart.count_dev = un_dev
                null_col = jnp.full(upart.width, -1, jnp.int32)
                arm_opt = item.edge_filter is not None and item.edge_filter.optional
                if step.close and arm_opt:
                    # arm-optional probe between two bound aliases: both
                    # endpoints survive; only the edge alias binds null
                    pass
                elif step.close:
                    # oracle: null src uses setdefault (keeps the bound dst);
                    # non-null src with no match explicitly nulls it
                    src_g = K.take_pad(srcs, ukeep, jnp.int32(-1))
                    upart.cols[dst_alias] = jnp.where(
                        src_g < 0, upart.cols[dst_alias], -1
                    )
                else:
                    upart.cols[dst_alias] = null_col
                self._bind_edge_alias(upart, item, -1, null_col)
                if item.target.depth_alias:
                    upart.depth_cols[item.target.depth_alias] = null_col
                parts.append(upart)
                counts.append(un)
        if not parts:
            # preserve column structure for downstream steps
            t = table.gather(jnp.full(K.bucket(1), -1, jnp.int32))
            t.count = 0
            t.count_dev = jnp.int32(0)
            t.cols[dst_alias] = jnp.full(t.width, -1, jnp.int32)
            self._bind_edge_alias(t, item, -1, jnp.full(t.width, -1, jnp.int32))
            if item.target.depth_alias:
                t.depth_cols[item.target.depth_alias] = jnp.full(
                    t.width, -1, jnp.int32
                )
            return t
        return _concat_tables(parts, counts)

    # -- method-form arms ---------------------------------------------------

    def _expand_bind_edge(self, table: Table, step: PlanStep, optional: bool) -> Table:
        """Bare ``.outE('EC'){as:e}``: the target alias binds the EDGE
        ([E] MatchFieldTraverser's edge-step). Expansion slots carry the
        global edge id; target-filter class/where apply to the edge."""
        e = step.edge
        item = e.item
        if step.reverse:
            raise Uncompilable("reverse edge-binding arm")
        # mesh path: _expand_one_dir shards the expansion transparently
        # (global edge ids out), edge-property WHERE reads row-sharded
        # columns in jit global view — nothing here is single-chip-only
        src_alias, dst_alias = e.from_alias, e.to_alias
        srcs = table.cols.get(src_alias)
        if srcs is None:
            raise Uncompilable(f"alias {src_alias} not bound before expansion")
        dst_node = self.pattern.nodes[dst_alias]
        tgt_classes = [f.class_name for f in dst_node.filters if f.class_name]
        tgt_wheres = [f.where for f in dst_node.filters if f.where is not None]
        concrete = self._resolve_edge_classes(item)
        for tc in tgt_classes:
            concrete = [
                c
                for c in concrete
                if (cl := self.db.schema.get_class(c)) is not None
                and cl.is_subclass_of(tc)
            ]
        visible = self._step_visible.get(id(step), set())
        sub_dirs = (
            ("out", "in") if item.direction == "both" else (item.direction,)
        )
        parts: List[Table] = []
        counts: List[int] = []
        width = table.width or 1
        matched_any = jnp.zeros(width, jnp.int32)
        for cname in concrete:
            dec = self.dg.edges[cname]
            where_fns = [self._edge_where(cname, w, visible) for w in tgt_wheres]
            uses = any(getattr(f, "uses_bindings", False) for f in where_fns)
            for d in sub_dirs:
                row, eid, nbr, total = self._expand_one_dir(dec, d, srcs)
                if total == 0:
                    continue
                env = {}
                if uses:
                    env = self._binding_env(table, row, visible)
                mask = (row >= 0) & (eid >= 0)
                for fn in where_fns:
                    mask = mask & fn(eid, env)
                ecls_idx = self.edge_class_idx[cname]
                if step.close:
                    bci, beid = table.edge_cols[dst_alias]
                    mask = mask & (
                        K.take_pad(bci, row, jnp.int32(-2)) == ecls_idx
                    ) & (K.take_pad(beid, row, jnp.int32(-2)) == eid)
                if optional:
                    matched_any = matched_any + K.rows_with_matches(
                        row, mask, width
                    )
                keep, kn, kn_dev = self._compact(mask)
                if kn == 0:
                    continue
                krow = K.take_pad(row, keep, jnp.int32(-1))
                part = table.gather(krow)
                part.count = kn
                part.count_dev = kn_dev
                keid = K.take_pad(eid, keep, jnp.int32(-1))
                part.edge_cols[dst_alias] = (
                    jnp.where(keid >= 0, ecls_idx, -1),
                    keid,
                )
                parts.append(part)
                counts.append(kn)
        if optional:
            matched = matched_any[:width] > 0
            unmatched = table.valid_device[:width].astype(bool) & ~matched
            ukeep, un, un_dev = self._compact(unmatched)
            if un > 0:
                upart = table.gather(ukeep)
                upart.count = un
                upart.count_dev = un_dev
                null_col = jnp.full(upart.width, -1, jnp.int32)
                if not step.close:
                    upart.edge_cols[dst_alias] = (null_col, null_col)
                parts.append(upart)
                counts.append(un)
        if not parts:
            t = table.gather(jnp.full(K.bucket(1), -1, jnp.int32))
            t.count = 0
            t.count_dev = jnp.int32(0)
            null_col = jnp.full(t.width, -1, jnp.int32)
            t.edge_cols[dst_alias] = (null_col, null_col)
            return t
        return _concat_tables(parts, counts)

    def _expand_edge_endpoint(
        self, table: Table, step: PlanStep, optional: bool, m: str
    ) -> Table:
        """``.outV()/.inV()/.bothV()`` from a bound edge alias to its
        endpoint vertex: a 1:1 (or 1:2 for bothV) per-row gather through
        the edge-id columns — no fan-out expansion."""
        e = step.edge
        item = e.item
        if step.reverse:
            raise Uncompilable("reverse endpoint arm")
        src_alias, dst_alias = e.from_alias, e.to_alias
        ecols = table.edge_cols.get(src_alias)
        if ecols is None:
            raise Uncompilable(f"edge alias {src_alias} not bound before endpoint step")
        ci, eid = ecols
        width = table.width or 1
        node_mask = self._node_masks[dst_alias]
        node_uses = getattr(node_mask, "uses_bindings", False)
        env = {}
        if node_uses:
            visible = self._step_visible.get(id(step), set())
            env = self._binding_env(table, None, visible)
        kinds = {"outv": ("src",), "inv": ("dst",), "bothv": ("src", "dst")}[m]
        live = table.valid_device[:width].astype(bool)
        parts: List[Table] = []
        counts: List[int] = []
        matched_any = jnp.zeros(width, bool)
        mg = self.dg.mesh_graph
        for kind in kinds:
            cand = jnp.full(width, -1, jnp.int32)
            for k, cname in enumerate(self.edge_class_list):
                dec = self.dg.edges[cname]
                if dec.num_edges == 0:
                    continue
                if mg is None:
                    arr = dec.edge_src if kind == "src" else dec.dst
                else:
                    # mesh: the flat per-edge endpoint arrays are not
                    # uploaded; the shard-blocked edge list IS the flat
                    # array row-blocked by shard (mesh_graph upload), so
                    # a global-view reshape recovers endpoint-by-global-
                    # eid gathers (XLA inserts the collectives)
                    p = mg.edge[cname].prefix
                    key = f"{p}:el:src" if kind == "src" else f"{p}:el:dst"
                    arr = self.dg.arrays[key].reshape(-1)
                g = K.take_pad(arr, jnp.where(ci == k, eid, -1), jnp.int32(-1))
                cand = jnp.where(ci == k, g, cand)
            mask = live & (cand >= 0) & node_mask(cand, env)
            if step.close:
                mask = mask & (cand == table.cols[dst_alias])
            matched_any = matched_any | mask
            keep, kn, kn_dev = self._compact(mask)
            if kn == 0:
                continue
            part = table.gather(keep)
            part.count = kn
            part.count_dev = kn_dev
            part.cols[dst_alias] = K.take_pad(cand, keep, jnp.int32(-1))
            parts.append(part)
            counts.append(kn)
        if optional:
            unmatched = live & ~matched_any
            ukeep, un, un_dev = self._compact(unmatched)
            if un > 0:
                upart = table.gather(ukeep)
                upart.count = un
                upart.count_dev = un_dev
                if not step.close:
                    upart.cols[dst_alias] = jnp.full(upart.width, -1, jnp.int32)
                parts.append(upart)
                counts.append(un)
        if not parts:
            t = table.gather(jnp.full(K.bucket(1), -1, jnp.int32))
            t.count = 0
            t.count_dev = jnp.int32(0)
            t.cols[dst_alias] = jnp.full(t.width, -1, jnp.int32)
            return t
        return _concat_tables(parts, counts)

    # -- variable-depth (WHILE / maxDepth) expansion ------------------------

    _VAR_DEPTH_CHUNK = 256

    @staticmethod
    def _var_chunk_rows(width: int, vb: int) -> int:
        """Rows per frontier-bitmap chunk: no wider than the (bucketed)
        binding table — a point lookup walks 8-row bitmaps, not 256 — and
        capped so one [rows, bucket(V)] bool chunk stays inside
        config.var_depth_bitmap_budget bytes at SF100-scale V."""
        budget_rows = max(1, config.var_depth_bitmap_budget // max(vb, 1))
        return max(1, min(TpuMatchSolver._VAR_DEPTH_CHUNK, width, budget_rows))

    def _expand_var_depth(
        self,
        table: Table,
        step: PlanStep,
        optional: bool,
        count_only: bool = False,
    ) -> Table:
        """Breadth-wise frontier iteration with per-row visited bitmaps —
        the SURVEY §5.7 design for the reference's per-record WHILE-DFS
        ([E] OWhileMatchPathItem): emit the origin at depth 0, then one
        bitmap hop per level, gating expansion with the WHILE mask at the
        level's $depth and stopping at maxDepth / frontier exhaustion.
        Depths are minimum-discovery depths (the oracle's BFS semantics).

        ``count_only`` is the var-depth COUNT pushdown (`_var_count_step`):
        a terminal WHILE arm under a lone COUNT(*) contributes
        popcount(level emission) per level instead of materialized binding
        rows — no compactions, no gathers, no per-level size observes, and
        the result table is just the device scalar.
        """
        e = step.edge
        item = e.item
        direction = item.direction
        reverse = step.reverse
        if reverse:
            direction = _REVERSE_DIR[direction]
        src_alias = e.to_alias if reverse else e.from_alias
        dst_alias = e.from_alias if reverse else e.to_alias
        srcs = table.cols.get(src_alias)
        if srcs is None:
            raise Uncompilable(f"alias {src_alias} not bound before expansion")
        max_depth = item.target.max_depth
        while_fn = self._while_fns.get(id(e))
        depth_alias = item.target.depth_alias
        V = self.dg.num_vertices
        vb = K.bucket(max(V, 1))
        univ = jnp.arange(vb, dtype=jnp.int32)
        univ = jnp.where(univ < V, univ, -1)
        node_mask_vec = self._node_masks[dst_alias](univ)  # [vb]
        # per-(class, dir) edge hop closures; edge WHERE fused as edge masks
        f = item.edge_filter
        items = []
        for cname in self._resolve_edge_classes(item):
            dec = self.dg.edges[cname]
            emask = None
            if f is not None and f.where is not None:
                eids = jnp.arange(dec.num_edges, dtype=jnp.int32)
                emask = self._edge_where(cname, f.where)(eids, {})
            for d in ("out", "in") if direction == "both" else (direction,):
                items.append((cname, d, emask))
        hops = build_bitmap_hops(
            self.dg, items, sched=self.sched, tier=self.tier,
            touched=self.tier_touched,
        )
        parts: List[Table] = []
        counts: List[int] = []
        width = table.width or 1
        matched_chunks = []
        total_dev = jnp.int32(0)  # count_only accumulators (+ f32 twin
        totalf_dev = jnp.float32(0.0)  # for the int32 wrap guard)
        C = self._var_chunk_rows(width, vb)
        # chunk over the bucketed WIDTH (not the recorded count): on a
        # parameter-generic replay live rows can occupy any slot under the
        # recorded capacity, and the per-slot valid mask (not a host count)
        # decides liveness
        valid_dev = table.valid_device
        for cs in range(0, width, C):
            chunk_rows = jnp.arange(cs, cs + C, dtype=jnp.int32)
            # take_pad clips (rather than fills) indices past the end, so
            # out-of-width slots must be sent negative explicitly
            in_range = jnp.where(chunk_rows < valid_dev.shape[0], chunk_rows, -1)
            chunk_valid = K.take_pad(valid_dev, in_range, jnp.int32(0)) > 0
            chunk_rows = jnp.where(chunk_valid, chunk_rows, -1)
            src_chunk = K.take_pad(srcs, chunk_rows, jnp.int32(-1))
            roots = K.rows_to_bitmap(src_chunk, vb)
            bound_chunk = None
            if step.close:
                bound_chunk = K.take_pad(
                    table.cols[dst_alias], chunk_rows, jnp.int32(-2)
                )
            matched = jnp.zeros(C, bool)

            def emit_level(reached, depth):
                nonlocal total_dev, totalf_dev
                if not count_only:
                    return self._emit_var_level(
                        table, reached, node_mask_vec, bound_chunk, cs,
                        depth, dst_alias, depth_alias, vb, parts, counts,
                    )
                emit = _var_emit_mask(reached, node_mask_vec, bound_chunk, vb)
                total_dev = total_dev + jnp.sum(emit, dtype=jnp.int32)
                totalf_dev = totalf_dev + jnp.sum(emit, dtype=jnp.float32)
                return matched  # unused in count mode (never optional)

            visited = roots
            frontier = roots
            depth = 0
            # emit the origin at depth 0
            matched = matched | emit_level(roots, depth)
            # level loop with PADDED trailing levels: recording runs
            # `var_depth_pad_levels` extra (empty) levels past frontier
            # exhaustion and keeps min-capacity emissions at every level,
            # so a replay whose walk is up to `pad` levels deeper — depth
            # varies with the query parameter — executes in place instead
            # of re-recording. The alive observes are free (the loop's
            # trip count replays from the schedule); the post-loop
            # structural observe flags replays needing even deeper walks.
            pad = max(1, config.var_depth_pad_levels)
            empty_streak = 0
            ended_by_bound = False
            while True:
                if max_depth is not None and depth >= max_depth:
                    ended_by_bound = True
                    break
                expandable = frontier
                if while_fn is not None:
                    gate = while_fn(univ, {"depth": depth})
                    expandable = expandable & gate[None, :]
                nxt = jnp.zeros_like(frontier)
                for hop in hops:
                    nxt = nxt | hop(expandable)
                nxt = nxt & ~visited
                alive = self.sched.observe(K.mask_count(nxt), free=True)
                empty_streak = empty_streak + 1 if alive == 0 else 0
                visited = visited | nxt
                depth += 1
                matched = matched | emit_level(nxt, depth)
                frontier = nxt
                if empty_streak >= pad:
                    break
                if depth > V:  # safety: no graph has longer shortest paths
                    ended_by_bound = True
                    break
            if not ended_by_bound:
                # exhaustion-ended: a replay still alive here needs more
                # levels than recorded+pad → overflow (recorded value is 0)
                self.sched.observe(K.mask_count(frontier))
            matched_chunks.append(matched)
        if count_only:
            if self.sched.recording:
                approx = float(totalf_dev)
                exact = int(total_dev)
                if not (
                    0 <= approx < 2**31 * 0.99
                    and abs(approx - exact) <= max(1e-3 * approx, 1.0)
                ):
                    raise Uncompilable(
                        f"var-depth COUNT overflows int32 (≈{approx:.6g})"
                    )
            # free observe: the count IS the result (see _apply_count_pushdown)
            total = self.sched.observe(total_dev, free=True)
            t = Table(count=int(total), width=0)
            t.count_dev = total_dev
            return t
        if optional:
            matched_all = jnp.concatenate(matched_chunks)[:width]
            if matched_all.shape[0] < width:
                matched_all = jnp.concatenate(
                    [
                        matched_all,
                        jnp.zeros(width - matched_all.shape[0], bool),
                    ]
                )
            unmatched = valid_dev[:width].astype(bool) & ~matched_all
            ukeep, un, un_dev = self._compact(unmatched)
            if un > 0:
                upart = table.gather(ukeep)
                upart.count = un
                upart.count_dev = un_dev
                null_col = jnp.full(upart.width, -1, jnp.int32)
                arm_opt = item.edge_filter is not None and item.edge_filter.optional
                if step.close and arm_opt:
                    pass  # arm-optional probe: endpoints survive (see _expand)
                elif step.close:
                    src_g = K.take_pad(srcs, ukeep, jnp.int32(-1))
                    upart.cols[dst_alias] = jnp.where(
                        src_g < 0, upart.cols[dst_alias], -1
                    )
                else:
                    upart.cols[dst_alias] = null_col
                if depth_alias:
                    upart.depth_cols[depth_alias] = null_col
                parts.append(upart)
                counts.append(un)
        if not parts:
            t = table.gather(jnp.full(K.bucket(1), -1, jnp.int32))
            t.count = 0
            t.count_dev = jnp.int32(0)
            t.cols[dst_alias] = jnp.full(t.width, -1, jnp.int32)
            if depth_alias:
                t.depth_cols[depth_alias] = jnp.full(t.width, -1, jnp.int32)
            return t
        return _concat_tables(parts, counts)

    def _emit_var_level(
        self,
        table: Table,
        reached: jnp.ndarray,
        node_mask_vec: jnp.ndarray,
        bound_chunk,
        cs: int,
        depth: int,
        dst_alias: str,
        depth_alias,
        vb: int,
        parts: List[Table],
        counts: List[int],
    ) -> jnp.ndarray:
        """Emit one BFS level's (row, vertex, depth) bindings; returns the
        per-chunk-row matched mask (for OPTIONAL bookkeeping).

        Levels whose recorded emission is EMPTY still append a
        min-capacity part: parameter-generic replays can emit up to that
        capacity at any level (incl. the padded post-exhaustion ones)
        without re-recording."""
        emit = _var_emit_mask(reached, node_mask_vec, bound_chunk, vb)
        matched = emit.any(axis=1)
        flat = emit.reshape(-1)
        keep, kn, kn_dev = _observe_compact(self.sched, flat, min_capacity=K.bucket(0))
        ok = keep >= 0
        c = jnp.where(ok, keep // vb, -1)
        v = jnp.where(ok, keep % vb, -1)
        rowid = jnp.where(ok, cs + c, -1)
        part = table.gather(rowid)
        part.count = kn
        part.count_dev = kn_dev
        part.cols[dst_alias] = v
        if depth_alias:
            part.depth_cols[depth_alias] = jnp.where(ok, depth, -1)
        parts.append(part)
        counts.append(kn)
        return matched

    def _bind_edge_alias(self, part: Table, item: A.MatchPathItem, ecls_idx, eid):
        f = item.edge_filter
        if f is not None and f.alias:
            if isinstance(ecls_idx, int):
                ci = jnp.where(eid >= 0, ecls_idx, -1)
            else:
                ci = ecls_idx
            part.edge_cols[f.alias] = (ci, eid)

    # -- marshalling --------------------------------------------------------

    @staticmethod
    def _live_rows(table: Table):
        """Row selector for marshalling: tables carry live rows scattered
        among bucket padding (the valid mask is authoritative); tables
        without a mask are contiguous-prefix (host-rebuilt ones)."""
        if table.valid is None:
            return slice(0, table.count)
        return np.flatnonzero(np.asarray(table.valid) > 0)

    def bindings_from_table(self, table: Table) -> List[Dict[str, object]]:
        sel = self._live_rows(table)
        cols = {a: np.asarray(c)[sel] for a, c in table.cols.items()}
        ecols = {
            a: (np.asarray(ci)[sel], np.asarray(pos)[sel])
            for a, (ci, pos) in table.edge_cols.items()
        }
        dcols = {a: np.asarray(c)[sel] for a, c in table.depth_cols.items()}
        n = next(iter(cols.values())).shape[0] if cols else (
            next(iter(ecols.values()))[0].shape[0] if ecols else table.count
        )
        # aliases that never hit a table column (fully detached optional
        # arms) marshal as None
        missing = [
            a
            for a in self.pattern.nodes
            if a not in cols and a not in ecols
        ]
        out: List[Dict[str, object]] = []
        doc_cache: Dict[int, object] = {}
        edge_cache: Dict[Tuple[int, int], object] = {}
        for i in range(n):
            b: Dict[str, object] = {}
            for a, arr in cols.items():
                v = int(arr[i])
                if v < 0:
                    b[a] = None
                else:
                    doc = doc_cache.get(v)
                    if doc is None:
                        doc = self.db.load(self.snap.rid_of(v))
                        doc_cache[v] = doc
                    b[a] = doc
            for a, (ci, pos) in ecols.items():
                c, p = int(ci[i]), int(pos[i])
                if c < 0 or p < 0:
                    b[a] = None
                else:
                    ed = edge_cache.get((c, p))
                    if ed is None:
                        rid = self.snap.edge_classes[self.edge_class_list[c]].edge_rids[p]
                        ed = self.db.load(rid)
                        edge_cache[(c, p)] = ed
                    b[a] = ed
            for a, arr in dcols.items():
                v = int(arr[i])
                b[a] = None if v < 0 else v
            for a in missing:
                b[a] = None
            out.append(b)
        return out

    def rows_from_table(self, table: Table, params: Optional[Dict] = None) -> List[Result]:
        params = self.params if params is None else params
        fast = self._fast_rows(table, params)
        if fast is not None:
            return fast
        named = [
            n.alias for n in self.pattern.nodes.values() if not n.anonymous
        ]
        rows = match_rows_from_bindings(
            self.db,
            self.stmt,
            named,
            self.bindings_from_table(table),
            params,
            None,
        )
        if self.element_alias is not None:
            # rewritten whole-record SELECT: the finalize tail (ORDER/
            # SKIP/LIMIT) ran on the props rows; unwrap to element rows
            rows = [
                Result(element=r.get_property(self.element_alias))
                for r in rows
            ]
        return rows

    # -- columnar fast RETURN path -----------------------------------------

    def count_only_name(self) -> Optional[str]:
        """Projection name when RETURN is a lone COUNT(*) (no grouping)."""
        stmt = self.stmt
        if stmt.group_by or stmt.unwind:
            return None
        r = stmt.returns
        if (
            len(r) == 1
            and isinstance(r[0].expr, A.FunctionCall)
            and r[0].expr.name.lower() == "count"
            and len(r[0].expr.args) == 1
            and isinstance(r[0].expr.args[0], A.Star)
        ):
            from orientdb_tpu.exec.oracle import expr_name

            return r[0].alias or expr_name(r[0].expr, 0)
        return None

    def finalize_count(
        self, name: str, count: int, params: Optional[Dict] = None
    ) -> List[Result]:
        # aggregate path applies only ORDER/SKIP/LIMIT (no DISTINCT)
        params = self.params if params is None else params
        out = [Result(props={name: count})]
        out = _order_rows(out, self.stmt.order_by, self.db, params, None)
        base_ctx = EvalContext(self.db, params=params)
        return _skip_limit(out, self.stmt.skip, self.stmt.limit, base_ctx)

    def _fast_rows(
        self, table: Table, params: Optional[Dict] = None
    ) -> Optional[List[Result]]:
        """Build result rows straight from device columns when RETURN is a
        count(*) or plain columnar projections — skipping per-row Document
        loads entirely (the [E] OResultInternal marshalling cost the north
        star calls out). Returns None when ineligible (shared slow path)."""
        stmt = self.stmt
        if stmt.group_by or stmt.unwind:
            return None
        returns = stmt.returns
        if len(returns) == 1 and isinstance(returns[0].expr, A.ContextVar):
            return None  # $matches/$paths/$elements need Documents
        # lone COUNT(*) → O(1): the table's valid row count
        name = self.count_only_name()
        if name is not None:
            return self.finalize_count(name, table.count, params)
        # plain columnar projections: alias.prop / depth aliases
        from orientdb_tpu.exec.eval import contains_aggregate

        if any(contains_aggregate(p.expr) for p in returns):
            return None
        plans = []  # (name, values np | None, present np | None, decode)
        sel = self._live_rows(table)
        n = table.count if isinstance(sel, slice) else int(sel.shape[0])
        for i, p in enumerate(returns):
            e = p.expr
            name = p.alias or _match_proj_name(e, i)
            if isinstance(e, A.Identifier) and e.name in table.depth_cols:
                arr = np.asarray(table.depth_cols[e.name])[sel]
                plans.append((name, arr, arr >= 0, None))
                continue
            if (
                isinstance(e, A.FieldAccess)
                and isinstance(e.base, A.Identifier)
                and e.base.name in table.cols
            ):
                prop = e.name
                if prop in self.dg.non_columnar or prop.startswith("@"):
                    return None
                idx = np.asarray(table.cols[e.base.name])[sel]
                col = self.snap.v_columns.get(prop)
                if col is None:
                    plans.append((name, None, None, None))  # never present
                    continue
                ci = np.clip(idx, 0, max(len(col.values) - 1, 0))
                vals = col.values[ci]
                pres = col.present[ci] & (idx >= 0)
                plans.append((name, vals, pres, col))
                continue
            return None
        # vectorized decode: one object column per projection (no per-value
        # Python decode calls — this loop runs per result row otherwise)
        names = []
        obj_cols = []
        for name, vals, pres, col in plans:
            names.append(name)
            if vals is None:
                obj_cols.append(np.full(n, None, object))
                continue
            if col is None:  # depth alias: plain ints
                o = vals.astype(object)
            elif col.kind == "str":
                d = col.dict_array()
                o = d[np.clip(vals, 0, len(d) - 1)]
            elif col.kind == "bool":
                o = (vals != 0).astype(object)
            elif col.kind == "float":
                o = vals.astype(float).astype(object)
            else:
                o = vals.astype(object)
            o[~pres] = None
            obj_cols.append(o)
        if not (stmt.distinct or stmt.order_by or stmt.skip or stmt.limit):
            # (unwind already bailed at the top of this function)
            # finalize tail is identity → hand the columns over whole; the
            # ResultSet serializes them in bulk without per-row Results
            return ColumnarRows(names, [c.tolist() for c in obj_cols], n)
        out = [
            Result(props=dict(zip(names, vals_row)))
            for vals_row in zip(*obj_cols)
        ] if obj_cols else [Result(props={}) for _ in range(n)]
        return finalize_match_rows(self.db, stmt, out, params or self.params, None)


# ---------------------------------------------------------------------------
# TRAVERSE compilation
# ---------------------------------------------------------------------------


class TpuTraverseSolver:
    """Compiled TRAVERSE: bitmap-BFS levels over the device CSR.

    The reference walks TRAVERSE per-record with a visited set ([E]
    OTraverseStatement → Depth/BreadthFirstTraverseStep, SURVEY.md §1
    layer 5); here each level is ONE frontier bitmap hop over the whole
    graph (psum-OR merged across mesh shards when sharded), with
    MAXDEPTH / WHILE($depth, fields) applied as level masks.

    Semantics vs the oracle (`oracle.execute_traverse`):
    - BREADTH_FIRST pops FIFO, so every record is admitted at its minimum
      discovery depth — exactly what level-wise bitmap BFS computes; the
      result SET matches the oracle, while within-level order is vertex
      index order (the oracle's is parent-enumeration order; TRAVERSE
      order within a level is enumeration-defined in the reference too).
    - DEPTH_FIRST admits records at possibly non-minimal depths, so it
      compiles only when no MAXDEPTH/WHILE can observe the difference —
      then the result set is the plain reachability closure.
    - LIMIT slices in traversal order → always falls back to the oracle.

    Fields compile for out()/in()/both() with literal class names (or
    none); '*' / outE/inE/bothE / link fields emit edge documents and
    fall back.
    """

    def __init__(self, db, stmt: A.TraverseStatement, params: Dict) -> None:
        self.db = db
        self.stmt = stmt
        self.params = params or {}
        snap = db.current_snapshot(require_fresh=True)
        if snap is None:
            raise Uncompilable("no fresh snapshot attached")
        self.snap = snap
        self.dg: DeviceGraph = device_graph(snap)
        self.overlay = getattr(snap, "_overlay", None)
        self.delta_gen = (
            self.overlay.plan_gen if self.overlay is not None else 0
        )
        #: hot/cold tier manager (storage/tiering) when the snapshot's
        #: adjacency exceeds the HBM cap; the recording run accumulates
        #: every faulted block into tier_touched — frozen at plan
        #: construction as the plan's dispatch-prefetch footprint
        self.tier = getattr(snap, "_tier", None)
        self.tier_touched: set = set()
        #: TRAVERSE replays are fully static — the roots array is baked
        #: at record time and the schedule's overflow flag is dropped
        #: (sound on immutable snapshots, where replay inputs are
        #: identical by construction). On a delta-maintained snapshot
        #: the plan therefore pins the overlay's data version and
        #: re-records when ANY delta has landed since (dispatch checks).
        self.delta_data_version = (
            self.overlay.data_version if self.overlay is not None else 0
        )
        self.sched = SizeSchedule()
        if stmt.limit is not None:
            raise Uncompilable("TRAVERSE LIMIT slices in traversal order")
        if stmt.strategy == "DEPTH_FIRST" and (
            stmt.max_depth is not None or stmt.while_cond is not None
        ):
            raise Uncompilable(
                "DEPTH_FIRST with MAXDEPTH/WHILE admits at non-minimal depths"
            )
        self.hop_items = self._compile_fields(stmt.fields)
        self.while_fn = None
        if stmt.while_cond is not None:
            scope = ColumnScope(self.dg.columns, self.dg.non_columnar)
            self.while_fn = compile_predicate(
                stmt.while_cond, scope, self.params, allow_depth=True
            )
        self.roots = self._resolve_roots()

    def _compile_fields(self, fields) -> List[Tuple[str, str, None]]:
        dirs: List[Tuple[str, Optional[str]]] = []
        if not fields:
            raise Uncompilable("TRAVERSE * follows edges as records")
        for f in fields:
            if not isinstance(f, A.FunctionCall):
                raise Uncompilable("TRAVERSE field is not out()/in()/both()")
            name = f.name.lower()
            if name not in ("out", "in", "both"):
                raise Uncompilable(f"TRAVERSE {name}() emits non-vertex records")
            classes: List[Optional[str]] = []
            if not f.args:
                classes.append(None)
            for a in f.args:
                if not (isinstance(a, A.Literal) and isinstance(a.value, str)):
                    raise Uncompilable("non-literal edge class in TRAVERSE field")
                classes.append(a.value)
            for cls in classes:
                dirs.append((name, cls))
        items = []
        for direction, cls in dirs:
            for cname in self.snap.concrete_edge_classes(cls):
                for d in ("out", "in") if direction == "both" else (direction,):
                    items.append((cname, d, None))
        return items

    def _resolve_roots(self) -> np.ndarray:
        """Root record → dense vertex indices, via the oracle's target
        resolution (host-side; supports class / rid / subquery targets)."""
        from orientdb_tpu.exec.oracle import resolve_target_rows

        base_ctx = EvalContext(self.db, params=self.params)
        idxs: List[int] = []
        for row in resolve_target_rows(self.db, self.stmt.target, base_ctx):
            doc = row if isinstance(row, Document) else (
                row.element if isinstance(row, Result) else None
            )
            if doc is None:
                continue
            i = self.snap.idx_of(doc.rid)
            if i is None:
                raise Uncompilable("TRAVERSE root is not a snapshot vertex")
            idxs.append(i)
        # preserve first-occurrence order for depth-0 emission; BFS admits
        # each root once
        seen = set()
        uniq = [i for i in idxs if not (i in seen or seen.add(i))]
        return np.asarray(uniq, np.int32)

    def solve(self) -> Tuple[jnp.ndarray, int]:
        """Returns (emitted vertex indices [bucketed], emitted count),
        level by level (depth-0 roots first, then each BFS level)."""
        V = self.dg.num_vertices
        vb = K.bucket(max(V, 1))
        univ = jnp.arange(vb, dtype=jnp.int32)
        univ = jnp.where(univ < V, univ, -1)
        hops = build_bitmap_hops(
            self.dg, self.hop_items, sched=self.sched, tier=self.tier,
            touched=self.tier_touched,
        )
        # one logical traversal row: [1, vb] bitmap with every root set
        roots = jnp.zeros((1, vb), bool)
        if self.roots.shape[0]:
            roots = roots.at[0, jnp.asarray(self.roots)].set(True)
        visited = roots
        frontier = roots
        depth = 0
        # depth-0 emits the caller's root order (host-known), not index order
        parts: List[jnp.ndarray] = [jnp.asarray(self.roots)]
        counts: List[int] = [int(self.roots.shape[0])]
        max_depth = self.stmt.max_depth
        while True:
            if max_depth is not None and depth >= max_depth:
                break
            nxt = jnp.zeros_like(frontier)
            for hop in hops:
                nxt = nxt | hop(frontier)
            nxt = nxt & ~visited
            if self.while_fn is not None:
                gate = self.while_fn(univ, {"depth": depth + 1})
                nxt = nxt & gate[None, :]
            keep, kn, _dev = _observe_compact(self.sched, nxt.reshape(-1))
            if kn == 0:
                break
            visited = visited | nxt
            depth += 1
            parts.append(keep)
            counts.append(kn)
            frontier = nxt
            if depth > V:  # safety: no min-depth exceeds |V|
                break
        total = sum(counts)
        width = K.bucket(max(total, 1))
        idx = _pad_concat([p[:c] for p, c in zip(parts, counts)], width)
        return idx, total

    def rows_from(self, idx: np.ndarray, count: int) -> List[Result]:
        out: List[Result] = []
        for i in np.asarray(idx)[:count]:
            doc = self.db.load(self.snap.rid_of(int(i)))
            if doc is not None:
                out.append(Result(element=doc))
        return out


import threading as _threading

#: serializes TRACE-bearing work: a background warm-up tracing one plan
#: while the main thread eagerly records another shares lazily-populated
#: device-graph caches; concurrent first-touch of those can leak one
#: trace's values into the other. Compiled-plan DISPATCHES never trace
#: and never take this lock.
_TRACE_LOCK = _threading.RLock()


class _AotWarmup:
    """Background trace+compile of a replay's jitted function.

    A freshly recorded plan returns its rows from the eager recording run —
    its `jax.jit` replay has never been called, so the FIRST replay dispatch
    would absorb the whole trace+XLA-compile (~10 s for a deep var-depth
    plan), landing squarely in what callers think is the steady state.
    `ensure_compiled` moves that cost to record time on a daemon thread
    (tracing swaps `dg.arrays` thread-locally, so concurrent queries are
    unaffected); `dispatch` waits for a pending warm-up instead of
    duplicating the compile."""

    _aot_ready = None  # threading.Event while a warm-up is in flight

    #: all in-flight warm-up events (drain_warmups waits on these; each
    #: worker removes its own entry, so the list stays bounded)
    _inflight: "List" = []

    def _warm_call(self):  # pragma: no cover - overridden
        raise NotImplementedError

    def _arg_subset(self):
        """The plan's jit-arg pytree: only the graph arrays its
        recording touched (`_record`'s touch log). Keeps every cached
        plan's pytree structure stable while pruned columns upload
        lazily, and ships executables only what they read."""
        arrays = self.solver.dg.arrays
        keys = getattr(self, "arg_keys", None)
        if keys is None:
            # SNAPSHOT the dict: another thread may fault a pruned
            # column in (ensure_key -> _put) while jax flattens the
            # pytree on this one
            return dict(arrays)
        return {k: arrays[k] for k in keys}

    def _is_compiled(self) -> bool:
        try:
            return self.jitted._cache_size() > 0
        except Exception:
            return False

    def ensure_compiled(self) -> None:
        if self._aot_ready is not None or self._is_compiled():
            return
        import threading

        from orientdb_tpu.utils.metrics import metrics

        ev = threading.Event()
        self._aot_ready = ev
        _AotWarmup._inflight.append(ev)
        # keep the exit-time drain AHEAD of JAX's own teardown handlers:
        # atexit runs in reverse registration order and JAX registers
        # teardown lazily at first compile — re-registering on every
        # warm-up start keeps the drain first, so no trace is in flight
        # when the compile machinery is dismantled
        import atexit

        atexit.unregister(drain_warmups)
        atexit.register(drain_warmups)

        def work():
            # the warm-up CALLS the jitted replay (result discarded): JAX's
            # AOT `lower().compile()` does not seed the jit call cache, so
            # executing once is the only way to make the next dispatch hit
            try:
                for attempt in (0, 1):
                    try:
                        snap = getattr(
                            getattr(self, "solver", None), "snap", None
                        )
                        if (
                            snap is not None
                            and snap._device_cache is None
                        ):
                            # the snapshot's device graph was released
                            # (delta-plane compaction swap): the plan is
                            # dead, warming it would only KeyError
                            metrics.incr("plan_cache.aot_skip_released")
                            break
                        # the lock serializes TRACING (thread-local
                        # device-graph cache swaps); device execution
                        # is async, so wait for it after release
                        with _TRACE_LOCK:
                            res = self._warm_call()
                        jax.block_until_ready(res)
                        metrics.incr("plan_cache.aot_compile")
                        break
                    except ScheduleOverflow:
                        # stale delta generation (_check_delta_gen):
                        # the next dispatch re-records — nothing to warm
                        metrics.incr("plan_cache.aot_skip_stale")
                        break
                    except Exception:
                        if attempt:
                            # give up: the next dispatch compiles inline
                            # (slower but correct)
                            log.exception("background plan warm-up failed")
                            metrics.incr("plan_cache.aot_compile_error")
                        else:
                            import time as _t

                            _t.sleep(0.05)
            finally:
                ev.set()
                try:
                    _AotWarmup._inflight.remove(ev)
                except ValueError:
                    pass  # a concurrent drain already claimed it

        threading.Thread(target=work, daemon=True, name="plan-aot").start()

    def wait_compiled(self) -> None:
        ev = self._aot_ready
        if ev is not None:
            ev.wait()
            self._aot_ready = None


def drain_warmups() -> None:
    """Block until every in-flight background plan compile finishes.

    Benchmarks and tests call this between warm-up and measurement so AOT
    compile threads (which hold the GIL through long trace phases) don't
    steal host time from the timed section. Also registered atexit:
    killing a daemon thread inside an XLA compile at interpreter teardown
    aborts the process ("FATAL: exception not rethrown")."""
    pending, _AotWarmup._inflight = _AotWarmup._inflight, []
    for ev in pending:
        ev.wait()


import atexit  # noqa: E402  (registration belongs right next to the drain)

atexit.register(drain_warmups)


class _CompiledTraverse(_AotWarmup):
    """Replayable TRAVERSE plan (same dispatch/materialize protocol as
    `_CompiledPlan` so `execute_batch` treats both uniformly)."""

    def __init__(self, solver: TpuTraverseSolver, count: int) -> None:
        self.solver = solver
        self.count = count
        self.tier_footprint = frozenset(solver.tier_touched)
        self.jitted = jax.jit(self._replay)

    def _warm_call(self):
        # snapshot the canonical dict: the main thread may _put new keys
        # (lazy class-id/edge uploads) while jit flattens the pytree here
        return self.jitted(self._arg_subset())

    def _replay(self, arrays):
        dg = self.solver.dg
        saved = dg.arrays
        dg.arrays = arrays
        try:
            self.solver.sched.start_replay()
            idx, _n = self.solver.solve()
        finally:
            dg.arrays = saved
        return idx

    def dispatch(self, params: Optional[Dict] = None):
        # TRAVERSE plans bake parameter values (their full values join the
        # plan-cache key), so `params` is accepted for interface parity
        # with _CompiledPlan and ignored
        _check_traverse_static(self.solver)
        self.wait_compiled()
        tier = self.solver.tier
        if tier is not None:
            args = tier.prepare_dispatch(self.tier_footprint, self._arg_subset)
        else:
            args = self._arg_subset()
        return self.jitted(args)

    def batchable(self) -> bool:
        """TRAVERSE plans bake their parameters, so every batch item
        sharing this plan is the IDENTICAL program on identical inputs:
        the group path serves them all with ONE dispatch (the no-dyn
        shared-dispatch case of execute_batch's grouping)."""
        return (
            self.solver.dg.mesh_graph is None and self.solver.tier is None
        )

    def _dyn_args(self, params: Optional[Dict]) -> Dict:
        _check_delta_gen(self.solver)
        _check_traverse_static(self.solver)
        return {}  # no dynamic args: grouping uses the shared dispatch

    def materialize(self, dev, params: Optional[Dict] = None) -> List[Result]:
        tier = self.solver.tier
        if tier is not None:
            tier.release_footprint(self.tier_footprint)
        return self.solver.rows_from(np.asarray(dev), self.count)

    def rows(self, params: Optional[Dict] = None) -> List[Result]:
        arr = _fetch_profiled([self.dispatch()])[0]
        with timed("tpu.host_s"):
            return self.materialize(arr)


# ---------------------------------------------------------------------------
# compiled plan cache ([E] OExecutionPlanCache analog)
# ---------------------------------------------------------------------------


class ScheduleOverflow(Exception):
    """A parameter-generic replay's live sizes exceeded the recorded
    schedule's capacities; the result was discarded. Caller re-records."""


def _check_delta_gen(solver) -> None:
    """Fail a dispatch whose plan was recorded under an older delta
    structure (storage/deltas bumps the generation on the first
    topology delta and on dictionary appends, clearing the plan cache;
    this guards plan objects picked BEFORE the bump). The overflow
    surface routes the caller straight into the re-record path."""
    ov = getattr(solver, "overlay", None)
    if ov is not None and ov.plan_gen != solver.delta_gen:
        raise ScheduleOverflow(
            f"delta structure moved (gen {solver.delta_gen} -> "
            f"{ov.plan_gen})"
        )


def _check_traverse_static(solver) -> None:
    """TRAVERSE replays bake their host-resolved roots and drop the
    size schedule's overflow flag — sound only while replay inputs are
    identical to the recording (immutable snapshots). On a
    delta-maintained snapshot ANY applied event invalidates that
    assumption, so the dispatch re-records (MATCH keeps its full
    delta-aware replay; TRAVERSE pays an eager solve under writes)."""
    ov = getattr(solver, "overlay", None)
    if ov is not None and ov.data_version != solver.delta_data_version:
        raise ScheduleOverflow(
            "traverse recording is stale under delta maintenance "
            f"(data v{solver.delta_data_version} -> v{ov.data_version})"
        )


class _CompiledPlan(_AotWarmup):
    """A solver whose size schedule is learned: re-executions replay the
    whole solve as one jitted, sync-free device dispatch.

    Numeric query parameters are jit ARGUMENTS of the replay (see
    predicates.ParamBox), so ONE recorded plan serves every parameter
    value — the way the reference's [E] OExecutionPlanCache caches per
    statement. Because buffer sizes were recorded under the recording
    parameters, the replay returns (alongside the result) a device valid
    mask, the true row count, and an overflow flag; materialization uses
    the live mask/count, and an overflow raises ScheduleOverflow so the
    front door re-records with the new parameters (bucket capacities grow
    monotonically, so this converges).

    Execution is split into ``dispatch()`` (enqueue the device work —
    microseconds) and ``materialize()`` (device→host transfer + row
    marshalling). On a tunneled TPU the transfer carries a fixed ~90 ms
    RTT regardless of size, so ``execute_batch`` dispatches a whole batch,
    starts async host copies for every result, and only then materializes —
    overlapping N round trips into ~one."""

    def __init__(self, solver: TpuMatchSolver, table: Table) -> None:
        self.solver = solver
        self.v_names = sorted(table.cols)
        self.e_names = sorted(table.edge_cols)
        self.d_names = sorted(table.depth_cols)
        self.count = table.count
        self.width = table.width
        self.count_name = solver.count_only_name()
        self.fetch_limit = self._literal_fetch_limit(solver.stmt)
        #: result columns in the packed data stack (vertex + 2-per-edge
        #: + depth) — shared by direct_fetch and the group-lane budget
        self.ncols = (
            len(self.v_names) + 2 * len(self.e_names) + len(self.d_names)
        )
        #: small full buffers ship whole in the batch's first transfer
        #: wave — no meta-gated page election (see _replay's direct path)
        self.direct_fetch = (
            self.count_name is None
            and self.ncols > 0
            and self.width >= 2  # meta row needs [count, overflow] slots
            and 4 * self.width * self.ncols <= config.result_direct_bytes
        )
        #: page-ladder HBM budget, frozen per plan at construction —
        #: reading config inside _replay would bake it at trace time
        #: invisibly (jaxlint); freezing here makes the staleness
        #: boundary explicit: retuning applies from the next recording
        self.page_budget_bytes = int(config.result_page_budget_bytes)
        #: dynamic parameters the compiled predicates actually read
        self.dyn_spec = dict(solver.param_box.used)
        #: index-seeded root capacities (alias → padded length)
        self.seed_spec = dict(solver.seed_box.spec)
        #: (ladder index, fits16) the LAST materialization elected —
        #: dispatch() speculatively starts that page's device→host copy
        #: so the transfer rides behind the compute instead of waiting
        #: for the meta wave (the r04 rows-path 12 ms serialized tail)
        self._page_guess: Optional[Tuple[int, bool]] = None
        #: (B, rows, fits16) the last GROUP page election (group_page's
        #: cache key) — _group_dispatch prefetches the slice when its
        #: executable is already compiled
        self._group_page_guess: Optional[Tuple[int, int, bool]] = None
        #: data-stack shape the guess's page fn was compiled against:
        #: a prefetch only fires on an exact shape match, so the jit
        #: call is a guaranteed cache hit — a differently-sized batch
        #: must never absorb a synchronous XLA compile on the drain path
        self._group_page_shape: Optional[Tuple[int, ...]] = None
        #: tiered snapshots: the blocks the recording run faulted —
        #: every dispatch re-ensures them resident (pin + async
        #: prefetch) before grabbing its argument pytree
        self.tier_footprint = frozenset(solver.tier_touched)
        self.jitted = jax.jit(self._replay)

    def _replay_core(self, arrays, dyn):
        """Shared replay body: run the recorded solve and front-pack the
        result columns. Returns ``(count_dev, overflow, data)`` where
        ``data`` is the [C, width] int32 column stack (None for
        count-only / column-less plans)."""
        # swap the tracer pytree into the device graph for the trace so the
        # graph buffers become jit ARGUMENTS (shared across every cached
        # plan) rather than per-executable HLO constants; same for the
        # dynamic parameter scalars via the param box
        solver = self.solver
        dg = solver.dg
        saved = dg.arrays
        dg.arrays = arrays
        solver.param_box.set_current(dyn)
        solver.seed_box.current = {
            a: dyn[f"__seed__:{a}"] for a in self.seed_spec
        }
        try:
            solver.sched.start_replay()
            table = solver.solve_table()
        finally:
            dg.arrays = saved
            solver.param_box.reset()
            solver.seed_box.current = {}
        overflow = solver.sched.overflow_flag().astype(jnp.int32)
        count_dev = table.count_device.astype(jnp.int32)
        if self.count_name is not None or self.width == 0:
            return count_dev, overflow, None
        flat: List[jnp.ndarray] = [table.cols[a] for a in self.v_names]
        for a in self.e_names:
            flat.extend(table.edge_cols[a])
        flat.extend(table.depth_cols[a] for a in self.d_names)
        if not flat:  # no columns (e.g. fully-detached optional pattern)
            return count_dev, overflow, None
        width = flat[0].shape[0]
        # front-pack live rows ON DEVICE (stable), so the host needs only
        # the first `count` slots: the batch fetch path reads meta first
        # and then transfers just a page-rounded live prefix instead of
        # the whole capacity-padded buffer (at demodb scale the padded
        # stack was ~1 MB/query on a ~10 MB/s tunnel — the measured
        # rows-path bottleneck)
        perm = K.compact_indices(table.valid_device[:width], width)
        data = jnp.stack([K.take_pad(c, perm, -1) for c in flat])
        return count_dev, overflow, data

    @staticmethod
    def _fits16_flag(data, count_dev, width):
        """Runtime bit-width election flag: 1 when every live value fits
        int16 — decided per dispatch by a meta flag, not per plan."""
        live = jnp.arange(width, dtype=jnp.int32)[None, :] < count_dev
        masked = jnp.where(live, data, 0)
        return (
            (jnp.max(masked) < 32767) & (jnp.min(masked) > -32768)
        ).astype(jnp.int32)

    def _replay_group(self, arrays, dyn):
        """Group-mode replay for row-returning plans: ``(meta, data)``
        with the FULL int32 column stack and no page ladder — the group
        fetch elects ONE page for the whole lane stack after the meta
        wave (`group_page`), so the ladder's per-dispatch
        materialization cost is not paid B times."""
        count_dev, overflow, data = self._replay_core(arrays, dyn)
        if data is None:
            return jnp.stack([count_dev, overflow, jnp.int32(0)]), None
        width = data.shape[1]
        meta = jnp.stack(
            [count_dev, overflow, self._fits16_flag(data, count_dev, width)]
        )
        return meta, data

    @staticmethod
    def _page_round(W: int, need: int) -> int:
        """Rows of the compact group page covering ``need`` live rows:
        pow-of-_GROUP_PAGE_ROUND rounding, capped at the full width —
        ONE formula shared by the election and the speculative
        dispatch-time prefetch so their keys can never drift."""
        return min(W, -(-max(need, 1) // _GROUP_PAGE_ROUND) * _GROUP_PAGE_ROUND)

    @staticmethod
    def _page_fn(B: int, n: int, fits16: bool):
        # both callers memoize the result in _group_page_fns keyed
        # (B, n, fits16) — the construction itself never serves a batch
        if fits16:
            return jax.jit(lambda d: d[:B, :, :n].astype(jnp.int16))  # lint: allow(jaxlint)
        return jax.jit(lambda d: d[:B, :, :n])  # lint: allow(jaxlint)

    def _compile_page_async(self, key, data_dev) -> None:
        """Background trace+compile of one (B, n, fits16) page fn —
        serving batches must never absorb an XLA compile."""
        import threading

        flags = self.__dict__.setdefault("_page_compiling", set())
        if key in flags:
            return
        flags.add(key)
        cache = self.__dict__.setdefault("_group_page_fns", {})

        def work():
            try:
                B, n, f16 = key
                fn = self._page_fn(B, n, f16)
                jax.block_until_ready(fn(data_dev))
                cache[key] = fn
            except Exception:
                log.exception("group page compile failed: %s", key)
            finally:
                flags.discard(key)

        threading.Thread(target=work, daemon=True).start()

    def precompile_group_pages(self, data_dev) -> None:
        """Compile the pow2 page-fn ladder for a group's stacked data
        shape — called from the background group-compile thread so the
        first grouped serving batch finds its page fn ready."""
        Bb, _C, W = (int(s) for s in data_dev.shape)
        cache = self.__dict__.setdefault("_group_page_fns", {})
        n = _GROUP_PAGE_ROUND
        sizes = []
        while n < W:
            sizes.append(n)
            n *= 2
        sizes.append(W)
        for n in sizes:
            for f16 in (False, True):
                key = (Bb, n, f16)
                if key not in cache:
                    try:
                        fn = self._page_fn(Bb, n, f16)
                        jax.block_until_ready(fn(data_dev))
                        cache[key] = fn
                    except Exception:
                        log.exception(
                            "group page precompile failed: %s", key
                        )
                        return

    def group_page(self, data_dev, B: int, need: int, fits16: bool):
        """Elect the compact page for a whole group's stacked data:
        [Bb, C, width] → [B, C, n] (int16 when every lane's live values
        fit), as ONE Execute. NEVER compiles synchronously: an exact
        (B, n, fits16) hit serves directly; a miss kicks a background
        compile and serves this batch from the smallest precompiled
        fallback (the pow2 ladder built by `precompile_group_pages`),
        or the raw full int32 stack when nothing is ready yet."""
        n = self._page_round(int(data_dev.shape[2]), need)
        cache = self.__dict__.setdefault("_group_page_fns", {})
        fn = cache.get((B, n, fits16))
        if fn is not None:
            return fn(data_dev)
        self._compile_page_async((B, n, fits16), data_dev)
        best = None
        # snapshot: background compile threads insert into this dict
        for (b2, n2, f2), fn2 in list(cache.items()):
            if b2 >= B and n2 >= n and f2 == fits16:
                if best is None or (n2, b2) < best[0]:
                    best = ((n2, b2), fn2)
        if best is not None:
            return best[1](data_dev)
        return data_dev  # nothing compiled yet: ship the raw stack once

    def _replay(self, arrays, dyn):
        count_dev, overflow, data = self._replay_core(arrays, dyn)
        if data is None:
            # COUNT(*) plan (or column-less table): two scalars suffice
            return jnp.stack([count_dev, overflow, jnp.int32(0)]), None, None
        width = data.shape[1]
        if self.direct_fetch:
            # small buffer: ONE fused [C+1, width] array (data rows + a
            # trailing [count, overflow, ...] meta row) = ONE device
            # buffer and ONE host copy per query, started in the batch's
            # first transfer wave. On the tunneled link every buffer
            # fetch carries a fixed cost, so for few-KB results a single
            # fused copy beats the meta-then-elected-page protocol (the
            # round-3 LDBC IS regression); big buffers keep the election.
            meta_row = (
                jnp.zeros(width, jnp.int32)
                .at[0].set(count_dev)
                .at[1].set(overflow)
            )
            return jnp.concatenate([data, meta_row[None, :]], axis=0)
        # runtime bit-width election: when every live value fits int16
        # (vertex indices on small graphs usually do; edge positions on
        # big ones don't), the fetch ships the half-size copy — decided
        # per dispatch by a meta flag, not per plan, so it stays general
        meta = jnp.stack(
            [count_dev, overflow, self._fits16_flag(data, count_dev, width)]
        )
        # pre-materialized pow2 page prefixes (both dtypes): the batch
        # fetch picks the smallest page covering the live count and reads
        # an EXISTING device buffer — per-query slice dispatches after the
        # meta wave measured ~15 ms each on the tunnel, dwarfing the
        # bytes they saved. The full ladder costs ~3x the plain buffer in
        # device memory (prefix sums ≈ 2x per dtype), so it is emitted
        # only under a budget: wide plans (where a 64-deep batch of
        # tripled result buffers could pressure HBM) fall back to the
        # single full-width buffer per dtype — their transfers hide
        # behind device compute in the interleaved fetch anyway.
        C = int(data.shape[0])
        pages32, pages16 = [], []
        if 12 * width * C <= self.page_budget_bytes:
            p = _PAGE_MIN
            while p < width:
                pages32.append(data[:, :p])
                pages16.append(data[:, :p].astype(jnp.int16))
                p *= 2
        pages32.append(data)
        pages16.append(data.astype(jnp.int16))
        return meta, pages32, pages16

    def _dyn_args(self, params: Optional[Dict]) -> Dict:
        # host-side (numpy) values: the jit call transfers them, and
        # dispatch_many can stack B of them into ONE transfer per key
        _check_delta_gen(self.solver)
        params = params if params is not None else self.solver.params
        dyn = {}
        for k, kind in self.dyn_spec.items():
            v = params[k]
            dtype = np.float32 if kind == "float" else np.int32
            dyn[k] = np.asarray(int(v) if kind != "float" else v, dtype)
        for alias, cap in self.seed_spec.items():
            hits = self.solver.compute_seed(alias, params)
            if hits.shape[0] > cap:
                # more index hits than the recorded capacity: this
                # replay's buffers are too small — re-record (variants)
                raise ScheduleOverflow(f"root seed '{alias}' > {cap}")
            arr = np.full(cap, -1, np.int32)
            arr[: hits.shape[0]] = hits
            dyn[f"__seed__:{alias}"] = arr
        return dyn

    def _warm_call(self):
        # dict snapshot for the same flatten-vs-insert reason as traverse
        return self.jitted(self._arg_subset(), self._dyn_args(None))

    def dispatch(self, params: Optional[Dict] = None):
        """Enqueue the replay on device; returns the un-fetched result."""
        self.wait_compiled()
        import orientdb_tpu.obs.timeline as _TL

        if self.solver.dg.mesh_graph is not None:
            _TL.note_path("sharded")
        dyn = self._dyn_args(params)
        if dyn:
            # EXPLICIT host→device upload of the parameter scalars/seed
            # arrays: handing the jitted call raw numpy made the same
            # transfer implicitly on every dispatch — invisible to
            # profiling and flagged by the deviceguard transfer guard
            import time as _time

            import orientdb_tpu.obs.critpath as _CP

            _t_up = _time.perf_counter()
            dyn = jax.device_put(dyn)
            _CP.add_segment("param_upload", _time.perf_counter() - _t_up)
            _TL.mark("param_upload")
        tier = self.solver.tier
        if tier is not None:
            # footprint prefetch + pin + atomic arg-pytree grab under
            # the tier lock — a concurrent eviction can never hand this
            # dispatch a torn (pool, page_of) pair; materialize unpins
            args = tier.prepare_dispatch(self.tier_footprint, self._arg_subset)
        else:
            args = self._arg_subset()
        devicefault.dispatch_point()
        dev = self.jitted(args, dyn)
        _TL.mark("device_dispatch")
        self._prefetch_elected(dev)
        return dev

    def _prefetch_elected(self, dev) -> None:
        """Speculative result-page prefetch: start the device→host copy
        of the page the LAST materialization elected, at DISPATCH time.
        The D2H queues behind the producing compute, so the bytes move
        during the next dispatch's formation instead of serializing
        after the meta wave (r04 rows path: 20 ms device + 12 ms
        transfer back-to-back; steady state re-elects the same page, so
        the transfer hides). A wrong guess costs one redundant page
        copy — the election itself stays exact."""
        guess = self._page_guess
        if guess is None or not (isinstance(dev, tuple) and len(dev) == 3):
            return
        idx, f16 = guess
        pages = dev[2] if f16 else dev[1]
        if pages and 0 <= idx < len(pages):
            _copy_to_host_async(pages[idx])
            metrics.incr("tpu.page_prefetch.start")
            from orientdb_tpu.obs.memledger import memledger
            from orientdb_tpu.obs.timeline import note_prefetch_start

            memledger.register(
                "prefetched_page",
                f"plan:{id(self):x}",
                "spec_page",
                arr=pages[idx],
            )
            note_prefetch_start()

    def batchable(self) -> bool:
        """Eligible for the vmapped one-Execute group dispatch: count-only
        and direct-fetch plans (one small output buffer per lane), plus
        row-returning plans whose full int32 stack fits the per-lane
        budget (the group replays with NO page ladder and elects one
        compact page for the whole stack after the meta wave —
        `group_page`). Mesh plans keep per-query dispatch because
        vmap-over-shard_map is not exercised anywhere."""
        if self.solver.dg.mesh_graph is not None:
            return False
        if self.solver.tier is not None:
            # tiered dispatches pin/ensure their footprint per call —
            # the shared group lane would fuse different footprints
            return False
        if self.count_name is not None or self.width == 0 or self.direct_fetch:
            return True
        return 4 * self.width * self.ncols <= config.result_group_lane_bytes

    def _rows_grouped(self) -> bool:
        """True when group dispatch uses the (meta, data) rows-group
        replay rather than the single-buffer count/direct replay."""
        return not (
            self.count_name is not None or self.width == 0 or self.direct_fetch
        )

    def dispatch_many(self, dyns: List[Dict], ring: "ParamRing" = None):
        """ONE Execute for B same-plan replays: the replay vmapped over
        stacked dynamic args, padded to a pow2 lane bucket so the jit
        cache stays O(log B) per plan. ``ring`` (a coalesce lane's
        :class:`ParamRing`) keeps the stacked parameter pytree
        device-resident across dispatches: a repeated value set reuses
        the staged buffer and ships zero host bytes.

        The tunneled runtime charges a fixed ~1.4 ms per Execute
        (measured: a trivial 8-element program and a 200k-row gather
        both cost ~1.4 ms/call), which floors per-query dispatch at
        ~700 q/s no matter how small the program; B stacked replays
        amortize it to ~1.4/B ms and fetch as ONE buffer.

        Returns None when this (plan, lane-bucket)'s vmapped executable
        is still compiling — compilation runs on a BACKGROUND thread
        (like the plan's own AOT warm-up) and the caller dispatches
        per-lane meanwhile, so a 10s+ vmapped XLA compile never lands in
        a serving batch. `drain_warmups()` blocks on these too."""
        self.wait_compiled()
        B = len(dyns)
        Bb = 1 << (B - 1).bit_length()
        cap = self._group_lane_cap()
        if Bb > cap and self._rows_grouped():
            # chunking would break the page ladder's (Bb, C, W) shape
            # contract; rows plans past the cap stay per-lane
            return None
        Bb = min(Bb, cap)
        nchunks = -(-B // Bb)  # oversized batches run capped chunks
        cache = self.__dict__.setdefault("_jitted_many", {})
        fn = cache.get(Bb)
        if fn is False:
            return None  # compile failed permanently: per-lane forever
        all_dyns = dyns + [dyns[-1]] * (nchunks * Bb - B)

        def _stack(c: int) -> Dict:
            # explicit upload (deviceguard): one device_put per chunk
            # instead of an implicit transfer inside the vmapped call
            host = {
                k: np.stack(
                    [
                        np.asarray(d[k])
                        for d in all_dyns[c * Bb : (c + 1) * Bb]
                    ]
                )
                for k in dyns[0]
            }
            if ring is not None:
                return ring.stage(host)
            return jax.device_put(host)

        if fn is None:
            self._compile_group_async(Bb, _stack(0))
            return None
        devicefault.dispatch_point()
        if nchunks == 1:
            return fn(self._arg_subset(), _stack(0))
        outs = [fn(self._arg_subset(), _stack(c)) for c in range(nchunks)]
        return jax.tree_util.tree_map(
            lambda *xs: jnp.concatenate(xs, axis=0), *outs
        )

    def _group_lane_cap(self) -> int:
        """Max vmapped lanes per Execute for plans that READ EDGE
        STATE: the fused edge-predicate select materializes an O(E)
        int32 intermediate per lane, so an uncapped pow2 width on an
        80M-edge graph asks the compiler for lanes × 320 MB and OOMs —
        which costs a failed 20s+ compile AND drops the plan to
        per-lane forever. Cap so lanes × 4E fits
        config.group_hbm_budget_bytes, sized by the LARGEST edge class
        this plan's recording touched; edge-free plans (vertex-only
        counts/filters) keep unbounded width — they materialize no
        O(E) intermediate and live off group amortization."""
        dg = self.solver.dg
        keys = getattr(self, "arg_keys", None)
        if keys is None:
            classes = set(dg.edges)
        else:
            classes = {
                k.split(":", 2)[1] for k in keys if k.startswith("e:")
            }
        E = max(
            (
                dg.edges[c].num_edges
                for c in classes
                if c in dg.edges
            ),
            default=0,
        )
        if E <= 0:
            return 1 << 30
        cap = max(1, int(config.group_hbm_budget_bytes) // (4 * E))
        return 1 << (cap.bit_length() - 1)  # floor to pow2

    def _compile_group_async(self, Bb: int, stacked: Dict) -> None:
        import atexit
        import threading

        flags = self.__dict__.setdefault("_many_compiling", set())
        if Bb in flags:
            return
        flags.add(Bb)
        ev = threading.Event()
        _AotWarmup._inflight.append(ev)
        atexit.unregister(drain_warmups)
        atexit.register(drain_warmups)

        replay = (
            self._replay_group if self._rows_grouped() else self._replay
        )

        def work():
            # one retry for transient failures (runtime hiccup, resource
            # pressure) — the same discipline as ensure_compiled; only a
            # repeated failure writes the permanent per-lane sentinel so
            # a doomed compile isn't re-launched on every batch
            try:
                for attempt in (0, 1):
                    try:
                        fn = jax.jit(
                            jax.vmap(replay, in_axes=(None, 0))
                        )
                        # tracing completes when the call returns;
                        # the device-side wait runs lock-free
                        with _TRACE_LOCK:
                            res = fn(self._arg_subset(), stacked)
                        jax.block_until_ready(res)
                        if (
                            isinstance(res, tuple)
                            and len(res) == 2
                            and res[1] is not None
                        ):
                            # rows group: build the pow2 page-fn ladder
                            # NOW (still on this background thread) so
                            # serving batches never absorb a page compile
                            self.precompile_group_pages(res[1])
                        self._jitted_many[Bb] = fn
                        metrics.incr("plan_cache.group_compile")
                        break
                    except Exception:
                        if attempt:
                            log.exception(
                                "vmapped group compile failed twice "
                                "(plan stays per-lane)"
                            )
                            self._jitted_many[Bb] = False
                            metrics.incr("plan_cache.group_compile_error")
            finally:
                flags.discard(Bb)
                ev.set()
                try:
                    _AotWarmup._inflight.remove(ev)
                except ValueError:
                    pass

        threading.Thread(target=work, daemon=True).start()

    def materialize(self, fetched, params: Optional[Dict] = None) -> List[Result]:
        """Marshal rows from a dispatched `(meta, data)` pair.

        Accepts device results or pre-fetched numpy arrays; `data` may be
        a page-rounded live prefix of the full buffer (≥ `count` slots) —
        only the first `count` rows are read — and may arrive int16 when
        the dispatch's bit-width election shipped the half-size copy."""
        tier = self.solver.tier
        if tier is not None:
            # the dispatch that produced `fetched` has drained (we hold
            # its fetched buffers) — drop its footprint pins so
            # eviction stops preferring around these blocks. Runs
            # before the overflow raise: every dispatch path
            # materializes exactly once, success or overflow.
            tier.release_footprint(self.tier_footprint)
        if isinstance(fetched, tuple) and len(fetched) == 3:
            meta_dev, data_dev, _p16 = fetched  # raw dispatch triple
            if isinstance(data_dev, (list, tuple)):
                data_dev = data_dev[-1] if data_dev else None  # full page
        elif isinstance(fetched, tuple):
            meta_dev, data_dev = fetched
        else:
            meta_dev, data_dev = fetched, None
        meta = np.asarray(meta_dev)
        if meta.ndim == 2:
            # direct-fetch fused buffer: data rows + trailing meta row
            data_dev = meta[:-1]
            meta = meta[-1]
        count, overflow = int(meta[0]), int(meta[1])
        if overflow:
            raise ScheduleOverflow(str(self.solver.stmt))
        if self.count_name is not None:
            return self.solver.finalize_count(self.count_name, count, params)
        if data_dev is None:
            # column-less non-count table (degenerate): count empty rows
            t = Table(count=count, width=0)
            return self.solver.rows_from_table(t, params)
        data = np.asarray(data_dev)
        if data.dtype != np.int32:
            data = data.astype(np.int32)  # bit-width-elected fetch
        return self.solver.rows_from_table(
            self._table_from(data, self.fetch_rows_needed(count)), params
        )

    def rows(self, params: Optional[Dict] = None) -> List[Result]:
        dev = self.dispatch(params)
        if not isinstance(dev, tuple):  # direct-fetch fused buffer
            arr = _fetch_profiled([dev], split_sync=False)[0]
            with timed("tpu.host_s"):
                return self.materialize(arr, params)
        meta_dev, pages32, _p16 = dev
        if pages32:
            # the lone-query path always ships the full int32 page:
            # remember that election so the next dispatch prefetches it
            self._page_guess = (len(pages32) - 1, False)
        data_dev = pages32[-1] if pages32 else None
        devs = [meta_dev] if data_dev is None else [meta_dev, data_dev]
        arrs = _fetch_profiled(devs, split_sync=False)
        data = arrs[1] if len(arrs) > 1 else None
        with timed("tpu.host_s"):
            return self.materialize((arrs[0], data), params)

    def fetch_rows_needed(self, count: int) -> int:
        """How many live rows the host actually needs to marshal the
        result: `count`, or `skip+limit` when a literal LIMIT can be
        pushed into the transfer (no DISTINCT/UNWIND/ORDER/aggregate —
        those need every row before the cut)."""
        lim = self.fetch_limit
        return count if lim is None else min(count, lim)

    @staticmethod
    def _literal_fetch_limit(stmt) -> Optional[int]:
        """skip+limit as a plain int when LIMIT can cut the TRANSFER:
        row-per-binding results only — DISTINCT/UNWIND/ORDER/GROUP/
        aggregates and the $matches/$paths/$elements forms consume every
        row before the cut, and non-literal expressions would need a ctx."""
        from orientdb_tpu.exec.eval import contains_aggregate

        if not isinstance(stmt, A.MatchStatement):
            return None
        if stmt.distinct or stmt.unwind or stmt.order_by or stmt.group_by:
            return None
        if stmt.limit is None:
            return None
        if any(contains_aggregate(p.expr) for p in stmt.returns):
            return None
        if len(stmt.returns) == 1 and isinstance(stmt.returns[0].expr, A.ContextVar):
            return None
        def lit(e):
            if e is None:
                return 0
            if isinstance(e, A.Literal) and isinstance(e.value, int):
                return e.value
            return None
        limit, skip = lit(stmt.limit), lit(stmt.skip)
        if limit is None or skip is None or limit < 0:
            return None
        return skip + limit

    def _table_from(self, data: np.ndarray, count: int) -> Table:
        """Host table from the transferred live prefix: rows were
        front-packed (stable) on device, so the first `count` slots of
        every column are the live rows in expansion order."""
        n = min(count, data.shape[1])
        t = Table(count=n, width=n)
        i = 0
        for a in self.v_names:
            t.cols[a] = data[i][:n]
            i += 1
        for a in self.e_names:
            t.edge_cols[a] = (data[i][:n], data[i + 1][:n])
            i += 2
        for a in self.d_names:
            t.depth_cols[a] = data[i][:n]
            i += 1
        return t


def _params_key(params) -> Optional[Tuple]:
    """Plan-cache key fragment: STATIC parameter values plus the
    names/kinds of dynamic (numeric) ones — dynamic values are jit
    arguments, so plans are shared across them."""
    dyn, static = split_params(params)
    try:
        t = (
            tuple(sorted((str(k), kind) for k, kind in dyn.items())),
            tuple(
                sorted((str(k), type(v).__name__, v) for k, v in static.items())
            ),
        )
        hash(t)
        return t
    except TypeError:
        return None  # unhashable param values → skip plan cache


# ---------------------------------------------------------------------------
# front door
# ---------------------------------------------------------------------------


def _plan_cache(snap) -> "OrderedDict":
    cache = getattr(snap, "_plan_cache", None)
    if cache is None:
        cache = snap._plan_cache = OrderedDict()
    return cache


def _all_values_key(params) -> Optional[Tuple]:
    """Every parameter value in the key (TRAVERSE plans bake values)."""
    try:
        t = tuple(
            sorted((str(k), type(v).__name__, v) for k, v in params.items())
        )
        hash(t)
        return t
    except TypeError:
        return None


def _cache_key(stmt, params) -> Optional[Tuple]:
    # MATCH and (rewritten) SELECT plans are parameter-generic; TRAVERSE
    # bakes parameter values into the plan
    pk = (
        _params_key(params)
        if isinstance(stmt, (A.MatchStatement, A.SelectStatement))
        else _all_values_key(params)
    )
    if pk is None:
        return None
    try:
        key = (stmt, pk)
        hash(key)
        return key
    except TypeError:  # statement holds an unhashable literal
        return None


#: SELECT→MATCH translation verdicts, keyed by statement (translation is
#: parameter-independent). Positive entries skip re-deriving the rewrite
#: on every cache-hit replay; negative entries (the Uncompilable reason)
#: make auto-routed workloads of permanently ineligible shapes (rid
#: lookups, SELECT *, LET) fail fast instead of re-rejecting per query.
_TRANSLATE_CACHE: "OrderedDict" = OrderedDict()
_TRANSLATE_CACHE_MAX = 512


def _translate(stmt):
    """SELECT compiles by rewriting to a single-node MATCH
    (select_compile); MATCH/TRAVERSE pass through."""
    if isinstance(stmt, A.SelectStatement):
        try:
            hashable = True
            verdict = _TRANSLATE_CACHE.get(stmt)
        except TypeError:  # statement holds an unhashable literal
            hashable = False
            verdict = None
        if verdict is not None:
            _TRANSLATE_CACHE.move_to_end(stmt)
            if isinstance(verdict, str):
                raise Uncompilable(verdict)
            return verdict
        from orientdb_tpu.exec.select_compile import rewrite_select

        try:
            out = rewrite_select(stmt)
        except Uncompilable as e:
            if hashable:
                _translate_remember(stmt, str(e))
            raise
        if hashable:
            _translate_remember(stmt, out)
        return out
    return stmt, None


def _translate_remember(stmt, verdict) -> None:
    while len(_TRANSLATE_CACHE) >= _TRANSLATE_CACHE_MAX:
        _TRANSLATE_CACHE.popitem(last=False)
    _TRANSLATE_CACHE[stmt] = verdict


def _record(db, stmt, params):
    """Recording first execution: eager solve with blocking size observes.
    Returns (plan, rows). Holds the trace lock: an eager solve must not
    interleave with a background warm-up's trace (see _TRACE_LOCK).

    The recording runs under the device graph's TOUCH LOG: every array
    key the solve reads becomes the plan's jit-arg subset
    (``arg_keys``), so lazily pruned columns uploading later never
    change a cached plan's pytree structure — and a plan ships only the
    graph arrays it actually uses to its executable."""
    from orientdb_tpu.obs.trace import span as _span

    stmt, element_alias = _translate(stmt)
    snap = db.current_snapshot(require_fresh=True)
    if snap is not None:
        # pin the buffers for the eager solve (see _snapshot_lease)
        snap.retain()
    try:
        return _record_leased(db, stmt, params, snap, element_alias)
    finally:
        if snap is not None:
            snap.release()


def _record_leased(db, stmt, params, snap, element_alias):
    from orientdb_tpu.obs.trace import span as _span

    with _span("tpu.load"):
        # snapshot → HBM upload (CSR + referenced columns); a warm cache
        # makes this span ~free, a cold one shows the real upload cost
        dg = device_graph(snap)
    with _TRACE_LOCK:
        dg.start_touch_log()
        try:
            if isinstance(stmt, A.MatchStatement):
                solver = TpuMatchSolver(
                    db, stmt, params, element_alias=element_alias
                )
                with _span("tpu.solve"):
                    table = solver.solve_table()
                with _span("tpu.marshal"):
                    rows = solver.rows_from_table(table)
                plan: object = _CompiledPlan(solver, table)
            else:
                tsolver = TpuTraverseSolver(db, stmt, params)
                with _span("tpu.solve"):
                    idx, total = tsolver.solve()
                with _span("tpu.marshal"):
                    rows = tsolver.rows_from(np.asarray(idx), total)
                plan = _CompiledTraverse(tsolver, total)
        finally:
            keys = dg.stop_touch_log()
        if plan.solver.dg is not dg:
            # a mutation re-attached the snapshot between our device_graph
            # fetch and the solver's own: the reads landed on a DIFFERENT
            # graph than the log watched — fall back to full-dict args
            # (correct, just unpruned) instead of poisoning the plan with
            # an empty subset
            plan.arg_keys = None
        else:
            # an empty log can only mean the reads bypassed this tracker
            # (unexpected): full-dict args are the safe fallback
            plan.arg_keys = keys if keys else None
        return plan, rows


def _prepare(db, stmt, params):
    """Plan-cache lookup, compiling (and executing) on miss.

    Returns ``(variants, None, None)`` on a cache hit — `variants` is the
    MRU-ordered list of schedule variants for this statement — or
    ``(None, rows, plan)`` when this call WAS the recording first
    execution (`plan` is the freshly cached plan with its background AOT
    warm-up started, or None when the statement was uncacheable)."""
    if not isinstance(
        stmt, (A.MatchStatement, A.TraverseStatement, A.SelectStatement)
    ):
        raise Uncompilable(f"{type(stmt).__name__} has no TPU compilation")
    if isinstance(stmt, A.SelectStatement):
        # fail fast on ineligible SELECT shapes BEFORE the miss metric —
        # the negative cache makes repeat rejections O(1)
        _translate(stmt)
    params = params or {}
    snap = db.current_snapshot(require_fresh=True)
    if snap is None:
        raise Uncompilable("no fresh snapshot attached")
    from orientdb_tpu.utils.metrics import metrics

    import orientdb_tpu.obs.stats as _stats

    cache = _plan_cache(snap)
    key = _cache_key(stmt, params)
    if key is not None:
        variants = cache.get(key)
        if variants is not None:
            cache.move_to_end(key)  # LRU: keep hot plans
            metrics.incr("plan_cache.hit")
            _stats.note_plan_cache(True)
            return variants, None, None
    metrics.incr("plan_cache.miss")
    _stats.note_plan_cache(False)
    # the eager recording execution IS the compile cost a caller absorbs
    # on a plan-cache miss: charge it to the query's fingerprint
    import time as _time

    _t0 = _time.perf_counter()
    plan_obj, rows = _record(db, stmt, params)
    _stats.add_compile(_time.perf_counter() - _t0)
    steps = getattr(getattr(plan_obj, "solver", None), "plan", None)
    if steps:
        _stats.note_plan(" -> ".join(s.describe() for s in steps))
    if key is not None and config.plan_cache_size > 0:
        while len(cache) >= config.plan_cache_size:
            cache.popitem(last=False)
        v = PlanVariants(plan_obj)
        v.remember(params, plan_obj)
        cache[key] = v
        # replay-compile off the critical path: rows came from the eager
        # recording, so the XLA compile would otherwise hit the NEXT caller
        plan_obj.ensure_compiled()
        return None, rows, plan_obj
    return None, rows, None


class PlanVariants:
    """Schedule variants for one cached statement, with a sticky
    per-parameter routing map: parameter populations whose live sizes
    cluster differently (e.g. shallow vs deep reply trees) each keep a
    fitting variant, and repeated parameter values dispatch straight to
    the variant that last served them — no retry round trips on the
    steady-state path."""

    __slots__ = ("plans", "by_param")

    _STICKY_MAX = 4096

    def __init__(self, first) -> None:
        self.plans = [first]
        self.by_param: Dict = {}

    @staticmethod
    def _pkey(params):
        try:
            t = tuple(sorted((str(k), str(v)) for k, v in (params or {}).items()))
            hash(t)
            return t
        except TypeError:
            return None

    def pick(self, params):
        plan = self.by_param.get(self._pkey(params))
        return plan if plan in self.plans else self.plans[0]

    def remember(self, params, plan) -> None:
        k = self._pkey(params)
        if k is None:
            return
        if len(self.by_param) >= self._STICKY_MAX:
            self.by_param.clear()
        self.by_param[k] = plan

    def add(self, plan) -> None:
        self.plans.insert(0, plan)
        del self.plans[max(1, config.plan_variants):]
        self.by_param = {
            k: p for k, p in self.by_param.items() if p in self.plans
        }


def _run_variants(
    db, stmt, params, variants: PlanVariants, tried=None, fresh=None
) -> List[Result]:
    """Walk the remaining variants after a miss; when every one overflows,
    record a NEW variant under these parameters. ``tried`` is the plan the
    caller already dispatched and saw overflow from; ``fresh`` (when given)
    collects newly recorded plans so a batch can block on their warm-ups."""
    for plan in list(variants.plans):
        if plan is tried:
            continue
        try:
            rows = plan.rows(params or {})
        except ScheduleOverflow:
            continue
        variants.remember(params, plan)
        return rows
    import time as _time

    import orientdb_tpu.obs.stats as _stats
    from orientdb_tpu.utils.metrics import metrics

    metrics.incr("plan_cache.overflow_rerecord")
    # recompile-due-to-shape: the replay's buffers were too small for
    # these parameters — charge the re-record to the fingerprint
    _t0 = _time.perf_counter()
    plan_obj, rows = _record(db, stmt, params)
    _stats.add_compile(_time.perf_counter() - _t0, rerecord=True)
    variants.add(plan_obj)
    variants.remember(params, plan_obj)
    plan_obj.ensure_compiled()
    if fresh is not None:
        fresh.append(plan_obj)
    return rows


from contextlib import contextmanager as _contextmanager


@_contextmanager
def _snapshot_lease(db):
    """Pin the attached snapshot's device buffers for the duration of
    one dispatch: a delta-plane compaction swapping the snapshot
    mid-flight defers its buffer free until the lease drops
    (``GraphSnapshot.retain``/``release``) — the in-flight dispatch
    finishes on the epoch it was admitted under."""
    snap = db.current_snapshot()
    if snap is not None:
        snap.retain()
    try:
        yield snap
    finally:
        if snap is not None:
            snap.release()


def execute(db, stmt, params, sql: Optional[str] = None) -> List[Result]:
    import orientdb_tpu.obs.timeline as _TL

    # flight record for the compiled single-dispatch path (refined to
    # "sharded" by a mesh plan's dispatch); an Uncompilable/overflow
    # escape drops the record uncommitted — only real dispatches ring
    rec = _TL.recorder.begin("single")
    with _TL.active(rec):
        for _attempt in range(4):
            # recording first executions run eagerly on device — the
            # ladder guards them like replays (stage "record")
            variants, rows, _fresh = devicefault.domain.run(
                lambda: _prepare(db, stmt, params),
                db=db,
                sql=sql,
                stage="record",
                passthrough=(ScheduleOverflow,),
            )
            if variants is None:
                break
            plan = variants.pick(params)
            _TL.mark("plan_resolve")
            # pin the plan's snapshot across the dispatch: a delta-plane
            # compaction swapping snapshots mid-flight defers its buffer
            # free until this lease drops (epoch-gated dispatch). A swap
            # landing BETWEEN plan resolution and the pin has already
            # freed this plan's buffers — re-resolve against the new
            # snapshot (try_retain refuses the stale DeviceGraph)
            snap = plan.solver.snap
            if not snap.try_retain(plan.solver.dg):
                metrics.incr("tpu.lease_raced")
                continue
            try:
                # the device fault domain's escalation ladder wraps the
                # whole dispatch+fetch section; ScheduleOverflow is the
                # caller's control flow and passes through untouched
                rows = devicefault.domain.run(
                    lambda: plan.rows(params or {}),
                    db=db,
                    sql=sql,
                    stage="dispatch",
                    passthrough=(ScheduleOverflow,),
                )
                variants.remember(params, plan)
            except ScheduleOverflow:
                rows = devicefault.domain.run(
                    lambda: _run_variants(
                        db, stmt, params, variants, tried=plan
                    ),
                    db=db,
                    sql=sql,
                    stage="dispatch",
                )
            finally:
                snap.release()
            break
        else:
            # four consecutive compaction swaps inside the resolve→pin
            # window: degrade to the oracle rather than crash the query
            raise Uncompilable("snapshot compaction raced plan dispatch")
    _TL.recorder.commit(rec)
    return rows


#: minimum same-plan items in a batch before the vmapped group dispatch
#: pays for its extra compile (per plan per pow2 lane bucket)
_GROUP_MIN = 4


class ParamRing:
    """Device-resident parameter buffers for one dispatch lane.

    A lane's repeated dispatches stack the same dynamic-arg pytree
    shapes over and over — and under steady serving traffic, often the
    same VALUES (hot parameter sets, un-parameterized statements' seed
    arrays). Each distinct stacked value set is ``jax.device_put`` ONCE
    and then reused in place: a dispatch whose host stack matches a
    staged slot ships zero host bytes. Two slots double-buffer the
    ring — the upload for micro-batch N+1 lands in the other slot, so
    it can never overwrite the buffer an in-flight dispatch for batch
    N still reads. Buffers are reused rather than donated: donation
    would invalidate the slot after one Execute and forfeit the reuse
    that makes the steady state transfer-free.

    NOT thread-safe by design: a ring belongs to exactly one lane
    worker thread (the coalesce lane owns it for the plan's lifetime).
    The one cross-thread touch is :meth:`clear` (device fault relief
    dropping staged buffers): a racing ``stage`` at worst misses a hit
    and re-uploads — each slot write is a single list-item assignment.
    """

    __slots__ = ("_slots", "_next", "__weakref__")

    def __init__(self, depth: int = 2) -> None:
        self._slots: List = [None] * max(1, depth)
        self._next = 0
        _PARAM_RINGS.add(self)

    @staticmethod
    def _same(a: Dict, b: Dict) -> bool:
        if a.keys() != b.keys():
            return False
        return all(np.array_equal(a[k], b[k]) for k in a)

    def stage(self, host: Dict):
        """Device form of ``host`` (a dict of stacked numpy arrays):
        the staged copy when a slot's value set matches, a fresh
        explicit upload into the next slot otherwise."""
        import time as _time

        import orientdb_tpu.obs.critpath as _CP
        from orientdb_tpu.obs.timeline import note_ring

        _t0 = _time.perf_counter()
        for slot in self._slots:
            if slot is not None and self._same(slot[0], host):
                metrics.incr("tpu.param_ring.hit")
                note_ring(True)
                _CP.add_segment("ring_hit", _time.perf_counter() - _t0)
                return slot[1]
        devicefault.transfer_point()
        dev = jax.device_put(host)
        _CP.add_segment("param_upload", _time.perf_counter() - _t0)
        nbytes = sum(int(a.nbytes) for a in host.values())
        metrics.incr("tpu.param_ring.upload")
        metrics.incr("tpu.param_ring.bytes", nbytes)
        note_ring(False, nbytes)
        from orientdb_tpu.obs.memledger import memledger

        memledger.register(
            "param_ring",
            f"ring:{id(self):x}",
            f"slot:{self._next}",
            arr=next(iter(dev.values()), None) if dev else None,
            nbytes=nbytes,
            pinned=True,
        )
        self._slots[self._next] = (host, dev)
        self._next = (self._next + 1) % len(self._slots)
        return dev

    def clear(self) -> int:
        """Drop every staged device buffer (a pure cache: the next
        dispatch re-uploads). Returns slots dropped."""
        dropped = 0
        for i in range(len(self._slots)):
            if self._slots[i] is not None:
                self._slots[i] = None
                dropped += 1
        if dropped:
            from orientdb_tpu.obs.memledger import memledger

            memledger.drop_owner("param_ring", f"ring:{id(self):x}")
        return dropped


#: live ParamRings (weak — a reaped coalesce lane's ring just vanishes);
#: the device fault domain's relief drops their staged buffers
_PARAM_RINGS: "weakref.WeakSet" = weakref.WeakSet()


def drop_param_rings() -> int:
    """Device fault relief actuator: drop every lane's staged param
    buffers. Pure cache, so the only cost is re-upload on next use."""
    return sum(ring.clear() for ring in list(_PARAM_RINGS))


class _Group:
    """Stacked device result of a vmapped group dispatch; fetched to
    host ONCE and sliced per lane.

    Row-returning groups additionally carry the stacked [B, C, width]
    data buffer (``data_dev``, from the rows-group replay) or — for the
    no-dyn shared-dispatch case — the single dispatch's page ladder
    (``shared_pages``); the batch fetch elects ONE compact page for the
    whole group after the meta wave."""

    __slots__ = (
        "dev",
        "_np",
        "data_dev",
        "shared_pages",
        "data_np",
        "spec_key",
        "spec_dev",
    )

    def __init__(self, dev, data_dev=None, shared_pages=None) -> None:
        self.dev = dev
        self._np = None
        self.data_dev = data_dev
        self.shared_pages = shared_pages
        self.data_np = None  # host copy of the elected group page
        #: speculative page slice started at dispatch time (group_page
        #: key + device buffer); the election keeps it only on a match
        self.spec_key = None
        self.spec_dev = None

    def arr(self) -> np.ndarray:
        if self._np is None:
            self._np = np.asarray(self.dev)
        return self._np


class _Lane:
    """One lane of a group: `grp.arr()[k]` is this query's meta
    (count-only) or fused buffer slice (direct-fetch). ``k=None`` marks
    a shared single dispatch (no dynamic args — all lanes identical)."""

    __slots__ = ("grp", "k")

    def __init__(self, grp: "_Group", k: Optional[int]) -> None:
        self.grp = grp
        self.k = k

    def meta(self) -> np.ndarray:
        a = self.grp.arr()
        return a if self.k is None else a[self.k]

    def data(self) -> Optional[np.ndarray]:
        d = self.grp.data_np
        if d is None:
            return None
        return d if self.k is None or d.ndim == 2 else d[self.k]


def execute_batch(db, items, sqls: Optional[List[Optional[str]]] = None) -> List:
    """Execute ``[(stmt, params), ...]`` with one overlapped transfer phase.

    The single-chip DP axis (SURVEY.md §5 "replicas = independent query
    streams"): every cached plan dispatches back-to-back, async host
    copies start for all results, and only then does materialization
    block — so N queries cost ~one tunnel RTT instead of N. Runs of the
    SAME plan (≥ _GROUP_MIN) collapse further into ONE vmapped Execute
    (`dispatch_many`), amortizing the ~1.4 ms fixed per-Execute cost of
    the tunneled runtime across the whole group.

    Per-item failures (Uncompilable) are returned in-place as the exception
    instance so the engine front door can fall back per statement."""
    out: List = [None] * len(items)
    prepared = []  # (i, variants, plan, params)
    fresh = []
    # pin every prepared plan's snapshot across the dispatch + fetch
    # waves: a delta-plane compaction swapping snapshots mid-batch
    # defers the old buffers' free until the leases drop
    leases: Dict[int, object] = {}
    try:
        for i, (stmt, params) in enumerate(items):
            try:
                # recording first executions are device work too: the
                # ladder guard degrades an exhausted one per-item
                # (DeviceQuarantined IS an Uncompilable)
                variants, rows, plan_obj = devicefault.domain.run(
                    lambda: _prepare(db, stmt, params),
                    db=db,
                    sql=(sqls[i] if sqls else None),
                    stage="record",
                    passthrough=(ScheduleOverflow,),
                )
            except Uncompilable as e:
                out[i] = e
                continue
            if variants is None:
                out[i] = rows
                if plan_obj is not None:
                    fresh.append(plan_obj)
                continue
            for _attempt in range(4):
                # sticky routing: repeated parameter values dispatch
                # straight to the variant that last served them
                plan = variants.pick(params)
                snap = plan.solver.snap
                # a held lease keeps the snapshot's device cache pinned
                # (deferred free), so a second plan on the same snapshot
                # needs no re-check; a NEW lease must refuse a plan
                # whose DeviceGraph a compaction swap already freed
                if id(snap) in leases or snap.try_retain(plan.solver.dg):
                    leases.setdefault(id(snap), snap)
                    prepared.append((i, variants, plan, params))
                    break
                metrics.incr("tpu.lease_raced")
                try:
                    variants, rows, plan_obj = devicefault.domain.run(
                        lambda: _prepare(db, stmt, params),
                        db=db,
                        sql=(sqls[i] if sqls else None),
                        stage="record",
                        passthrough=(ScheduleOverflow,),
                    )
                except Uncompilable as e:
                    out[i] = e
                    break
                if variants is None:
                    out[i] = rows
                    if plan_obj is not None:
                        fresh.append(plan_obj)
                    break
            else:
                out[i] = Uncompilable(
                    "snapshot compaction raced plan dispatch"
                )
        if not prepared:
            for plan in fresh:
                plan.wait_compiled()
            return out
        try:
            # the escalation ladder wraps the whole dispatch+fetch wave;
            # a retry re-dispatches the prepared plans (reads are
            # idempotent and the leases stay held in the outer finally)
            return devicefault.domain.run(
                lambda: _execute_batch_leased(db, items, out, prepared, fresh),
                db=db,
                sql=(sqls[prepared[0][0]] if sqls else None),
                stage="batch",
                passthrough=(ScheduleOverflow,),
            )
        except devicefault.DeviceQuarantined as e:
            # exhaustion mid-wave: per-item contract — hand the not-yet
            # materialized items the Uncompilable so the front door
            # falls back per statement (completed slots keep their rows)
            for i in range(len(out)):
                if out[i] is None:
                    out[i] = e
            return out
    finally:
        for snap in leases.values():
            snap.release()


def _execute_batch_leased(db, items, out, prepared, fresh) -> List:
    groups: Dict[int, List[int]] = {}
    for j, (_i, _v, plan, _params) in enumerate(prepared):
        if getattr(plan, "batchable", None) is not None and plan.batchable():
            groups.setdefault(id(plan), []).append(j)
    grouped = {
        j for idxs in groups.values() if len(idxs) >= _GROUP_MIN for j in idxs
    }
    pending = []
    for j, (i, variants, plan, params) in enumerate(prepared):
        if j in grouped:
            continue  # dispatched below as a vmapped group
        stmt, _ = items[i]
        try:
            dev = plan.dispatch(params or {})
        except ScheduleOverflow:
            # seed capacity overflow surfaces at dispatch (host-side
            # index probe) — walk the variants now
            out[i] = _run_variants(
                db, stmt, params, variants, tried=plan, fresh=fresh
            )
            continue
        pending.append((i, variants, plan, dev))
    for idxs in groups.values():
        if len(idxs) < _GROUP_MIN:
            continue
        plan = prepared[idxs[0]][2]
        dyns, lanes = [], []
        for j in idxs:
            i, variants, _p, params = prepared[j]
            try:
                dyns.append(plan._dyn_args(params or {}))
                lanes.append(j)
            except ScheduleOverflow:
                out[i] = _run_variants(
                    db, items[i][0], params, variants, tried=plan, fresh=fresh
                )
        if not lanes:
            continue
        g = _group_dispatch(plan, dyns)
        if g is None:
            # vmapped executable still compiling in the background
            # (or permanently unavailable): serve per-lane, with the
            # same overflow walk as the singles path — a seed grown
            # since the group's _dyn_args probe must not fail the batch
            for j in lanes:
                i, variants, _p, params = prepared[j]
                try:
                    pending.append(
                        (i, variants, plan, plan.dispatch(params or {}))
                    )
                except ScheduleOverflow:
                    out[i] = _run_variants(
                        db, items[i][0], params, variants,
                        tried=plan, fresh=fresh,
                    )
            continue
        grp, ks = g
        for k, j in zip(ks, lanes):
            i, variants, _p, _params = prepared[j]
            pending.append((i, variants, plan, _Lane(grp, k)))
    _finish_pending(db, items, pending, out, fresh)
    # a batch returns replay-ready: block on warm-ups this call started so
    # plans recorded here don't leak their XLA compile into the next batch
    for plan in fresh:
        plan.wait_compiled()
    return out


def _group_dispatch(plan, dyns: List[Dict], ring: ParamRing = None):
    """Dispatch B same-plan replays as ONE group. Returns ``(grp, ks)``
    — ``ks[k]`` is each item's index into the stacked result, or None
    for the shared-single-dispatch case — or None while the vmapped
    executable is still compiling (callers dispatch per-lane instead).
    Shared by ``execute_batch``'s same-plan runs and the coalescer's
    lane drains (``dispatch_lane``)."""
    import orientdb_tpu.obs.timeline as _TL

    _TL.note_path("group")
    if not dyns[0]:
        # no dynamic args: every lane is the SAME program on the same
        # inputs — one plain dispatch serves the whole group
        try:
            dev = plan.dispatch({})
        except ScheduleOverflow:
            # a delta landed between the _dyn_args probe and this
            # dispatch (traverse static-replay guard): fall back to the
            # per-lane path, whose overflow handling re-records
            return None
        if isinstance(dev, tuple) and len(dev) == 3 and dev[1]:
            # rows plan: keep the single dispatch's page ladder so
            # the group elects one shared page after the meta wave
            grp = _Group(dev[0], shared_pages=(dev[1], dev[2]))
        else:
            grp = _Group(dev[0] if isinstance(dev, tuple) else dev)
        return grp, [None] * len(dyns)
    dev = plan.dispatch_many(dyns, ring=ring)
    if dev is None:
        return None
    _TL.mark("device_dispatch")
    if isinstance(dev, tuple) and len(dev) == 2 and dev[1] is not None:
        # rows-group replay: (meta stack, data stack)
        grp = _Group(dev[0], data_dev=dev[1])
        # speculative page prefetch: slice + start copying the page the
        # last batch elected while THIS batch's device work runs —
        # served only from an already-compiled page fn AND an exact
        # data-stack shape match (the fn's jit cache keys shapes), so a
        # guess can never absorb an XLA compile
        guess = plan._group_page_guess
        if guess is not None and plan._group_page_shape == tuple(
            dev[1].shape
        ):
            fn = plan.__dict__.get("_group_page_fns", {}).get(guess)
            if fn is not None:
                grp.spec_key = guess
                grp.spec_dev = fn(dev[1])
                _copy_to_host_async(grp.spec_dev)
                metrics.incr("tpu.page_prefetch.start")
                _TL.note_prefetch_start()
    else:
        grp = _Group(dev[0] if isinstance(dev, tuple) else dev)
    return grp, list(range(len(dyns)))


def _finish_pending(db, items, pending, out, fresh) -> None:
    """Fetch + materialize dispatched work: the overlapped meta wave,
    per-query/group page election, and host marshalling, with overflow
    fallbacks walked per item. ``pending`` holds ``(i, variants, plan,
    dev)`` rows dispatched by ``execute_batch`` or a coalesce lane
    (``LaneDispatch``); results land in ``out[i]``."""
    # wave 1: metas (tiny, overlapped) — traverse plans ship their whole
    # payload here since they have no meta/data split
    meta_devs, data_devs = [], []
    for _i, _v, _plan, dev in pending:
        if isinstance(dev, tuple):
            meta_devs.append(dev[0])
            data_devs.append(dev[1:])  # (data32, data16)
        else:
            meta_devs.append(dev)  # bare array, or a group _Lane
            data_devs.append(None)
    # interleaved fetch: the device executes the batch in dispatch order,
    # so each query's meta is read as IT lands (not after the whole batch
    # syncs) and its elected result page starts copying immediately —
    # page transfers overlap the device compute of later queries instead
    # of waiting behind it. Page choice: smallest pre-materialized pow2
    # prefix covering the live count (and a literal LIMIT cuts `need`
    # further); the meta's bit-width flag picks the int16 copy when live
    # values allow, halving the bytes again.
    import time as _time

    from orientdb_tpu.obs.timeline import (
        add_phase as _tl_add_phase,
        note_prefetch as _tl_note_prefetch,
    )
    from orientdb_tpu.obs.memledger import memledger as _ml

    pages_sel: List = [None] * len(pending)
    devicefault.transfer_point()
    seen_groups = set()
    for d in meta_devs:
        # direct-fetch plans ride this same wave: their dev IS the fused
        # single buffer (data + meta row), so one copy covers the query;
        # a group's stacked buffer starts ONE copy for all its lanes
        if isinstance(d, _Lane):
            if id(d.grp) in seen_groups:
                continue
            seen_groups.add(id(d.grp))
            d = d.grp.dev
        _copy_to_host_async(d)
    t0 = _time.perf_counter()
    metas: List = []
    for k, (_i, _v, plan, _dev) in enumerate(pending):
        md = meta_devs[k]
        meta = md.meta() if isinstance(md, _Lane) else np.asarray(md)
        metas.append(meta)
        pair = data_devs[k]
        if pair is None or not pair[0] or meta.ndim != 1 or int(meta[1]):
            continue  # count-only result, traverse payload, or overflow
        f16 = bool(int(meta[2]))
        pages = pair[1] if f16 else pair[0]
        need = plan.fetch_rows_needed(int(meta[0]))
        idx, d = next(
            (i, p) for i, p in enumerate(pages) if int(p.shape[1]) >= need
        )
        # election bookkeeping for the speculative dispatch-time
        # prefetch: a repeat election means the copy started with the
        # dispatch and this async call is a no-op
        if plan._page_guess is not None:
            hit = plan._page_guess == (idx, f16)
            metrics.incr(
                "tpu.page_prefetch.hit" if hit else "tpu.page_prefetch.miss"
            )
            _tl_note_prefetch(hit, int(d.nbytes) if hit else 0)
        plan._page_guess = (idx, f16)
        _copy_to_host_async(d)
        pages_sel[k] = d
        _ml.register("result_page", f"plan:{id(plan):x}", "page", arr=d)
    # rows groups: elect ONE compact page for each group's whole lane
    # stack — a single slice(+int16 cast) Execute and a single host
    # copy replace B per-query ladders (the measured rows-path floor
    # was per-query dispatch+meta overhead, ~20 ms/query on the tunnel)
    grp_lane_metas: Dict[int, List[np.ndarray]] = {}
    grp_objs: Dict[int, Tuple[_Group, object]] = {}
    for k, (_i, _v, plan, dev) in enumerate(pending):
        if isinstance(dev, _Lane) and (
            dev.grp.data_dev is not None
            or dev.grp.shared_pages is not None
        ):
            grp_lane_metas.setdefault(id(dev.grp), []).append(metas[k])
            grp_objs[id(dev.grp)] = (dev.grp, plan)
    grp_fetch: List[Tuple[_Group, object]] = []
    for gid, lane_metas in grp_lane_metas.items():
        grp, plan = grp_objs[gid]
        needs, fits16 = [], True
        for m in lane_metas:
            if int(m[1]):
                continue  # overflow lane: re-dispatched later anyway
            needs.append(plan.fetch_rows_needed(int(m[0])))
            fits16 = fits16 and bool(int(m[2]))
        if not needs:
            continue
        need = max(max(needs), 1)
        if grp.shared_pages is not None:
            p32, p16 = grp.shared_pages
            pages = p16 if fits16 else p32
            idx, d = next(
                (i, p)
                for i, p in enumerate(pages)
                if int(p.shape[1]) >= need
            )
            # the shared dispatch rode plan.dispatch(): its ladder
            # prefetch reuses the per-query guess
            plan._page_guess = (idx, fits16)
        else:
            key = (
                len(lane_metas),
                plan._page_round(int(grp.data_dev.shape[2]), need),
                fits16,
            )
            if grp.spec_key is not None:
                hit = grp.spec_key == key
                metrics.incr(
                    "tpu.page_prefetch.hit" if hit else "tpu.page_prefetch.miss"
                )
                _tl_note_prefetch(
                    hit, int(grp.spec_dev.nbytes) if hit else 0
                )
            plan._group_page_guess = key
            plan._group_page_shape = tuple(grp.data_dev.shape)
            if grp.spec_key == key:
                d = grp.spec_dev  # copy already in flight since dispatch
            else:
                d = plan.group_page(
                    grp.data_dev, len(lane_metas), need, fits16
                )
        _copy_to_host_async(d)
        _ml.register("result_page", f"grp:{id(grp):x}", "page", arr=d)
        grp_fetch.append((grp, d))
    t1 = _time.perf_counter()
    datas: List = [None] * len(pending)
    nbytes = sum(int(m.nbytes) for m in metas)
    for k, d in enumerate(pages_sel):
        if d is not None:
            a = np.asarray(d)
            datas[k] = a
            nbytes += int(a.nbytes)
    for grp, d in grp_fetch:
        a = np.asarray(d)
        if a.dtype != np.int32:
            a = a.astype(np.int32)
        grp.data_np = a
        nbytes += int(d.nbytes)
    t2 = _time.perf_counter()
    if pending:
        # overlapped phases: the meta drain tracks device compute, the
        # page drain is the transfer tail that didn't hide behind it
        metrics.observe("tpu.device_s", t1 - t0)
        metrics.observe("tpu.transfer_s", t2 - t1)
        metrics.incr("tpu.bytes_fetched", nbytes)
        # per-fingerprint attribution (obs/stats): a no-op without an
        # active accumulator (the query_batch front door deliberately
        # skips per-item device fiction), but the coalesce lane wraps
        # its collect in stats.capture() and splits this batch-level
        # split across its members
        from orientdb_tpu.obs.stats import add_device

        add_device(t1 - t0, t2 - t1, nbytes)
        _tl_add_phase(t1 - t0, t2 - t1, nbytes)
    overflowed = []
    with timed("tpu.host_s"):
        for k, ((i, variants, plan, dev), meta) in enumerate(
            zip(pending, metas)
        ):
            stmt, params = items[i]
            if isinstance(dev, _Lane) and dev.grp.data_np is not None:
                fetched = (meta, dev.data())  # rows-group lane
            elif isinstance(dev, tuple):
                fetched = (meta, datas[k])
            else:
                fetched = meta
            try:
                out[i] = plan.materialize(fetched, params or {})
                variants.remember(params, plan)
            except ScheduleOverflow:
                overflowed.append((i, variants, plan))
    # overflow fallbacks re-dispatch (and may re-record) whole plans —
    # outside the host-marshalling timer so the phase split stays honest.
    # A homogeneous batch overflows as a COHORT (e.g. a delta landed and
    # every lane's replay outgrew the recorded schedule): identical
    # (statement, params) items share one resolution's rows instead of
    # each paying a lone re-dispatch behind the fresh plan's compile —
    # measured 15x the fallback cost on the mixed read/write bench.
    resolved: Dict[Tuple, object] = {}
    for i, variants, plan in overflowed:
        stmt, params = items[i]
        pk = PlanVariants._pkey(params)
        rk = (id(variants), pk) if pk is not None else None
        if rk is not None and rk in resolved:
            out[i] = resolved[rk]
            continue
        rows = _run_variants(
            db, stmt, params, variants, tried=plan, fresh=fresh
        )
        out[i] = rows
        if rk is not None:
            resolved[rk] = rows


class LaneDispatch:
    """An in-flight homogeneous micro-batch: dispatched on device, not
    yet fetched. The coalescer's lane worker dispatches micro-batch N+1
    (staging its parameters into the lane's :class:`ParamRing`) BEFORE
    collecting batch N — double-buffered dispatch, so batch formation
    and parameter upload overlap the device execution in front of them
    instead of serializing behind it. Carries the dispatch's flight
    record (obs/timeline) across the dispatch→collect gap — the lane
    worker thread runs other work in between, so the record cannot
    stay thread-local."""

    __slots__ = ("db", "items", "pending", "rec", "lease", "sql")

    def __init__(self, db, items, pending, rec=None, lease=None, sql=None) -> None:
        self.db = db
        self.items = items
        self.pending = pending
        self.rec = rec
        #: retained snapshot pinning the dispatched buffers across the
        #: double-buffered dispatch→collect gap (epoch-gated dispatch:
        #: a compaction swap cannot free them while this batch flies)
        self.lease = lease
        #: the lane's fingerprint source text — the device fault
        #: domain's quarantine key if collect's fetch faults out
        self.sql = sql

    def collect(self) -> List:
        """Fetch + marshal the dispatched batch; returns per-item row
        lists in submission order (blocking — the device round trip
        this batch amortizes across its members)."""
        import orientdb_tpu.obs.timeline as _TL

        out: List = [None] * len(self.items)
        fresh: List = []
        try:
            with _TL.active(self.rec):
                # escalation-ladder guard on the fetch/marshal wave; an
                # exhausted fault raises DeviceQuarantined out of
                # collect(), which the coalescer's batch-failure path
                # catches and re-runs per item through the front door
                # (admit gate → oracle while quarantined)
                devicefault.domain.run(
                    lambda: _finish_pending(
                        self.db, self.items, self.pending, out, fresh
                    ),
                    db=self.db,
                    sql=self.sql,
                    stage="lane_collect",
                    passthrough=(ScheduleOverflow,),
                )
        finally:
            if self.lease is not None:
                self.lease.release()
                self.lease = None
        for plan in fresh:
            plan.wait_compiled()
        _TL.recorder.commit(self.rec)
        return out


def dispatch_lane(
    db,
    items,
    ring: ParamRing = None,
    sql: Optional[str] = None,
    enqueue_ts: Optional[float] = None,
    window_s: Optional[float] = None,
    min_epoch: Optional[int] = None,
):
    """Lane-aware dispatch entry: a fingerprint-keyed coalesce lane
    drains a HOMOGENEOUS micro-batch — every item the same statement
    shape — so ONE cached plan serves all of them, with the stacked
    dynamic args staged through the lane's device-resident ``ring``.

    Non-blocking: enqueues the replay(s) on device and returns a
    :class:`LaneDispatch` to collect later, or None when the fast path
    does not apply (no cached plan yet, sticky-variant split, seed
    overflow, vmapped executable still compiling) — the caller falls
    back to the generic batch path, which also handles the recording
    first execution."""
    if db.tx is not None or not items:
        return None
    stmt0, params0 = items[0]
    key = _cache_key(stmt0, params0)
    if key is None:
        return None
    snap = db.current_snapshot(require_fresh=True)
    if snap is None:
        return None
    if min_epoch is not None and db._snapshot_epoch < min_epoch:
        # coalesce-lane epoch keying: an item was admitted AFTER a
        # write this snapshot does not cover — a lane window formed
        # pre-write must not serve that item stale results. The generic
        # path re-resolves freshness (delta catch-up or oracle).
        return None
    cache = _plan_cache(snap)
    variants = cache.get(key)
    if variants is None:
        return None  # recording first execution: generic path records
    cache.move_to_end(key)
    plan = variants.pick(params0)
    if getattr(plan, "batchable", None) is None or not plan.batchable():
        return None
    import orientdb_tpu.obs.timeline as _TL

    # the lane drain's flight record: enqueue (first rider's lane
    # entry) and collection window come from the coalescer; it travels
    # on the LaneDispatch handle because collect() runs later, after
    # the worker double-buffers the next batch
    rec = _TL.recorder.begin("lane", sql=sql, n=len(items))
    if rec is not None:
        if enqueue_ts is not None:
            rec.add_event("enqueue", enqueue_ts)
        if window_s:
            rec.marks["window_s"] = float(window_s)
            rec.add_event("lane_window")
        rec.add_event("plan_resolve")
    dyns = []
    try:
        for stmt, params in items:
            if (stmt is not stmt0 or params is not params0) and _cache_key(
                stmt, params
            ) != key:
                # lanes fold LITERALS into one fingerprint, but plans
                # bake literals (and static params) into the recording:
                # a mixed-literal drain must not replay item[0]'s plan
                # for everyone — the generic path plans each item
                return None
            if variants.pick(params) is not plan:
                # sticky routing split the lane across variants: the
                # generic path groups each variant's run correctly
                return None
            dyns.append(plan._dyn_args(params or {}))
    except ScheduleOverflow:
        return None  # the variant walk belongs to the generic path
    lease = plan.solver.snap
    if not lease.try_retain(plan.solver.dg):
        # compaction swap freed this plan's buffers between resolution
        # and the pin: the generic path re-plans on the new snapshot
        metrics.incr("tpu.lease_raced")
        return None
    handed_off = False
    try:
        try:
            with _TL.active(rec):
                # escalation-ladder guard on the lane's group dispatch;
                # exhaustion degrades this drain to the generic path
                # (whose admit gate serves the quarantined plan from
                # the oracle) rather than failing the whole micro-batch
                g = devicefault.domain.run(
                    lambda: _group_dispatch(plan, dyns, ring=ring),
                    db=db,
                    sql=sql,
                    stage="lane",
                    passthrough=(ScheduleOverflow,),
                )
        except devicefault.DeviceQuarantined:
            return None
        if g is None:
            return None  # group executable still compiling: generic path
        handed_off = True
    finally:
        if not handed_off:
            lease.release()
    grp, ks = g
    pending = [(i, variants, plan, _Lane(grp, k)) for i, k in enumerate(ks)]
    metrics.incr("tpu.lane_dispatch")
    metrics.incr("tpu.lane_items", len(items))
    return LaneDispatch(db, items, pending, rec, lease=lease, sql=sql)


def explain_plan_steps(db, stmt) -> List[str]:
    """Plan description for EXPLAIN (the [E] prettyPrint analog)."""
    solver = TpuMatchSolver(db, stmt, {})
    return [s.describe() for s in solver.plan]


def profile_execute(db, stmt, params) -> Tuple[List[Result], Dict]:
    """Execute on the compiled path with per-phase wall timings — the
    observability PROFILE needs to attack dispatch overhead (SURVEY.md
    §5.1; the whole device solve is ONE fused dispatch, so phases — not
    per-step device kernels — are the honest breakdown).

    Also traces: the returned phases carry ``traceId`` and ``spans`` —
    per-hop TPU-engine stage spans (``tpu.load``/``tpu.step``/
    ``tpu.marshal``). A replay is one fused dispatch with no per-hop
    boundary, so PROFILE re-solves eagerly under the tracer to produce
    them; PROFILE is an explicitly-requested diagnostic, so paying one
    extra eager execution for real timings is the honest trade."""
    import time as _time

    from orientdb_tpu.obs.trace import span as _span, tracer as _tracer

    if db.tx is not None:
        # same guard as engine._run: the snapshot cannot see the tx overlay
        raise Uncompilable("active transaction on this thread")
    phases: Dict[str, object] = {}
    with _span("profile", statement=type(stmt).__name__) as root, (
        _snapshot_lease(db)
    ):
        t0 = _time.perf_counter()
        variants, rows, _fresh = _prepare(db, stmt, params)
        phases["prepareUs"] = round((_time.perf_counter() - t0) * 1e6, 1)
        if variants is None:
            # recording first execution: eager, one blocking sync per
            # observe — the per-hop spans came from solve_table just now
            phases["mode"] = "record"
        else:
            plan = variants.pick(params)
            phases["mode"] = "replay"
            phases["variants"] = len(variants.plans)
            t0 = _time.perf_counter()
            plan.wait_compiled()  # keep a pending AOT compile out of dispatchUs
            phases["compileWaitUs"] = round((_time.perf_counter() - t0) * 1e6, 1)
            t0 = _time.perf_counter()
            with _span("tpu.dispatch"):
                dev = devicefault.domain.run(
                    lambda: plan.dispatch(params or {}),
                    db=db,
                    stage="profile",
                    passthrough=(ScheduleOverflow,),
                )
            phases["dispatchUs"] = round((_time.perf_counter() - t0) * 1e6, 1)
            t0 = _time.perf_counter()
            with _span("tpu.device"):
                devicefault.domain.run(
                    lambda: (
                        devicefault.transfer_point(),
                        jax.block_until_ready(dev),
                    ),
                    db=db,
                    stage="profile",
                )
            phases["deviceUs"] = round((_time.perf_counter() - t0) * 1e6, 1)
            t0 = _time.perf_counter()
            with _span("tpu.marshal"):
                try:
                    rows = devicefault.domain.run(
                        lambda: plan.materialize(dev, params or {}),
                        db=db,
                        stage="profile",
                        passthrough=(ScheduleOverflow,),
                    )
                    variants.remember(params, plan)
                except ScheduleOverflow:
                    rows = _run_variants(db, stmt, params, variants, tried=plan)
                    phases["mode"] = "overflow-variant"
            phases["fetchMarshalUs"] = round(
                (_time.perf_counter() - t0) * 1e6, 1
            )
            solver = plan.solver
            sched = getattr(solver, "sched", None)
            if sched is not None:
                phases["scheduleObserves"] = len(sched.values)
                phases["scheduleSizes"] = sched.values[:32]
            steps = getattr(solver, "plan", None)
            if steps:
                phases["steps"] = [s.describe() for s in steps]
            # the replay has no per-hop boundaries: re-solve eagerly under
            # the tracer so the spans show real per-hop stage timings
            try:
                _record(db, stmt, params)
            except Exception as e:  # noqa: BLE001 - diagnostic only
                # rows are already computed; a failing diagnostic
                # re-solve must not fail the PROFILE itself
                phases["traceError"] = f"{type(e).__name__}: {e}"
    phases["traceId"] = root.trace_id
    phases["spans"] = [
        s.to_dict() for s in _tracer.spans(trace_id=root.trace_id)
    ]
    return rows, phases
