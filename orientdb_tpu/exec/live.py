"""Live queries (LIVE SELECT).

Analog of [E] OLiveQueryHookV2 / OLiveQueryMonitor (SURVEY.md §2 "Live
queries / hooks"): a LIVE SELECT subscribes to post-commit record events on
its target class; every matching create/update/delete pushes an event
``{"token", "operation", "rid", "record"}`` to the subscriber callback.
The WHERE clause (if any) is evaluated against the record for create/update
(delete events always fire, as in the reference, since the stored record no
longer matches anything).

Monitors are callback-mode consumers of the database's CDC plane
(``orientdb_tpu/cdc``): on a WAL-armed database events derive from the
committed log — a replica's monitors therefore see replication-applied
writes too (the hook path never fired for those), gap-free and carrying
real LSNs; on a plain in-memory database the feed's hook tap preserves
the original embedded semantics (post-commit delivery, tx events only
after the whole commit succeeded).

Python API: ``monitor = live_query(db, sql, callback)`` →
``monitor.unsubscribe()``. SQL surface: ``LIVE SELECT FROM Class`` returns
a row with the monitor token and buffers events on the monitor
(``live_unsubscribe(db, token)`` cancels).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List

from orientdb_tpu.exec.result import Result
from orientdb_tpu.sql import ast as A
from orientdb_tpu.utils.logging import get_logger

log = get_logger("live")


class LiveQueryMonitor:
    """One live subscription ([E] OLiveQueryMonitor) — a callback-mode
    CDC consumer restricted to the statement's class + WHERE."""

    def __init__(self, db, stmt: A.SelectStatement, callback: Callable) -> None:
        if not isinstance(stmt.target, A.ClassTarget):
            raise ValueError("LIVE SELECT supports class targets only")
        from orientdb_tpu.cdc.feed import live_feed

        self.db = db
        self.stmt = stmt
        self.callback = callback
        self.class_name = stmt.target.name
        self._lock = threading.Lock()
        self._active = True
        self._consumer = live_feed(db).register(
            classes=[self.class_name],
            where=stmt.where,
            callback=self._on_change,
        )
        self.token = self._consumer.token

    def _on_change(self, ev: Dict) -> None:
        if not self._active:
            return
        record = ev.get("record")
        if record is not None:
            # WAL-derived events carry wire-encoded values ({"@link"},
            # {"@bytes"}); embedded subscribers expect the native shapes
            # the hook path always delivered (RID objects, bytes). _dec
            # is a no-op on already-native values, so hook-tap events
            # pass through unchanged.
            from orientdb_tpu.storage.durability import _dec

            record = {
                k: (v if k.startswith("@") else _dec(v))
                for k, v in record.items()
            }
        try:
            self.callback(
                {
                    "token": self.token,
                    "operation": ev["op"].upper(),
                    "rid": ev["rid"],
                    "record": record,
                    "lsn": ev.get("lsn"),
                }
            )
        except Exception:  # subscriber errors must not break commits
            log.exception("live subscriber %s failed", self.token)

    def unsubscribe(self) -> None:
        with self._lock:
            if self._active:
                self._active = False
                self._consumer.feed.unregister(self.token)
                reg = getattr(self.db, "_live_registry", None)
                if reg is not None:
                    reg.monitors.pop(self.token, None)


class LiveQueryRegistry:
    def __init__(self) -> None:
        self.monitors: Dict[int, LiveQueryMonitor] = {}

    def add(self, m: LiveQueryMonitor) -> None:
        self.monitors[m.token] = m

    def get(self, token: int):
        return self.monitors.get(token)

    def remove(self, token: int) -> bool:
        m = self.monitors.pop(token, None)
        if m is None:
            return False
        m.unsubscribe()
        return True


def _registry(db) -> LiveQueryRegistry:
    reg = getattr(db, "_live_registry", None)
    if reg is None:
        reg = db._live_registry = LiveQueryRegistry()
    return reg


def live_query(db, sql_or_stmt, callback: Callable) -> LiveQueryMonitor:
    """Subscribe; returns the monitor (Python API entry)."""
    if isinstance(sql_or_stmt, str):
        from orientdb_tpu.exec.engine import parse_cached

        stmt = parse_cached(sql_or_stmt)
    else:
        stmt = sql_or_stmt
    if isinstance(stmt, A.LiveSelectStatement):
        stmt = stmt.inner
    if not isinstance(stmt, A.SelectStatement):
        raise ValueError("live queries wrap a SELECT")
    m = LiveQueryMonitor(db, stmt, callback)
    _registry(db).add(m)
    return m


def live_monitor(db, token: int):
    return _registry(db).get(token)


def live_unsubscribe(db, token: int) -> bool:
    return _registry(db).remove(token)


class BufferedEvents:
    """Thread-safe event buffer with long-poll semantics: writers `push`,
    a reader `drain(timeout)` blocks until at least one event (or the
    timeout) and takes the whole buffer. The HTTP live-query transport
    ([E] the reference pushes to remote clients; long-poll is the
    pull-shaped equivalent over plain HTTP)."""

    def __init__(self, keep: int = 1000) -> None:
        self._events: List[dict] = []
        self._cv = threading.Condition()
        self._keep = keep

    def push(self, ev: dict) -> None:
        with self._cv:
            self._events.append(ev)
            del self._events[: -self._keep]
            self._cv.notify_all()

    def drain(self, timeout: float = 0.0) -> List[dict]:
        with self._cv:
            if not self._events and timeout > 0:
                self._cv.wait(timeout)
            out = self._events[:]
            self._events.clear()
            return out


def subscribe(db, stmt: A.LiveSelectStatement, params) -> List[Result]:
    """SQL surface: events buffer on the monitor until consumed (pull style)
    or a callback replaces the buffer."""
    events: List[dict] = []
    m = live_query(db, stmt, events.append)
    m.events = events  # buffered for pull-style consumers
    return [Result(props={"token": m.token, "operation": "live"})]
