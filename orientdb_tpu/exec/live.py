"""Live queries (LIVE SELECT).

Placeholder until the live-query hook system lands (analog of [E]
OLiveQueryHookV2 / ORecordHook, SURVEY.md §2 "Live queries / hooks").
"""

from __future__ import annotations

from typing import List

from orientdb_tpu.exec.result import Result


def subscribe(db, stmt, params) -> List[Result]:
    raise NotImplementedError("live queries are not implemented yet")
