"""Query results.

Parity layer for OrientDB's ``OResult`` / ``OResultInternal`` / ``OResultSet``
([E] core/.../sql/executor/OResultInternal.java, SURVEY.md §1 layer 5): a
result is either an *element* (a record) or a *projection* (a computed row of
named properties); a result set is a forward-only stream with ``has_next`` /
``next`` plus pythonic iteration.

The TPU engine marshals device arrays back into these rows (the
"OResultInternal-parity rows" requirement of the north star), so parity tests
compare `[sorted] list(rs.to_dicts())` across engines.
"""

from __future__ import annotations

import hashlib
from collections import Counter
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from orientdb_tpu.models.record import Document
from orientdb_tpu.models.rid import RID


# ---------------------------------------------------------------------------
# result canonicalization (THE parity definition)
# ---------------------------------------------------------------------------
# bench.py's parity gates and the shadow-oracle auditor (exec/audit) must
# agree on what "the same result set" means; both import these helpers so
# the two parity planes cannot drift apart.


def canonical_rows(rows: Iterable[Dict[str, object]]) -> List[Tuple]:
    """Order-insensitive canonical form of a list of plain-dict rows
    (the ``to_dicts()`` shape): each row becomes a sorted item tuple,
    the rows sort as a multiset. Mixed-type rows that defeat tuple
    ordering fall back to a repr sort key — multiset equality is
    preserved either way (same deterministic key on both sides)."""
    items = [tuple(sorted(r.items())) for r in rows]
    try:
        return sorted(items)
    except TypeError:
        return sorted(items, key=repr)


def result_digest(rows: Iterable[Dict[str, object]]) -> str:
    """Stable 64-bit hex digest of :func:`canonical_rows` — what the
    auditor compares (and divergence records carry) instead of keeping
    both row sets alive."""
    h = hashlib.blake2b(digest_size=8)
    for row in canonical_rows(rows):
        h.update(repr(row).encode())
        h.update(b"\x00")
    return h.hexdigest()


def rows_diff_sample(
    served: Iterable[Dict[str, object]],
    oracle: Iterable[Dict[str, object]],
    limit: int = 5,
) -> Dict[str, List[str]]:
    """Row-level divergence sample for a replayable divergence record:
    up to ``limit`` canonical rows present only on each side."""
    ca = Counter(repr(t) for t in canonical_rows(served))
    cb = Counter(repr(t) for t in canonical_rows(oracle))
    return {
        "only_served": list((ca - cb).elements())[:limit],
        "only_oracle": list((cb - ca).elements())[:limit],
    }


class Result:
    """One row: wraps a record or a projection map."""

    __slots__ = ("_element", "_props", "_metadata")

    def __init__(
        self,
        element: Optional[Document] = None,
        props: Optional[Dict[str, object]] = None,
    ) -> None:
        self._element = element
        self._props: Dict[str, object] = props or {}
        self._metadata: Dict[str, object] = {}

    # -- OResult surface ---------------------------------------------------

    @property
    def is_element(self) -> bool:
        return self._element is not None and not self._props

    @property
    def element(self) -> Optional[Document]:
        return self._element

    def get_property(self, name: str, default=None):
        if name in self._props:
            return self._props[name]
        if self._element is not None:
            return self._element.get(name, default)
        return default

    def property_names(self) -> List[str]:
        if self._props:
            return list(self._props.keys())
        if self._element is not None:
            return self._element.field_names()
        return []

    def set_property(self, name: str, value) -> None:
        self._props[name] = value

    def set_metadata(self, name: str, value) -> None:
        self._metadata[name] = value

    def get_metadata(self, name: str, default=None):
        return self._metadata.get(name, default)

    @property
    def rid(self) -> Optional[RID]:
        return self._element.rid if self._element is not None else None

    def __getitem__(self, name: str):
        return self.get_property(name)

    def to_dict(self) -> Dict[str, object]:
        """Plain-python row; records are rendered as their RID string (the
        stable identity used by parity comparisons)."""
        if self.is_element:
            assert self._element is not None
            return self._element.to_dict()
        return {k: _plain(v) for k, v in self._props.items()}

    def __repr__(self) -> str:
        if self.is_element:
            return f"Result({self._element!r})"
        return f"Result({self._props!r})"


def _plain(v):
    if isinstance(v, Document):
        return str(v.rid) if v.rid.is_persistent else v.to_dict()
    if isinstance(v, RID):
        return str(v)
    if isinstance(v, Result):
        return v.to_dict()
    if isinstance(v, (list, tuple)):
        return [_plain(x) for x in v]
    if isinstance(v, dict):
        return {k: _plain(x) for k, x in v.items()}
    return v


class ColumnarRows:
    """Projection rows kept as decoded object columns.

    The TPU engine's columnar fast path (`tpu_engine._fast_rows`) decodes
    device columns into per-projection object arrays; building a `Result`
    per row up front costs more host time than the whole device solve for
    large result sets. This sequence materializes `Result` objects only if
    a caller actually iterates, and `to_dicts()` goes straight from the
    columns (the common parity/serialization consumer)."""

    __slots__ = ("names", "cols", "n")

    def __init__(self, names: List[str], cols: List, n: int) -> None:
        self.names = names
        self.cols = cols
        self.n = n

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i):
        """List-compatible access (int index or slice), materializing
        `Result` objects on demand — callers annotated `List[Result]`
        must not explode just because the fast path produced the rows."""
        if isinstance(i, slice):
            idx = range(*i.indices(self.n))
            return [self._row(j) for j in idx]
        j = i + self.n if i < 0 else i
        if not 0 <= j < self.n:
            raise IndexError(i)
        return self._row(j)

    def _row(self, j: int) -> Result:
        return Result(
            props={n: c[j] for n, c in zip(self.names, self.cols)}
        )

    def __iter__(self) -> Iterator[Result]:
        names = self.names
        if not self.cols:
            for _ in range(self.n):
                yield Result(props={})
            return
        for row in zip(*self.cols):
            yield Result(props=dict(zip(names, row)))

    def to_dicts(self) -> List[Dict[str, object]]:
        names = self.names
        if not self.cols:
            return [{} for _ in range(self.n)]
        return [dict(zip(names, row)) for row in zip(*self.cols)]


class ResultSet:
    """Forward-only row stream ([E] OResultSet), with an attached execution
    plan for EXPLAIN/PROFILE."""

    def __init__(self, rows: Iterable[Result], plan=None) -> None:
        self._rows = rows
        self._it: Optional[Iterator[Result]] = None
        self._peeked: Optional[Result] = None
        self._exhausted = False
        self.plan = plan

    def has_next(self) -> bool:
        if self._peeked is not None:
            return True
        if self._exhausted:
            return False
        if self._it is None:
            self._it = iter(self._rows)
        try:
            self._peeked = next(self._it)
            return True
        except StopIteration:
            self._exhausted = True
            return False

    def next(self) -> Result:
        if not self.has_next():
            raise StopIteration
        row, self._peeked = self._peeked, None
        assert row is not None
        return row

    def __iter__(self) -> Iterator[Result]:
        while self.has_next():
            yield self.next()

    def __next__(self) -> Result:
        return self.next()

    def to_list(self) -> List[Result]:
        return list(self)

    def to_dicts(self) -> List[Dict[str, object]]:
        # bulk path: untouched columnar rows skip Result materialization
        # entirely (consumes the stream, like the row-by-row path below)
        if (
            self._it is None
            and not self._exhausted
            and isinstance(self._rows, ColumnarRows)
        ):
            self._exhausted = True
            return self._rows.to_dicts()
        return [r.to_dict() for r in self]

    def close(self) -> None:  # API parity; nothing to release host-side
        self._exhausted = True
        self._peeked = None
