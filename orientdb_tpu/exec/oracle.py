"""Pure-Python reference interpreter — the parity oracle.

Defines the *semantics* the TPU engine must reproduce, playing the role of
the reference's pull-based step executor ([E] core/.../sql/executor/ —
OSelectExecutionPlanner step chains, OMatchExecutionPlanner +
MatchEdgeTraverser per-record DFS, Depth/BreadthFirstTraverseStep;
SURVEY.md §3.2–§3.3). Deliberately simple and record-at-a-time: this is the
slow path OrientDB actually runs, and the baseline `bench.py` compares the
batched TPU engine against.

MATCH semantics implemented here (the golden-corpus spec, mirroring
[E] OMatchStatementExecutionNewTest):
- one result row per distinct alias-binding combination, duplicates kept
  unless DISTINCT;
- aliases shared across comma-separated arms join; disjoint sub-patterns
  produce cartesian products;
- `while`/`maxDepth` arms iterate breadth of a DFS with a per-expansion
  visited set; depth 0 (the origin) is itself a candidate of the target
  alias — OrientDB's depth-0-includes-start behavior;
- the target `where` filters *emission* while `while` gates *traversal*;
- `optional:true` targets bind null when unmatched; NOT arms reject any
  binding for which the negated pattern is satisfiable;
- RETURN $matches / $paths give one row per match (named / all aliases);
  $elements / $pathElements flatten to one row per bound record.
"""

from __future__ import annotations

import itertools
from collections import deque
from typing import Deque
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

from orientdb_tpu.exec.eval import (
    EvalContext,
    EvalError,
    AGGREGATE_FUNCTIONS,
    as_list,
    compare,
    contains_aggregate,
    evaluate,
    get_prop,
    nav_edges,
    nav_vertices,
    resolve_links,
    truthy,
)
from orientdb_tpu.exec.result import Result, ResultSet
from orientdb_tpu.models.record import Document, Edge, Vertex, Direction
from orientdb_tpu.models.rid import RID
from orientdb_tpu.sql import ast as A


class ExecutionError(Exception):
    pass


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def expr_name(expr: A.Expression, index: int) -> str:
    """Deterministic column name for an unaliased projection ([E] the
    reference uses the expression's source text)."""
    if isinstance(expr, A.Identifier):
        return expr.name
    if isinstance(expr, A.FieldAccess):
        return expr.name
    if isinstance(expr, A.FunctionCall):
        return f"{expr.name}"
    if isinstance(expr, A.MethodCall):
        return expr.name
    if isinstance(expr, A.ContextVar):
        return f"${expr.name}"
    return f"_col{index}"


def resolve_target_rows(db, target: Optional[A.Target], ctx: EvalContext) -> Iterator:
    """FROM-target resolution → iterator of Documents / Results / values."""
    if target is None:
        yield Result(props={})
        return
    if isinstance(target, A.ClassTarget):
        cls = db.schema.get_class(target.name)
        if cls is None:
            raise ExecutionError(f"class '{target.name}' not found")
        yield from db.browse_class(cls.name, polymorphic=target.polymorphic)
        return
    if isinstance(target, A.ClusterTarget):
        if isinstance(target.name_or_id, int):
            yield from db.browse_cluster(target.name_or_id)
            return
        # cluster names are "<classname>" (first cluster) or "<classname>_N"
        name = str(target.name_or_id)
        cls = db.schema.get_class(name)
        if cls is None or not cls.cluster_ids:
            raise ExecutionError(f"cluster '{name}' not found")
        yield from db.browse_cluster(cls.cluster_ids[0])
        return
    if isinstance(target, A.RidTarget):
        for r in target.rids:
            doc = db.load(RID(r.cluster, r.position))
            if doc is not None:
                yield doc
        return
    if isinstance(target, A.IndexTarget):
        idx = db.indexes.get_index(target.name)
        if idx is None:
            raise ExecutionError(f"index '{target.name}' not found")
        keys = idx.keys()
        for k in keys:
            for rid in sorted(idx.get(k)):
                yield Result(props={"key": k, "rid": rid})
        return
    if isinstance(target, A.SubQueryTarget):
        for r in execute_statement(db, target.query, ctx.params, parent_ctx=ctx):
            yield r.element if r.is_element else r
        return
    if isinstance(target, A.ExpressionTarget):
        val = evaluate(ctx, target.expr)
        for item in as_list(resolve_links(ctx, val)):
            if item is not None:
                yield item
        return
    raise ExecutionError(f"unsupported target {target!r}")


def _row_ctx(db, row, params, parent_ctx) -> EvalContext:
    return EvalContext(db, current=row, params=params, parent=parent_ctx)


# ---------------------------------------------------------------------------
# index-driven candidate pruning ([E] the planner's index-vs-scan choice,
# SURVEY.md §3.2: "OSelectExecutionPlanner … index vs scan choice")
# ---------------------------------------------------------------------------


def _const_operand(expr: A.Expression, ctx: EvalContext):
    """(ok, value) for expressions that cannot reference the current row —
    literals, parameters, and their negations. Anything else is not a
    constant for index-probe purposes."""
    if isinstance(expr, A.Literal):
        return True, expr.value
    if isinstance(expr, A.Parameter):
        key = expr.name if expr.name is not None else expr.index
        if key in ctx.params:
            return True, ctx.params[key]
        return False, None
    if isinstance(expr, A.Unary) and expr.op in ("-", "+"):
        ok, v = _const_operand(expr.expr, ctx)
        if ok and isinstance(v, (int, float)) and not isinstance(v, bool):
            return True, (-v if expr.op == "-" else v)
        return False, None
    return False, None


_FLIP_OP = {"<": ">", "<=": ">=", ">": "<", ">=": "<=", "=": "="}


def _spatial_probe(db, class_name, fn, rhs, op, ctx):
    """Candidate RIDs for a ``distance(latF, lngF, <x>, <y>[, unit]) < r``
    conjunct via a SPATIAL grid index ([E] the lucene-spatial
    within-distance query; SURVEY.md §2 "Lucene"). Returns a SUPERSET —
    the caller still row-filters with the full WHERE — or None when the
    shape/index doesn't apply."""
    if (
        fn.name.lower() != "distance"
        or len(fn.args) < 4
        or op not in ("<", "<=")
        or db._indexes is None
    ):
        return None
    a0, a1 = fn.args[0], fn.args[1]
    if not (isinstance(a0, A.Identifier) and isinstance(a1, A.Identifier)):
        return None
    ok_r, r = _const_operand(rhs, ctx)
    ok_lat, latv = _const_operand(fn.args[2], ctx)
    ok_lng, lngv = _const_operand(fn.args[3], ctx)

    def num(v):
        return isinstance(v, (int, float)) and not isinstance(v, bool)

    if not (ok_r and ok_lat and ok_lng and num(r) and num(latv) and num(lngv)):
        return None
    if len(fn.args) > 4:
        from orientdb_tpu.utils.geo import MILE_UNITS, MILES_PER_KM

        u = fn.args[4]
        if not isinstance(u, A.Literal):
            return None
        unit = str(u.value).lower()
        if unit in MILE_UNITS:
            r = float(r) / MILES_PER_KM
        elif unit != "km":
            return None
    from orientdb_tpu.models.indexes import SpatialIndex

    cls = db.schema.get_class(class_name)
    if cls is None:
        return None
    for idx in db._indexes.all():
        if (
            isinstance(idx, SpatialIndex)
            and idx.fields == [a0.name, a1.name]
            and cls.is_subclass_of(idx.class_name)
        ):
            return idx.near(float(latv), float(lngv), float(r))
    return None


def index_lookup_rids(db, class_name: str, where: A.Expression, ctx: EvalContext):
    """RIDs satisfying ONE indexable conjunct of ``where``, or None when no
    single-field index applies. The caller still evaluates the FULL WHERE
    per row — the index is a pruning prefetch, so using it can only shrink
    the scanned set, never change results."""
    if isinstance(where, A.Binary) and where.op == "AND":
        left = index_lookup_rids(db, class_name, where.left, ctx)
        if left is not None:
            return left
        return index_lookup_rids(db, class_name, where.right, ctx)
    if db._indexes is None:
        return None

    def probe(lhs, rhs, op):
        if not isinstance(lhs, A.Identifier):
            return None
        idx = db._indexes.best_for(class_name, lhs.name)
        if idx is None:
            return None
        ok, v = _const_operand(rhs, ctx)
        if not ok or v is None:
            return None
        try:
            if op == "=":
                return set(idx.get(v))
            if not idx.range_capable:
                return None
            lo, hi = (v, None) if op in (">", ">=") else (None, v)
            out = set()
            for _k, rids in idx.range(
                lo=lo,
                hi=hi,
                lo_inclusive=(op != ">"),
                hi_inclusive=(op != "<"),
            ):
                out |= rids
            return out
        except TypeError:
            return None  # mixed-type keys: leave it to the row filter

    if isinstance(where, A.Binary) and where.op in _FLIP_OP:
        if isinstance(where.left, A.FunctionCall):
            return _spatial_probe(
                db, class_name, where.left, where.right, where.op, ctx
            )
        if isinstance(where.right, A.FunctionCall):
            return _spatial_probe(
                db,
                class_name,
                where.right,
                where.left,
                _FLIP_OP[where.op],
                ctx,
            )
        return probe(where.left, where.right, where.op) if isinstance(
            where.left, A.Identifier
        ) else probe(where.right, where.left, _FLIP_OP[where.op])
    if isinstance(where, A.Between) and isinstance(where.expr, A.Identifier):
        idx = db._indexes.best_for(class_name, where.expr.name)
        if idx is None or not idx.range_capable:
            return None
        ok_lo, lo = _const_operand(where.low, ctx)
        ok_hi, hi = _const_operand(where.high, ctx)
        if not (ok_lo and ok_hi) or lo is None or hi is None:
            return None
        try:
            out = set()
            for _k, rids in idx.range(lo=lo, hi=hi):
                out |= rids
            return out
        except TypeError:
            return None
    return None


def indexed_class_docs(db, class_name: str, polymorphic: bool, where, ctx):
    """Documents of ``class_name`` pruned through an index, or None → the
    caller scans. Disabled under an active transaction (indexes don't see
    the tx overlay)."""
    if db.tx is not None or where is None:
        return None
    cls = db.schema.get_class(class_name)
    if cls is None:
        return None
    rids = index_lookup_rids(db, cls.name, where, ctx)
    if rids is None:
        return None
    docs = []
    for rid in sorted(rids):
        d = db._load_raw(rid)
        if d is None:
            continue
        dcls = db.schema.get_class(d.class_name)
        if dcls is None or not dcls.is_subclass_of(cls.name):
            continue  # the index may span sibling subclasses
        if not polymorphic and d.class_name != cls.name:
            continue
        docs.append(d)
    return docs


def _skip_limit(rows: List, skip_expr, limit_expr, ctx) -> List:
    skip = int(evaluate(ctx, skip_expr)) if skip_expr is not None else 0
    limit = int(evaluate(ctx, limit_expr)) if limit_expr is not None else None
    if skip:
        rows = rows[skip:]
    if limit is not None and limit >= 0:
        rows = rows[:limit]
    return rows


def _sort_key_fn(vals: List):
    """Total order over heterogeneous projection values: None sorts first,
    then by (type-rank, value)."""

    def rank(v):
        if v is None:
            return (0, 0)
        if isinstance(v, bool):
            return (1, v)
        if isinstance(v, (int, float)):
            return (2, v)
        if isinstance(v, str):
            return (3, v)
        if isinstance(v, RID):
            return (4, (v.cluster, v.position))
        if isinstance(v, Document):
            return (4, (v.rid.cluster, v.rid.position))
        return (5, repr(v))

    return tuple(rank(v) for v in vals)


def _order_rows(
    rows: List[Result], order_by, db, params, parent_ctx, sources=None
) -> List[Result]:
    """Sort rows; an ORDER BY key may name a projection alias or (failing
    that) a field of the *source* record, as in the reference's executor."""
    if not order_by:
        return rows
    keyed = []
    for i, r in enumerate(rows):
        ctx = _row_ctx(db, r, params, parent_ctx)
        vals = []
        for item in order_by:
            v = evaluate(ctx, item.expr)
            if v is None and sources is not None and sources[i] is not None:
                sctx = _row_ctx(db, sources[i], params, parent_ctx)
                v = evaluate(sctx, item.expr)
            vals.append(v)
        keyed.append((vals, r))
    # stable multi-key sort: apply keys right-to-left
    for i in range(len(order_by) - 1, -1, -1):
        keyed.sort(
            key=lambda kv: _sort_key_fn([kv[0][i]]),
            reverse=not order_by[i].ascending,
        )
    return [r for _, r in keyed]


def _canonical(v) -> object:
    """Hashable canonical form for DISTINCT / GROUP BY keys."""
    if isinstance(v, Document):
        return ("rec", str(v.rid))
    if isinstance(v, RID):
        return ("rid", str(v))
    if isinstance(v, Result):
        return ("row", tuple(sorted((k, _canonical(v.get_property(k))) for k in v.property_names())))
    if isinstance(v, (list, tuple)):
        return ("list", tuple(_canonical(x) for x in v))
    if isinstance(v, set):
        return ("set", tuple(sorted(map(repr, v))))
    if isinstance(v, dict):
        return ("map", tuple(sorted((k, _canonical(x)) for k, x in v.items())))
    return v


# ---------------------------------------------------------------------------
# SELECT
# ---------------------------------------------------------------------------


class _Aggregator:
    __slots__ = ("fn", "count", "acc", "seen")

    def __init__(self, fn: str) -> None:
        self.fn = fn
        self.count = 0
        self.acc = None
        self.seen = False

    def add(self, value) -> None:
        if self.fn == "count":
            if value is not None:
                self.count += 1
            return
        if value is None:
            return
        if not self.seen:
            self.acc = value
            self.seen = True
            self.count = 1
            return
        self.count += 1
        if self.fn == "sum" or self.fn == "avg":
            self.acc = self.acc + value
        elif self.fn == "min":
            c = compare(value, self.acc)
            if c is not None and c < 0:
                self.acc = value
        elif self.fn == "max":
            c = compare(value, self.acc)
            if c is not None and c > 0:
                self.acc = value

    def result(self):
        if self.fn == "count":
            return self.count
        if not self.seen:
            return None
        if self.fn == "avg":
            return self.acc / self.count
        return self.acc


def _eval_with_aggregates(ctx: EvalContext, expr: A.Expression, aggs: Dict[int, _Aggregator]):
    """Evaluate a projection expression replacing aggregate calls with their
    accumulated results (aggs keyed by id of the FunctionCall node)."""
    if isinstance(expr, A.FunctionCall) and expr.name in AGGREGATE_FUNCTIONS:
        return aggs[id(expr)].result()
    if isinstance(expr, A.Binary):
        lv = _eval_with_aggregates(ctx, expr.left, aggs)
        rv = _eval_with_aggregates(ctx, expr.right, aggs)
        return evaluate(ctx, A.Binary(expr.op, A.Literal(lv), A.Literal(rv)))
    if isinstance(expr, A.Unary):
        v = _eval_with_aggregates(ctx, expr.expr, aggs)
        return evaluate(ctx, A.Unary(expr.op, A.Literal(v)))
    return evaluate(ctx, expr)


def _collect_aggregates(expr: A.Expression, out: List[A.FunctionCall]) -> None:
    if isinstance(expr, A.FunctionCall):
        if expr.name in AGGREGATE_FUNCTIONS:
            out.append(expr)
            return
        for a in expr.args:
            _collect_aggregates(a, out)
    elif isinstance(expr, A.Binary):
        _collect_aggregates(expr.left, out)
        _collect_aggregates(expr.right, out)
    elif isinstance(expr, A.Unary):
        _collect_aggregates(expr.expr, out)
    elif isinstance(expr, A.MethodCall):
        _collect_aggregates(expr.base, out)
    elif isinstance(expr, A.FieldAccess):
        _collect_aggregates(expr.base, out)


def execute_select(db, stmt: A.SelectStatement, params, parent_ctx=None) -> List[Result]:
    base_ctx = EvalContext(db, params=params, parent=parent_ctx)
    source = None
    if isinstance(stmt.target, A.ClassTarget) and db.schema.exists_class(
        stmt.target.name
    ):
        pruned = indexed_class_docs(
            db, stmt.target.name, stmt.target.polymorphic, stmt.where, base_ctx
        )
        if pruned is not None:
            source = iter(pruned)
    if source is None:
        source = resolve_target_rows(db, stmt.target, base_ctx)

    # per-row context with LET variables
    def contexts() -> Iterator[Tuple[EvalContext, object]]:
        for row in source:
            ctx = _row_ctx(db, row, params, parent_ctx)
            for let in stmt.lets:
                if isinstance(let.value, A.Statement):
                    sub = execute_statement(db, let.value, params, parent_ctx=ctx)
                    ctx.variables[let.name] = [
                        r.element if r.is_element else r for r in sub
                    ]
                else:
                    ctx.variables[let.name] = evaluate(ctx, let.value)
            yield ctx, row

    filtered: List[Tuple[EvalContext, object]] = []
    for ctx, row in contexts():
        if stmt.where is None or truthy(evaluate(ctx, stmt.where)):
            filtered.append((ctx, row))

    aggregate_mode = bool(stmt.group_by) or any(
        contains_aggregate(p.expr) for p in stmt.projections
    )

    rows: List[Result]
    sources: Optional[List[object]]
    if aggregate_mode:
        rows = _aggregate_rows(db, stmt, filtered, params, parent_ctx)
        sources = None
    else:
        rows = _project_rows(db, stmt.projections, filtered)
        sources = (
            [row for _, row in filtered] if len(rows) == len(filtered) else None
        )

    if stmt.distinct:
        seen = set()
        deduped, dd_sources = [], []
        for i, r in enumerate(rows):
            key = _canonical(r)
            if key not in seen:
                seen.add(key)
                deduped.append(r)
                if sources is not None:
                    dd_sources.append(sources[i])
        rows = deduped
        sources = dd_sources if sources is not None else None

    for field in stmt.unwind:
        unwound: List[Result] = []
        unwound_sources: List[object] = []
        for i, r in enumerate(rows):
            src = sources[i] if sources is not None else None
            vals = as_list(r.get_property(field))
            expanded = vals if vals else [None]
            for v in expanded:
                rr = Result(props={k: r.get_property(k) for k in r.property_names()})
                rr.set_property(field, v)
                unwound.append(rr)
                unwound_sources.append(src)
        rows = unwound
        sources = unwound_sources

    rows = _order_rows(rows, stmt.order_by, db, params, parent_ctx, sources)
    rows = _skip_limit(rows, stmt.skip, stmt.limit, base_ctx)
    return rows


def _project_rows(db, projections, filtered) -> List[Result]:
    if not projections:
        return [
            (row if isinstance(row, Result) else Result(element=row))
            for _, row in filtered
        ]
    # single expand(...) projection flattens to element rows
    if len(projections) == 1 and isinstance(projections[0].expr, A.FunctionCall) and (
        projections[0].expr.name == "expand"
    ):
        inner = projections[0].expr.args[0]
        out = []
        for ctx, _row in filtered:
            val = evaluate(ctx, inner)
            for item in as_list(resolve_links(ctx, val)):
                if isinstance(item, Document):
                    out.append(Result(element=item))
                elif isinstance(item, Result):
                    out.append(item)
                elif item is not None:
                    out.append(Result(props={"value": item}))
        return out
    out = []
    for ctx, row in filtered:
        props: Dict[str, object] = {}
        for i, p in enumerate(projections):
            if isinstance(p.expr, A.Star):
                if isinstance(row, Document):
                    props.update(row.to_dict(include_meta=False))
                elif isinstance(row, Result):
                    for k in row.property_names():
                        props[k] = row.get_property(k)
                continue
            name = p.alias or expr_name(p.expr, i)
            props[name] = evaluate(ctx, p.expr)
        out.append(Result(props=props))
    return out


def _aggregate_rows(db, stmt, filtered, params, parent_ctx) -> List[Result]:
    # groups: key → (first_ctx, aggregators per projection)
    groups: Dict[object, Tuple[EvalContext, Dict[int, _Aggregator]]] = {}
    order: List[object] = []
    agg_nodes: List[A.FunctionCall] = []
    for p in stmt.projections:
        _collect_aggregates(p.expr, agg_nodes)

    for ctx, _row in filtered:
        key = tuple(_canonical(evaluate(ctx, g)) for g in stmt.group_by)
        if key not in groups:
            groups[key] = (ctx, {id(n): _Aggregator(n.name) for n in agg_nodes})
            order.append(key)
        _, aggs = groups[key]
        for node in agg_nodes:
            agg = aggs[id(node)]
            if len(node.args) == 1 and isinstance(node.args[0], A.Star):
                agg.count += 1
            else:
                agg.add(evaluate(ctx, node.args[0]) if node.args else None)

    out = []
    for key in order:
        ctx, aggs = groups[key]
        props = {}
        for i, p in enumerate(stmt.projections):
            name = p.alias or expr_name(p.expr, i)
            props[name] = _eval_with_aggregates(ctx, p.expr, aggs)
        out.append(Result(props=props))
    if not out and not stmt.group_by and agg_nodes:
        # aggregate over empty input still yields one row (count(*) = 0)
        props = {}
        empty_aggs = {id(n): _Aggregator(n.name) for n in agg_nodes}
        ctx = EvalContext(db, params=params, parent=parent_ctx)
        for i, p in enumerate(stmt.projections):
            name = p.alias or expr_name(p.expr, i)
            props[name] = _eval_with_aggregates(ctx, p.expr, empty_aggs)
        out.append(Result(props=props))
    return out


# ---------------------------------------------------------------------------
# MATCH
# ---------------------------------------------------------------------------


class PatternNode:
    """[E] PatternNode: one alias with its merged constraints."""

    __slots__ = ("alias", "filters", "anonymous", "optional", "is_edge_alias")

    def __init__(self, alias: str, anonymous: bool) -> None:
        self.alias = alias
        self.anonymous = anonymous
        self.filters: List[A.MatchFilter] = []
        self.optional = False
        self.is_edge_alias = False


class PatternEdge:
    """[E] PatternEdge: one path item connecting two aliases."""

    __slots__ = ("from_alias", "to_alias", "item", "negated_arm")

    def __init__(self, from_alias: str, to_alias: str, item: A.MatchPathItem, negated: bool):
        self.from_alias = from_alias
        self.to_alias = to_alias
        self.item = item
        self.negated_arm = negated


class Pattern:
    """[E] Pattern: nodes + edges, built from the MATCH AST."""

    def __init__(self) -> None:
        self.nodes: Dict[str, PatternNode] = {}
        self.edges: List[PatternEdge] = []
        self._anon = itertools.count()

    def node(self, flt: Optional[A.MatchFilter]) -> PatternNode:
        alias = flt.alias if flt is not None and flt.alias else None
        anonymous = alias is None
        if alias is None:
            alias = f"$anon{next(self._anon)}"
        n = self.nodes.get(alias)
        if n is None:
            n = self.nodes[alias] = PatternNode(alias, anonymous)
        if flt is not None:
            n.filters.append(flt)
            if flt.optional:
                n.optional = True
        return n


def build_pattern(stmt: A.MatchStatement) -> Tuple[Pattern, List[A.MatchPath]]:
    pattern = Pattern()
    not_paths: List[A.MatchPath] = []
    for path in stmt.paths:
        if path.negated:
            not_paths.append(path)
            # ensure shared aliases exist as nodes (without adding filters)
            continue
        prev = pattern.node(path.first)
        for item in path.items:
            tgt = pattern.node(item.target)
            if item.method and item.method.lower() in ("oute", "ine", "bothe") and (
                item.edge_filter is None
            ):
                # bare .outE(){as:e}: target alias binds the EDGE
                tgt.is_edge_alias = True
            pattern.edges.append(PatternEdge(prev.alias, tgt.alias, item, False))
            if item.edge_filter is not None and item.edge_filter.alias:
                en = pattern.node(A.MatchFilter(alias=item.edge_filter.alias))
                en.is_edge_alias = True
            prev = tgt
    return pattern, not_paths


_REVERSE_DIR = {"out": "in", "in": "out", "both": "both"}


def _expr_uses_bindings(expr, pattern_nodes: Dict[str, "PatternNode"]) -> bool:
    """True if a where-expression references other aliases ($matched,
    $currentMatch, or an alias name used as an identifier)."""
    if isinstance(expr, A.ContextVar):
        return expr.name in ("matched", "currentMatch")
    if isinstance(expr, A.Identifier):
        return expr.name in pattern_nodes
    if isinstance(expr, A.Binary):
        return _expr_uses_bindings(expr.left, pattern_nodes) or _expr_uses_bindings(
            expr.right, pattern_nodes
        )
    if isinstance(expr, A.Unary):
        return _expr_uses_bindings(expr.expr, pattern_nodes)
    if isinstance(expr, A.Between):
        return any(
            _expr_uses_bindings(e, pattern_nodes)
            for e in (expr.expr, expr.low, expr.high)
        )
    if isinstance(expr, (A.IsNull, A.IsDefined)):
        return _expr_uses_bindings(expr.expr, pattern_nodes)
    if isinstance(expr, A.FieldAccess):
        return _expr_uses_bindings(expr.base, pattern_nodes)
    if isinstance(expr, A.IndexAccess):
        return _expr_uses_bindings(expr.base, pattern_nodes) or _expr_uses_bindings(
            expr.index, pattern_nodes
        )
    if isinstance(expr, A.MethodCall):
        return _expr_uses_bindings(expr.base, pattern_nodes) or any(
            _expr_uses_bindings(a, pattern_nodes) for a in expr.args
        )
    if isinstance(expr, A.FunctionCall):
        return any(_expr_uses_bindings(a, pattern_nodes) for a in expr.args)
    if isinstance(expr, A.ListExpr):
        return any(_expr_uses_bindings(a, pattern_nodes) for a in expr.items)
    return False


class MatchInterpreter:
    """Per-record DFS, the [E] MatchEdgeTraverser analog."""

    def __init__(self, db, stmt: A.MatchStatement, params, parent_ctx=None) -> None:
        self.db = db
        self.stmt = stmt
        self.params = params
        self.parent_ctx = parent_ctx
        self.pattern, self.not_paths = build_pattern(stmt)
        # alias → binding-independent candidate list, computed once per query
        self._cand_cache: Dict[str, List[Document]] = {}

    # -- candidate sets ----------------------------------------------------

    def node_candidates(self, node: PatternNode) -> List[Document]:
        """Binding-independent candidate set for an alias, cached per query.
        Where-clauses that reference other bindings ($matched / alias names)
        are NOT applied here — callers re-check with
        `check_node(node, cand, bindings)` once bindings exist."""
        cached = self._cand_cache.get(node.alias)
        if cached is not None:
            return cached
        rid = None
        class_names = []
        for f in node.filters:
            if f.rid is not None:
                rid = RID(f.rid.cluster, f.rid.position)
            if f.class_name:
                class_names.append(f.class_name)
        if rid is not None:
            doc = self.db.load(rid)
            docs = [doc] if doc is not None else []
        elif class_names:
            # index-seeded when some filter's WHERE has an indexable
            # conjunct ([E] MatchPrefetchStep's index use, SURVEY.md §3.3);
            # check_node below still applies every filter in full
            docs = None
            if self.db.tx is None:
                ctx = EvalContext(self.db, params=self.params)
                for f in node.filters:
                    if f.where is None:
                        continue
                    seeded = indexed_class_docs(
                        self.db, class_names[0], True, f.where, ctx
                    )
                    if seeded is not None:
                        docs = [
                            d
                            for d in seeded
                            if all(self._doc_is_class(d, c) for c in class_names[1:])
                        ]
                        break
            if docs is None:
                # most selective: intersect by scanning the first and
                # checking all
                docs = [
                    d
                    for d in self.db.browse_class(class_names[0])
                    if all(self._doc_is_class(d, c) for c in class_names[1:])
                ]
        elif node.is_edge_alias:
            docs = list(self.db.browse_class("E"))
        else:
            docs = list(self.db.browse_class("V"))
        out = [d for d in docs if self.check_node(node, d, {}, prefilter=True)]
        self._cand_cache[node.alias] = out
        return out

    def estimate(self, node: PatternNode) -> int:
        """Candidate-set size estimate for greedy root/expansion ordering
        ([E] OMatchExecutionPlanner's index-aware estimates): class count
        scaled by a WHERE-selectivity prior — an equality on a
        unique-indexed field is a point lookup; plain equalities and
        ranges get blunt priors. Without this, a `where:(id = ?)` root is
        costed like a full class scan and the planner roots at the wrong
        alias (e.g. walking every Post's reply tree backwards instead of
        starting from the one matched Message)."""
        for f in node.filters:
            if f.rid is not None:
                return 1
        base = None
        cname = None
        for f in node.filters:
            if f.class_name:
                cls = self.db.schema.get_class(f.class_name)
                if cls is not None:
                    base = self.db.count_class(cls.name)
                    cname = cls.name
                    break
        if base is None:
            base = self.db.count_class("E" if node.is_edge_alias else "V") + 10**6
        sel = 1.0
        for f in node.filters:
            if f.where is not None:
                sel = min(sel, self._where_selectivity(cname, f.where))
        return max(1, int(base * sel))

    def _where_selectivity(self, cname: Optional[str], w) -> float:
        if isinstance(w, A.Binary):
            if w.op == "AND":
                return max(
                    1e-6,
                    self._where_selectivity(cname, w.left)
                    * self._where_selectivity(cname, w.right),
                )
            if w.op == "OR":
                return min(
                    1.0,
                    self._where_selectivity(cname, w.left)
                    + self._where_selectivity(cname, w.right),
                )
            if w.op == "=":
                fld = None
                if isinstance(w.left, A.Identifier):
                    fld = w.left.name
                elif isinstance(w.right, A.Identifier):
                    fld = w.right.name
                if fld and cname and self.db._indexes is not None:
                    idx = self.db._indexes.best_for(cname, fld)
                    if idx is not None and idx.unique:
                        return 1e-9  # point lookup
                return 0.01 if fld else 1.0
            if w.op in ("<", "<=", ">", ">="):
                return 0.3
            if w.op == "IN":
                return 0.05
        if isinstance(w, A.Between):
            return 0.2
        return 1.0

    def _doc_is_class(self, doc: Document, class_name: str) -> bool:
        cls = self.db.schema.get_class(doc.class_name)
        return cls is not None and cls.is_subclass_of(class_name)

    def check_node(
        self,
        node: PatternNode,
        doc: Document,
        bindings: Dict[str, object],
        prefilter: bool = False,
    ) -> bool:
        """With ``prefilter=True``, binding-dependent where-clauses are
        skipped (evaluating them with empty bindings would wrongly drop
        every candidate)."""
        for f in node.filters:
            if f.class_name and not self._doc_is_class(doc, f.class_name):
                return False
            if f.rid is not None and doc.rid != RID(f.rid.cluster, f.rid.position):
                return False
            if f.where is not None:
                if prefilter and _expr_uses_bindings(f.where, self.pattern.nodes):
                    continue
                ctx = self._where_ctx(doc, bindings)
                if not truthy(evaluate(ctx, f.where)):
                    return False
        return True

    def _where_ctx(self, doc, bindings, extra=None) -> EvalContext:
        variables = dict(bindings)
        variables["matched"] = {
            k: v for k, v in bindings.items() if not k.startswith("$anon")
        }
        variables["currentMatch"] = doc
        if extra:
            variables.update(extra)
        return EvalContext(
            self.db,
            current=doc,
            params=self.params,
            variables=variables,
            parent=self.parent_ctx,
        )

    # -- expansion ---------------------------------------------------------

    def expand(
        self,
        start: Document,
        item: A.MatchPathItem,
        bindings: Dict[str, object],
        reverse: bool = False,
    ) -> Iterator[Tuple[Document, Optional[Edge], int, List[Document]]]:
        """Yield (candidate, last_edge, depth, path) expanding one pattern
        edge from ``start``. ``reverse`` walks the arrow backwards (target
        alias was already bound)."""
        direction = item.direction
        method = (item.method or "").lower()
        if method in ("outv", "inv", "bothv"):
            # from a bound edge to its endpoint(s)
            if isinstance(start, Edge) and not reverse:
                if method == "outv":
                    yield start.from_vertex(), None, 1, [start]
                elif method == "inv":
                    yield start.to_vertex(), None, 1, [start]
                else:
                    yield start.from_vertex(), None, 1, [start]
                    yield start.to_vertex(), None, 1, [start]
            elif reverse and isinstance(start, Vertex):
                # reverse of outV: edges whose out is this vertex
                want = "out" if method == "outv" else "in"
                for e in start.edges(Direction.BOTH):
                    end = e.out_rid if want == "out" else e.in_rid
                    if end == start.rid:
                        yield e, None, 1, [start]
            return
        if reverse:
            direction = _REVERSE_DIR[direction]
        edge_dir = {"out": Direction.OUT, "in": Direction.IN, "both": Direction.BOTH}[
            direction
        ]
        edge_classes = item.edge_classes or (None,)
        binds_edge = method in ("oute", "ine", "bothe") and item.edge_filter is None
        while_cond = item.target.while_cond
        max_depth = item.target.max_depth
        if while_cond is None and max_depth is None:
            # single hop
            for ec in edge_classes:
                for edge in start.edges(edge_dir, ec) if isinstance(start, Vertex) else []:
                    if not self._edge_ok(edge, item, bindings):
                        continue
                    if binds_edge and not reverse:
                        yield edge, edge, 1, [start]
                        continue
                    other = self._other_end(edge, start, direction)
                    if other is not None:
                        yield other, edge, 1, [start, other]
            return
        # variable-depth: BFS with visited-at-enqueue; emit every reached
        # node including the origin at depth 0. BFS (not the reference's
        # per-record DFS) makes $depth the MINIMUM depth and the emitted
        # set independent of traversal order — DFS can reach a node first
        # through a long path and then refuse to expand it under
        # WHILE($depth<N), making results order-dependent on cyclic graphs.
        visited: Set[RID] = {start.rid}
        yield start, None, 0, [start]
        queue: Deque[Tuple[Document, int, List[Document]]] = deque([(start, 0, [start])])
        while queue:
            node, depth, path = queue.popleft()
            # gate traversal: while-condition at the current node
            if not self._while_ok(node, depth, while_cond, max_depth, bindings):
                continue
            for ec in edge_classes:
                if not isinstance(node, Vertex):
                    continue
                for edge in node.edges(edge_dir, ec):
                    if not self._edge_ok(edge, item, bindings):
                        continue
                    other = self._other_end(edge, node, direction)
                    if other is None or other.rid in visited:
                        continue
                    visited.add(other.rid)
                    npath = path + [other]
                    yield other, edge, depth + 1, npath
                    queue.append((other, depth + 1, npath))

    def _while_ok(self, node, depth, while_cond, max_depth, bindings) -> bool:
        if max_depth is not None and depth >= max_depth:
            return False
        if while_cond is not None:
            ctx = self._where_ctx(node, bindings, extra={"depth": depth})
            if not truthy(evaluate(ctx, while_cond)):
                return False
        elif max_depth is None:
            return False
        return True

    def _edge_ok(self, edge: Edge, item: A.MatchPathItem, bindings) -> bool:
        f = item.edge_filter
        if f is None:
            return True
        if f.class_name and not self._doc_is_class(edge, f.class_name):
            return False
        if f.where is not None:
            ctx = self._where_ctx(edge, bindings)
            if not truthy(evaluate(ctx, f.where)):
                return False
        return True

    def _other_end(self, edge: Edge, from_doc: Document, direction: str):
        if direction == "out":
            return self.db.load(edge.in_rid)
        if direction == "in":
            return self.db.load(edge.out_rid)
        other = edge.in_rid if edge.out_rid == from_doc.rid else edge.out_rid
        return self.db.load(other)

    # -- the solver --------------------------------------------------------

    def solve(self) -> Iterator[Dict[str, object]]:
        required = [e for e in self.pattern.edges if not self._edge_is_optional(e)]
        optionals = [e for e in self.pattern.edges if self._edge_is_optional(e)]
        isolated = self.enumerable_isolated(required, optionals)
        for bindings in self._solve_required(required, isolated, {}):
            for full in self._solve_optionals(optionals, bindings):
                if self._not_arms_ok(full):
                    yield full

    def enumerable_isolated(
        self, required: List[PatternEdge], optionals: List[PatternEdge]
    ) -> List[PatternNode]:
        """Nodes needing up-front candidate enumeration: not touched by any
        REQUIRED edge (isolated nodes, and the from-side of optional-only
        arms), but excluding
        - optional nodes (they bind null when unmatched),
        - filterless nodes created only for NOT-arm sharing,
        - aliases bound as a side effect of some arm's edge braces
          ({as:kn} between the dashes) — they bind when their arm runs,
        - targets of optional arms: enumerating a filtered target of an
          arm-optional probe would turn the left join into a cross product
          (the probe must *bind* it, nulling on no-match).

        This is the shared admission rule — the TPU planner replays it, so
        any edit here is an engine-parity change."""
        arm_bound = {
            e.item.edge_filter.alias
            for e in self.pattern.edges
            if e.item.edge_filter is not None and e.item.edge_filter.alias
        }
        opt_targets = {e.to_alias for e in optionals}
        return [
            n
            for n in self.pattern.nodes.values()
            if not any(
                e.from_alias == n.alias or e.to_alias == n.alias for e in required
            )
            and not n.optional
            and n.filters
            and n.alias not in arm_bound
            and n.alias not in opt_targets
        ]

    def _edge_is_optional(self, e: PatternEdge) -> bool:
        # node-level (reference semantics: an optional target binds null
        # when unmatched) or arm-level — `optional:true` inside the edge
        # braces marks just this arm as a left join, so a cyclic arm
        # between two required aliases can probe edge existence (the IS7
        # "knows" flag) without making either endpoint optional.
        return self.pattern.nodes[e.to_alias].optional or self._arm_optional(e)

    @staticmethod
    def _arm_optional(e: PatternEdge) -> bool:
        f = e.item.edge_filter
        return f is not None and f.optional

    def _solve_required(
        self,
        edges: List[PatternEdge],
        isolated: List[PatternNode],
        bindings: Dict[str, object],
    ) -> Iterator[Dict[str, object]]:
        if not edges:
            if not isolated:
                yield bindings
                return
            node, rest = isolated[0], isolated[1:]
            if node.alias in bindings:
                yield from self._solve_required(edges, rest, bindings)
                return
            for cand in self.node_candidates(node):
                if not self.check_node(node, cand, bindings):
                    continue
                nb = dict(bindings)
                nb[node.alias] = cand
                yield from self._solve_required(edges, rest, nb)
            return
        # pick the next edge: prefer both endpoints bound, then one bound;
        # otherwise start a new component at the smallest-estimate alias
        # ([E] OMatchExecutionPlanner's greedy smallest-first ordering)
        def edge_rank(e: PatternEdge):
            fb = e.from_alias in bindings
            tb = e.to_alias in bindings
            if fb and tb:
                return 0
            if fb:
                return 1
            if tb:
                return 2
            return 3

        edges_sorted = sorted(range(len(edges)), key=lambda i: edge_rank(edges[i]))
        best = edges_sorted[0]
        e = edges[best]
        rest = edges[:best] + edges[best + 1 :]
        fb = e.from_alias in bindings
        tb = e.to_alias in bindings
        if not fb and not tb:
            # new component: enumerate candidates for the cheaper endpoint
            # ([E] OMatchExecutionPlanner's smallest-first root choice)
            from_node = self.pattern.nodes[e.from_alias]
            to_node = self.pattern.nodes[e.to_alias]
            root = (
                from_node
                if self.estimate(from_node) <= self.estimate(to_node)
                else to_node
            )
            for cand in self.node_candidates(root):
                if not self.check_node(root, cand, bindings):
                    continue
                nb = dict(bindings)
                nb[root.alias] = cand
                yield from self._solve_required([e] + rest, isolated, nb)
            return
        yield from self._expand_edge(e, rest, isolated, bindings, solver=self._solve_required)

    def _expand_edge(
        self, e: PatternEdge, rest, isolated, bindings, solver
    ) -> Iterator[Dict[str, object]]:
        fb = e.from_alias in bindings
        tb = e.to_alias in bindings
        to_node = self.pattern.nodes[e.to_alias]
        from_node = self.pattern.nodes[e.from_alias]
        if fb:
            start = bindings[e.from_alias]
            if start is None:
                # optional upstream bound to null: propagate null
                nb = dict(bindings)
                nb.setdefault(e.to_alias, None)
                yield from solver(rest, isolated, nb)
                return
            for cand, edge, depth, path in self.expand(start, e.item, bindings):
                if tb:
                    bound = bindings[e.to_alias]
                    if bound is None or cand.rid != bound.rid:
                        continue
                elif not self.check_node(to_node, cand, bindings):
                    continue
                nb = dict(bindings)
                nb[e.to_alias] = cand
                self._bind_extras(nb, e.item, edge, depth, path)
                yield from solver(rest, isolated, nb)
        else:
            # reverse expansion: to is bound, from is not
            start = bindings[e.to_alias]
            if start is None:
                nb = dict(bindings)
                nb.setdefault(e.from_alias, None)
                yield from solver(rest, isolated, nb)
                return
            for cand, edge, depth, path in self.expand(
                start, e.item, bindings, reverse=True
            ):
                if not self.check_node(from_node, cand, bindings):
                    continue
                nb = dict(bindings)
                nb[e.from_alias] = cand
                self._bind_extras(nb, e.item, edge, depth, path)
                yield from solver(rest, isolated, nb)

    def _bind_extras(self, bindings, item: A.MatchPathItem, edge, depth, path) -> None:
        f = item.edge_filter
        if f is not None and f.alias and edge is not None:
            bindings[f.alias] = edge
        tgt = item.target
        if tgt.depth_alias:
            bindings[tgt.depth_alias] = depth
        if tgt.path_alias:
            bindings[tgt.path_alias] = list(path)

    def _solve_optionals(
        self, optionals: List[PatternEdge], bindings: Dict[str, object]
    ) -> Iterator[Dict[str, object]]:
        if not optionals:
            yield bindings
            return
        # process optional edges whose from-side is decided first
        e = None
        for i, cand_e in enumerate(optionals):
            if cand_e.from_alias in bindings or cand_e.to_alias in bindings:
                e = cand_e
                rest = optionals[:i] + optionals[i + 1 :]
                break
        if e is None:
            # fully detached optional arm: bind nulls
            nb = dict(bindings)
            for oe in optionals:
                nb.setdefault(oe.from_alias, None)
                nb.setdefault(oe.to_alias, None)
            yield nb
            return
        matched_any = False
        results = []
        for nb in self._expand_edge(
            e, rest, [], bindings, solver=lambda r, i, b: self._solve_optionals(r, b)
        ):
            matched_any = True
            results.append(nb)
        if matched_any:
            yield from iter(results)
        else:
            nb = dict(bindings)
            both_bound = e.from_alias in bindings and e.to_alias in bindings
            if not (both_bound and self._arm_optional(e)):
                # node-optional: the undecided endpoint binds null. An
                # arm-optional probe between two bound aliases must NOT
                # overwrite either endpoint — only its own extras null.
                nb[e.to_alias if e.from_alias in bindings else e.from_alias] = None
            f = e.item.edge_filter
            if f is not None and f.alias:
                nb[f.alias] = None
            if e.item.target.depth_alias:
                nb[e.item.target.depth_alias] = None
            if e.item.target.path_alias:
                nb[e.item.target.path_alias] = None
            yield from self._solve_optionals(rest, nb)

    def _not_arms_ok(self, bindings: Dict[str, object]) -> bool:
        for path in self.not_paths:
            if self._not_path_satisfiable(path, bindings):
                return False
        return True

    def _not_path_satisfiable(self, path: A.MatchPath, bindings) -> bool:
        # build a little sub-pattern for the NOT arm, sharing bound aliases
        sub = Pattern()
        prev = sub.node(path.first)
        for item in path.items:
            tgt = sub.node(item.target)
            sub.edges.append(PatternEdge(prev.alias, tgt.alias, item, True))
            prev = tgt
        saved_nodes = self.pattern.nodes
        saved_edges = self.pattern.edges
        # merge: nodes referenced by the NOT arm use the arm's filters; bound
        # aliases stay fixed through `bindings`
        merged = dict(sub.nodes)
        self.pattern = Pattern()
        self.pattern.nodes = merged
        self.pattern.edges = sub.edges
        try:
            start_bindings = {
                k: v for k, v in bindings.items() if k in merged and v is not None
            }
            for _ in self._solve_required(list(sub.edges), [], start_bindings):
                return True
            return False
        finally:
            self.pattern = Pattern()
            self.pattern.nodes = saved_nodes
            self.pattern.edges = saved_edges

    # -- RETURN ------------------------------------------------------------

    def rows(self) -> List[Result]:
        named = [
            n.alias for n in self.pattern.nodes.values() if not n.anonymous
        ]
        return match_rows_from_bindings(
            self.db, self.stmt, named, self.solve(), self.params, self.parent_ctx
        )


def match_rows_from_bindings(
    db, stmt: A.MatchStatement, named: List[str], bindings_iter, params, parent_ctx
) -> List[Result]:
    """RETURN/DISTINCT/UNWIND/ORDER/SKIP/LIMIT marshalling shared by the
    oracle interpreter and the TPU engine — both produce binding dicts
    (alias → Document/None), so result semantics are defined once here."""
    out: List[Result] = []
    returns = stmt.returns
    special = None
    if len(returns) == 1 and isinstance(returns[0].expr, A.ContextVar):
        cv = returns[0].expr.name.lower()
        if cv in ("matches", "paths", "elements", "pathelements"):
            special = cv
    aggregate_mode = bool(stmt.group_by) or any(
        contains_aggregate(p.expr) for p in returns
    )
    if aggregate_mode:
        sel = A.SelectStatement(
            projections=returns, target=None, group_by=stmt.group_by
        )
        filtered = []
        for bindings in bindings_iter:
            ctx = EvalContext(
                db,
                current=None,
                params=params,
                variables=_return_vars(bindings, named),
                parent=parent_ctx,
            )
            filtered.append((ctx, None))
        out = _aggregate_rows(db, sel, filtered, params, parent_ctx)
        out = _order_rows(out, stmt.order_by, db, params, parent_ctx)
        base_ctx = EvalContext(db, params=params, parent=parent_ctx)
        return _skip_limit(out, stmt.skip, stmt.limit, base_ctx)
    for bindings in bindings_iter:
        if special in ("matches", "paths"):
            aliases = (
                named
                if special == "matches"
                else [a for a in bindings if not _is_internal_alias(a, named)]
            )
            props = {a: bindings.get(a) for a in aliases}
            out.append(Result(props=props))
            continue
        if special in ("elements", "pathelements"):
            aliases = named if special == "elements" else list(bindings.keys())
            for a in aliases:
                v = bindings.get(a)
                if isinstance(v, Document):
                    out.append(Result(element=v))
            continue
        ctx = EvalContext(
            db,
            current=None,
            params=params,
            variables=_return_vars(bindings, named),
            parent=parent_ctx,
        )
        props = {}
        for i, p in enumerate(returns):
            name = p.alias or _match_proj_name(p.expr, i)
            props[name] = evaluate(ctx, p.expr)
        out.append(Result(props=props))

    return finalize_match_rows(db, stmt, out, params, parent_ctx)


def finalize_match_rows(
    db, stmt: A.MatchStatement, out: List[Result], params, parent_ctx
) -> List[Result]:
    """DISTINCT/UNWIND/ORDER/SKIP/LIMIT tail, shared with the TPU engine's
    columnar fast path (which builds `out` straight from device columns)."""
    if stmt.distinct:
        seen = set()
        deduped = []
        for r in out:
            key = _canonical(r)
            if key not in seen:
                seen.add(key)
                deduped.append(r)
        out = deduped
    for field in stmt.unwind:
        unwound = []
        for r in out:
            vals = as_list(r.get_property(field))
            if not vals:
                unwound.append(r)
            for v in vals:
                rr = Result(props={k: r.get_property(k) for k in r.property_names()})
                rr.set_property(field, v)
                unwound.append(rr)
        out = unwound
    out = _order_rows(out, stmt.order_by, db, params, parent_ctx)
    base_ctx = EvalContext(db, params=params, parent=parent_ctx)
    out = _skip_limit(out, stmt.skip, stmt.limit, base_ctx)
    return out


def _is_internal_alias(a: str, named: List[str]) -> bool:
    return a not in named and not a.startswith("$anon")


def _return_vars(bindings: Dict[str, object], named: List[str]) -> Dict[str, object]:
    variables = dict(bindings)
    variables["matched"] = {k: v for k, v in bindings.items() if k in named}
    variables["matches"] = variables["matched"]
    return variables


def _match_proj_name(expr: A.Expression, i: int) -> str:
    if isinstance(expr, A.Identifier):
        return expr.name
    if isinstance(expr, A.FieldAccess) and isinstance(expr.base, A.Identifier):
        return f"{expr.base.name}.{expr.name}"
    return expr_name(expr, i)


def execute_match(db, stmt: A.MatchStatement, params, parent_ctx=None) -> List[Result]:
    return MatchInterpreter(db, stmt, params, parent_ctx).rows()


# ---------------------------------------------------------------------------
# TRAVERSE
# ---------------------------------------------------------------------------


def _traverse_expand(db, doc: Document, fields: Sequence[A.Expression], ctx) -> List[Document]:
    """Records reachable in one step per the TRAVERSE projection list."""
    out: List[Document] = []
    if not fields or any(isinstance(f, A.Star) for f in fields):
        # '*' follows every link: vertex → incident edges; edge → endpoints;
        # plus any explicit link-valued fields
        if isinstance(doc, Vertex):
            out.extend(doc.edges(Direction.OUT))
            out.extend(doc.edges(Direction.IN))
        elif isinstance(doc, Edge):
            fv, tv = db.load(doc.out_rid), db.load(doc.in_rid)
            out.extend(d for d in (fv, tv) if d is not None)
        for name in doc.field_names():
            v = doc.get(name)
            for item in as_list(v):
                if isinstance(item, RID):
                    d = db.load(item)
                    if d is not None:
                        out.append(d)
                elif isinstance(item, Document):
                    out.append(item)
        return out
    for f in fields:
        if isinstance(f, A.FunctionCall):
            name = f.name.lower()
            classes = [evaluate(ctx.child(current=doc), a) for a in f.args]
            if name in ("out", "in", "both"):
                out.extend(nav_vertices(ctx.child(current=doc), doc, name, classes))
                continue
            if name in ("oute", "ine", "bothe"):
                out.extend(nav_edges(ctx.child(current=doc), doc, name[:-1], classes))
                continue
            if name == "any":
                out.extend(_traverse_expand(db, doc, (A.Star(),), ctx))
                continue
        if isinstance(f, A.Identifier):
            v = doc.get(f.name)
            for item in as_list(v):
                if isinstance(item, RID):
                    d = db.load(item)
                    if d is not None:
                        out.append(d)
                elif isinstance(item, Document):
                    out.append(item)
            continue
    return out


def execute_traverse(db, stmt: A.TraverseStatement, params, parent_ctx=None) -> List[Result]:
    base_ctx = EvalContext(db, params=params, parent=parent_ctx)
    roots: List[Document] = []
    for row in resolve_target_rows(db, stmt.target, base_ctx):
        if isinstance(row, Document):
            roots.append(row)
        elif isinstance(row, Result) and row.is_element:
            roots.append(row.element)  # type: ignore[arg-type]
    limit = int(evaluate(base_ctx, stmt.limit)) if stmt.limit is not None else None
    visited: Set[RID] = set()
    out: List[Result] = []
    depth_first = stmt.strategy == "DEPTH_FIRST"

    # frontier entries: (doc, depth)
    frontier: List[Tuple[Document, int]] = [(r, 0) for r in roots]
    if depth_first:
        frontier.reverse()  # stack pops from the end; keep root order

    def admit(doc: Document, depth: int) -> bool:
        if doc.rid in visited:
            return False
        if stmt.max_depth is not None and depth > stmt.max_depth:
            return False
        if stmt.while_cond is not None and depth > 0:
            ctx = EvalContext(
                db,
                current=doc,
                params=params,
                variables={"depth": depth},
                parent=parent_ctx,
            )
            if not truthy(evaluate(ctx, stmt.while_cond)):
                return False
        return True

    while frontier:
        if depth_first:
            doc, depth = frontier.pop()
        else:
            doc, depth = frontier.pop(0)
        if not admit(doc, depth):
            continue
        visited.add(doc.rid)
        out.append(Result(element=doc))
        if limit is not None and len(out) >= limit:
            break
        children = _traverse_expand(db, doc, stmt.fields, base_ctx)
        entries = [(c, depth + 1) for c in children if c.rid not in visited]
        if depth_first:
            frontier.extend(reversed(entries))
        else:
            frontier.extend(entries)
    return out


# ---------------------------------------------------------------------------
# dispatch
# ---------------------------------------------------------------------------


def execute_statement(db, stmt: A.Statement, params, parent_ctx=None) -> List[Result]:
    if isinstance(stmt, A.SelectStatement):
        return execute_select(db, stmt, params, parent_ctx)
    if isinstance(stmt, A.MatchStatement):
        return execute_match(db, stmt, params, parent_ctx)
    if isinstance(stmt, A.TraverseStatement):
        return execute_traverse(db, stmt, params, parent_ctx)
    from orientdb_tpu.exec import dml

    return dml.execute(db, stmt, params, parent_ctx)
