"""Remote database client over the binary channel.

Analog of [E] OStorageRemote / ODatabaseDocumentRemote (SURVEY.md §2
"Remote client"): mirrors the embedded Database's query/command/load/save/
delete surface over the length-prefixed protocol, with a thread-safe
connection and lazy reconnect. `remote:` URL scheme:

    db = connect("remote:127.0.0.1:2424/demodb", "admin", "admin")
"""

from __future__ import annotations

import socket
import threading
from typing import Dict, List, Optional

from orientdb_tpu.server.binary_server import recv_frame, send_frame


class RemoteError(Exception):
    pass


class RemoteResultSet:
    """List-backed result mirror of the embedded ResultSet surface."""

    def __init__(self, rows: List[dict], engine: Optional[str]) -> None:
        self._rows = rows
        self.engine = engine

    def to_dicts(self) -> List[dict]:
        return list(self._rows)

    def __iter__(self):
        return iter(self._rows)

    def __len__(self) -> int:
        return len(self._rows)


class RemoteDatabase:
    def __init__(
        self, host: str, port: int, name: str, user: str, password: str
    ) -> None:
        self.host, self.port, self.name = host, port, name
        self._user, self._password = user, password
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._connect()

    # -- channel ------------------------------------------------------------

    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port), timeout=30)
        resp = self._call({"op": "connect", "user": self._user, "password": self._password})
        if not resp.get("ok"):
            raise RemoteError(resp.get("error", "connect failed"))
        if self.name:
            resp = self._call({"op": "db_open", "name": self.name})
            if not resp.get("ok"):
                raise RemoteError(resp.get("error", "open failed"))

    def _call(self, req: dict) -> dict:
        with self._lock:
            if self._sock is None:
                raise RemoteError("connection closed")
            send_frame(self._sock, req)
            resp = recv_frame(self._sock)
            if resp is None:
                raise RemoteError("connection lost")
            return resp

    def _checked(self, req: dict) -> dict:
        resp = self._call(req)
        if not resp.get("ok"):
            raise RemoteError(resp.get("error", "request failed"))
        return resp

    # -- database surface ---------------------------------------------------

    def query(self, sql: str, params: Optional[Dict] = None) -> RemoteResultSet:
        r = self._checked({"op": "query", "sql": sql, "params": params})
        return RemoteResultSet(r["result"], r.get("engine"))

    def command(self, sql: str, params: Optional[Dict] = None) -> RemoteResultSet:
        r = self._checked({"op": "command", "sql": sql, "params": params})
        return RemoteResultSet(r["result"], r.get("engine"))

    def load(self, rid) -> Optional[dict]:
        return self._checked({"op": "load", "rid": str(rid)})["record"]

    def save(self, record: dict) -> dict:
        return self._checked({"op": "save", "record": record})["record"]

    def delete(self, rid) -> None:
        self._checked({"op": "delete", "rid": str(rid)})

    def databases(self) -> List[str]:
        return self._checked({"op": "db_list"})["databases"]

    def create_database(self, name: str) -> None:
        """Create (and open) a database on the server ([E] OServerAdmin
        createDatabase); requires database-create permission."""
        self._checked({"op": "db_create", "name": name})

    def close(self) -> None:
        try:
            self._call({"op": "close"})
        except RemoteError:
            pass
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None

    def __enter__(self) -> "RemoteDatabase":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect(url: str, user: str, password: str) -> RemoteDatabase:
    """`remote:<host>:<port>/<database>` ([E] the remote: URL scheme)."""
    if not url.startswith("remote:"):
        raise ValueError(f"not a remote: url: {url!r}")
    rest = url[len("remote:") :]
    hostport, _, name = rest.partition("/")
    host, _, port = hostport.partition(":")
    return RemoteDatabase(host or "127.0.0.1", int(port or 2424), name, user, password)
